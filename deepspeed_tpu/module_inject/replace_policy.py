"""External-model injection policies — HF-Flax models onto the TPU kernels.

Reference: ``deepspeed/module_inject/replace_policy.py:43-239`` ships
per-architecture policies (HFBertLayerPolicy, HFGPT2LayerPolicy, ...) that
``replace_module.py:11-88`` uses to swap *other people's* nn.Modules for
DeepSpeed's fused/TP kernel modules in place.

Flax modules are pure functions of a param tree, so "kernel injection" is
a WEIGHT-LAYOUT conversion instead of module surgery: each policy maps an
HF-Flax model's param tree onto the in-tree family (``models/gpt.py`` /
``models/bert.py``), whose forward already routes through the Pallas flash
kernels, the fused CE head, KV-cache decode and the Megatron TP partition
rules. ``init_inference(model=<hf flax model>,
replace_with_kernel_inject=True)`` then serves their weights on our
engine — the same outcome as the reference's injection, TPU-style.

Numerics note: GPT-2's tanh-approximated gelu matches exactly; HF-BERT's
exact (erf) gelu differs from our tanh approximation by O(1e-3) per
activation — parity tests use a correspondingly loose tolerance.
"""

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


def _get(tree, *path):
    node = tree
    for p in path:
        node = node[p]
    return node


def _t(x):
    return np.asarray(x).T


class HFGPT2Policy:
    """FlaxGPT2Model / FlaxGPT2LMHeadModel → models.gpt.GPT.

    HF's Conv1D-style kernels are stored [out, in] (transposed vs flax
    Dense); qkv ordering and head reshape match 1:1.
    """

    model_type = "gpt2"

    @staticmethod
    def applies(model) -> bool:
        return getattr(getattr(model, "config", None), "model_type",
                       None) == "gpt2"

    @staticmethod
    def convert(hf_params: Dict, hf_config) -> Tuple[Any, Dict]:
        from deepspeed_tpu.models.gpt import GPT, GPTConfig

        d = int(hf_config.n_embd)
        inner = int(getattr(hf_config, "n_inner", None) or 4 * d)
        if inner % d:
            raise ValueError(f"n_inner={inner} not a multiple of n_embd={d}")
        cfg = GPTConfig(vocab_size=int(hf_config.vocab_size),
                        max_seq_len=int(hf_config.n_positions),
                        hidden_size=d,
                        num_layers=int(hf_config.n_layer),
                        num_heads=int(hf_config.n_head),
                        mlp_ratio=inner // d,
                        dropout_rate=0.0,
                        layer_norm_epsilon=float(
                            hf_config.layer_norm_epsilon),
                        tie_embeddings=True)
        tr = hf_params.get("transformer", hf_params)
        out = {
            "wte": np.asarray(_get(tr, "wte", "embedding")),
            "wpe": np.asarray(_get(tr, "wpe", "embedding")),
            "ln_f": dict(_get(tr, "ln_f")),
        }
        for i in range(cfg.num_layers):
            h = _get(tr, "h", str(i))
            out[f"h_{i}"] = {
                "ln_1": dict(h["ln_1"]),
                "ln_2": dict(h["ln_2"]),
                "c_attn": {"kernel": _t(h["attn"]["c_attn"]["kernel"]),
                           "bias": np.asarray(h["attn"]["c_attn"]["bias"])},
                "c_proj": {"kernel": _t(h["attn"]["c_proj"]["kernel"]),
                           "bias": np.asarray(h["attn"]["c_proj"]["bias"])},
                "c_fc": {"kernel": _t(h["mlp"]["c_fc"]["kernel"]),
                         "bias": np.asarray(h["mlp"]["c_fc"]["bias"])},
                "mlp_proj": {"kernel": _t(h["mlp"]["c_proj"]["kernel"]),
                             "bias": np.asarray(h["mlp"]["c_proj"]["bias"])},
            }
        return GPT(cfg), out


class HFBertPolicy:
    """FlaxBertForMaskedLM / FlaxBertForPreTraining → models.bert.BertModel
    (post-LN; a headless FlaxBertModel is rejected — the in-tree forward
    needs the MLM head). Separate q/k/v Dense kernels merge into the fused
    c_attn [D, 3D] — the same q;k;v concatenation the reference's
    HFBertLayerPolicy feeds its ``attn_qkvw`` (replace_policy.py:43)."""

    model_type = "bert"

    @staticmethod
    def applies(model) -> bool:
        return getattr(getattr(model, "config", None), "model_type",
                       None) == "bert"

    @staticmethod
    def convert(hf_params: Dict, hf_config) -> Tuple[Any, Dict]:
        from deepspeed_tpu.models.bert import BertConfig, BertModel

        d = int(hf_config.hidden_size)
        inner = int(hf_config.intermediate_size)
        if inner % d:
            raise ValueError(
                f"intermediate_size={inner} not a multiple of hidden={d}")
        cfg = BertConfig(vocab_size=int(hf_config.vocab_size),
                         max_seq_len=int(hf_config.max_position_embeddings),
                         hidden_size=d,
                         num_layers=int(hf_config.num_hidden_layers),
                         num_heads=int(hf_config.num_attention_heads),
                         mlp_ratio=inner // d,
                         type_vocab_size=int(hf_config.type_vocab_size),
                         dropout_rate=0.0,
                         layer_norm_epsilon=float(hf_config.layer_norm_eps),
                         pre_layer_norm=False)
        bert = hf_params.get("bert", hf_params)
        emb = bert["embeddings"]
        out = {
            "wte": np.asarray(_get(emb, "word_embeddings", "embedding")),
            "wpe": np.asarray(_get(emb, "position_embeddings", "embedding")),
            "tte": np.asarray(_get(emb, "token_type_embeddings",
                                   "embedding")),
            "ln_emb": dict(emb["LayerNorm"]),
        }
        for i in range(cfg.num_layers):
            lay = _get(bert, "encoder", "layer", str(i))
            att = lay["attention"]
            qkv_k = np.concatenate(
                [np.asarray(att["self"][n]["kernel"])
                 for n in ("query", "key", "value")], axis=1)
            qkv_b = np.concatenate(
                [np.asarray(att["self"][n]["bias"])
                 for n in ("query", "key", "value")], axis=0)
            out[f"layer_{i}"] = {
                "c_attn": {"kernel": qkv_k, "bias": qkv_b},
                "c_proj": {
                    "kernel": np.asarray(att["output"]["dense"]["kernel"]),
                    "bias": np.asarray(att["output"]["dense"]["bias"])},
                "ln_attn": dict(att["output"]["LayerNorm"]),
                "c_fc": {
                    "kernel": np.asarray(
                        lay["intermediate"]["dense"]["kernel"]),
                    "bias": np.asarray(lay["intermediate"]["dense"]["bias"])},
                "mlp_proj": {
                    "kernel": np.asarray(lay["output"]["dense"]["kernel"]),
                    "bias": np.asarray(lay["output"]["dense"]["bias"])},
                "ln_mlp": dict(lay["output"]["LayerNorm"]),
            }
        cls = hf_params.get("cls")
        if cls is None:
            raise ValueError(
                "headless FlaxBertModel has no MLM head ('cls' params) and "
                "the in-tree BertModel forward requires one — convert a "
                "FlaxBertForMaskedLM / FlaxBertForPreTraining instead")
        tr = _get(cls, "predictions", "transform")
        out["mlm_transform"] = {
            "kernel": np.asarray(tr["dense"]["kernel"]),
            "bias": np.asarray(tr["dense"]["bias"])}
        out["mlm_ln"] = dict(tr["LayerNorm"])
        out["mlm_bias"] = np.asarray(_get(cls, "predictions", "bias"))
        return BertModel(cfg), out




def policy_for(model) -> Optional[type]:
    for pol in REPLACE_POLICIES:
        if pol.applies(model):
            return pol
    return None


def convert_external_model(model, params: Any = None,
                           injection_policy: Optional[type] = None,
                           dtype: Any = None):
    """(in-tree module, converted params) for a recognized external model,
    or None if no policy matches. ``injection_policy`` forces a policy
    class (the reference's ``injection_policy=`` dict argument); ``dtype``
    sets the in-tree family's compute dtype (the engine passes its serving
    dtype so fp32 serving stays fp32 end to end)."""
    pol = injection_policy or policy_for(model)
    if pol is None:
        return None
    hf_params = params if params is not None else getattr(model, "params",
                                                          None)
    if hf_params is None:
        raise ValueError(
            f"{type(model).__name__}: pass params= (the HF param dict) — "
            f"the model instance carries none")
    # fp32 leaves; the engine casts to its serving dtype.
    hf_params = jax.tree_util.tree_map(np.asarray, hf_params)
    module, converted = pol.convert(hf_params, model.config)
    if dtype is not None:
        from dataclasses import replace

        module = type(module)(replace(module.cfg, dtype=dtype))
    return module, converted


class HFGPTNeoPolicy:
    """FlaxGPTNeoForCausalLM / FlaxGPTNeoModel → models.gpt.GPT (the
    reference's HFGPTNEOLayerPolicy, replace_policy.py:102).

    GPT-Neo particulars honored: plain-Dense [in, out] kernels (no Conv1D
    transpose), bias-free q/k/v merged into c_attn with a zero bias,
    UNSCALED attention scores (attention_scale=1.0), tied lm_head. Local
    (windowed) attention layers are exact only while the sequence fits the
    window, so the converted model's max_seq_len is clamped to
    ``min(max_position_embeddings, window_size)`` when any layer is local
    — within that range local and global causal attention coincide.
    """

    model_type = "gpt_neo"

    @staticmethod
    def applies(model) -> bool:
        return getattr(getattr(model, "config", None), "model_type",
                       None) == "gpt_neo"

    @staticmethod
    def convert(hf_params: Dict, hf_config) -> Tuple[Any, Dict]:
        from deepspeed_tpu.models.gpt import GPT, GPTConfig
        from deepspeed_tpu.utils.logging import logger

        d = int(hf_config.hidden_size)
        inner = int(getattr(hf_config, "intermediate_size", None) or 4 * d)
        if inner % d:
            raise ValueError(
                f"intermediate_size={inner} not a multiple of hidden={d}")
        if not getattr(hf_config, "tie_word_embeddings", True):
            raise ValueError(
                "GPT-Neo with tie_word_embeddings=False has a separate "
                "lm_head the in-tree tied GPT cannot represent — untied "
                "conversion is not supported")
        act = getattr(hf_config, "activation_function", "gelu_new")
        if act not in ("gelu_new", "gelu"):
            raise ValueError(
                f"GPT-Neo activation_function='{act}' is not the gelu the "
                f"in-tree GPT computes — conversion would be silently wrong")
        max_pos = int(hf_config.max_position_embeddings)
        attn_types = [t for block in hf_config.attention_types
                      for t in block[0] * block[1]]
        if "local" in attn_types and int(hf_config.window_size) < max_pos:
            max_pos = int(hf_config.window_size)
            logger.warning(
                f"GPT-Neo has local-attention layers (window "
                f"{max_pos}): the converted model's context is clamped "
                f"from {hf_config.max_position_embeddings} to {max_pos} "
                f"tokens, within which local and global causal attention "
                f"coincide exactly; longer prompts need a banded-mask "
                f"forward (not yet wired)")
        cfg = GPTConfig(vocab_size=int(hf_config.vocab_size),
                        max_seq_len=max_pos,
                        hidden_size=d,
                        num_layers=int(hf_config.num_layers),
                        num_heads=int(hf_config.num_heads),
                        mlp_ratio=inner // d,
                        dropout_rate=0.0,
                        layer_norm_epsilon=float(
                            hf_config.layer_norm_epsilon),
                        tie_embeddings=True,
                        attention_scale=1.0)
        tr = hf_params.get("transformer", hf_params)
        out = {
            "wte": np.asarray(_get(tr, "wte", "embedding")),
            "wpe": np.asarray(_get(tr, "wpe", "embedding"))[:max_pos],
            "ln_f": dict(_get(tr, "ln_f")),
        }
        for i in range(cfg.num_layers):
            h = _get(tr, "h", str(i))
            att = h["attn"]["attention"]
            qkv_k = np.concatenate(
                [np.asarray(att[n]["kernel"])
                 for n in ("q_proj", "k_proj", "v_proj")], axis=1)
            out[f"h_{i}"] = {
                "ln_1": dict(h["ln_1"]),
                "ln_2": dict(h["ln_2"]),
                "c_attn": {"kernel": qkv_k,
                           "bias": np.zeros((3 * d,), np.float32)},
                "c_proj": {"kernel": np.asarray(att["out_proj"]["kernel"]),
                           "bias": np.asarray(att["out_proj"]["bias"])},
                "c_fc": {"kernel": np.asarray(h["mlp"]["c_fc"]["kernel"]),
                         "bias": np.asarray(h["mlp"]["c_fc"]["bias"])},
                "mlp_proj": {
                    "kernel": np.asarray(h["mlp"]["c_proj"]["kernel"]),
                    "bias": np.asarray(h["mlp"]["c_proj"]["bias"])},
            }
        return GPT(cfg), out


REPLACE_POLICIES = (HFGPT2Policy, HFBertPolicy, HFGPTNeoPolicy)
