from deepspeed_tpu.module_inject.replace_policy import (
    HFBertPolicy, HFGPT2Policy, HFGPTNeoPolicy, REPLACE_POLICIES,
    convert_external_model, policy_for)

__all__ = ["HFGPT2Policy", "HFBertPolicy", "HFGPTNeoPolicy",
           "REPLACE_POLICIES",
           "convert_external_model", "policy_for"]
