from deepspeed_tpu.module_inject.replace_policy import (
    HFBertPolicy, HFGPT2Policy, REPLACE_POLICIES, convert_external_model,
    policy_for)

__all__ = ["HFGPT2Policy", "HFBertPolicy", "REPLACE_POLICIES",
           "convert_external_model", "policy_for"]
