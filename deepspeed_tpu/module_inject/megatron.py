"""Megatron-LM layer injection — checkpoint import, MP resharding, revert.

Reference: ``deepspeed/module_inject/replace_policy.py:146``
(MegatronLayerPolicy reads ``attention.query_key_value`` /
``mlp.dense_h_to_4h`` off a live ParallelTransformerLayer) and the Megatron
checkpoint loader ``deepspeed/runtime/state_dict_factory.py:199`` (merge /
split / reshard across MP degrees with special qkv handling). The revert
direction mirrors ``replace_module.py:310`` (restoring the original module
layout).

TPU-native framing: a Megatron-trained GPT is a WEIGHT-LAYOUT away from the
in-tree GPT family — torch ``[out, in]`` Linear kernels transpose to flax
``[in, out]``, the fused qkv keeps its ``[q; k; v]`` column order (version
>= 1; version 0's per-head interleaving is de-interleaved), and LayerNorm
``weight``/``bias`` become ``scale``/``bias``. Per-MP-rank checkpoint
shards merge through the same declarative rules as
``runtime/state_dict_factory`` before conversion; serving at a new MP
degree is then ``init_inference(mp_size=N)`` — GSPMD re-partitions, no
per-rank files needed.
"""

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepspeed_tpu.runtime.state_dict_factory import (_merge_qkv,
                                                      merge_mp_checkpoints)

_STRIP_PREFIXES = ("model.", "module.", "language_model.", "encoder.",
                   "transformer.")


def megatron_mp_rules() -> Tuple[Tuple[str, Optional[Tuple[str, int]]], ...]:
    """MP merge rules over DOTTED Megatron state-dict keys (torch layout:
    Linear weights [out, in]): column-parallel qkv/h_to_4h shard dim 0,
    row-parallel dense/4h_to_h shard dim 1, embeddings shard the vocab."""
    return (
        (r"query_key_value\.weight$", ("qkv", 0)),
        (r"query_key_value\.bias$", ("qkv", 0)),
        (r"dense_h_to_4h\.weight$", ("cat", 0)),
        (r"dense_h_to_4h\.bias$", ("cat", 0)),
        (r"(attention|self_attention)\.dense\.weight$", ("cat", 1)),
        (r"dense_4h_to_h\.weight$", ("cat", 1)),
        (r"word_embeddings\.weight$", ("cat", 0)),
        (r".*", None),
    )


def normalize_megatron_keys(sd: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Strip wrapper prefixes (``model.``/``language_model.``/...) so layer
    keys start at ``layers.N`` / ``embedding`` / ``final_layernorm``."""
    out = {}
    for k, v in sd.items():
        changed = True
        while changed:
            changed = False
            for p in _STRIP_PREFIXES:
                if k.startswith(p):
                    k = k[len(p):]
                    changed = True
        out[k] = np.asarray(v)
    return out


def _deinterleave_qkv_v0(w: np.ndarray, num_heads: int) -> np.ndarray:
    """Megatron version-0 checkpoints store qkv rows per-head interleaved
    ([h0q, h0k, h0v, h1q, ...]); reorder to the global [q; k; v] layout."""
    three_d = w.shape[0]
    hd = three_d // (3 * num_heads)
    rest = w.shape[1:]
    return (w.reshape(num_heads, 3, hd, *rest)
            .transpose(1, 0, 2, *range(3, 3 + len(rest)))
            .reshape(three_d, *rest))


def _interleave_qkv_v0(w: np.ndarray, num_heads: int) -> np.ndarray:
    three_d = w.shape[0]
    hd = three_d // (3 * num_heads)
    rest = w.shape[1:]
    return (w.reshape(3, num_heads, hd, *rest)
            .transpose(1, 0, 2, *range(3, 3 + len(rest)))
            .reshape(three_d, *rest))


class MegatronLayerPolicy:
    """Megatron-GPT state dict → in-tree GPT family (the reference policy's
    weight extraction, applied to checkpoints instead of live modules)."""

    model_type = "megatron"
    version = 1    # >=1: [q;k;v] fused rows; 0: per-head interleaved

    @staticmethod
    def applies(model) -> bool:
        # Megatron models arrive as checkpoints, not flax modules — the
        # entry point is convert_megatron_checkpoint / load_megatron.
        return False

    @staticmethod
    def convert(sd: Dict[str, np.ndarray], num_heads: int,
                max_seq_len: Optional[int] = None, version: int = 1,
                layer_norm_epsilon: float = 1e-5, dtype: Any = None):
        """One (merged) Megatron state dict → (GPT module, params)."""
        from deepspeed_tpu.models.gpt import GPT, GPTConfig

        sd = normalize_megatron_keys(sd)
        wte = sd["embedding.word_embeddings.weight"]
        wpe = sd["embedding.position_embeddings.weight"]
        layer_ids = sorted({int(m.group(1)) for k in sd
                            for m in [re.match(r"layers\.(\d+)\.", k)] if m})
        if layer_ids != list(range(len(layer_ids))):
            raise ValueError(f"non-contiguous Megatron layers {layer_ids}")
        vocab, d = wte.shape
        kw = {} if dtype is None else {"dtype": dtype}
        cfg = GPTConfig(vocab_size=int(vocab),
                        max_seq_len=int(max_seq_len or wpe.shape[0]),
                        hidden_size=int(d), num_layers=len(layer_ids),
                        num_heads=int(num_heads), dropout_rate=0.0,
                        layer_norm_epsilon=float(layer_norm_epsilon),
                        tie_embeddings=True, **kw)

        def ln(prefix):
            return {"scale": sd[prefix + ".weight"],
                    "bias": sd[prefix + ".bias"]}

        params: Dict[str, Any] = {
            "wte": wte, "wpe": wpe, "ln_f": ln("final_layernorm")}
        for i in layer_ids:
            p = f"layers.{i}."
            attn = ("self_attention" if p + "self_attention.dense.weight"
                    in sd else "attention")
            qkv_w = sd[p + f"{attn}.query_key_value.weight"]
            qkv_b = sd[p + f"{attn}.query_key_value.bias"]
            if version == 0:
                qkv_w = _deinterleave_qkv_v0(qkv_w, num_heads)
                qkv_b = _deinterleave_qkv_v0(qkv_b, num_heads)
            params[f"h_{i}"] = {
                "ln_1": ln(p + "input_layernorm"),
                "ln_2": ln(p + "post_attention_layernorm"),
                "c_attn": {"kernel": qkv_w.T, "bias": qkv_b},
                "c_proj": {"kernel": sd[p + f"{attn}.dense.weight"].T,
                           "bias": sd[p + f"{attn}.dense.bias"]},
                "c_fc": {"kernel": sd[p + "mlp.dense_h_to_4h.weight"].T,
                         "bias": sd[p + "mlp.dense_h_to_4h.bias"]},
                "mlp_proj": {"kernel": sd[p + "mlp.dense_4h_to_h.weight"].T,
                             "bias": sd[p + "mlp.dense_4h_to_h.bias"]},
            }
        return GPT(cfg), params

    @staticmethod
    def revert(params: Dict[str, Any], num_heads: int,
               version: int = 1) -> Dict[str, np.ndarray]:
        """In-tree GPT params → Megatron state-dict layout (the reference's
        revert direction, replace_module.py:310) — exact inverse of
        ``convert``, so round-trips are bit-equal."""
        sd: Dict[str, np.ndarray] = {
            "embedding.word_embeddings.weight": np.asarray(params["wte"]),
            "embedding.position_embeddings.weight":
                np.asarray(params["wpe"]),
            "final_layernorm.weight": np.asarray(params["ln_f"]["scale"]),
            "final_layernorm.bias": np.asarray(params["ln_f"]["bias"]),
        }
        attn = "self_attention" if version >= 1 else "attention"
        i = 0
        while f"h_{i}" in params:
            h = params[f"h_{i}"]
            p = f"layers.{i}."
            qkv_w = np.asarray(h["c_attn"]["kernel"]).T
            qkv_b = np.asarray(h["c_attn"]["bias"])
            if version == 0:
                qkv_w = _interleave_qkv_v0(qkv_w, num_heads)
                qkv_b = _interleave_qkv_v0(qkv_b, num_heads)
            sd[p + "input_layernorm.weight"] = np.asarray(h["ln_1"]["scale"])
            sd[p + "input_layernorm.bias"] = np.asarray(h["ln_1"]["bias"])
            sd[p + "post_attention_layernorm.weight"] = \
                np.asarray(h["ln_2"]["scale"])
            sd[p + "post_attention_layernorm.bias"] = \
                np.asarray(h["ln_2"]["bias"])
            sd[p + f"{attn}.query_key_value.weight"] = qkv_w
            sd[p + f"{attn}.query_key_value.bias"] = qkv_b
            sd[p + f"{attn}.dense.weight"] = \
                np.asarray(h["c_proj"]["kernel"]).T
            sd[p + f"{attn}.dense.bias"] = np.asarray(h["c_proj"]["bias"])
            sd[p + "mlp.dense_h_to_4h.weight"] = \
                np.asarray(h["c_fc"]["kernel"]).T
            sd[p + "mlp.dense_h_to_4h.bias"] = np.asarray(h["c_fc"]["bias"])
            sd[p + "mlp.dense_4h_to_h.weight"] = \
                np.asarray(h["mlp_proj"]["kernel"]).T
            sd[p + "mlp.dense_4h_to_h.bias"] = \
                np.asarray(h["mlp_proj"]["bias"])
            i += 1
        return sd


def convert_megatron_checkpoint(shards: Sequence[Dict[str, Any]],
                                num_heads: int,
                                max_seq_len: Optional[int] = None,
                                version: int = 1, dtype: Any = None):
    """Per-MP-rank Megatron state dicts (rank order; a single dict is
    degree 1) → (GPT module, merged params). The reference needs its
    megatron sd loader to target a new MP degree file-by-file
    (state_dict_factory.py:199); here the merged tree serves ANY degree —
    hand it to ``init_inference(..., mp_size=N)`` and GSPMD re-partitions.
    """
    if isinstance(shards, dict):
        shards = [shards]
    shards = [normalize_megatron_keys(s) for s in shards]
    if version == 0:
        # De-interleave per rank BEFORE merging: each rank's rows are
        # per-head interleaved within its own head slice.
        heads_per_rank = num_heads // len(shards)
        fixed = []
        for s in shards:
            t = dict(s)
            for k in t:
                if k.endswith("query_key_value.weight") or \
                        k.endswith("query_key_value.bias"):
                    t[k] = _deinterleave_qkv_v0(t[k], heads_per_rank)
            fixed.append(t)
        shards = fixed
    merged = _merge_dotted(shards)
    return MegatronLayerPolicy.convert(merged, num_heads,
                                       max_seq_len=max_seq_len, version=1,
                                       dtype=dtype)


def split_megatron_state_dict(sd: Dict[str, Any], mp: int
                              ) -> List[Dict[str, np.ndarray]]:
    """Split a full (version >= 1) Megatron state dict into ``mp`` per-rank
    shards — the reference's ``split_state_dict`` direction
    (state_dict_factory.py), used to emit Megatron-consumable checkpoints
    and to build synthetic MP fixtures."""
    from deepspeed_tpu.runtime.state_dict_factory import _split_qkv

    sd = normalize_megatron_keys(sd)
    if mp == 1:
        return [dict(sd)]
    rules = megatron_mp_rules()
    out: List[Dict[str, np.ndarray]] = [{} for _ in range(mp)]
    for key, leaf in sd.items():
        action = None
        for pat, a in rules:
            if re.search(pat, key):
                action = a
                break
        if action is None:
            for r in range(mp):
                out[r][key] = leaf
            continue
        kind, axis = action
        if leaf.shape[axis] % ((3 * mp) if kind == "qkv" else mp):
            raise ValueError(f"'{key}' dim {axis} ({leaf.shape[axis]}) not "
                             f"divisible for mp={mp}")
        pieces = (_split_qkv(leaf, mp, axis) if kind == "qkv"
                  else np.split(leaf, mp, axis=axis))
        for r in range(mp):
            out[r][key] = pieces[r]
    return out


def _merge_dotted(shards: Sequence[Dict[str, np.ndarray]]
                  ) -> Dict[str, np.ndarray]:
    """merge_mp_checkpoints over flat dotted-key dicts."""
    if len(shards) == 1:
        return dict(shards[0])
    rules = megatron_mp_rules()
    out = {}
    for key in shards[0]:
        pieces = [np.asarray(s[key]) for s in shards]
        action = None
        for pat, a in rules:
            if re.search(pat, key):
                action = a
                break
        if action is None:
            out[key] = pieces[0]
        elif action[0] == "cat":
            out[key] = np.concatenate(pieces, axis=action[1])
        elif action[0] == "qkv":
            out[key] = _merge_qkv(pieces, action[1])
    return out


__all__ = ["MegatronLayerPolicy", "convert_megatron_checkpoint",
           "megatron_mp_rules", "normalize_megatron_keys",
           "split_megatron_state_dict"]
