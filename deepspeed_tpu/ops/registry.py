"""Op registry — discoverable, named op implementations.

Reference: ``op_builder/`` + ``deepspeed/ops/__init__.py``: every CUDA
extension registers a builder that reports availability/compatibility and
is listed by ``ds_report``. On TPU there is nothing to compile at install
time, but the same discoverability contract matters: which attention/
optimizer/quantizer implementations exist, which are Pallas-accelerated,
and whether the current backend can run them.
"""

from dataclasses import dataclass
from typing import Callable, Dict, Optional

import jax


@dataclass(frozen=True)
class OpSpec:
    name: str
    kind: str                      # attention | optimizer | quantizer | ...
    loader: Callable               # () -> the op callable/class
    pallas: bool = False           # uses a hand-written Pallas kernel
    requires_tpu: bool = False
    available_fn: Optional[Callable] = None   # env-dependent availability

    def available(self) -> bool:
        if self.available_fn is not None:
            try:
                return bool(self.available_fn())
            except Exception:
                return False
        if self.requires_tpu:
            try:
                return jax.devices()[0].platform == "tpu"
            except Exception:
                return False
        return True

    def load(self):
        return self.loader()


_REGISTRY: Dict[str, OpSpec] = {}


def register_op(name: str, kind: str, loader: Callable, *,
                pallas: bool = False, requires_tpu: bool = False,
                available_fn: Optional[Callable] = None) -> None:
    if name in _REGISTRY:
        raise ValueError(f"op '{name}' already registered")
    _REGISTRY[name] = OpSpec(name, kind, loader, pallas, requires_tpu,
                             available_fn)


def get_op(name: str):
    if name not in _REGISTRY:
        raise KeyError(f"unknown op '{name}'; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name].load()


def list_ops(kind: Optional[str] = None) -> Dict[str, OpSpec]:
    return {n: s for n, s in _REGISTRY.items()
            if kind is None or s.kind == kind}


def _builtin(name, kind, path, attr, **kw):
    def loader():
        import importlib
        return getattr(importlib.import_module(path), attr)

    register_op(name, kind, loader, **kw)


# ---------------------------------------------------------------------------
# Built-in ops (the in-tree analogue of op_builder's ALL_OPS table)
# ---------------------------------------------------------------------------
_builtin("xla_attention", "attention",
         "deepspeed_tpu.ops.transformer.attention", "xla_attention")
_builtin("flash_attention", "attention",
         "deepspeed_tpu.ops.transformer.flash_attention", "flash_attention",
         pallas=True, requires_tpu=True)
_builtin("sparse_attention", "attention",
         "deepspeed_tpu.ops.sparse_attention", "sparse_attention")
_builtin("fused_adam", "optimizer",
         "deepspeed_tpu.ops.adam.fused_adam", "FusedAdam")
_builtin("fused_adamw", "optimizer",
         "deepspeed_tpu.ops.adam.fused_adam", "FusedAdamW")
_builtin("cpu_adam", "optimizer",
         "deepspeed_tpu.ops.adam.fused_adam", "HostOffloadAdam")
_builtin("fused_lamb", "optimizer",
         "deepspeed_tpu.ops.lamb.fused_lamb", "FusedLamb")
_builtin("onebit_adam", "optimizer",
         "deepspeed_tpu.ops.onebit.adam", "OneBitAdam")
_builtin("onebit_lamb", "optimizer",
         "deepspeed_tpu.ops.onebit.lamb", "OneBitLamb")
_builtin("transformer_layer", "transformer",
         "deepspeed_tpu.ops.transformer", "DeepSpeedTransformerLayer")
_builtin("moq_quantizer", "quantizer",
         "deepspeed_tpu.ops.quantizer", "MoQQuantizer")
_builtin("weight_quantizer", "quantizer",
         "deepspeed_tpu.inference.quantization", "quantize_params")


def _aio_loader():
    from deepspeed_tpu.ops.aio_native import load_aio
    mod = load_aio()
    if mod is None:
        raise RuntimeError("native aio unavailable (no C++ toolchain); the "
                           "swap tier uses the numpy fallback")
    return mod


def _aio_available():
    from deepspeed_tpu.ops.aio_native import load_aio
    return load_aio() is not None


register_op("async_io", "io", _aio_loader, available_fn=_aio_available)
