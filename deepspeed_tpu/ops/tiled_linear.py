"""TiledLinear — piecewise-gathered huge layers under ZeRO-3.

Reference: ``deepspeed/runtime/zero/tiling.py:1-294`` (``TiledLinear``
splits one enormous ``nn.Linear`` into an in_splits x out_splits grid of
sub-Linears so ZeRO-3 fetches/releases tile-by-tile and the full weight is
never resident at once).

TPU-native design: the weight is stored ``[T, D, O/T]`` (leading tile dim)
and the forward is a ``lax.scan`` over tiles. Under the stage-3 placement
policy the weight leaf is sharded over ``data`` on a non-leading dim, so
each scan iteration's slice gathers ONLY that tile — XLA's liveness then
frees tile i before tile i+1 is gathered, bounding the transient gathered
bytes at ``numel/T`` instead of ``numel`` (with ``remat`` the backward
re-gathers tile-by-tile too). That is the fetch/release economy of the
reference's tiled sub-Linears, scheduled by the compiler instead of module
hooks. Peak-memory evidence: tests/test_memory.py::TestTiledLinear.
"""

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


class TiledLinear(nn.Module):
    """Drop-in Dense whose output dim is computed in ``out_splits`` tiles.

    y = concat_t(x @ W_t) + b — numerically identical to ``nn.Dense``
    (per-column results are independent), parity-tested in
    tests/test_memory.py.
    """

    features: int
    out_splits: int = 4
    use_bias: bool = True
    dtype: Any = None
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()
    remat_tiles: bool = True

    @nn.compact
    def __call__(self, x):
        if self.features % self.out_splits:
            raise ValueError(f"features {self.features} not divisible by "
                             f"out_splits {self.out_splits}")
        d = x.shape[-1]
        tile = self.features // self.out_splits

        def tiled_init(key, shape, dtype=jnp.float32):
            # Same distribution as one [d, features] kernel, drawn per tile.
            keys = jax.random.split(key, self.out_splits)
            return jnp.stack([self.kernel_init(k, (d, tile), dtype)
                              for k in keys])

        w = self.param("kernel", tiled_init, (self.out_splits, d, tile))
        dt = self.dtype if self.dtype is not None else x.dtype

        def one_tile(_, wt):
            return None, jnp.einsum(
                "...d,dt->...t", x, wt.astype(dt))

        body = jax.checkpoint(one_tile) if self.remat_tiles else one_tile
        _, tiles = jax.lax.scan(body, None, w)   # [T, ..., tile]
        y = jnp.moveaxis(tiles, 0, -2).reshape(*x.shape[:-1], self.features)
        if self.use_bias:
            b = self.param("bias", self.bias_init, (self.features,))
            y = y + b.astype(dt)
        return y


def tiled_linear_spec(data_axis: str = "data") -> Any:
    """Stage-3 PartitionSpec for the [T, D, tile] kernel: shard the D dim
    (never the leading tile dim — scan slices must stay shard-local)."""
    from jax.sharding import PartitionSpec
    return PartitionSpec(None, data_axis, None)
