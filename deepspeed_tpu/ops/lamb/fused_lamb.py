"""Fused LAMB.

Parity with the reference ``FusedLamb`` (``deepspeed/ops/lamb/fused_lamb.py:12``
over ``csrc/lamb/fused_lamb_cuda_kernel.cu``): layer-wise adaptive moments for
large-batch training (BERT-large pretraining in the baseline ladder).

Per-tensor trust ratio = ||w|| / ||update||, clamped by max_coeff/min_coeff
like the reference kernel's ``lamb_coeff`` handling.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp


class LambState(NamedTuple):
    step: jax.Array
    exp_avg: Any
    exp_avg_sq: Any


class FusedLamb:
    def __init__(self,
                 lr: float = 1e-3,
                 betas=(0.9, 0.999),
                 eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 bias_correction: bool = True,
                 max_coeff: float = 10.0,
                 min_coeff: float = 0.01):
        self.lr = float(lr)
        self.beta1, self.beta2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.bias_correction = bool(bias_correction)
        self.max_coeff = float(max_coeff)
        self.min_coeff = float(min_coeff)

    def init(self, params: Any) -> LambState:
        z = lambda p: jnp.zeros(p.shape, jnp.float32)  # noqa: E731
        return LambState(step=jnp.zeros((), jnp.int32),
                         exp_avg=jax.tree_util.tree_map(z, params),
                         exp_avg_sq=jax.tree_util.tree_map(z, params))

    def update(self, grads: Any, state: LambState, params: Any,
               lr: Optional[jax.Array] = None):
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.beta1, self.beta2
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = jnp.float32(1.0)
            bc2 = jnp.float32(1.0)

        def leaf(p, g, m, v):
            g = g.astype(jnp.float32)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * jnp.square(g)
            update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if self.weight_decay != 0.0:
                update = update + self.weight_decay * p
            w_norm = jnp.linalg.norm(p.reshape(-1))
            u_norm = jnp.linalg.norm(update.reshape(-1))
            trust = jnp.where((w_norm > 0) & (u_norm > 0),
                              w_norm / u_norm, jnp.float32(1.0))
            trust = jnp.clip(trust, self.min_coeff, self.max_coeff)
            return p - lr * trust * update, m, v

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        outs = [leaf(p, g, m, v) for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
        return (jax.tree_util.tree_unflatten(treedef, [o[0] for o in outs]),
                LambState(step=step,
                          exp_avg=jax.tree_util.tree_unflatten(treedef, [o[1] for o in outs]),
                          exp_avg_sq=jax.tree_util.tree_unflatten(treedef, [o[2] for o in outs])))
