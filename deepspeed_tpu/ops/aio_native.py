"""Loader for the native aio extension (``csrc/aio/aio.cpp``).

Compiles the C++ module once into a per-user cache directory (the
op_builder JIT-build model of the reference: ``op_builder/builder.py``
``jit_load``) and imports it. Falls back to None when no toolchain is
present — callers keep a pure-numpy path.
"""

import importlib.util
import os
import subprocess
import sys
import sysconfig
from typing import Optional

_CACHE: dict = {}


def _src_path() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, "csrc", "aio", "aio.cpp")


def _build_dir() -> str:
    d = os.environ.get("DSTPU_BUILD_DIR",
                       os.path.join(os.path.expanduser("~"), ".cache",
                                    "deepspeed_tpu", "build"))
    os.makedirs(d, exist_ok=True)
    return d


def load_aio(verbose: bool = False) -> Optional[object]:
    """Import the compiled ``_dstpu_aio`` module, building it on first use.
    Returns None (and remembers it) when building is impossible."""
    if "aio" in _CACHE:
        return _CACHE["aio"]
    so_path = os.path.join(
        _build_dir(),
        f"_dstpu_aio.{sysconfig.get_config_var('SOABI')}.so")
    src = _src_path()
    try:
        if (not os.path.exists(so_path)
                or os.path.getmtime(so_path) < os.path.getmtime(src)):
            include = sysconfig.get_paths()["include"]
            # Build to a per-pid temp and rename atomically: N launcher
            # workers may race on a fresh cache, and dlopen of a
            # half-written .so poisons the process (the reference
            # op_builder holds a build lock for the same reason).
            tmp = f"{so_path}.{os.getpid()}.tmp"
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                   f"-I{include}", src, "-o", tmp]
            subprocess.run(cmd, check=True, capture_output=not verbose)
            os.replace(tmp, so_path)
        spec = importlib.util.spec_from_file_location("_dstpu_aio", so_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _CACHE["aio"] = mod
    except Exception as e:  # no g++ / headers — numpy fallback
        if verbose:
            print(f"native aio unavailable: {e}", file=sys.stderr)
        _CACHE["aio"] = None
    return _CACHE["aio"]
