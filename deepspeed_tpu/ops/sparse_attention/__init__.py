"""Block-sparse attention: sparsity layouts + executors."""

from deepspeed_tpu.ops.sparse_attention.sparse_attention import (
    SparseSelfAttention, layout_kv_indices, layout_to_dense_mask,
    pad_to_block_size, sparse_attention)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparsityConfig, VariableSparsityConfig,
    causal_blockmask)
from deepspeed_tpu.ops.sparse_attention.utils import (
    SPARSE_MODES, SparseAttentionUtils, get_sparse_self_attention,
    sparsity_config_from_dict)

__all__ = [
    "SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
    "VariableSparsityConfig", "BigBirdSparsityConfig",
    "BSLongformerSparsityConfig", "causal_blockmask", "sparse_attention",
    "SparseSelfAttention", "layout_to_dense_mask", "layout_kv_indices",
    "pad_to_block_size", "SPARSE_MODES", "SparseAttentionUtils",
    "get_sparse_self_attention", "sparsity_config_from_dict",
]
