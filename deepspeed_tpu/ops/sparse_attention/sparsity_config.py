"""Block-sparse attention layouts (reference
``deepspeed/ops/sparse_attention/sparsity_config.py:9-544``).

A *layout* is an int32 tensor ``[num_heads, B, B]`` (B = seq_len/block) where
``layout[h, qi, ki] == 1`` means q-block ``qi`` attends kv-block ``ki`` for
head ``h``. The config classes reproduce the reference's families —
Dense, Fixed, Variable, BigBird, BSLongformer — as pure layout math
(numpy; no kernels here). The Pallas/jnp executors consume the layout.
"""

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: common fields + layout scaffolding (reference :9)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    @property
    def num_layout_heads(self) -> int:
        return self.num_heads if self.different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block:
            raise ValueError(f"seq_len {seq_len} not divisible by block "
                             f"{self.block}")
        b = seq_len // self.block
        return np.zeros((self.num_heads, b, b), np.int32)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attend all blocks (reference :63) — the parity baseline."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed local windows + fixed global blocks (reference :94).

    Each q-block attends every block in its own local window of
    ``num_local_blocks``; the last ``num_global_blocks`` of each window act
    as global: every later block attends them (unidirectional), and with
    bidirectional/horizontal attention those rows also attend everything.
    """

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % max(num_global_blocks, 1):
            raise ValueError("num_local_blocks must be divisible by "
                             "num_global_blocks")
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError(f"invalid attention '{attention}'")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires "
                             "bidirectional attention")
        if (num_different_global_patterns > 1 and
                not different_layout_per_head):
            raise ValueError("different global patterns require "
                             "different_layout_per_head")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        b = layout.shape[1]
        uni = self.attention == "unidirectional"
        for h in range(self.num_layout_heads):
            # local windows
            for start in range(0, b, self.num_local_blocks):
                end = min(start + self.num_local_blocks, b)
                for qi in range(start, end):
                    hi = qi + 1 if uni else end
                    layout[h, qi, start:hi] = 1
            # global columns: pattern index rotates across heads
            pattern = h % self.num_different_global_patterns
            # the global blocks are the LAST num_global_blocks of each
            # window, offset by the head's pattern
            first_global = (self.num_local_blocks - (1 + pattern) *
                            self.num_global_blocks)
            for wstart in range(0, b, self.num_local_blocks):
                g0 = wstart + max(first_global, 0)
                g1 = min(g0 + self.num_global_blocks, b)
                for ki in range(g0, g1):
                    if uni:
                        layout[h, ki:, ki] = 1   # later queries attend it
                    else:
                        layout[h, :, ki] = 1
                    if self.horizontal_global_attention:
                        layout[h, ki, :] = 1
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local window sizes + user-chosen global blocks + random
    blocks (reference :243)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 rng_seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError(f"invalid attention '{attention}'")
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None and \
                len(global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global_block_end_indices length mismatch")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.rng = np.random.default_rng(rng_seed)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        b = layout.shape[1]
        uni = self.attention == "unidirectional"
        for h in range(self.num_layout_heads):
            # variable local windows: cycle through the size list
            start = 0
            i = 0
            while start < b:
                size = self.local_window_blocks[
                    min(i, len(self.local_window_blocks) - 1)]
                end = min(start + size, b)
                for qi in range(start, end):
                    hi = qi + 1 if uni else end
                    layout[h, qi, start:hi] = 1
                start, i = end, i + 1
            # globals
            for gi, g in enumerate(self.global_block_indices):
                if self.global_block_end_indices is None:
                    cols = [g] if g < b else []
                else:
                    cols = range(g, min(self.global_block_end_indices[gi], b))
                for ki in cols:
                    if uni:
                        layout[h, ki:, ki] = 1
                    else:
                        layout[h, :, ki] = 1
                    if self.horizontal_global_attention:
                        layout[h, ki, :] = 1
            # random blocks
            for qi in range(b):
                if self.num_random_blocks:
                    cols = self.rng.choice(
                        qi + 1 if uni else b,
                        size=min(self.num_random_blocks,
                                 qi + 1 if uni else b),
                        replace=False)
                    layout[h, qi, cols] = 1
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding-window + global-edge blocks (reference :421)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 rng_seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError(f"invalid attention '{attention}'")
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.rng = np.random.default_rng(rng_seed)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        b = layout.shape[1]
        uni = self.attention == "unidirectional"
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for qi in range(b):
                lo = max(0, qi - w)
                hi = qi + 1 if uni else min(b, qi + w + 1)
                layout[h, qi, lo:hi] = 1
            g = min(self.num_global_blocks, b)
            layout[h, :, :g] = 1              # everyone attends first blocks
            if not uni:
                layout[h, :g, :] = 1          # first blocks attend everyone
                layout[h, :, b - g:] = 1      # and last blocks are global
                layout[h, b - g:, :] = 1
            for qi in range(b):
                pool = qi + 1 if uni else b
                k = min(self.num_random_blocks, pool)
                cols = self.rng.choice(pool, size=k, replace=False)
                layout[h, qi, cols] = 1
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + selected global blocks (reference :544)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = (global_block_indices
                                     if global_block_indices is not None
                                     else [0])
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        b = layout.shape[1]
        uni = self.attention == "unidirectional"
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_layout_heads):
            for qi in range(b):
                lo = max(0, qi - w)
                hi = qi + 1 if uni else min(b, qi + w + 1)
                layout[h, qi, lo:hi] = 1
            for gi, g in enumerate(self.global_block_indices):
                if self.global_block_end_indices is None:
                    cols = [g] if g < b else []
                else:
                    cols = range(g, min(self.global_block_end_indices[gi], b))
                for ki in cols:
                    layout[h, :, ki] = 1
                    if not uni:
                        layout[h, ki, :] = 1
        return self.check_and_propagate_first_head_layout(layout)


def causal_blockmask(layout: np.ndarray) -> np.ndarray:
    """Intersect a layout with block-level causality (strictly-above-diagonal
    blocks dropped; the diagonal keeps intra-block causal masking for the
    executor)."""
    b = layout.shape[1]
    tril = np.tril(np.ones((b, b), np.int32))
    return layout * tril[None]
