"""Block-sparse attention executors.

The reference executes block-sparse attention with Triton SDD/DSD/DDS
matmuls + block softmax (``ops/sparse_attention/matmul.py``,
``softmax.py``); here the same layouts run through:

- ``impl="xla"`` — dense attention under the layout-expanded mask (the
  numerics oracle, and perfectly fine for modest sequence lengths);
- ``impl="pallas"`` — a flash-style Pallas kernel that, per (head,
  q-block), loops ONLY over that row's active kv-blocks. The active-index
  list is precomputed on the host from the (static) layout, so compute and
  HBM traffic scale with layout density — the O(s·√s) long-sequence story
  of the reference (docs/index.md:142), TPU-style. Training goes through a
  custom VJP whose dq / dk+dv kernels walk the layout (and its transpose)
  exactly like the reference's Triton SDD/DSD/DDS backward modes
  (matmul.py:749, trsrc/softmax_bwd.tr) — peak memory stays density-
  scaled in backward too.

Layouts come from ``sparsity_config.py`` as [H, B, B] int32.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.flash_attention import _vmem_params

NEG_INF = -1e30


def layout_to_dense_mask(layout: np.ndarray, block: int) -> np.ndarray:
    """[H, B, B] block layout -> [H, S, S] bool element mask."""
    return np.kron(np.asarray(layout), np.ones((block, block))).astype(bool)


def layout_kv_indices(layout: np.ndarray):
    """Per (head, q-block) active kv-block ids, padded with -1:
    -> int32 [H, B, max_active]."""
    layout = np.asarray(layout)
    h, b, _ = layout.shape
    max_active = int(layout.sum(-1).max())
    idx = np.full((h, b, max_active), -1, np.int32)
    for hi in range(h):
        for qi in range(b):
            cols = np.nonzero(layout[hi, qi])[0]
            idx[hi, qi, :len(cols)] = cols
    return idx, max_active


def layout_q_indices(layout: np.ndarray):
    """Transpose layout: per (head, kv-block) active Q-block ids, padded
    with -1 — the dk/dv backward iteration order (the reference runs its
    Triton matmuls with a transposed layout for the same purpose,
    ops/sparse_attention/matmul.py:749 ``mode`` dsd/dds)."""
    layout = np.asarray(layout)
    return layout_kv_indices(layout.transpose(0, 2, 1))


def _xla_sparse(q, k, v, layout, block, causal, scale, key_mask=None):
    mask = jnp.asarray(layout_to_dense_mask(layout, block))   # [H, S, S]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    logits = jnp.where(mask[None], logits, NEG_INF)
    if key_mask is not None:
        # [B, S] key-padding mask (the reference's key_padding_mask,
        # sparse_self_attention.py:58) — masked keys drop out of every row.
        logits = jnp.where(key_mask[:, None, None, :].astype(jnp.bool_),
                           logits, NEG_INF)
    if causal:
        s = q.shape[1]
        cm = jnp.tril(jnp.ones((s, s), jnp.bool_))
        logits = jnp.where(cm[None, None], logits, NEG_INF)
    # guard fully-masked rows (no allowed keys) against NaN
    rowmax = jnp.max(logits, axis=-1, keepdims=True)
    probs = jnp.where(rowmax > NEG_INF / 2,
                      jax.nn.softmax(logits, axis=-1), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


LANES = 128  # per-row lse/delta broadcast across lanes for (8,128) tiling


def _sparse_kernel(kv_idx_ref, cnt_ref, *refs, causal: bool, scale: float,
                   block: int, num_heads: int, has_mask: bool):
    """grid: (B*H, q_blocks). Refs: q [1, block, D]; k/v [1, S, D];
    optional key-padding mask [1, 1, S] (1 = keep, reference
    sparse_self_attention.py:58 key_padding_mask); kv_idx [H, qb, max]
    + per-row counts [H, qb] in SMEM (scalar-prefetched — SMEM supports
    the arbitrary dynamic indexing a layout lookup needs). The loop runs
    this ROW's actual active count (dynamic trip count), not the global
    max — rows touched by a few global columns don't pay for the densest
    row. Saves per-row logsumexp for the backward recomputation."""
    if has_mask:
        q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        mask_ref = None
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    h = jax.lax.rem(bh, num_heads)
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale

    def body(j, carry):
        m_prev, l_prev, acc = carry
        ki = kv_idx_ref[h, qi, j]
        kblk = k_ref[0, pl.ds(ki * block, block), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if has_mask:
            mblk = mask_ref[0, 0, pl.ds(ki * block, block)]
            s = jnp.where(mblk[None, :] > 0, s, NEG_INF)
        if causal:
            q_pos = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = ki * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        # rows that have seen nothing yet keep NEG_INF; exp underflows to 0
        p = jnp.exp(s - jnp.maximum(m_new, NEG_INF / 2)[:, None])
        alpha = jnp.exp(m_prev - jnp.maximum(m_new, NEG_INF / 2))
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, alpha)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jnp.dot(
            p, vblk, preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    init = (jnp.full((block,), NEG_INF, jnp.float32),
            jnp.zeros((block,), jnp.float32),
            jnp.zeros((block, d), jnp.float32))
    m, l, acc = jax.lax.fori_loop(0, cnt_ref[h, qi], body, init)
    out = jnp.where((l > 0)[:, None], acc / jnp.maximum(l, 1e-30)[:, None], 0.0)
    o_ref[0] = out.astype(o_ref.dtype)
    # Fully-masked rows keep lse ~ NEG_INF; the backward guards on it.
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
    lse_ref[0] = jnp.broadcast_to(lse[:, None], (block, LANES))


def _sparse_bwd_dq_kernel(kv_idx_ref, cnt_ref, *refs, causal: bool,
                          scale: float, block: int, num_heads: int,
                          has_mask: bool):
    """dq over (B*H, q_blocks): loop this row's active kv-blocks, recompute
    p from the saved lse, ds = p (dp - delta), dq += ds @ k. Mirrors the
    flash _bwd_dq_kernel but walks the layout's active list."""
    if has_mask:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref, dq_ref \
            = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref = refs
        mask_ref = None
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    h = jax.lax.rem(bh, num_heads)
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = jnp.maximum(lse_ref[0, :, 0], NEG_INF / 2)   # guard empty rows
    delta = delta_ref[0, :, 0]

    def body(j, dq):
        ki = kv_idx_ref[h, qi, j]
        kblk = k_ref[0, pl.ds(ki * block, block), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if has_mask:
            mblk = mask_ref[0, 0, pl.ds(ki * block, block)]
            s = jnp.where(mblk[None, :] > 0, s, NEG_INF)
        if causal:
            q_pos = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = ki * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jnp.dot(ds, kblk, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, cnt_ref[h, qi], body,
                           jnp.zeros((block, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _sparse_bwd_dkv_kernel(q_idx_ref, cnt_ref, *refs, causal: bool,
                           scale: float, block: int, num_heads: int,
                           has_mask: bool):
    """dk/dv over (B*H, kv_blocks): loop this column's active q-blocks via
    the TRANSPOSE layout (layout_q_indices); dv += pᵀ @ dO,
    dk += dsᵀ @ q. The dynamic per-COLUMN trip count matters most here:
    global columns are touched by every q-block while window columns see
    ~3 — padding every column to the densest one made the backward
    effectively dense (measured 2x dense flash at seq 16k before)."""
    if has_mask:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref, \
            dk_ref, dv_ref = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref \
            = refs
        mask_ref = None
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    h = jax.lax.rem(bh, num_heads)
    d = k_ref.shape[2]
    kblk = k_ref[0].astype(jnp.float32)
    vblk = v_ref[0].astype(jnp.float32)
    kmask = mask_ref[0, 0] if has_mask else None   # [block], this kv block

    def body(j, carry):
        dk, dv = carry
        qi = q_idx_ref[h, ki, j]
        q = q_ref[0, pl.ds(qi * block, block), :].astype(
            jnp.float32) * scale
        do = do_ref[0, pl.ds(qi * block, block), :].astype(jnp.float32)
        lse = jnp.maximum(lse_ref[0, pl.ds(qi * block, block), 0],
                          NEG_INF / 2)
        delta = delta_ref[0, pl.ds(qi * block, block), 0]
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if has_mask:
            s = jnp.where(kmask[None, :] > 0, s, NEG_INF)
        if causal:
            q_pos = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = ki * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                        # [bq, bk]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        0, cnt_ref[h, ki], body,
        (jnp.zeros((block, d), jnp.float32),
         jnp.zeros((block, d), jnp.float32)))
    # q rides pre-scaled into ds, so dk = dsᵀ @ (q·scale) already carries
    # the softmax scale — no extra factor (unlike dq, whose ds @ k product
    # has no scale in it).
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _sparse_forward(qf, kf, vf, kv_mask, kv_idx, kv_cnt, block, causal,
                    scale, num_heads, interpret):
    bh, s, d = qf.shape
    qb = s // block
    has_mask = kv_mask is not None
    esz = qf.dtype.itemsize
    kernel = functools.partial(_sparse_kernel, causal=causal, scale=scale,
                               block=block, num_heads=num_heads,
                               has_mask=has_mask)
    in_specs = [
        pl.BlockSpec((1, block, d), lambda b, i, idx, cnt: (b, i, 0)),
        pl.BlockSpec((1, s, d), lambda b, i, idx, cnt: (b, 0, 0)),
        pl.BlockSpec((1, s, d), lambda b, i, idx, cnt: (b, 0, 0)),
    ]
    inputs = [qf, kf, vf]
    if has_mask:
        in_specs.append(pl.BlockSpec(
            (1, 1, s), lambda b, i, idx, cnt: (b // num_heads, 0, 0)))
        inputs.append(kv_mask)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,       # kv_idx + per-row counts ride in SMEM
        grid=(bh, qb),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block, d), lambda b, i, idx, cnt: (b, i, 0)),
            pl.BlockSpec((1, block, LANES),
                         lambda b, i, idx, cnt: (b, i, 0)),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), qf.dtype),
            jax.ShapeDtypeStruct((bh, s, LANES), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_vmem_params(
            2 * s * d * esz + 2 * block * d * esz + block * LANES * 4
            + (4 * s if has_mask else 0)),
    )(kv_idx, kv_cnt, *inputs)
    return out, lse


def _sparse_backward(qf, kf, vf, kv_mask, do, out, lse, kv_idx, kv_cnt,
                     q_idx, q_cnt, block, causal, scale, num_heads,
                     interpret):
    bh, s, d = qf.shape
    qb = s // block
    has_mask = kv_mask is not None
    esz = qf.dtype.itemsize
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (LANES,))

    dq_specs = [
        pl.BlockSpec((1, block, d), lambda b, i, idx, cnt: (b, i, 0)),
        pl.BlockSpec((1, s, d), lambda b, i, idx, cnt: (b, 0, 0)),
        pl.BlockSpec((1, s, d), lambda b, i, idx, cnt: (b, 0, 0)),
        pl.BlockSpec((1, block, d), lambda b, i, idx, cnt: (b, i, 0)),
        pl.BlockSpec((1, block, LANES), lambda b, i, idx, cnt: (b, i, 0)),
        pl.BlockSpec((1, block, LANES), lambda b, i, idx, cnt: (b, i, 0)),
    ]
    dq_inputs = [qf, kf, vf, do, lse, delta]
    if has_mask:
        dq_specs.append(pl.BlockSpec(
            (1, 1, s), lambda b, i, idx, cnt: (b // num_heads, 0, 0)))
        dq_inputs.append(kv_mask)
    dq = pl.pallas_call(
        functools.partial(_sparse_bwd_dq_kernel, causal=causal, scale=scale,
                          block=block, num_heads=num_heads,
                          has_mask=has_mask),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, qb),
            in_specs=dq_specs,
            out_specs=pl.BlockSpec((1, block, d),
                                   lambda b, i, idx, cnt: (b, i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), qf.dtype),
        interpret=interpret,
        compiler_params=_vmem_params(
            2 * s * d * esz + 4 * block * d * esz + 2 * block * LANES * 4
            + (4 * s if has_mask else 0)),
    )(kv_idx, kv_cnt, *dq_inputs)

    dkv_specs = [
        pl.BlockSpec((1, s, d), lambda b, i, idx, cnt: (b, 0, 0)),
        pl.BlockSpec((1, block, d), lambda b, i, idx, cnt: (b, i, 0)),
        pl.BlockSpec((1, block, d), lambda b, i, idx, cnt: (b, i, 0)),
        pl.BlockSpec((1, s, d), lambda b, i, idx, cnt: (b, 0, 0)),
        pl.BlockSpec((1, s, LANES), lambda b, i, idx, cnt: (b, 0, 0)),
        pl.BlockSpec((1, s, LANES), lambda b, i, idx, cnt: (b, 0, 0)),
    ]
    dkv_inputs = [qf, kf, vf, do, lse, delta]
    if has_mask:
        # This kv block's mask slice rides blocked like k/v.
        dkv_specs.append(pl.BlockSpec(
            (1, 1, block), lambda b, i, idx, cnt: (b // num_heads, 0, i)))
        dkv_inputs.append(kv_mask)
    dk, dv = pl.pallas_call(
        functools.partial(_sparse_bwd_dkv_kernel, causal=causal, scale=scale,
                          block=block, num_heads=num_heads,
                          has_mask=has_mask),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, qb),
            in_specs=dkv_specs,
            out_specs=[
                pl.BlockSpec((1, block, d),
                             lambda b, i, idx, cnt: (b, i, 0)),
                pl.BlockSpec((1, block, d),
                             lambda b, i, idx, cnt: (b, i, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), kf.dtype),
            jax.ShapeDtypeStruct((bh, s, d), vf.dtype),
        ],
        interpret=interpret,
        compiler_params=_vmem_params(
            2 * s * d * esz + 2 * s * LANES * 4 + 4 * block * d * esz
            + (4 * s if has_mask else 0)),
    )(q_idx, q_cnt, *dkv_inputs)
    return dq, dk, dv


@functools.lru_cache(maxsize=64)
def _sparse_vjp_fn(layout_key, block, causal, scale, interpret,
                   has_mask=False):
    """Build (and cache) a differentiable [B*H, S, D]-layout sparse
    attention closure for one static layout. The layout rides in the cache
    key as bytes (custom_vjp nondiff args must be hashable). With
    ``has_mask`` the closure takes a [B, 1, S] fp32 key-padding mask as a
    fourth (zero-cotangent) argument."""
    layout_bytes, h, nb = layout_key
    layout = np.frombuffer(layout_bytes, np.int8).reshape(h, nb, nb)
    kv_idx_np, _ = layout_kv_indices(layout)
    q_idx_np, _ = layout_q_indices(layout)
    kv_idx = jnp.asarray(kv_idx_np)
    q_idx = jnp.asarray(q_idx_np)
    kv_cnt = jnp.asarray(layout.sum(-1).astype(np.int32))         # [H, B]
    q_cnt = jnp.asarray(layout.sum(-2).astype(np.int32))          # [H, B]

    if has_mask:
        @jax.custom_vjp
        def fn(qf, kf, vf, mf):
            out, _ = _sparse_forward(qf, kf, vf, mf, kv_idx, kv_cnt, block,
                                     causal, scale, h, interpret)
            return out

        def fwd(qf, kf, vf, mf):
            out, lse = _sparse_forward(qf, kf, vf, mf, kv_idx, kv_cnt,
                                       block, causal, scale, h, interpret)
            return out, (qf, kf, vf, mf, out, lse)

        def bwd(res, g):
            qf, kf, vf, mf, out, lse = res
            dq, dk, dv = _sparse_backward(
                qf, kf, vf, mf, g, out, lse, kv_idx, kv_cnt, q_idx, q_cnt,
                block, causal, scale, h, interpret)
            return dq, dk, dv, jnp.zeros_like(mf)
    else:
        @jax.custom_vjp
        def fn(qf, kf, vf):
            out, _ = _sparse_forward(qf, kf, vf, None, kv_idx, kv_cnt,
                                     block, causal, scale, h, interpret)
            return out

        def fwd(qf, kf, vf):
            out, lse = _sparse_forward(qf, kf, vf, None, kv_idx, kv_cnt,
                                       block, causal, scale, h, interpret)
            return out, (qf, kf, vf, out, lse)

        def bwd(res, g):
            qf, kf, vf, out, lse = res
            return _sparse_backward(qf, kf, vf, None, g, out, lse, kv_idx,
                                    kv_cnt, q_idx, q_cnt, block, causal,
                                    scale, h, interpret)

    fn.defvjp(fwd, bwd)
    return fn


def _pallas_sparse(q, k, v, layout, block, causal, scale, interpret,
                   key_mask=None):
    b, s, h, d = q.shape
    layout = np.asarray(layout).astype(np.int8)

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    key = (layout.tobytes(), layout.shape[0], layout.shape[1])
    fn = _sparse_vjp_fn(key, int(block), bool(causal), float(scale),
                        bool(interpret), key_mask is not None)
    if key_mask is not None:
        mf = key_mask.astype(jnp.float32)[:, None, :]       # [B, 1, S]
        out = fn(to_bhsd(q), to_bhsd(k), to_bhsd(v), mf)
    else:
        out = fn(to_bhsd(q), to_bhsd(k), to_bhsd(v))
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def sparse_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     layout, block: int, *,
                     causal: bool = False,
                     softmax_scale: Optional[float] = None,
                     impl: str = "xla",
                     key_mask: Optional[jax.Array] = None,
                     interpret: Optional[bool] = None) -> jax.Array:
    """Block-sparse attention over [B, S, H, D] with an [H, B, B] layout.

    ``key_mask``: optional [B, S] key-padding mask (1 = keep) — masked
    keys drop out of every row (reference sparse_self_attention.py:58
    key_padding_mask); supported by BOTH executors."""
    s = q.shape[1]
    if s % block:
        raise ValueError(f"seq {s} not divisible by block {block}")
    if np.asarray(layout).shape[1] != s // block:
        raise ValueError(f"layout has {np.asarray(layout).shape[1]} blocks, "
                         f"sequence needs {s // block}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    on_tpu = jax.devices()[0].platform == "tpu"
    # Mosaic lane-alignment constraint: the masked kernels slice the
    # [B, 1, S] mask on its LANE dim at the dynamic per-row column offset
    # (col*block), which TPU lowering only admits when it is provably a
    # multiple of 128 — i.e. block % 128 == 0 (the long-sequence configs;
    # the K/V slices are sublane-dim and only need block % 8). Interpret
    # mode (CPU) has no such constraint.
    masked_pallas_ok = key_mask is None or block % 128 == 0
    if impl == "auto":
        impl = ("pallas" if on_tpu and masked_pallas_ok else "xla")
    if impl == "xla":
        return _xla_sparse(q, k, v, layout, block, causal, scale, key_mask)
    if impl == "pallas":
        if interpret is None:
            interpret = not on_tpu
        if not interpret and not masked_pallas_ok:
            raise ValueError(
                f"key_mask with block={block} cannot lower to Mosaic "
                "(mask lane-slices need block % 128 == 0 on TPU) — use "
                "block >= 128, impl='xla', or drop the mask")
        return _pallas_sparse(q, k, v, layout, block, causal, scale,
                              interpret, key_mask=key_mask)
    raise ValueError(f"unknown sparse attention impl '{impl}'")


class SparseSelfAttention:
    """Layout-bound attention callable (reference
    ops/sparse_attention/sparse_self_attention.py:14): construct once with a
    SparsityConfig, call with q/k/v [B, S, H, D]."""

    def __init__(self, sparsity_config, max_seq_length: int = 2048,
                 attn_mask_mode: str = "mul", impl: str = "xla"):
        self.sparsity_config = sparsity_config
        self.max_seq_length = max_seq_length
        self.impl = impl
        self._layouts = {}

    def layout(self, seq_len: int):
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, q, k, v, *, causal: Optional[bool] = None,
                 key_mask: Optional[jax.Array] = None,
                 softmax_scale: Optional[float] = None):
        if causal is None:
            causal = getattr(self.sparsity_config, "attention",
                             "bidirectional") == "unidirectional"
        return sparse_attention(q, k, v, self.layout(q.shape[1]),
                                self.sparsity_config.block, causal=causal,
                                softmax_scale=softmax_scale,
                                key_mask=key_mask, impl=self.impl)


def pad_to_block_size(x: jax.Array, block: int, axis: int = 1):
    """SparseAttentionUtils.pad_to_block_size analogue: right-pad the seq
    axis to a block multiple; returns (padded, pad_len)."""
    s = x.shape[axis]
    pad = (-s) % block
    if pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), pad
