"""Block-sparse attention executors.

The reference executes block-sparse attention with Triton SDD/DSD/DDS
matmuls + block softmax (``ops/sparse_attention/matmul.py``,
``softmax.py``); here the same layouts run through:

- ``impl="xla"`` — dense attention under the layout-expanded mask (the
  numerics oracle, and perfectly fine for modest sequence lengths);
- ``impl="pallas"`` — a flash-style Pallas kernel that, per (head,
  q-block), loops ONLY over that row's active kv-blocks. The active-index
  list is precomputed on the host from the (static) layout, so compute and
  HBM traffic scale with layout density — the O(s·√s) long-sequence story
  of the reference (docs/index.md:142), TPU-style.

Layouts come from ``sparsity_config.py`` as [H, B, B] int32.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def layout_to_dense_mask(layout: np.ndarray, block: int) -> np.ndarray:
    """[H, B, B] block layout -> [H, S, S] bool element mask."""
    return np.kron(np.asarray(layout), np.ones((block, block))).astype(bool)


def layout_kv_indices(layout: np.ndarray):
    """Per (head, q-block) active kv-block ids, padded with -1:
    -> int32 [H, B, max_active]."""
    layout = np.asarray(layout)
    h, b, _ = layout.shape
    max_active = int(layout.sum(-1).max())
    idx = np.full((h, b, max_active), -1, np.int32)
    for hi in range(h):
        for qi in range(b):
            cols = np.nonzero(layout[hi, qi])[0]
            idx[hi, qi, :len(cols)] = cols
    return idx, max_active


def _xla_sparse(q, k, v, layout, block, causal, scale):
    mask = jnp.asarray(layout_to_dense_mask(layout, block))   # [H, S, S]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    logits = jnp.where(mask[None], logits, NEG_INF)
    if causal:
        s = q.shape[1]
        cm = jnp.tril(jnp.ones((s, s), jnp.bool_))
        logits = jnp.where(cm[None, None], logits, NEG_INF)
    # guard fully-masked rows (no allowed keys) against NaN
    rowmax = jnp.max(logits, axis=-1, keepdims=True)
    probs = jnp.where(rowmax > NEG_INF / 2,
                      jax.nn.softmax(logits, axis=-1), 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


def _sparse_kernel(kv_idx_ref, q_ref, k_ref, v_ref, o_ref, *,
                   causal: bool, scale: float, block: int, num_heads: int,
                   max_active: int):
    """grid: (B*H, q_blocks). Refs: q [1, block, D]; k/v [1, S, D];
    kv_idx [H, qb, max_active] in SMEM (scalar-prefetched — SMEM supports
    the arbitrary dynamic indexing a layout lookup needs)."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    h = jax.lax.rem(bh, num_heads)
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale

    def body(j, carry):
        m_prev, l_prev, acc = carry
        ki = kv_idx_ref[h, qi, j]
        active = ki >= 0
        ki_safe = jnp.maximum(ki, 0)
        kblk = k_ref[0, pl.ds(ki_safe * block, block), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki_safe * block, block), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0)
            k_pos = ki_safe * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        s = jnp.where(active, s, NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        # rows that have seen nothing yet keep NEG_INF; exp underflows to 0
        p = jnp.exp(s - jnp.maximum(m_new, NEG_INF / 2)[:, None])
        alpha = jnp.exp(m_prev - jnp.maximum(m_new, NEG_INF / 2))
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, alpha)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jnp.dot(
            p, vblk, preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    init = (jnp.full((block,), NEG_INF, jnp.float32),
            jnp.zeros((block,), jnp.float32),
            jnp.zeros((block, d), jnp.float32))
    m, l, acc = jax.lax.fori_loop(0, max_active, body, init)
    out = jnp.where((l > 0)[:, None], acc / jnp.maximum(l, 1e-30)[:, None], 0.0)
    o_ref[0] = out.astype(o_ref.dtype)


def _pallas_sparse(q, k, v, layout, block, causal, scale, interpret):
    b, s, h, d = q.shape
    kv_idx, max_active = layout_kv_indices(np.asarray(layout))
    qb = s // block

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    qf, kf, vf = to_bhsd(q), to_bhsd(k), to_bhsd(v)

    kernel = functools.partial(_sparse_kernel, causal=causal, scale=scale,
                               block=block, num_heads=h,
                               max_active=max_active)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,       # kv_idx rides in SMEM
        grid=(b * h, qb),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda bh, i, idx: (bh, i, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i, idx: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, i, idx: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, d), lambda bh, i, idx: (bh, i, 0)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        interpret=interpret,
    )(jnp.asarray(kv_idx), qf, kf, vf)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def sparse_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     layout, block: int, *,
                     causal: bool = False,
                     softmax_scale: Optional[float] = None,
                     impl: str = "xla",
                     interpret: Optional[bool] = None) -> jax.Array:
    """Block-sparse attention over [B, S, H, D] with an [H, B, B] layout."""
    s = q.shape[1]
    if s % block:
        raise ValueError(f"seq {s} not divisible by block {block}")
    if np.asarray(layout).shape[1] != s // block:
        raise ValueError(f"layout has {np.asarray(layout).shape[1]} blocks, "
                         f"sequence needs {s // block}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if impl == "xla":
        return _xla_sparse(q, k, v, layout, block, causal, scale)
    if impl == "pallas":
        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"
        return _pallas_sparse(q, k, v, layout, block, causal, scale, interpret)
    raise ValueError(f"unknown sparse attention impl '{impl}'")


class SparseSelfAttention:
    """Layout-bound attention callable (reference
    ops/sparse_attention/sparse_self_attention.py:14): construct once with a
    SparsityConfig, call with q/k/v [B, S, H, D]."""

    def __init__(self, sparsity_config, max_seq_length: int = 2048,
                 attn_mask_mode: str = "mul", impl: str = "xla"):
        self.sparsity_config = sparsity_config
        self.max_seq_length = max_seq_length
        self.impl = impl
        self._layouts = {}

    def layout(self, seq_len: int):
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, q, k, v, *, causal: Optional[bool] = None):
        if causal is None:
            causal = getattr(self.sparsity_config, "attention",
                             "bidirectional") == "unidirectional"
        return sparse_attention(q, k, v, self.layout(q.shape[1]),
                                self.sparsity_config.block, causal=causal,
                                impl=self.impl)


def pad_to_block_size(x: jax.Array, block: int, axis: int = 1):
    """SparseAttentionUtils.pad_to_block_size analogue: right-pad the seq
    axis to a block multiple; returns (padded, pad_len)."""
    s = x.shape[axis]
    pad = (-s) % block
    if pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), pad
