"""Config-driven sparse-attention wiring — the analogue of the reference's
``SparseAttentionUtils`` model surgery
(``deepspeed/ops/sparse_attention/sparse_attention_utils.py:1-225``) and the
``sparse_attention`` config presets
(``deepspeed/runtime/config.py:261-407``).

TPU-first surgery: the reference swaps ``nn.Module`` attention instances
inside a pretrained torch model; here the in-tree model families route
attention by CONFIG (``GPTConfig.sparse_attention`` /
``BertConfig.sparse_attention``), so "replacing self-attention" is a frozen
-dataclass ``replace`` — no weight surgery, since a sparse layout masks the
same dense projections. ``deepspeed_tpu.initialize`` applies it
automatically when the DeepSpeed config carries a ``sparse_attention``
block.
"""

import dataclasses
import functools
import json
from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.sparse_attention import (
    SparseSelfAttention, pad_to_block_size)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparsityConfig, VariableSparsityConfig)

# Reference mode names (runtime/config.py:249-258 SPARSE_*_MODE).
SPARSE_MODES = {
    "dense": DenseSparsityConfig,
    "fixed": FixedSparsityConfig,
    "variable": VariableSparsityConfig,
    "bigbird": BigBirdSparsityConfig,
    "bslongformer": BSLongformerSparsityConfig,
}


def sparsity_config_from_dict(d: Dict[str, Any],
                              num_heads: int) -> SparsityConfig:
    """Build a SparsityConfig from a ``sparse_attention`` config block —
    same keys as the reference's presets (``mode``, ``block``,
    ``num_local_blocks``, ``num_sliding_window_blocks``, ...)."""
    d = dict(d or {})
    mode = d.pop("mode", "fixed")
    d.pop("impl", None)   # executor choice, not a layout parameter
    if mode not in SPARSE_MODES:
        raise ValueError(f"unknown sparse_attention mode '{mode}' "
                         f"(one of {sorted(SPARSE_MODES)})")
    try:
        return SPARSE_MODES[mode](num_heads=num_heads, **d)
    except TypeError as e:
        raise ValueError(
            f"invalid sparse_attention key for mode '{mode}': {e}") from None


@functools.lru_cache(maxsize=None)
def _cached_ssa(cfg_json: str, num_heads: int, impl: str):
    d = json.loads(cfg_json)
    return SparseSelfAttention(sparsity_config_from_dict(d, num_heads),
                               impl=impl)


def get_sparse_self_attention(d: Dict[str, Any], num_heads: int,
                              impl: str = None) -> SparseSelfAttention:
    """Cached layout-bound attention for a config block (model families
    call this per block — the layout is built once per (config, seq))."""
    if impl is None:
        impl = (d or {}).get("impl", "auto")
    return _cached_ssa(json.dumps(d or {}, sort_keys=True), num_heads, impl)


class SparseAttentionUtils:
    """Reference-named utility surface (sparse_attention_utils.py:14)."""

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            model, sparse_attention_config: Dict[str, Any]):
        """Route an in-tree family's attention through the sparse executor.
        Parameter-free: a sparse layout masks the same dense q/k/v
        projections, so the params tree is unchanged (unlike the
        reference's module transplant, :177)."""
        cfg = getattr(model, "cfg", None)
        if cfg is None or not hasattr(cfg, "sparse_attention"):
            raise ValueError(
                f"sparse attention surgery supports the in-tree model "
                f"families (GPT/BERT with a `sparse_attention` config "
                f"field); got {type(model).__name__} — route attention "
                f"through ops.sparse_attention.SparseSelfAttention in your "
                f"model instead")
        new_cfg = dataclasses.replace(
            cfg, sparse_attention=dict(sparse_attention_config))
        return type(model)(new_cfg)

    @staticmethod
    def extend_position_embedding(params: Dict[str, Any], max_position: int,
                                  key: str = "wpe") -> Dict[str, Any]:
        """Tile a learned position table to a longer max length (reference
        :19 repeats the pretrained table). Returns a NEW params tree."""
        table = params[key]
        orig = table.shape[0]
        if max_position <= orig:
            raise ValueError(f"max_position {max_position} must exceed the "
                             f"current table length {orig}")
        reps = -(-max_position // orig)
        new = jnp.tile(table, (reps, 1))[:max_position]
        out = dict(params)
        out[key] = new
        return out

    @staticmethod
    def pad_to_block_size(block_size: int, input_ids, pad_token_id: int = 0,
                          attention_mask=None, labels=None
                          ) -> Tuple[int, Dict[str, Any]]:
        """Right-pad a token batch to a block multiple (reference :142):
        ids with ``pad_token_id``, mask with 0, labels with -100. Returns
        ``(pad_len, batch_dict)``."""
        s = input_ids.shape[1]
        pad = (-s) % block_size
        batch = {"input_ids": input_ids}
        if attention_mask is None:
            attention_mask = jnp.ones(input_ids.shape, jnp.int32)
        if pad:
            batch["input_ids"] = jnp.pad(input_ids, ((0, 0), (0, pad)),
                                         constant_values=pad_token_id)
            attention_mask = jnp.pad(attention_mask, ((0, 0), (0, pad)))
            if labels is not None:
                labels = jnp.pad(labels, ((0, 0), (0, pad)),
                                 constant_values=-100)
        batch["attention_mask"] = attention_mask
        if labels is not None:
            batch["labels"] = labels
        return pad, batch

    @staticmethod
    def unpad_sequence_output(pad_len: int, sequence_output):
        """Reference :208 — strip the pad tail added by pad_to_block_size."""
        if pad_len:
            return sequence_output[:, :-pad_len]
        return sequence_output


__all__ = ["SPARSE_MODES", "SparseAttentionUtils",
           "get_sparse_self_attention", "sparsity_config_from_dict",
           "pad_to_block_size"]
