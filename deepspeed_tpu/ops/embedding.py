"""Embedding lookup with an MXU-matmul gradient.

The forward is an ordinary row gather (cheap everywhere). The BACKWARD of a
gather is a scatter-add into the [V, D] table, which XLA lowers on TPU to a
slow serialized scatter (measured 0.6 GB + scatter per GPT-2 microbatch,
PROFILE.md r3). ``matmul_grad=True`` swaps that transpose for a one-hot
contraction ``dW = onehot(ids)ᵀ @ g`` — a [V, N] x [N, D] matmul that rides
the MXU with fp32 accumulation; the one-hot lowers to an elementwise
compare fused into the matmul operand.

Reference analogue: none — torch's embedding backward is a CUDA
scatter/atomics kernel (fast on GPU); this is a TPU-roofline redesign.
Numerics: the matmul path sums contributions in fp32 in a fixed reduction
order — parity-tested against the scatter path in tests/test_models.py.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.custom_vjp
def _lookup_matmul_grad(table, ids):
    return jnp.take(table, ids, axis=0)


def _lookup_fwd(table, ids):
    # The table residual is a reference (params stay live anyway), not a
    # copy; it carries the static vocab size and dtype into the backward.
    return jnp.take(table, ids, axis=0), (table, ids)


def _lookup_bwd(res, g):
    table, ids = res
    v = table.shape[0]
    d = g.shape[-1]
    oh = jax.nn.one_hot(ids.reshape(-1), v, dtype=g.dtype)
    dtable = jnp.einsum("nv,nd->vd", oh, g.reshape(-1, d),
                        preferred_element_type=jnp.float32)
    return dtable.astype(table.dtype), np.zeros(ids.shape, jax.dtypes.float0)


_lookup_matmul_grad.defvjp(_lookup_fwd, _lookup_bwd)


def _make_lookup_sparse(mesh, axes):
    """Embedding lookup whose VJP exchanges TOUCHED ROWS over the data
    axes instead of letting GSPMD all-reduce the dense [V, D] cotangent —
    the engine-automatic ``sparse_gradients`` path (reference
    deepspeed/runtime/engine.py:1530-1586 exchanges CSR index/value
    tensors; here the exchange is an all_gather of (ids, per-token rows)
    inside the op's custom VJP, wire bytes ∝ batch tokens, then a local
    scatter-add rebuilds the dense gradient on every rank)."""
    from deepspeed_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.comm.sparse import row_sparse_allreduce, scatter_rows

    @jax.custom_vjp
    def lookup(table, ids):
        return jnp.take(table, ids, axis=0)

    def fwd(table, ids):
        return jnp.take(table, ids, axis=0), (table, ids)

    def bwd(res, g):
        table, ids = res
        v, d = table.shape
        flat_ids = ids.reshape(ids.shape[0], -1)
        rows = g.reshape(g.shape[0], -1, d).astype(jnp.float32)
        if mesh is None or all(mesh.shape.get(a, 1) <= 1 for a in axes):
            dense = scatter_rows(flat_ids.reshape(-1),
                                 rows.reshape(-1, d), v)
        else:
            spec = P(axes if len(axes) > 1 else axes[0])

            def body(i, r):
                # Cotangents SUM over data shards (GSPMD convention);
                # the loss's global-batch mean already divided.
                return row_sparse_allreduce(i.reshape(-1),
                                            r.reshape(-1, d), v,
                                            axis=axes, mean=False)

            dense = shard_map(body, mesh=mesh, in_specs=(spec, spec),
                              out_specs=P(), axis_names=set(axes),
                              check_vma=False)(flat_ids, rows)
        return dense.astype(table.dtype), np.zeros(ids.shape,
                                                   jax.dtypes.float0)

    lookup.defvjp(fwd, bwd)
    return lookup


def resolve_sparse_grad_spec(setting):
    """Model-config helper -> ``(mesh, axes)`` or None (dense path).

    ``setting`` forms: falsy -> None; ``(mesh, axes)`` (what
    ``deepspeed_tpu.initialize()`` bakes in — the ENGINE's mesh, pinned
    at surgery time so the exchange never binds to whatever ambient mesh
    an unrelated engine registered first); a bare axes tuple or ``True``
    -> the ambient default mesh (custom-loop use; in a multi-mesh
    process prefer the explicit form)."""
    if not setting:
        return None
    from deepspeed_tpu.parallel.mesh import (DATA_AXIS, DCN_AXIS,
                                             get_default_mesh)
    from jax.sharding import Mesh

    if (isinstance(setting, tuple) and len(setting) == 2
            and isinstance(setting[0], Mesh)):
        return setting
    mesh = get_default_mesh()
    if setting is True:
        if mesh is None:
            return None
        from deepspeed_tpu.parallel.mesh import data_like_axes

        # Size-1 everywhere still routes through the sparse path (local
        # scatter only) so the config toggle is honored uniformly.
        return mesh, data_like_axes(mesh)
    return mesh, tuple(setting)


def embedding_lookup(table: jax.Array, ids: jax.Array,
                     matmul_grad: bool = False,
                     sparse_grad_axes=None) -> jax.Array:
    """``table[ids]`` ([V, D] x [...] int -> [..., D]) with a selectable
    gradient path: XLA scatter-add (default), the one-hot MXU matmul, or —
    with ``sparse_grad_axes`` (mesh axis names, batch dim 0) — the
    row-sparse cross-rank exchange (config ``sparse_gradients: true``)."""
    if sparse_grad_axes:
        if matmul_grad:
            raise ValueError("matmul_grad and sparse_grad_axes are "
                             "mutually exclusive embedding-grad paths")
        spec = resolve_sparse_grad_spec(sparse_grad_axes)
        if spec is None:
            return jnp.take(table, ids, axis=0)
        mesh, axes = spec
        return _make_lookup_sparse(mesh, tuple(axes))(table, ids)
    if matmul_grad:
        return _lookup_matmul_grad(table, ids)
    return jnp.take(table, ids, axis=0)


def vocab_pad_mask(padded_vocab: int, vocab_size: int) -> jax.Array:
    """[padded_vocab] fp32 additive logit mask: 0 on real rows, -1e9 on pad
    rows — keeps a padded-vocab CE numerically identical to the unpadded
    model (pad logits vanish from the logsumexp; pad table rows get zero
    gradient and stay at init)."""
    return jnp.where(jnp.arange(padded_vocab) < vocab_size,
                     0.0, -1e9).astype(jnp.float32)
