"""Embedding lookup with an MXU-matmul gradient.

The forward is an ordinary row gather (cheap everywhere). The BACKWARD of a
gather is a scatter-add into the [V, D] table, which XLA lowers on TPU to a
slow serialized scatter (measured 0.6 GB + scatter per GPT-2 microbatch,
PROFILE.md r3). ``matmul_grad=True`` swaps that transpose for a one-hot
contraction ``dW = onehot(ids)ᵀ @ g`` — a [V, N] x [N, D] matmul that rides
the MXU with fp32 accumulation; the one-hot lowers to an elementwise
compare fused into the matmul operand.

Reference analogue: none — torch's embedding backward is a CUDA
scatter/atomics kernel (fast on GPU); this is a TPU-roofline redesign.
Numerics: the matmul path sums contributions in fp32 in a fixed reduction
order — parity-tested against the scatter path in tests/test_models.py.
"""

import jax
import jax.numpy as jnp
import numpy as np


@jax.custom_vjp
def _lookup_matmul_grad(table, ids):
    return jnp.take(table, ids, axis=0)


def _lookup_fwd(table, ids):
    # The table residual is a reference (params stay live anyway), not a
    # copy; it carries the static vocab size and dtype into the backward.
    return jnp.take(table, ids, axis=0), (table, ids)


def _lookup_bwd(res, g):
    table, ids = res
    v = table.shape[0]
    d = g.shape[-1]
    oh = jax.nn.one_hot(ids.reshape(-1), v, dtype=g.dtype)
    dtable = jnp.einsum("nv,nd->vd", oh, g.reshape(-1, d),
                        preferred_element_type=jnp.float32)
    return dtable.astype(table.dtype), np.zeros(ids.shape, jax.dtypes.float0)


_lookup_matmul_grad.defvjp(_lookup_fwd, _lookup_bwd)


def embedding_lookup(table: jax.Array, ids: jax.Array,
                     matmul_grad: bool = False) -> jax.Array:
    """``table[ids]`` ([V, D] x [...] int -> [..., D]) with a selectable
    gradient path: XLA scatter-add (default) or the one-hot MXU matmul."""
    if matmul_grad:
        return _lookup_matmul_grad(table, ids)
    return jnp.take(table, ids, axis=0)


def vocab_pad_mask(padded_vocab: int, vocab_size: int) -> jax.Array:
    """[padded_vocab] fp32 additive logit mask: 0 on real rows, -1e9 on pad
    rows — keeps a padded-vocab CE numerically identical to the unpadded
    model (pad logits vanish from the logsumexp; pad table rows get zero
    gradient and stay at init)."""
    return jnp.where(jnp.arange(padded_vocab) < vocab_size,
                     0.0, -1e9).astype(jnp.float32)
