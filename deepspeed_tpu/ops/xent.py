"""Fused softmax cross-entropy head.

The [N, V] logits tensor is the biggest intermediate in LM training — at
the GPT-2 bench shape (16x512 tokens x 50257 vocab) it is 1.6 GB in fp32,
and the stock jax path materializes it several times over (einsum output,
``log_softmax`` residual saved for backward, backward softmax grad):
measured 9.5 ms of the 73 ms GPT-2 microbatch, almost all HBM traffic
(``tools/perf_probe_r3.py``, PROFILE.md). This op removes most of it:

- logits are stored in the model's compute dtype (fp32 MXU accumulation,
  bf16 store under mixed precision — halves every HBM pass; exact fp32
  when the model computes in fp32);
- the custom VJP saves only the per-row logsumexp: backward *recomputes*
  the logits (one extra MXU matmul — cheap) instead of reading a saved
  fp32 log-softmax from HBM;
- ``dlogits = (softmax − onehot)·g`` fuses into the two backward matmuls
  (``one_hot`` lowers to an elementwise compare, so no [N, V] one-hot
  buffer exists).

Reference analogue: none — torch autograd keeps the log-softmax
activations; this is the HBM-economy redesign the TPU roofline demands
(head matmul runs at ~180 flop/byte; the stock CE passes run at ~0).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.custom_vjp
def _fused_nll(x, w, labels):
    """Per-token negative log-likelihood. x [N, D], w [V, D], labels [N]
    (already clipped to valid range). Returns nll [N] fp32."""
    logits = jnp.einsum("nd,vd->nv", x, w).astype(jnp.float32)
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    picked = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    return lse - picked


def _fused_nll_fwd(x, w, labels):
    logits = jnp.einsum("nd,vd->nv", x, w).astype(jnp.float32)
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    picked = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    return lse - picked, (x, w, labels, lse)


def _fused_nll_bwd(res, g):
    x, w, labels, lse = res
    v = w.shape[0]
    logits = jnp.einsum("nd,vd->nv", x, w).astype(jnp.float32)
    p = jnp.exp(logits - lse[:, None])
    dlogits = ((p - jax.nn.one_hot(labels, v, dtype=jnp.float32))
               * g[:, None]).astype(x.dtype)
    dx = jnp.einsum("nv,vd->nd", dlogits, w)
    dw = jnp.einsum("nv,nd->vd", dlogits, x)
    return dx, dw, np.zeros(labels.shape, jax.dtypes.float0)


_fused_nll.defvjp(_fused_nll_fwd, _fused_nll_bwd)


@jax.custom_vjp
def _fused_nll_bias(x, w, b, labels):
    """As _fused_nll with a per-vocab bias (BERT MLM head shape)."""
    logits = (jnp.einsum("nd,vd->nv", x, w).astype(jnp.float32) + b)
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    picked = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    return lse - picked


def _fused_nll_bias_fwd(x, w, b, labels):
    logits = (jnp.einsum("nd,vd->nv", x, w).astype(jnp.float32) + b)
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    picked = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    return lse - picked, (x, w, b, labels, lse)


def _fused_nll_bias_bwd(res, g):
    x, w, b, labels, lse = res
    v = w.shape[0]
    logits = (jnp.einsum("nd,vd->nv", x, w).astype(jnp.float32) + b)
    p = jnp.exp(logits - lse[:, None])
    dlog32 = (p - jax.nn.one_hot(labels, v, dtype=jnp.float32)) * g[:, None]
    dlogits = dlog32.astype(x.dtype)
    dx = jnp.einsum("nv,vd->nd", dlogits, w)
    dw = jnp.einsum("nv,nd->vd", dlogits, x)
    db = dlog32.sum(axis=0).astype(b.dtype)
    return dx, dw, db, np.zeros(labels.shape, jax.dtypes.float0)


_fused_nll_bias.defvjp(_fused_nll_bias_fwd, _fused_nll_bias_bwd)


def fused_cross_entropy(x: jax.Array, w: jax.Array, labels: jax.Array,
                        ignore_index: int = -100,
                        w_transposed: bool = False,
                        bias: jax.Array = None) -> jax.Array:
    """Token-mean cross entropy of ``x @ w.T`` against ``labels``,
    ignoring ``ignore_index`` positions — drop-in for
    ``cross_entropy_with_ignore(logits, labels)`` that never materializes
    fp32 logits (under mixed precision) nor a saved log-softmax.

    x: [..., D] activations (compute dtype), w: [V, D] tied-embedding
    layout (or [D, V] with ``w_transposed``), labels: [...] int.
    """
    d = x.shape[-1]
    n = int(np.prod(x.shape[:-1]))
    if w_transposed:
        w = w.T
    xf = x.reshape(n, d)
    lf = labels.reshape(n)
    valid = lf != ignore_index
    safe = jnp.where(valid, lf, 0).astype(jnp.int32)
    if bias is not None:
        nll = _fused_nll_bias(xf, w.astype(x.dtype),
                              bias.astype(jnp.float32), safe)
    else:
        nll = _fused_nll(xf, w.astype(x.dtype), safe)
    nll = jnp.where(valid, nll, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)
