"""Fused softmax cross-entropy head.

The [N, V] logits tensor is the biggest intermediate in LM training — at
the GPT-2 bench shape (16x512 tokens x 50257 vocab) it is 1.6 GB in fp32,
and the stock jax path materializes it several times over (einsum output,
``log_softmax`` residual saved for backward, backward softmax grad):
measured 9.5 ms of the 73 ms GPT-2 microbatch, almost all HBM traffic
(``tools/perf_probe_r3.py``, PROFILE.md). This op removes most of it:

- logits are stored in the model's compute dtype (fp32 MXU accumulation,
  bf16 store under mixed precision — halves every HBM pass; exact fp32
  when the model computes in fp32). For parity-sensitive runs,
  ``logits_fp32=True`` computes the logits einsum with
  ``preferred_element_type=float32`` — identical numerics to the unfused
  ``cross_entropy_with_ignore`` path at the cost of the fp32 HBM pass;
- the custom VJP saves only the per-row logsumexp: backward *recomputes*
  the logits (one extra MXU matmul — cheap) instead of reading a saved
  fp32 log-softmax from HBM;
- ``dlogits = (softmax − onehot)·g`` fuses into the two backward matmuls
  (``one_hot`` lowers to an elementwise compare, so no [N, V] one-hot
  buffer exists).

Reference analogue: none — torch autograd keeps the log-softmax
activations; this is the HBM-economy redesign the TPU roofline demands
(head matmul runs at ~180 flop/byte; the stock CE passes run at ~0).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=None)
def _make_fused_nll(with_bias: bool, logits_fp32: bool,
                    const_bias: bool = False):
    """Build the custom-VJP per-token NLL for one (bias, dtype) variant.

    With ``logits_fp32`` every logits(-grad) einsum carries
    ``preferred_element_type=float32`` so bf16 inputs never round the
    logits to bf16 before the logsumexp (the unfused path's numerics)."""
    pet = jnp.float32 if logits_fp32 else None

    def logits_of(x, w, b):
        out = jnp.einsum("nd,vd->nv", x, w,
                         preferred_element_type=pet).astype(jnp.float32)
        return out + b if with_bias else out

    def nll_of(logits, labels):
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        picked = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
        return lse - picked, lse

    if with_bias:
        @jax.custom_vjp
        def fused(x, w, b, labels):
            return nll_of(logits_of(x, w, b), labels)[0]

        def fwd(x, w, b, labels):
            nll, lse = nll_of(logits_of(x, w, b), labels)
            return nll, (x, w, b, labels, lse)

        def bwd(res, g):
            x, w, b, labels, lse = res
            v = w.shape[0]
            logits = logits_of(x, w, b)
            p = jnp.exp(logits - lse[:, None])
            dlog32 = ((p - jax.nn.one_hot(labels, v, dtype=jnp.float32))
                      * g[:, None])
            dlogits = dlog32 if logits_fp32 else dlog32.astype(x.dtype)
            dx = jnp.einsum("nv,vd->nd", dlogits, w,
                            preferred_element_type=pet).astype(x.dtype)
            dw = jnp.einsum("nv,nd->vd", dlogits, x,
                            preferred_element_type=pet).astype(w.dtype)
            # const_bias: the bias is a non-parameter mask (vocab padding)
            # — skip the [N, V] reduction its cotangent would cost.
            db = (jnp.zeros_like(b) if const_bias
                  else dlog32.sum(axis=0).astype(b.dtype))
            return dx, dw, db, np.zeros(labels.shape, jax.dtypes.float0)
    else:
        @jax.custom_vjp
        def fused(x, w, labels):
            return nll_of(logits_of(x, w, None), labels)[0]

        def fwd(x, w, labels):
            nll, lse = nll_of(logits_of(x, w, None), labels)
            return nll, (x, w, labels, lse)

        def bwd(res, g):
            x, w, labels, lse = res
            v = w.shape[0]
            logits = logits_of(x, w, None)
            p = jnp.exp(logits - lse[:, None])
            dlog32 = ((p - jax.nn.one_hot(labels, v, dtype=jnp.float32))
                      * g[:, None])
            dlogits = dlog32 if logits_fp32 else dlog32.astype(x.dtype)
            dx = jnp.einsum("nv,vd->nd", dlogits, w,
                            preferred_element_type=pet).astype(x.dtype)
            dw = jnp.einsum("nv,nd->vd", dlogits, x,
                            preferred_element_type=pet).astype(w.dtype)
            return dx, dw, np.zeros(labels.shape, jax.dtypes.float0)

    fused.defvjp(fwd, bwd)
    return fused


# Back-compat aliases for the default compute-dtype variants.
_fused_nll = _make_fused_nll(False, False)
_fused_nll_bias = _make_fused_nll(True, False)


def fused_cross_entropy(x: jax.Array, w: jax.Array, labels: jax.Array,
                        ignore_index: int = -100,
                        w_transposed: bool = False,
                        bias: jax.Array = None,
                        bias_grad: bool = True,
                        logits_fp32: bool = False) -> jax.Array:
    """Token-mean cross entropy of ``x @ w.T`` against ``labels``,
    ignoring ``ignore_index`` positions — drop-in for
    ``cross_entropy_with_ignore(logits, labels)`` that never materializes
    fp32 logits (under mixed precision) nor a saved log-softmax.

    x: [..., D] activations (compute dtype), w: [V, D] tied-embedding
    layout (or [D, V] with ``w_transposed``), labels: [...] int.
    ``logits_fp32`` keeps the unfused path's exact fp32-logits numerics
    (ADVICE r3: bf16 configs otherwise see a silent numerics change).
    """
    d = x.shape[-1]
    n = int(np.prod(x.shape[:-1]))
    if w_transposed:
        w = w.T
    xf = x.reshape(n, d)
    lf = labels.reshape(n)
    valid = lf != ignore_index
    safe = jnp.where(valid, lf, 0).astype(jnp.int32)
    if bias is not None:
        nll = _make_fused_nll(True, bool(logits_fp32), not bias_grad)(
            xf, w.astype(x.dtype), bias.astype(jnp.float32), safe)
    else:
        nll = _make_fused_nll(False, bool(logits_fp32))(
            xf, w.astype(x.dtype), safe)
    nll = jnp.where(valid, nll, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)
