"""Fused blockwise Adam(W) update — Pallas TPU kernel, kernel tier
round 2 for the training hot loop.

``FusedAdam.update`` is a whole-tree elementwise chain that XLA lowers to
~10 HBM-bound ops per leaf: each of master params, grads and both moments
is read and written across several fused loops, so the optimizer step
pays the parameter bytes multiple times. This kernel is the reference's
``multi_tensor_adam.cu`` capability TPU-native (SURVEY §2.9): one Pallas
pass per flat block reads ``(p, g, m, v)`` once, runs the full Adam(W)
recurrence in fp32 registers, and writes ``(p', m', v')`` — and
optionally the compute-dtype (bf16) cast of ``p'`` — in a single HBM
round-trip.

The math is **bit-for-bit the ``FusedAdam.update`` leaf chain** (same op
order, fp32 throughout), so the XLA chain stays the parity oracle; the
traced scalars (lr and the two bias corrections, functions of the traced
step counter) ride as a tiny broadcast VMEM tile. Leaves are flattened,
padded to lane tiles and processed as ``(rows, 128)`` blocks — the
blockwise layout, not the tree structure, is what the kernel sees, so
every ZeRO tier's (possibly sharded) master partition goes through the
same program.

``interpret=True`` (automatic off-TPU) runs the same kernel through the
Pallas interpreter so CPU tier-1 parity tests cover the real kernel
arithmetic.
"""

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.adam.fused_adam import AdamState, FusedAdam

__all__ = ["fused_adam_leaf", "fused_adam_apply", "fused_update_cost"]

_LANE = 128
# Max block rows per grid step; multiple of 16 so an optional bf16 cast
# output tiles on the sublane dim too (f32 needs 8, bf16 needs 16).
_MAX_ROWS = 256


def _use_interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # pragma: no cover - no backend
        return True


def fused_adam_update_kernel(sc_ref, p_ref, g_ref, m_ref, v_ref, *out_refs,
                             b1: float, b2: float, eps: float, wd: float,
                             adamw: bool, cast: bool):
    if cast:
        p_out, m_out, v_out, c_out = out_refs
    else:
        p_out, m_out, v_out = out_refs
        c_out = None
    lr = sc_ref[0, 0]
    bc1 = sc_ref[0, 1]
    bc2 = sc_ref[0, 2]
    p = p_ref[...]
    g = g_ref[...].astype(jnp.float32)
    # Same op order as FusedAdam.update's leaf chain — the XLA chain is
    # the parity oracle and the test bound is ulp-level, not atol-level.
    if wd != 0.0 and not adamw:
        g = g + wd * p
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * jnp.square(g)
    denom = jnp.sqrt(v / bc2) + eps
    update = (m / bc1) / denom
    if wd != 0.0 and adamw:
        update = update + wd * p
    pn = p - lr * update
    p_out[...] = pn
    m_out[...] = m
    v_out[...] = v
    if cast:
        c_out[...] = pn.astype(c_out.dtype)


def fused_adam_leaf(p: jax.Array, g: jax.Array, m: jax.Array, v: jax.Array,
                    scalars: jax.Array, *, b1: float, b2: float, eps: float,
                    weight_decay: float, adamw_mode: bool,
                    cast_dtype: Optional[Any] = None,
                    interpret: Optional[bool] = None):
    """One leaf's fused update. ``p``/``m``/``v`` fp32, ``g`` any float
    dtype (cast in kernel, like the XLA chain). ``scalars``: [8, 128]
    fp32 broadcast tile with ``(lr, bc1, bc2)`` at ``[0, :3]``. Returns
    ``(p', m', v')`` in the leaf's shape — plus ``p'.astype(cast_dtype)``
    when ``cast_dtype`` is set (the compute-param cast rides the same
    HBM round-trip)."""
    interpret = _use_interpret() if interpret is None else interpret
    shape = p.shape
    n = int(p.size)
    if n == 0:
        outs = (p, m, v)
        if cast_dtype is not None:
            outs += (p.astype(cast_dtype),)
        return outs

    rows = -(-n // _LANE)
    rows = -(-rows // 16) * 16              # sublane tile (bf16-safe)
    br = min(_MAX_ROWS, rows)
    rows = -(-rows // br) * br              # grid covers exactly
    padded = rows * _LANE

    def flat(x, dtype):
        x = x.reshape(-1).astype(dtype)
        return jnp.pad(x, (0, padded - n)).reshape(rows, _LANE)

    pf = flat(p, jnp.float32)
    gf = flat(g, g.dtype)
    mf = flat(m, jnp.float32)
    vf = flat(v, jnp.float32)

    cast = cast_dtype is not None
    kernel = functools.partial(fused_adam_update_kernel, b1=float(b1),
                               b2=float(b2), eps=float(eps),
                               wd=float(weight_decay),
                               adamw=bool(adamw_mode), cast=cast)
    blk = lambda i: (i, 0)
    out_shape = [jax.ShapeDtypeStruct((rows, _LANE), jnp.float32)] * 3
    out_specs = [pl.BlockSpec((br, _LANE), blk)] * 3
    if cast:
        out_shape.append(jax.ShapeDtypeStruct((rows, _LANE), cast_dtype))
        out_specs.append(pl.BlockSpec((br, _LANE), blk))
    outs = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((8, _LANE), lambda i: (0, 0)),   # scalar tile
            pl.BlockSpec((br, _LANE), blk),
            pl.BlockSpec((br, _LANE), blk),
            pl.BlockSpec((br, _LANE), blk),
            pl.BlockSpec((br, _LANE), blk),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(scalars, pf, gf, mf, vf)
    return tuple(o.reshape(-1)[:n].reshape(shape) for o in outs)


def scalar_tile(lr, bc1, bc2) -> jax.Array:
    """Pack the traced step scalars into the kernel's [8, 128] fp32
    broadcast tile (one VMEM tile; re-read per grid step, negligible
    next to the parameter stream)."""
    vals = jnp.stack([jnp.asarray(lr, jnp.float32),
                      jnp.asarray(bc1, jnp.float32),
                      jnp.asarray(bc2, jnp.float32)])
    return jnp.zeros((8, _LANE), jnp.float32).at[0, :3].set(vals)


def fused_adam_apply(optimizer: FusedAdam, grads: Any, state: AdamState,
                     params: Any, lr=None,
                     cast_dtype: Optional[Any] = None):
    """Drop-in for ``FusedAdam.update`` over the whole tree, one fused
    kernel launch per leaf. Returns ``(new_params, new_state)`` — or
    ``(new_params, new_state, compute_params)`` when ``cast_dtype`` is
    set. Signature/semantics mirror ``FusedAdam.update`` so
    ``_make_apply_step`` can substitute it at the single computation
    site."""
    lr = optimizer.lr if lr is None else lr
    step = state.step + 1
    b1, b2 = optimizer.beta1, optimizer.beta2
    if optimizer.bias_correction:
        bc1 = 1.0 - b1 ** step.astype(jnp.float32)
        bc2 = 1.0 - b2 ** step.astype(jnp.float32)
    else:
        bc1 = jnp.float32(1.0)
        bc2 = jnp.float32(1.0)
    sc = scalar_tile(lr, bc1, bc2)

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state.exp_avg)
    flat_v = treedef.flatten_up_to(state.exp_avg_sq)
    outs = [fused_adam_leaf(p, g, m, v, sc, b1=b1, b2=b2, eps=optimizer.eps,
                            weight_decay=optimizer.weight_decay,
                            adamw_mode=optimizer.adamw_mode,
                            cast_dtype=cast_dtype)
            for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
    new_p = treedef.unflatten([o[0] for o in outs])
    new_m = treedef.unflatten([o[1] for o in outs])
    new_v = treedef.unflatten([o[2] for o in outs])
    new_state = AdamState(step=step, exp_avg=new_m, exp_avg_sq=new_v)
    if cast_dtype is not None:
        return new_p, new_state, treedef.unflatten([o[3] for o in outs])
    return new_p, new_state


def fused_update_cost(params: Any) -> Tuple[float, float]:
    """Analytic ``(flops, bytes)`` of one fused update over ``params`` —
    XLA's ``cost_analysis`` cannot see inside a Pallas custom call, so
    the engine books these at its goodput ``set_flops`` site to keep the
    roofline verdict and ``devicetime/mfu_measured`` honest under the
    fused path. Per element: ~12 flops (the Adam recurrence) and 28
    bytes (read p/g/m/v + write p'/m'/v', fp32)."""
    n = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    return 12.0 * n, 28.0 * n
