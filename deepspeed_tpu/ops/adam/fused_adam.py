"""Fused Adam/AdamW.

Capability parity with the reference's multi-tensor fused Adam
(``deepspeed/ops/adam/fused_adam.py:15`` over ``csrc/adam/multi_tensor_adam.cu``)
and the host-side ``DeepSpeedCPUAdam`` (``ops/adam/cpu_adam.py:13`` over AVX
``csrc/adam/cpu_adam.cpp``).

TPU-first design: the whole-tree update is a single jitted function — XLA
fuses the elementwise chains across *all* parameters into a handful of
kernels, which is exactly what multi-tensor-apply buys on CUDA; no explicit
kernel chunking is needed. The update runs in fp32 on the (possibly
data-axis-sharded) master params; with ZeRO>=1 every device only updates its
own shard, matching stage2.py:1554's "local Adam on own partition".

``FusedAdam`` packages init/update over a pytree; XLA fuses the whole-tree
elementwise update without a hand-written kernel.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    step: jax.Array  # int32 scalar
    exp_avg: Any     # m, same tree as params (fp32)
    exp_avg_sq: Any  # v, same tree as params (fp32)


class FusedAdam:
    """Functional Adam(W) on fp32 master params.

    Args mirror the reference wrapper: betas, eps, weight_decay, adamw_mode
    (True => decoupled weight decay), bias_correction.
    """

    def __init__(self,
                 lr: float = 1e-3,
                 betas=(0.9, 0.999),
                 eps: float = 1e-8,
                 weight_decay: float = 0.0,
                 adamw_mode: bool = True,
                 bias_correction: bool = True,
                 amsgrad: bool = False):
        if amsgrad:
            raise NotImplementedError("amsgrad not supported (parity with reference "
                                      "ops/adam/fused_adam.py which also rejects it)")
        self.lr = float(lr)
        self.beta1, self.beta2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.adamw_mode = bool(adamw_mode)
        self.bias_correction = bool(bias_correction)

    # -- functional API ----------------------------------------------------
    def init(self, params: Any) -> AdamState:
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        zeros2 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return AdamState(step=jnp.zeros((), jnp.int32), exp_avg=zeros, exp_avg_sq=zeros2)

    def update(self, grads: Any, state: AdamState, params: Any,
               lr: Optional[jax.Array] = None):
        """One Adam step. grads/params fp32; returns (new_params, new_state)."""
        lr = self.lr if lr is None else lr
        step = state.step + 1
        b1, b2 = self.beta1, self.beta2
        if self.bias_correction:
            bc1 = 1.0 - b1 ** step.astype(jnp.float32)
            bc2 = 1.0 - b2 ** step.astype(jnp.float32)
        else:
            bc1 = jnp.float32(1.0)
            bc2 = jnp.float32(1.0)

        def leaf(p, g, m, v):
            g = g.astype(jnp.float32)
            if self.weight_decay != 0.0 and not self.adamw_mode:
                g = g + self.weight_decay * p  # classic L2 into the gradient
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * jnp.square(g)
            denom = jnp.sqrt(v / bc2) + self.eps
            update = (m / bc1) / denom
            if self.weight_decay != 0.0 and self.adamw_mode:
                update = update + self.weight_decay * p  # decoupled decay
            return p - lr * update, m, v

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.exp_avg)
        flat_v = treedef.flatten_up_to(state.exp_avg_sq)
        outs = [leaf(p, g, m, v) for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
        new_p = treedef.unflatten([o[0] for o in outs])
        new_m = treedef.unflatten([o[1] for o in outs])
        new_v = treedef.unflatten([o[2] for o in outs])
        return new_p, AdamState(step=step, exp_avg=new_m, exp_avg_sq=new_v)


class FusedAdamW(FusedAdam):
    def __init__(self, **kwargs):
        kwargs.setdefault("adamw_mode", True)
        super().__init__(**kwargs)


class HostOffloadAdam(FusedAdam):
    """Host-memory Adam — the DeepSpeedCPUAdam analogue (ZeRO-Offload).

    Selecting this optimizer (config ``optimizer.type: "cpu_adam"``) enables
    the engine's host offload tier even without an ``offload_optimizer``
    block: fp32 master params + moments live in host RAM
    (``runtime/zero/offload.py`` owns the placement and the jitted XLA:CPU
    update — the AVX-kernel analogue), and each step streams sharded grads
    down / compute-dtype params back. The update math is FusedAdam's.
    """

    host_resident = True
