"""MoQ — Mixture-of-Quantization training quantizer.

Reference: ``deepspeed/runtime/quantize.py:12`` (schedule + groupwise
sim-quantization driven by the ``quantize_training`` config block) over the
CUDA kernel ``csrc/quantization/quantizer.cu``. TPU-native: the
quantize→dequantize constraint is one jitted whole-tree function (XLA fuses
the per-group min/max/scale chain); stochastic rounding uses the engine's
PRNG stream instead of curand.

Semantics (matching the reference schedule):
- precision starts at ``start_bits`` and steps down by 1 toward
  ``target_bits`` every ``quantize_period`` steps, the period doubling after
  each drop; nothing happens before ``schedule_offset``.
- symmetric: per-group scale = max|w| / (2^(b-1)-1), zero-centred;
  asymmetric: per-group (min, max) affine grid.
- optional eigenvalue modulation: layers with larger Hessian eigenvalues
  keep higher precision longer (period scaled by normalized eigenvalue),
  reference quantize.py + eigenvalue.py wiring.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


@dataclass
class MoQConfig:
    enabled: bool = False
    verbose: bool = False
    quantizer_kernel: bool = False      # accepted for parity; XLA fuses
    quantize_type: str = "symmetric"    # or "asymmetric"
    rounding: str = "nearest"           # or "stochastic"
    start_bits: int = 16
    target_bits: int = 8
    quantize_period: int = 100
    schedule_offset: int = 0
    quantize_groups: int = 1
    fp16_mixed_quantize: bool = False
    quantize_change_ratio: float = 0.001
    eigenvalue: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "MoQConfig":
        if not d:
            return cls()
        bits = d.get("quantize_bits", {})
        sched = d.get("quantize_schedule", {})
        algo = d.get("quantize_algo", {})
        mixed = d.get("fp16_mixed_quantize", {})
        known = {"enabled", "quantize_verbose", "quantizer_kernel",
                 "quantize_bits", "quantize_schedule", "quantize_algo",
                 "quantize_groups", "fp16_mixed_quantize", "eigenvalue",
                 "quantize_type", "rounding"}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown quantize_training keys: "
                             f"{sorted(unknown)}")
        return cls(
            enabled=bool(d.get("enabled", False)),
            verbose=bool(d.get("quantize_verbose", False)),
            quantizer_kernel=bool(d.get("quantizer_kernel", False)),
            quantize_type=str(algo.get("q_type",
                                       d.get("quantize_type", "symmetric"))),
            rounding=str(algo.get("rounding", d.get("rounding", "nearest"))),
            start_bits=int(bits.get("start_bits", 16)),
            target_bits=int(bits.get("target_bits", 8)),
            quantize_period=int(sched.get("quantize_period", 100)),
            schedule_offset=int(sched.get("schedule_offset", 0)),
            quantize_groups=int(d.get("quantize_groups", 1)),
            fp16_mixed_quantize=bool(mixed.get("enabled", False)),
            quantize_change_ratio=float(
                mixed.get("quantize_change_ratio", 0.001)),
            eigenvalue=dict(d.get("eigenvalue", {})),
        )

    def __post_init__(self):
        if self.quantize_type not in ("symmetric", "asymmetric"):
            raise ValueError(f"quantize_type must be symmetric|asymmetric, "
                             f"got '{self.quantize_type}'")
        if self.rounding not in ("nearest", "stochastic"):
            raise ValueError(f"rounding must be nearest|stochastic, got "
                             f"'{self.rounding}'")
        if self.target_bits > self.start_bits:
            raise ValueError("target_bits must be <= start_bits")


def _group(w: jax.Array, groups: int):
    rows = w.shape[0]
    g = groups if w.ndim >= 1 and rows % groups == 0 else 1
    return w.reshape((g, -1)), g


def sim_quantize(w: jax.Array, bits, groups: int, symmetric: bool,
                 stochastic: bool, key) -> jax.Array:
    """Quantize→dequantize ``w`` on a ``bits``-bit per-group grid. ``bits``
    may be traced (schedule changes need no recompile)."""
    if w.ndim == 0:
        return w
    orig_shape, orig_dtype = w.shape, w.dtype
    grouped, g = _group(w.astype(jnp.float32), groups)
    levels = jnp.float32(2.0) ** (jnp.asarray(bits, jnp.float32) - 1.0) - 1.0
    if symmetric:
        scale = jnp.max(jnp.abs(grouped), axis=1, keepdims=True) / levels
        scale = jnp.maximum(scale, 1e-12)
        q = grouped / scale
        lo, hi = -levels - 1.0, levels
    else:
        mn = jnp.min(grouped, axis=1, keepdims=True)
        mx = jnp.max(grouped, axis=1, keepdims=True)
        scale = jnp.maximum(mx - mn, 1e-12) / (2.0 * levels + 1.0)
        q = (grouped - mn) / scale
        lo, hi = 0.0, 2.0 * levels + 1.0
    if stochastic:
        q = jnp.floor(q + jax.random.uniform(key, q.shape))
    else:
        q = jnp.round(q)
    q = jnp.clip(q, lo, hi)
    deq = q * scale if symmetric else q * scale + mn
    return deq.reshape(orig_shape).astype(orig_dtype)


class MoQQuantizer:
    """Schedule + whole-tree sim-quantization (the engine's MoQ hook).

    ``layer_eigenvalues`` (optional, from ``runtime/eigenvalue.py``):
    layers with larger Hessian eigenvalues are more quantization-sensitive,
    so their period is stretched by lambda/lambda_min — the reference's
    eigenvalue-modulated schedule (quantize.py + engine eigenvalue hook).
    """

    def __init__(self, config: MoQConfig, layer_eigenvalues=None):
        self.cfg = config
        self._apply_jit = None
        self.eigenvalues = {}
        if layer_eigenvalues:
            self.set_eigenvalues(layer_eigenvalues)

    def set_eigenvalues(self, layer_eigenvalues) -> None:
        # Clamp nonpositive estimates (flat layers legitimately power-
        # iterate to ~0) so one zero doesn't explode every other period.
        self.eigenvalues = {k: max(float(v), 1e-6)
                            for k, v in dict(layer_eigenvalues).items()}
        self._lambda_min = min(self.eigenvalues.values())

    def period_scale(self, layer: str = None) -> float:
        if not self.eigenvalues or layer not in self.eigenvalues:
            return 1.0
        return max(self.eigenvalues[layer] / self._lambda_min, 1.0)

    def current_bits(self, global_step: int, layer: str = None) -> int:
        """start_bits → target_bits, dropping 1 bit every period, period
        doubling after each drop (reference quantize.py schedule); per-layer
        periods stretched by the eigenvalue ratio when provided."""
        c = self.cfg
        if global_step < c.schedule_offset:
            return c.start_bits
        t = global_step - c.schedule_offset
        bits = c.start_bits
        period = c.quantize_period * self.period_scale(layer)
        while bits > c.target_bits and t >= period:
            t -= period
            period *= 2
            bits -= 1
        return bits

    def quantize_tree(self, params: Any, global_step: int, key) -> Any:
        if global_step < self.cfg.schedule_offset:
            return params
        c = self.cfg
        # Per-leaf bit widths: each leaf's TOP-LEVEL subtree name is its
        # "layer" for the eigenvalue-stretched schedule; without
        # eigenvalues every leaf shares the global schedule. Bits ride as
        # a traced vector, so schedule changes never recompile.
        paths = jax.tree_util.tree_flatten_with_path(params)[0]
        bits = jnp.asarray(
            [self.current_bits(
                global_step,
                str(getattr(p[0][0], "key", p[0][0])) if p[0] else None)
             for p in paths], jnp.int32)
        if self._apply_jit is None:
            def apply(tree, bits, key):
                leaves, treedef = jax.tree_util.tree_flatten(tree)
                keys = jax.random.split(key, len(leaves))
                out = [sim_quantize(l, bits[i], c.quantize_groups,
                                    c.quantize_type == "symmetric",
                                    c.rounding == "stochastic", k)
                       if l.ndim >= 2 else l
                       for i, (l, k) in enumerate(zip(leaves, keys))]
                return jax.tree_util.tree_unflatten(treedef, out)

            self._apply_jit = jax.jit(apply, donate_argnums=(0,))
        return self._apply_jit(params, bits, key)
