"""MoQ training quantization (reference deepspeed/runtime/quantize.py +
csrc/quantization/)."""

from deepspeed_tpu.ops.quantizer.quantizer import (MoQConfig, MoQQuantizer,
                                                   sim_quantize)

__all__ = ["MoQConfig", "MoQQuantizer", "sim_quantize"]
