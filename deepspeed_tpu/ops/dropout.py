"""Fused counter-hash dropout for activations.

Reference: ``csrc/transformer/dropout_kernels.cu`` — the reference's fused
kernels apply dropout nearly free by folding a Philox draw into the same
pass as the surrounding op. The stock flax path costs real time on TPU:
``jax.random.bernoulli`` lowers threefry2x32 (a long scalar-op chain per
element) plus an fp32 uniform and a select, paid once per dropout site per
microbatch (3 sites/layer on GPT).

This op replaces the draw with the SAME counter-based integer hash the
flash kernel's in-kernel dropout uses (``ops/transformer/flash_attention.
dropout_keep_mask``): one iota + ~5 integer ops + an int compare per
element, all fused by XLA into the neighbouring elementwise chain — the
mask never hits HBM. Statistical quality is the hash's (splitmix-style
avalanche), deterministic given the rng key, decorrelated across sites by
the flax rng path fold.
"""

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def _hash_u32(x: jax.Array) -> jax.Array:
    # splitmix32-style finalizer (same avalanche core as the flash
    # kernel's _hash_u32; duplicated to keep this module pallas-free).
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def hash_dropout(x: jax.Array, rate: float,
                 rng: Optional[jax.Array]) -> jax.Array:
    """Dropout via a counter hash: keep-prob ``1-rate``, scaled by
    ``1/(1-rate)``. ``rng``: a PRNG key (only its bits are consumed)."""
    if rate <= 0.0 or rng is None:
        return x
    kd = jax.random.key_data(rng).astype(jnp.uint32).reshape(-1)
    seed = kd[0] ^ (kd[-1] << jnp.uint32(1))
    idx = jax.lax.iota(jnp.uint32, x.size).reshape(x.shape)
    bits = _hash_u32(idx * jnp.uint32(0x9E3779B9)
                     ^ (seed + jnp.uint32(0x165667B1)))
    # top 24 bits vs integer threshold (shared convention with the flash
    # kernel's dropout_keep_mask: int compare, no uint->float cast)
    thresh = int(float(rate) * (1 << 24))
    keep = (bits >> jnp.uint32(8)).astype(jnp.int32) >= thresh
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def dropout_module(cfg):
    """The model families' dropout selector: :class:`HashDropout` when
    ``cfg.fast_dropout`` (default for the in-tree families — measured
    +19.9% on dropout-on GPT-2, PROFILE.md r4), else ``nn.Dropout``."""
    if getattr(cfg, "fast_dropout", False):
        return HashDropout
    return nn.Dropout


class HashDropout(nn.Module):
    """Drop-in for ``nn.Dropout(rate, deterministic=...)`` backed by
    :func:`hash_dropout`; draws its key from the ``dropout`` rng
    collection (the flax path fold decorrelates sites/layers)."""

    rate: float
    deterministic: bool = True

    @nn.compact
    def __call__(self, x):
        if self.deterministic or self.rate <= 0.0:
            return x
        return hash_dropout(x, self.rate, self.make_rng("dropout"))
