"""Shared machinery for the 1-bit optimizers (OneBitAdam / OneBitLamb).

The engine splits a 1-bit step into two phases. The reference composes its
1-bit optimizers with engine flavors at runtime by switching communication
paths (``deepspeed/runtime/fp16/onebit/adam.py:92-104`` probes the engine
for ``pipeline_enable_backward_allreduce``); here the composition is
structural:

- ``sync_phase`` runs INSIDE the engine's manual ``shard_map`` region
  (axes: the compression axis, the dense ICI-inner data axis on
  hierarchical meshes, plus ``pipe`` under the PipelineEngine) on
  rank-LOCAL gradients. It performs a dense ``pmean`` during warmup and the
  error-compensated 1-bit collective (comm/compressed.py) once frozen —
  gated by ``lax.cond`` on the replicated step counter so each step pays
  exactly ONE collective family.
- ``finish_step`` runs in GSPMD-auto mode: the elementwise optimizer apply.
  ZeRO-1 optimizer-state sharding (the engine's ``opt_specs``) composes
  freely here — XLA inserts the gather/slice collectives implied by the
  sharding mismatch, exactly the placement-policy realisation of ZeRO
  (runtime/zero/partition.py) — because the compressed protocol constrains
  the *sync*, not the state placement.

Error-feedback buffers are per-rank persistent state in a flat, 8·n-aligned
layout (n = compression-axis size). Under pipeline parallelism a param leaf
is pipe-sharded (the stacked-blocks dim), so the buffers are laid out per
LOCAL shard: ``[n, S * pad(local_numel)]`` sharded ``(comp_axis, pipe)``;
``configure_partitioning`` records the manual shard factor per leaf. Inside
the manual region each rank then sees the same ``[1, pad]`` local view
regardless of pipeline composition.
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from deepspeed_tpu.comm.compressed import sync_momentum_compressed
from deepspeed_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS


def _pad_len(numel: int, n: int) -> int:
    align = 8 * n
    return (numel + align - 1) // align * align


class OneBitBase:
    """Common state-layout + sync-phase machinery. Subclasses add their
    moment/apply math (``init``/``state_specs``/``finish_step``) and keep a
    monolithic ``update`` for direct (non-engine) use."""

    needs_local_grads = True

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999),
                 eps: float = 1e-8, weight_decay: float = 0.0,
                 freeze_step: int = 100, mesh=None, axis: str = DATA_AXIS,
                 comm_size: int = None, **_ignored):
        self.lr = float(lr)
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.freeze_step = int(freeze_step)
        self.axis = axis
        self.n = int(comm_size if comm_size is not None
                     else (mesh.shape.get(axis, 1) if mesh is not None else 1))
        self._base_specs = None
        self._mesh_shape = dict(mesh.shape) if mesh is not None else {}
        self._shard_axes: Tuple[str, ...] = (PIPE_AXIS,)

    # -- partition-aware error-buffer layout -------------------------------
    def configure_partitioning(self, base_specs: Any, mesh,
                               shard_axes: Tuple[str, ...] = (PIPE_AXIS,)):
        """Record which MANUAL mesh axes shard each param leaf (the
        pipeline's stacked-blocks dim). Must be called before ``init`` when
        params carry manual shardings; model/sequence axes stay GSPMD-auto
        and are ignored here."""
        self._base_specs = base_specs
        self._mesh_shape = dict(mesh.shape) if mesh is not None else {}
        self._shard_axes = tuple(shard_axes)

    def _flat_with_specs(self, params):
        flat, treedef = jax.tree_util.tree_flatten(params)
        if self._base_specs is None:
            specs = [None] * len(flat)
        else:
            specs = treedef.flatten_up_to(self._base_specs)
        return flat, treedef, specs

    def _leaf_layout(self, p, spec):
        """(manual shard axes, S, pad) for one param leaf: S = product of
        manual-axis sizes sharding it, pad = aligned LOCAL flat length."""
        numel = int(np.prod(p.shape) or 1)
        axes = []
        if spec is not None:
            for entry in tuple(spec):
                parts = entry if isinstance(entry, tuple) else (entry,)
                axes += [a for a in parts if a in self._shard_axes]
        S = 1
        for a in axes:
            S *= self._mesh_shape.get(a, 1)
        if numel % S:
            raise ValueError(
                f"param numel {numel} not divisible by manual shard factor "
                f"{S} (axes {axes})")
        return tuple(axes), S, _pad_len(numel // S, self.n)

    def _init_error_buffers(self, params):
        flat, treedef, specs = self._flat_with_specs(params)
        we, se = [], []
        for p, s in zip(flat, specs):
            _, S, pad = self._leaf_layout(p, s)
            we.append(jnp.zeros((self.n, S * pad), jnp.float32))
            se.append(jnp.zeros((self.n, S * pad // self.n), jnp.float32))
        unflat = lambda leaves: jax.tree_util.tree_unflatten(treedef, leaves)
        return unflat(we), unflat(se)

    def _error_specs(self, params):
        """Leading dim over the compression axis; second dim over the
        manual shard axes (pipe) when the leaf is pipe-sharded."""
        flat, treedef, specs = self._flat_with_specs(params)
        we_s = []
        for p, s in zip(flat, specs):
            axes, S, _ = self._leaf_layout(p, s)
            if S > 1:
                dim1 = axes[0] if len(axes) == 1 else tuple(axes)
                we_s.append(PartitionSpec(self.axis, dim1))
            else:
                we_s.append(PartitionSpec(self.axis))
        spec_tree = jax.tree_util.tree_unflatten(treedef, we_s)
        return spec_tree, spec_tree  # worker and server shard identically

    # -- phase 1: rank-local momentum sync (manual region) -----------------
    def sync_phase(self, grads, m, worker_error, server_error, step):
        """grads are LOCAL (per-rank along the compression axis; per-shard
        along pipe). Returns ``(m_new, g_dense, we_new, se_new)``:
        ``m_new`` is the synchronised momentum (identical across the
        compression axis), ``g_dense`` the densely-averaged gradient during
        warmup (the local gradient — unused downstream — once frozen)."""
        warm = (step + 1) <= self.freeze_step

        def leaf(g, m, we, se):
            g = g.astype(jnp.float32)
            we2d, se2d = we.ndim == 2, se.ndim == 2
            if we2d:
                we = we[0]
            if se2d:
                se = se[0]
            if self.n > 1:
                def warm_branch(g, m, we, se):
                    gd = jax.lax.pmean(g, self.axis)
                    return self.b1 * m + (1 - self.b1) * gd, gd, we, se

                def comp_branch(g, m, we, se):
                    m_local = self.b1 * m + (1 - self.b1) * g
                    m_new, we_new, se_new = sync_momentum_compressed(
                        m_local, we, se, self.axis, self.n)
                    return m_new, g, we_new, se_new

                m_new, gd, we_new, se_new = jax.lax.cond(
                    warm, warm_branch, comp_branch, g, m, we, se)
            else:
                m_new = self.b1 * m + (1 - self.b1) * g
                gd, we_new, se_new = g, we, se
            if we2d:
                we_new = we_new[None]
            if se2d:
                se_new = se_new[None]
            return m_new, gd, we_new, se_new

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        out = [leaf(*args) for args in zip(
            flat_g,
            treedef.flatten_up_to(m),
            treedef.flatten_up_to(worker_error),
            treedef.flatten_up_to(server_error))]
        unflat = lambda i: jax.tree_util.tree_unflatten(
            treedef, [o[i] for o in out])
        return unflat(0), unflat(1), unflat(2), unflat(3)
