"""Communication-efficient 1-bit optimizers."""

from deepspeed_tpu.ops.onebit.adam import OneBitAdam, OneBitState
from deepspeed_tpu.ops.onebit.lamb import OneBitLamb

__all__ = ["OneBitAdam", "OneBitLamb", "OneBitState"]
