"""1-bit LAMB (reference ``deepspeed/runtime/fp16/onebit/lamb.py``): the
compressed-momentum scheme of 1-bit Adam plus LAMB's layerwise trust-ratio
scaling. During warmup it is plain LAMB and the per-param trust ratio is
recorded every step; at ``freeze_step`` the variance AND the last recorded
trust ratios freeze, and the compressed phase applies those frozen scaling
coefficients — only 1-bit momentum crosses the wire (the reference likewise
freezes per-layer ``scaling_coeff`` at the boundary rather than recomputing
trust from sign-compressed momentum).

Split into ``sync_phase`` (manual region, shared with OneBitAdam via
ops/onebit/common.py) and ``finish_step`` (GSPMD-auto apply, where the
trust-ratio norms and ZeRO-1 state sharding are XLA's problem).
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from deepspeed_tpu.ops.onebit.common import OneBitBase
from deepspeed_tpu.parallel.mesh import DATA_AXIS


class LambState(NamedTuple):
    step: jax.Array
    m: Any              # first moment (per-param tree)
    v: Any              # second moment (frozen after warmup)
    worker_error: Any   # flat error-feedback per param [n, S·pad]
    server_error: Any   # flat server error per param [n, S·pad / n]
    scale: Any          # per-param trust ratio (frozen after warmup)


class OneBitLamb(OneBitBase):
    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.0, freeze_step: int = 100,
                 max_trust_ratio: float = 10.0, mesh=None,
                 axis: str = DATA_AXIS, comm_size: int = None, **_ignored):
        super().__init__(lr=lr, betas=betas, eps=eps,
                         weight_decay=weight_decay, freeze_step=freeze_step,
                         mesh=mesh, axis=axis, comm_size=comm_size)
        self.max_trust = float(max_trust_ratio)

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        we, se = self._init_error_buffers(params)
        return LambState(
            step=jnp.zeros((), jnp.int32),
            m=jax.tree_util.tree_map(zeros, params),
            v=jax.tree_util.tree_map(zeros, params),
            worker_error=we, server_error=se,
            scale=jax.tree_util.tree_map(
                lambda _: jnp.ones((), jnp.float32), params))

    def state_specs(self, params, opt_specs=None):
        rep = jax.tree_util.tree_map(lambda _: PartitionSpec(), params)
        mv = opt_specs if opt_specs is not None else rep
        we_s, se_s = self._error_specs(params)
        return LambState(step=PartitionSpec(), m=mv, v=mv,
                         worker_error=we_s, server_error=se_s, scale=rep)

    # ------------------------------------------------------------------
    def finish_step(self, params, state: LambState, m_new, g_dense,
                    we_new, se_new, lr=None):
        lr = self.lr if lr is None else lr
        step = state.step + 1
        t = step.astype(jnp.float32)
        warm = step <= self.freeze_step
        bc1 = 1 - self.b1 ** t
        bc2 = 1 - self.b2 ** t

        def leaf(p, m, gd, v, sc):
            gd = gd.astype(jnp.float32)
            v_new = jnp.where(warm, self.b2 * v + (1 - self.b2) * gd**2, v)
            upd = (m / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p
            w_norm = jnp.linalg.norm(p.reshape(-1))
            u_norm = jnp.linalg.norm(upd.reshape(-1))
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, 0.0, self.max_trust), 1.0)
            sc_new = jnp.where(warm, trust, sc)
            return p - lr * sc_new * upd, v_new, sc_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        out = [leaf(*args) for args in zip(
            flat_p,
            treedef.flatten_up_to(m_new),
            treedef.flatten_up_to(g_dense),
            treedef.flatten_up_to(state.v),
            treedef.flatten_up_to(state.scale))]
        unflat = lambda i: jax.tree_util.tree_unflatten(
            treedef, [o[i] for o in out])
        new_state = LambState(step=step, m=m_new, v=unflat(1),
                              worker_error=we_new, server_error=se_new,
                              scale=unflat(2))
        return unflat(0), new_state

    def update(self, grads, state: LambState, params, lr=None):
        m_new, gd, we_new, se_new = self.sync_phase(
            grads, state.m, state.worker_error, state.server_error,
            state.step)
        return self.finish_step(params, state, m_new, gd, we_new, se_new, lr)
