"""1-bit LAMB (reference ``deepspeed/runtime/fp16/onebit/lamb.py``): the
compressed-momentum scheme of 1-bit Adam plus LAMB's layerwise trust-ratio
scaling. During warmup it is plain LAMB; in the compressed phase the frozen
variance and the scaling factors learned during warmup keep the layerwise
adaptivity while only 1-bit momentum crosses the wire."""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm.compressed import compressed_allreduce_local
from deepspeed_tpu.ops.onebit.adam import OneBitState, _pad_len
from deepspeed_tpu.parallel.mesh import DATA_AXIS


class OneBitLamb:
    needs_local_grads = True

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.0, freeze_step: int = 100,
                 max_trust_ratio: float = 10.0, mesh=None,
                 axis: str = DATA_AXIS, comm_size: int = None, **_ignored):
        self.lr = float(lr)
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.freeze_step = int(freeze_step)
        self.max_trust = float(max_trust_ratio)
        self.axis = axis
        self.n = int(comm_size if comm_size is not None
                     else (mesh.shape.get(axis, 1) if mesh is not None else 1))

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return OneBitState(
            step=jnp.zeros((), jnp.int32),
            m=jax.tree_util.tree_map(zeros, params),
            v=jax.tree_util.tree_map(zeros, params),
            worker_error=jax.tree_util.tree_map(
                lambda p: jnp.zeros(
                    (self.n, _pad_len(int(np.prod(p.shape) or 1), self.n)),
                    jnp.float32), params),
            server_error=jax.tree_util.tree_map(
                lambda p: jnp.zeros(
                    (self.n, _pad_len(int(np.prod(p.shape) or 1), self.n)
                     // self.n), jnp.float32), params))

    def state_specs(self, params):
        from jax.sharding import PartitionSpec as P

        rep = jax.tree_util.tree_map(lambda _: P(), params)
        shard0 = jax.tree_util.tree_map(lambda _: P(self.axis), params)
        return OneBitState(step=P(), m=rep, v=rep,
                           worker_error=shard0, server_error=shard0)

    def update(self, grads, state: OneBitState, params, lr=None):
        lr = self.lr if lr is None else lr
        step = state.step + 1
        t = step.astype(jnp.float32)
        warm = step <= self.freeze_step

        def leaf(p, g, m, v, we, se):
            g = g.astype(jnp.float32)
            numel = int(np.prod(p.shape) or 1)
            we2d, se2d = we.ndim == 2, se.ndim == 2
            if we2d:
                we = we[0]
            if se2d:
                se = se[0]
            g_dense = jax.lax.pmean(g, self.axis) if self.n > 1 else g
            m_warm = self.b1 * m + (1 - self.b1) * g_dense
            v_new = jnp.where(warm, self.b2 * v + (1 - self.b2) * g_dense**2, v)
            if self.n > 1:
                m_local = self.b1 * m + (1 - self.b1) * g
                flat = jnp.zeros(we.shape[0], jnp.float32).at[:numel].set(
                    m_local.reshape(-1))
                synced, we_new, se_new = compressed_allreduce_local(
                    flat, we, se, self.axis, self.n)
                m_comp = synced[:numel].reshape(p.shape)
            else:
                m_comp, we_new, se_new = m_warm, we, se
            m_new = jnp.where(warm, m_warm, m_comp)
            we_new = jnp.where(warm, we, we_new)
            se_new = jnp.where(warm, se, se_new)
            if we2d:
                we_new = we_new[None]
            if se2d:
                se_new = se_new[None]
            bc1 = 1 - self.b1 ** t
            bc2 = 1 - self.b2 ** t
            upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p
            w_norm = jnp.linalg.norm(p.reshape(-1))
            u_norm = jnp.linalg.norm(upd.reshape(-1))
            trust = jnp.where((w_norm > 0) & (u_norm > 0),
                              jnp.clip(w_norm / u_norm, 0.0, self.max_trust),
                              1.0)
            return p - lr * trust * upd, m_new, v_new, we_new, se_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        out = [leaf(*args) for args in zip(
            flat_p,
            treedef.flatten_up_to(grads),
            treedef.flatten_up_to(state.m),
            treedef.flatten_up_to(state.v),
            treedef.flatten_up_to(state.worker_error),
            treedef.flatten_up_to(state.server_error))]
        unflat = lambda i: jax.tree_util.tree_unflatten(
            treedef, [o[i] for o in out])
        new_state = OneBitState(step=step, m=unflat(1), v=unflat(2),
                                worker_error=unflat(3), server_error=unflat(4))
        return unflat(0), new_state
