"""1-bit LAMB (reference ``deepspeed/runtime/fp16/onebit/lamb.py``): the
compressed-momentum scheme of 1-bit Adam plus LAMB's layerwise trust-ratio
scaling. During warmup it is plain LAMB and the per-param trust ratio is
recorded every step; at ``freeze_step`` the variance AND the last recorded
trust ratios freeze, and the compressed phase applies those frozen scaling
coefficients — only 1-bit momentum crosses the wire (the reference likewise
freezes per-layer ``scaling_coeff`` at the boundary rather than recomputing
trust from sign-compressed momentum).

The two phases are gated with ``lax.cond`` on the replicated step counter so
each step pays exactly one collective family (dense ``pmean`` in warmup, the
1-bit ``all_to_all``+``allgather`` afterwards).
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm.compressed import sync_momentum_compressed
from deepspeed_tpu.ops.onebit.adam import _pad_len
from deepspeed_tpu.parallel.mesh import DATA_AXIS


class LambState(NamedTuple):
    step: jax.Array
    m: Any              # first moment (per-param tree)
    v: Any              # second moment (frozen after warmup)
    worker_error: Any   # flat error-feedback per param [padded numel]
    server_error: Any   # flat server error per param [padded numel / n]
    scale: Any          # per-param trust ratio (frozen after warmup)


class OneBitLamb:
    needs_local_grads = True

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.0, freeze_step: int = 100,
                 max_trust_ratio: float = 10.0, mesh=None,
                 axis: str = DATA_AXIS, comm_size: int = None, **_ignored):
        self.lr = float(lr)
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.freeze_step = int(freeze_step)
        self.max_trust = float(max_trust_ratio)
        self.axis = axis
        self.n = int(comm_size if comm_size is not None
                     else (mesh.shape.get(axis, 1) if mesh is not None else 1))

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return LambState(
            step=jnp.zeros((), jnp.int32),
            m=jax.tree_util.tree_map(zeros, params),
            v=jax.tree_util.tree_map(zeros, params),
            worker_error=jax.tree_util.tree_map(
                lambda p: jnp.zeros(
                    (self.n, _pad_len(int(np.prod(p.shape) or 1), self.n)),
                    jnp.float32), params),
            server_error=jax.tree_util.tree_map(
                lambda p: jnp.zeros(
                    (self.n, _pad_len(int(np.prod(p.shape) or 1), self.n)
                     // self.n), jnp.float32), params),
            scale=jax.tree_util.tree_map(
                lambda _: jnp.ones((), jnp.float32), params))

    def state_specs(self, params):
        from jax.sharding import PartitionSpec as P

        rep = jax.tree_util.tree_map(lambda _: P(), params)
        shard0 = jax.tree_util.tree_map(lambda _: P(self.axis), params)
        return LambState(step=P(), m=rep, v=rep,
                         worker_error=shard0, server_error=shard0, scale=rep)

    def update(self, grads, state: LambState, params, lr=None):
        lr = self.lr if lr is None else lr
        step = state.step + 1
        t = step.astype(jnp.float32)
        warm = step <= self.freeze_step

        def leaf(p, g, m, v, we, se, sc):
            g = g.astype(jnp.float32)
            we2d, se2d = we.ndim == 2, se.ndim == 2
            if we2d:
                we = we[0]
            if se2d:
                se = se[0]
            bc1 = 1 - self.b1 ** t
            bc2 = 1 - self.b2 ** t

            def trust_of(pp, upd):
                w_norm = jnp.linalg.norm(pp.reshape(-1))
                u_norm = jnp.linalg.norm(upd.reshape(-1))
                return jnp.where(
                    (w_norm > 0) & (u_norm > 0),
                    jnp.clip(w_norm / u_norm, 0.0, self.max_trust), 1.0)

            def finish(m_new, v_new, we_new, se_new, sc_new):
                upd = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
                if self.weight_decay:
                    upd = upd + self.weight_decay * p
                return upd, m_new, v_new, we_new, se_new, sc_new

            if self.n > 1:
                def warm_branch(g, m, v, we, se, sc):
                    g_dense = jax.lax.pmean(g, self.axis)
                    m_new = self.b1 * m + (1 - self.b1) * g_dense
                    v_new = self.b2 * v + (1 - self.b2) * g_dense**2
                    upd, *rest = finish(m_new, v_new, we, se, sc)
                    trust = trust_of(p, upd)
                    return (p - lr * trust * upd, *rest[:4], trust)

                def comp_branch(g, m, v, we, se, sc):
                    m_local = self.b1 * m + (1 - self.b1) * g
                    m_new, we_new, se_new = sync_momentum_compressed(
                        m_local, we, se, self.axis, self.n)
                    upd, *rest = finish(m_new, v, we_new, se_new, sc)
                    return (p - lr * sc * upd, *rest[:4], sc)

                p_new, m_new, v_new, we_new, se_new, sc_new = jax.lax.cond(
                    warm, warm_branch, comp_branch, g, m, v, we, se, sc)
            else:
                m_new = self.b1 * m + (1 - self.b1) * g
                v_new = jnp.where(
                    warm, self.b2 * v + (1 - self.b2) * g**2, v)
                upd, _, _, we_new, se_new, _ = finish(m_new, v_new, we, se, sc)
                trust = trust_of(p, upd)
                sc_new = jnp.where(warm, trust, sc)
                p_new = p - lr * sc_new * upd
            if we2d:
                we_new = we_new[None]
            if se2d:
                se_new = se_new[None]
            return p_new, m_new, v_new, we_new, se_new, sc_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        out = [leaf(*args) for args in zip(
            flat_p,
            treedef.flatten_up_to(grads),
            treedef.flatten_up_to(state.m),
            treedef.flatten_up_to(state.v),
            treedef.flatten_up_to(state.worker_error),
            treedef.flatten_up_to(state.server_error),
            treedef.flatten_up_to(state.scale))]
        unflat = lambda i: jax.tree_util.tree_unflatten(
            treedef, [o[i] for o in out])
        new_state = LambState(step=step, m=unflat(1), v=unflat(2),
                              worker_error=unflat(3), server_error=unflat(4),
                              scale=unflat(5))
        return unflat(0), new_state
