"""1-bit Adam — communication-efficient Adam (reference
``deepspeed/runtime/fp16/onebit/adam.py:14``).

Two phases, as in the reference:
- **warmup** (step < freeze_step): ordinary Adam on densely-averaged
  gradients — variance and momentum both update.
- **compressed** (step >= freeze_step): the variance is FROZEN; the
  *momentum* is synchronised with the error-compensated 1-bit collective
  (comm/compressed.py) instead of any dense gradient allreduce.

Engine contract: ``needs_local_grads = True`` — the engine runs
``sync_phase`` inside a shard_map manual over the compression axis (plus
``pipe`` under the PipelineEngine) on this rank's LOCAL (unreduced)
gradients, then ``finish_step`` in GSPMD-auto mode where ZeRO-0/1 optimizer
-state placement composes (see ops/onebit/common.py for the design). The
reference similarly picks its comm path per engine flavor
(onebit/adam.py:92-104).

State layout: moments per param (placed by the engine's ZeRO ``opt_specs``);
error-feedback buffers per param in a flat, 8·n-aligned, shard-aware
representation (common.py).
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from deepspeed_tpu.ops.onebit.common import OneBitBase, _pad_len  # noqa: F401 (_pad_len re-exported for lamb/tests)


class OneBitState(NamedTuple):
    step: jax.Array
    m: Any              # first moment (per-param tree)
    v: Any              # second moment (frozen after warmup)
    worker_error: Any   # flat error-feedback per param [n, S·pad]
    server_error: Any   # flat server error per param [n, S·pad / n]


class OneBitAdam(OneBitBase):
    """Functional optimizer. ``sync_phase`` must run inside a manual
    shard_map (the engine arranges this when ``needs_local_grads``);
    ``finish_step``/``update`` are elementwise."""

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        we, se = self._init_error_buffers(params)
        return OneBitState(step=jnp.zeros((), jnp.int32),
                           m=jax.tree_util.tree_map(zeros, params),
                           v=jax.tree_util.tree_map(zeros, params),
                           worker_error=we, server_error=se)

    def state_specs(self, params, opt_specs=None):
        """Placement: moments follow the engine's ZeRO opt-state specs
        (replicated at stage 0, data-sharded at stage 1, pipe-composed under
        the PipelineEngine); error buffers shard over (compression axis,
        pipe)."""
        rep = jax.tree_util.tree_map(lambda _: PartitionSpec(), params)
        mv = opt_specs if opt_specs is not None else rep
        we_s, se_s = self._error_specs(params)
        return OneBitState(step=PartitionSpec(), m=mv, v=mv,
                           worker_error=we_s, server_error=se_s)

    # ------------------------------------------------------------------
    def finish_step(self, params, state: OneBitState, m_new, g_dense,
                    we_new, se_new, lr=None):
        """GSPMD-auto phase: variance update (warmup only) + bias-corrected
        Adam apply. ``m_new``/``g_dense`` come from ``sync_phase``."""
        lr = self.lr if lr is None else lr
        step = state.step + 1
        t = step.astype(jnp.float32)
        warm = step <= self.freeze_step
        bc1 = 1 - self.b1 ** t
        bc2 = 1 - self.b2 ** t

        def leaf(p, m, gd, v):
            gd = gd.astype(jnp.float32)
            v_new = jnp.where(warm, self.b2 * v + (1 - self.b2) * gd**2, v)
            upd = (m / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p
            return p - lr * upd, v_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        out = [leaf(*args) for args in zip(
            flat_p,
            treedef.flatten_up_to(m_new),
            treedef.flatten_up_to(g_dense),
            treedef.flatten_up_to(state.v))]
        unflat = lambda i: jax.tree_util.tree_unflatten(
            treedef, [o[i] for o in out])
        new_state = OneBitState(step=step, m=m_new, v=unflat(1),
                                worker_error=we_new, server_error=se_new)
        return unflat(0), new_state

    def update(self, grads, state: OneBitState, params, lr=None):
        """Monolithic step (sync + apply) for direct use inside a manual
        region; grads are LOCAL (per-rank)."""
        m_new, gd, we_new, se_new = self.sync_phase(
            grads, state.m, state.worker_error, state.server_error,
            state.step)
        return self.finish_step(params, state, m_new, gd, we_new, se_new, lr)
