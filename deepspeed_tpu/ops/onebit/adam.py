"""1-bit Adam — communication-efficient Adam (reference
``deepspeed/runtime/fp16/onebit/adam.py:14``).

Two phases, as in the reference:
- **warmup** (step < freeze_step): ordinary Adam on densely-averaged
  gradients — variance and momentum both update.
- **compressed** (step >= freeze_step): the variance is FROZEN; the
  *momentum* is synchronised with the error-compensated 1-bit collective
  (comm/compressed.py) instead of any dense gradient allreduce.

Engine contract: this optimizer sets ``needs_local_grads = True`` — the
engine then runs the whole update inside a shard_map manual over ``data``
and hands it this rank's LOCAL (unreduced) gradients; during warmup the
optimizer densely ``pmean``s them itself. Params/moments are replicated
across data (ZeRO-0; the reference similarly bypasses ZeRO here).

State layout: moments per param; error feedback buffers per param in a
flat, 8·n-aligned representation.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm.compressed import sync_momentum_compressed
from deepspeed_tpu.parallel.mesh import DATA_AXIS


class OneBitState(NamedTuple):
    step: jax.Array
    m: Any              # first moment (per-param tree)
    v: Any              # second moment (frozen after warmup)
    worker_error: Any   # flat error-feedback per param [padded numel]
    server_error: Any   # flat server error per param [padded numel / n]


def _pad_len(numel: int, n: int) -> int:
    align = 8 * n
    return (numel + align - 1) // align * align


class OneBitAdam:
    """Functional optimizer. ``update`` must run inside a data-manual
    shard_map (the engine arranges this when ``needs_local_grads``)."""

    needs_local_grads = True

    def __init__(self, lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, freeze_step: int = 100,
                 mesh=None, axis: str = DATA_AXIS, comm_size: int = None,
                 **_ignored):
        self.lr = float(lr)
        self.b1, self.b2 = float(betas[0]), float(betas[1])
        self.eps = float(eps)
        self.weight_decay = float(weight_decay)
        self.freeze_step = int(freeze_step)
        self.axis = axis
        self.n = int(comm_size if comm_size is not None
                     else (mesh.shape.get(axis, 1) if mesh is not None else 1))

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        m = jax.tree_util.tree_map(zeros, params)
        v = jax.tree_util.tree_map(zeros, params)
        # Error buffers are PER-RANK state: stored [n, ...] with the leading
        # dim sharded over data so each rank keeps its own slice across steps.
        we = jax.tree_util.tree_map(
            lambda p: jnp.zeros(
                (self.n, _pad_len(int(np.prod(p.shape) or 1), self.n)),
                jnp.float32), params)
        se = jax.tree_util.tree_map(
            lambda p: jnp.zeros(
                (self.n, _pad_len(int(np.prod(p.shape) or 1), self.n)
                 // self.n), jnp.float32), params)
        return OneBitState(step=jnp.zeros((), jnp.int32), m=m, v=v,
                           worker_error=we, server_error=se)

    def state_specs(self, params):
        """Placement: moments replicated, error buffers sharded over data
        (consumed by the engine's local-grad shard_map path)."""
        from jax.sharding import PartitionSpec as P

        rep = jax.tree_util.tree_map(lambda _: P(), params)
        shard0 = jax.tree_util.tree_map(lambda _: P(self.axis), params)
        return OneBitState(step=P(), m=rep, v=rep,
                           worker_error=shard0, server_error=shard0)

    # ------------------------------------------------------------------
    def update(self, grads, state: OneBitState, params, lr=None):
        """grads are LOCAL (per-rank); runs inside data-manual shard_map."""
        lr = self.lr if lr is None else lr
        step = state.step + 1
        t = step.astype(jnp.float32)
        warm = step <= self.freeze_step

        def leaf(p, g, m, v, we, se):
            g = g.astype(jnp.float32)
            we2d, se2d = we.ndim == 2, se.ndim == 2
            if we2d:
                we = we[0]
            if se2d:
                se = se[0]
            if self.n > 1:
                # Phases gated with lax.cond on the (replicated) step counter
                # so each step pays exactly ONE collective: dense pmean during
                # warmup, the 1-bit all_to_all+allgather once frozen — the
                # bandwidth saving that is the point of 1-bit optimizers
                # (reference onebit/adam.py: freeze_step switches comm paths).
                def warm_branch(g, m, v, we, se):
                    g_dense = jax.lax.pmean(g, self.axis)
                    m_new = self.b1 * m + (1 - self.b1) * g_dense
                    v_new = self.b2 * v + (1 - self.b2) * g_dense**2
                    return m_new, v_new, we, se

                def comp_branch(g, m, v, we, se):
                    m_local = self.b1 * m + (1 - self.b1) * g
                    m_new, we_new, se_new = sync_momentum_compressed(
                        m_local, we, se, self.axis, self.n)
                    return m_new, v, we_new, se_new

                m_new, v_new, we_new, se_new = jax.lax.cond(
                    warm, warm_branch, comp_branch, g, m, v, we, se)
            else:
                m_new = self.b1 * m + (1 - self.b1) * g
                v_new = jnp.where(
                    warm, self.b2 * v + (1 - self.b2) * g**2, v)
                we_new, se_new = we, se
            if we2d:
                we_new = we_new[None]
            if se2d:
                se_new = se_new[None]
            # --- Adam step with bias correction ---------------------------
            bc1 = 1 - self.b1 ** t
            bc2 = 1 - self.b2 ** t
            denom = jnp.sqrt(v_new / bc2) + self.eps
            upd = (m_new / bc1) / denom
            if self.weight_decay:
                upd = upd + self.weight_decay * p
            return p - lr * upd, m_new, v_new, we_new, se_new

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.m)
        flat_v = treedef.flatten_up_to(state.v)
        flat_we = treedef.flatten_up_to(state.worker_error)
        flat_se = treedef.flatten_up_to(state.server_error)
        out = [leaf(*args) for args in
               zip(flat_p, flat_g, flat_m, flat_v, flat_we, flat_se)]
        unflat = lambda i: jax.tree_util.tree_unflatten(
            treedef, [o[i] for o in out])
        new_state = OneBitState(step=step, m=unflat(1), v=unflat(2),
                                worker_error=unflat(3), server_error=unflat(4))
        return unflat(0), new_state
