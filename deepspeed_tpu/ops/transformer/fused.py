"""Fused LayerNorm + projection — the TPU half of the reference's fused
transformer block.

The reference's defining kernel is one fused body per layer: LN, QKV
projection, attention, bias/GELU/dropout all execute without HBM
round-trips between them (``csrc/transformer/ds_transformer_cuda.cpp:147``
forward, ``:295`` backward, with ``normalize_kernels.cu`` and
``gelu_kernels.cu`` folded in). XLA already fuses elementwise epilogues
into matmuls, but it cannot fuse a row *reduction* (the LayerNorm
mean/variance) into a matmul operand — so every pre-LN site pays a
[tokens, hidden] round-trip to HBM for the normalized activations in the
forward AND for their gradient in the backward. At GPT-2 bench shapes
that is ~25 MB × 2 sites × 12 layers × fwd+bwd per microbatch.

``ln_matmul`` fuses ``y = act(LN(x) @ W + b)`` into one Pallas kernel:
the normalized rows live only in VMEM. The backward is a second kernel
that recomputes the (cheap, VPU) LayerNorm from ``x`` and produces all
five gradients in a single sweep over the row blocks, accumulating
``dW``/``db``/``dgamma``/``dbeta`` in VMEM-resident fp32 blocks across
the sequential TPU grid.

Matmul dtype discipline matches the unfused flax path so the fused op is
trajectory-compatible: LN in fp32, normalized output cast to the weight
dtype for the MXU dot, fp32 accumulation (``preferred_element_type``),
output cast back to the activation dtype.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.flash_attention import (_use_interpret,
                                                           _vmem_params)

DEFAULT_BLOCK_ROWS = 512
_SQRT_2_OVER_PI = 0.7978845608028654


def _gelu_tanh(x):
    """tanh-approximate GELU, fp32 — bit-matches ``nn.gelu(approximate=
    True)`` evaluated in fp32."""
    return 0.5 * x * (1.0 + jnp.tanh(_SQRT_2_OVER_PI
                                     * (x + 0.044715 * x * x * x)))


def _gelu_tanh_grad(x):
    """d/dx of the tanh-approximate GELU."""
    u = _SQRT_2_OVER_PI * (x + 0.044715 * x * x * x)
    t = jnp.tanh(u)
    du = _SQRT_2_OVER_PI * (1.0 + 3.0 * 0.044715 * x * x)
    return 0.5 * (1.0 + t) + 0.5 * x * (1.0 - t * t) * du


def _layernorm_rows(xf, gamma, beta, eps):
    """fp32 LayerNorm over the last dim; returns (ln, xhat, rstd)."""
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    return xhat * gamma + beta, xhat, rstd


def _fwd_kernel(x_ref, g_ref, b_ref, w_ref, bias_ref, o_ref, *,
                eps: float, activation: Optional[str]):
    xf = x_ref[...].astype(jnp.float32)
    ln, _, _ = _layernorm_rows(xf, g_ref[0].astype(jnp.float32),
                               b_ref[0].astype(jnp.float32), eps)
    y = jnp.dot(ln.astype(w_ref.dtype), w_ref[...],
                preferred_element_type=jnp.float32)
    y = y + bias_ref[0].astype(jnp.float32)
    if activation == "gelu":
        y = _gelu_tanh(y)
    o_ref[...] = y.astype(o_ref.dtype)


def _bwd_kernel(x_ref, g_ref, b_ref, w_ref, bias_ref, dy_ref,
                dx_ref, dw_ref, dbias_ref, dg_ref, db_ref, *,
                eps: float, activation: Optional[str]):
    step = pl.program_id(0)
    xf = x_ref[...].astype(jnp.float32)
    gamma = g_ref[0].astype(jnp.float32)
    ln, xhat, rstd = _layernorm_rows(xf, gamma,
                                     b_ref[0].astype(jnp.float32), eps)
    ln_c = ln.astype(w_ref.dtype)
    dy = dy_ref[...].astype(jnp.float32)
    if activation == "gelu":
        pre = jnp.dot(ln_c, w_ref[...], preferred_element_type=jnp.float32)
        pre = pre + bias_ref[0].astype(jnp.float32)
        dy = dy * _gelu_tanh_grad(pre)
    dy_c = dy.astype(w_ref.dtype)

    dw = jax.lax.dot_general(ln_c, dy_c, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dbias = jnp.sum(dy, axis=0, keepdims=True)
    dln = jax.lax.dot_general(dy_c, w_ref[...], (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    dg = jnp.sum(dln * xhat, axis=0, keepdims=True)
    db = jnp.sum(dln, axis=0, keepdims=True)

    dxhat = dln * gamma
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (dxhat - m1 - xhat * m2)).astype(dx_ref.dtype)

    @pl.when(step == 0)
    def _init():
        dw_ref[...] = dw
        dbias_ref[...] = dbias
        dg_ref[...] = dg
        db_ref[...] = db

    @pl.when(step != 0)
    def _acc():
        dw_ref[...] += dw
        dbias_ref[...] += dbias
        dg_ref[...] += dg
        db_ref[...] += db


def _fit_rows(block: int, n: int) -> int:
    """Largest multiple-of-8 row count <= block dividing n (sublane
    granularity); 0 if none exists."""
    block = min(block, n)
    while block >= 8 and (n % block or block % 8):
        block -= 8
    return block if block >= 8 and n % block == 0 else 0


def _run_fwd(x, gamma, beta, w, bias, eps, activation, block_rows,
             interpret):
    n, d = x.shape
    f = w.shape[1]
    bn = _fit_rows(block_rows, n)
    kernel = functools.partial(_fwd_kernel, eps=eps, activation=activation)
    return pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((d, f), lambda i: (0, 0)),
            pl.BlockSpec((1, f), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, f), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, f), x.dtype),
        interpret=interpret,
        compiler_params=_vmem_params(
            d * f * w.dtype.itemsize + bn * d * x.dtype.itemsize
            + 2 * bn * f * 4 + bn * d * 4),
    )(x, gamma[None], beta[None], w, bias[None])


def _run_bwd(x, gamma, beta, w, bias, dy, eps, activation, block_rows,
             interpret):
    n, d = x.shape
    f = w.shape[1]
    bn = _fit_rows(block_rows, n)
    kernel = functools.partial(_bwd_kernel, eps=eps, activation=activation)
    dx, dw, dbias, dg, db = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((d, f), lambda i: (0, 0)),
            pl.BlockSpec((1, f), lambda i: (0, 0)),
            pl.BlockSpec((bn, f), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bn, d), lambda i: (i, 0)),
            pl.BlockSpec((d, f), lambda i: (0, 0)),
            pl.BlockSpec((1, f), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, d), x.dtype),
            jax.ShapeDtypeStruct((d, f), jnp.float32),
            jax.ShapeDtypeStruct((1, f), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_vmem_params(
            2 * d * f * 4 + 2 * bn * (d + f) * 4 + 2 * (d + f) * 4),
    )(x, gamma[None], beta[None], w, bias[None], dy)
    return dx, dw, dbias[0], dg[0], db[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _ln_matmul(x, gamma, beta, w, bias, eps, activation, block_rows,
               interpret):
    return _run_fwd(x, gamma, beta, w, bias, eps, activation, block_rows,
                    interpret)


def _ln_matmul_fwd(x, gamma, beta, w, bias, eps, activation, block_rows,
                   interpret):
    out = _run_fwd(x, gamma, beta, w, bias, eps, activation, block_rows,
                   interpret)
    return out, (x, gamma, beta, w, bias)


def _ln_matmul_bwd(eps, activation, block_rows, interpret, res, dy):
    x, gamma, beta, w, bias = res
    dx, dw, dbias, dg, db = _run_bwd(x, gamma, beta, w, bias, dy, eps,
                                     activation, block_rows, interpret)
    return (dx, dg.astype(gamma.dtype), db.astype(beta.dtype),
            dw.astype(w.dtype), dbias.astype(bias.dtype))


_ln_matmul.defvjp(_ln_matmul_fwd, _ln_matmul_bwd)


def ln_matmul_reference(x, gamma, beta, w, bias, *, eps: float = 1e-5,
                        activation: Optional[str] = None):
    """jnp oracle with the exact dtype discipline of the kernel (and of the
    unfused flax path): fp32 LN, weight-dtype MXU dot, fp32 accumulate."""
    xf = x.astype(jnp.float32)
    ln, _, _ = _layernorm_rows(xf, gamma.astype(jnp.float32),
                               beta.astype(jnp.float32), eps)
    y = jnp.dot(ln.astype(w.dtype), w, preferred_element_type=jnp.float32)
    y = y + bias.astype(jnp.float32)
    if activation == "gelu":
        y = _gelu_tanh(y)
    return y.astype(x.dtype)


def ln_matmul_ok(n: int, d: int, f: int,
                 block_rows: int = DEFAULT_BLOCK_ROWS) -> bool:
    """Shape gate for the fused path: lane-aligned hidden/output dims and a
    viable row block (mirrors the flash kernel's dispatch gating)."""
    return (d % 128 == 0 and f % 128 == 0
            and _fit_rows(block_rows, n) >= 128)


def ln_matmul(x: jax.Array, gamma: jax.Array, beta: jax.Array,
              w: jax.Array, bias: jax.Array, *, eps: float = 1e-5,
              activation: Optional[str] = None,
              block_rows: int = DEFAULT_BLOCK_ROWS,
              interpret: Optional[bool] = None) -> jax.Array:
    """``act(LayerNorm(x; gamma, beta) @ w + bias)`` without the LN
    round-trip. ``x``: [..., D] (leading dims flattened internally);
    ``w``: [D, F]; ``activation``: None or "gelu".

    Reference: csrc/transformer/ds_transformer_cuda.cpp:147 (the fused
    LN→QKV prologue) and gelu_kernels.cu (the fused bias+GELU epilogue).
    """
    if activation not in (None, "gelu"):
        raise ValueError(f"unknown activation {activation!r}")
    lead = x.shape[:-1]
    d = x.shape[-1]
    f = w.shape[1]
    n = 1
    for s in lead:
        n *= s
    if d % 128 or f % 128 or _fit_rows(block_rows, n) == 0:
        raise ValueError(f"shapes (n={n}, d={d}, f={f}) not tileable with "
                         f"block_rows={block_rows} — gate with "
                         "ln_matmul_ok()")
    interpret = _use_interpret() if interpret is None else interpret
    out = _ln_matmul(x.reshape(n, d), gamma, beta, w, bias, float(eps),
                     activation, block_rows, interpret)
    return out.reshape(*lead, f)


# ---------------------------------------------------------------------------
# Shadow parameter modules
# ---------------------------------------------------------------------------
# Declare parameters with the exact names/shapes/initializers of
# ``nn.LayerNorm`` / ``nn.Dense`` WITHOUT applying the op, so a model can
# route through :func:`ln_matmul` while keeping its checkpointed parameter
# tree (and TP partition-rule regexes) byte-identical to the unfused
# build. flax folds param RNG over the module path, not declaration
# order, so initial values are bit-identical too.

import flax.linen as nn  # noqa: E402  (kernels above stay flax-free)


class LNParams(nn.Module):
    """``nn.LayerNorm``'s parameter tree ({scale, bias}), params only."""

    features: int

    @nn.compact
    def __call__(self):
        scale = self.param("scale", nn.initializers.ones,
                           (self.features,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        return scale, bias


class DenseParams(nn.Module):
    """``nn.Dense``'s parameter tree ({kernel, bias}), params only."""

    in_features: int
    features: int

    @nn.compact
    def __call__(self):
        kernel = self.param("kernel", nn.linear.default_kernel_init,
                            (self.in_features, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        return kernel, bias
