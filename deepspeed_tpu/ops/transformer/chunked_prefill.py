"""Ragged chunked-prefill attention — Pallas TPU kernel, kernel tier
round 2 for the serving hot loop (Sarathi-style chunked prefill,
arXiv 2308.16369).

The bucketed serving path compiles one prefill program per bucket (plus
tail variants) and a capped-gather ladder for decode — a whole family of
programs whose cold compiles land inside TTFT under bursty traffic. This
kernel collapses all of it into ONE program per engine step: the batch is
a flat **ragged token batch** ``[T]`` mixing decode tokens (one per
running sequence) with prefill *chunks* of admitted prompts, bounded by a
per-step token budget. Each token carries its own position and its own
row of the block table, so segments of any length coexist in one launch
and the program never retraces as traffic shifts (one compile ever —
recompile-detector-proven in tests).

Grid: ``(tokens, heads, table_width)`` with the table walk innermost —
each ``(t, h)`` pair streams its sequence's pool blocks through VMEM
accumulating the online-softmax running max / normaliser / fp32
accumulator, exactly the ``paged_attention.py`` recurrence with a single
query row. The per-token table (the sequence's block-table row, gathered
host-side by slot) and positions ride as scalar prefetch so the DMA
engine chases the block ids.

Masking is per ragged segment: key position ``j`` is visible to token
``t`` iff ``j <= pos[t]`` — within a prefill chunk every token sees the
prompt prefix up to itself (causal), decode tokens see their whole
written past, and cross-sequence isolation is by construction (a token's
walk only ever touches its own sequence's blocks). Int8 pools dequantize
in-kernel with the PR 15 whole-heads ``[BS, H]`` scale-block layout.

``interpret=True`` (automatic off-TPU) runs the same kernel through the
Pallas interpreter so CPU tier-1 parity tests cover the real kernel
arithmetic.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.flash_attention import LANES, NEG_INF

__all__ = ["chunked_prefill_attention", "chunked_prefill_ok"]


def _use_interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # pragma: no cover - no backend
        return True


def chunked_prefill_ok(head_dim: int, block_size: int) -> bool:
    """Auto-dispatch gate (same tiling law as ``paged_decode_ok``): the
    lane dim is the head_dim (128-multiple) and each streamed K/V block
    is a ``[block_size, head_dim]`` tile (sublane dim: 8-multiple). On
    geometries that fail, the engine falls back to the bucketed path —
    and the interpret path used by CPU tier-1 takes any shape."""
    return head_dim % 128 == 0 and block_size % 8 == 0


def chunked_prefill_attention_kernel(tbl_ref, pos_ref, *refs, scale: float,
                                     block_size: int, int8: bool):
    if int8:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_scr, l_scr, acc = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc = refs
        ks_ref = vs_ref = None
    ti = pl.program_id(0)
    hi = pl.program_id(1)
    wi = pl.program_id(2)
    num_w = pl.num_programs(2)

    @pl.when(wi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc[...] = jnp.zeros_like(acc)

    q = q_ref[0, 0, :][None, :].astype(jnp.float32) * scale   # [1, D]
    k = k_ref[0, :, 0, :].astype(jnp.float32)                 # [BS, D]
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    if int8:
        # In-kernel dequant: whole-heads [BS, H] scale blocks, this
        # head's column sliced in kernel (paged_attention.py layout).
        ks = jax.lax.dynamic_slice_in_dim(ks_ref[0], hi, 1, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(vs_ref[0], hi, 1, axis=1)
        k = k * ks                                            # [BS, 1]
        v = v * vs

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [1, BS]
    # Ragged-segment causal visibility: key j visible to this token iff
    # j <= pos[t]. A prefill chunk's later tokens (written this same
    # step at j > pos[t]) and scratch-pointing table tail entries are
    # masked out exactly like the gather path's kpos <= qpos mask.
    kpos = wi * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_size), 1)
    s = jnp.where(kpos <= pos_ref[ti], s, NEG_INF)

    m_prev = m_scr[:, 0]                                      # [1]
    l_prev = l_scr[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    p = jnp.exp(s - m_new[:, None])
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=1)
    acc[...] = acc[...] * alpha[:, None] + jnp.dot(
        p, v, preferred_element_type=jnp.float32)
    m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(wi == num_w - 1)
    def _finish():
        l_safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0, 0, :] = (acc[...] / l_safe[:, None])[0].astype(o_ref.dtype)


def chunked_prefill_attention(q: jax.Array, k_pool: jax.Array,
                              v_pool: jax.Array,
                              k_scale: Optional[jax.Array],
                              v_scale: Optional[jax.Array],
                              table: jax.Array, pos: jax.Array, *,
                              block_size: int,
                              softmax_scale: Optional[float] = None,
                              interpret: Optional[bool] = None) -> jax.Array:
    """Attention of a ragged token batch ``q`` [T, H, D] over the paged
    pool through **per-token** block tables.

    ``k_pool``/``v_pool``: [N, BS, H, D] (fp, or int8 with ``k_scale``/
    ``v_scale`` [N, BS, H] fp32 per-(token, head) scales). ``table``:
    [T, WB] int32 — row ``t`` is the block-table row of the sequence that
    token ``t`` belongs to (the caller gathers ``block_table[slots]``;
    pad tokens carry an all-scratch row). ``pos``: [T] int32 — token
    ``t``'s own cache position; it attends to key positions ``<= pos[t]``.
    Returns [T, H, D] in ``q.dtype``. The batch's K/V must already be
    written into the pools (``ChunkedLayerCache.update_attend`` does
    both).
    """
    t, h, d = q.shape
    wb = table.shape[1]
    bs = int(block_size)
    if k_pool.shape[1] != bs:
        raise ValueError(f"pool block size {k_pool.shape[1]} != {bs}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / (d ** 0.5)
    interpret = _use_interpret() if interpret is None else interpret
    int8 = k_scale is not None

    kernel = functools.partial(chunked_prefill_attention_kernel,
                               scale=float(scale), block_size=bs, int8=int8)
    in_specs = [
        pl.BlockSpec((1, 1, d), lambda ti, hi, wi, tb, p: (ti, hi, 0)),
        pl.BlockSpec((1, bs, 1, d),
                     lambda ti, hi, wi, tb, p: (tb[ti, wi], 0, hi, 0)),
        pl.BlockSpec((1, bs, 1, d),
                     lambda ti, hi, wi, tb, p: (tb[ti, wi], 0, hi, 0)),
    ]
    inputs = [q, k_pool, v_pool]
    if int8:
        in_specs += [
            pl.BlockSpec((1, bs, h),
                         lambda ti, hi, wi, tb, p: (tb[ti, wi], 0, 0)),
            pl.BlockSpec((1, bs, h),
                         lambda ti, hi, wi, tb, p: (tb[ti, wi], 0, 0)),
        ]
        inputs += [k_scale, v_scale]

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,        # per-token table + positions
            grid=(t, h, wb),              # table walk innermost: scratch
                                          # accumulates per (token, head)
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, d), lambda ti, hi, wi, tb, p: (ti, hi, 0)),
            scratch_shapes=[
                pltpu.VMEM((1, LANES), jnp.float32),   # running max
                pltpu.VMEM((1, LANES), jnp.float32),   # normaliser
                pltpu.VMEM((1, d), jnp.float32),       # fp32 accumulator
            ]),
        out_shape=jax.ShapeDtypeStruct((t, h, d), q.dtype),
        interpret=interpret,
    )(table.astype(jnp.int32), pos.astype(jnp.int32), *inputs)
    return out
