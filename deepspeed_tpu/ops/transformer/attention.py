"""Multi-head attention — the framework's central attention dispatch.

TPU-native equivalent of the reference's fused attention kernels
(``csrc/transformer/softmax_kernels.cu``, ``transform_kernels.cu``, and the
strided-batch GEMMs inside ``ds_transformer_cuda.cpp:147``): on TPU the hot
path is a Pallas flash-attention kernel (``deepspeed_tpu/ops/transformer/
flash_attention.py``); the ``xla`` implementation is the always-correct
reference that XLA fuses on its own and the numerics oracle for kernel-parity
tests (the reference's ``tests/unit/test_cuda_forward.py`` methodology).

All implementations share one signature over ``[batch, seq, heads, head_dim]``
tensors. ``impl``:

- ``"xla"``    — pure jnp einsum attention (softmax in fp32).
- ``"pallas"`` — fused flash attention Pallas kernel (O(S) memory).
- ``"auto"``   — pallas on TPU when shapes are tileable, else xla.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - no backend
        return False


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                  causal: bool = False,
                  bias: Optional[jax.Array] = None,
                  mask: Optional[jax.Array] = None,
                  dropout_rate: float = 0.0,
                  dropout_rng: Optional[jax.Array] = None,
                  deterministic: bool = True,
                  softmax_scale: Optional[float] = None) -> jax.Array:
    """Reference attention. q,k,v: [B, S, H, D] (k/v seq may differ from q's).

    Softmax is computed in fp32 regardless of input dtype — the same
    numerical-stability choice as the reference's ``attn_softmax`` kernel
    (csrc/transformer/softmax_kernels.cu).
    """
    orig_dtype = q.dtype
    head_dim = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / (head_dim ** 0.5)
    # [B, H, Sq, Sk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    neg = jnp.finfo(jnp.float32).min
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((sq, sk), jnp.bool_), k=sk - sq)
        logits = jnp.where(causal_mask[None, None], logits, neg)
    if mask is not None:
        # mask: [B, Sk] key-padding, or broadcastable to [B, H, Sq, Sk];
        # True/1 = attend.
        if mask.ndim == 2:
            mask = mask[:, None, None, :]
        elif mask.ndim == 3:
            mask = mask[:, None]
        logits = jnp.where(mask.astype(jnp.bool_), logits, neg)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and not deterministic:
        if dropout_rng is None:
            raise ValueError("dropout_rate>0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(orig_dtype), v)
    return out


def _as_kv_mask(mask, batch, sk):
    """Extract a key-padding mask [B, Sk] from the common mask forms, or
    None if the mask is a general [B, H, Sq, Sk] pattern the flash kernel
    cannot take."""
    if mask is None:
        return None
    if mask.ndim == 2 and mask.shape == (batch, sk):
        return mask
    if (mask.ndim == 4 and mask.shape[0] == batch and mask.shape[1] == 1
            and mask.shape[2] == 1 and mask.shape[3] == sk):
        return mask[:, 0, 0, :]
    if (mask.ndim == 3 and mask.shape[0] == batch and mask.shape[1] == 1
            and mask.shape[2] == sk):
        return mask[:, 0, :]
    return None


# Auto-mode crossover, measured on a real v5e (8-layer BERT-large-shaped
# stacks, fwd+bwd, with the flash kernel's tuned 512x1024 blocks):
#   seq 128:  XLA  97 vs pallas 86 TFLOP/s  -> XLA
#   seq 512:  XLA  79 vs pallas 87          -> pallas
#   seq 1024: XLA  64 vs pallas 96          -> pallas
#   seq 2048: XLA  50 vs pallas 85          -> pallas
#   seq 4096: XLA  37 vs pallas 78          -> pallas
# Short sequences stay on XLA's fused materialized attention (tiny score
# tensors, better fusion with the surrounding matmuls); from 512 keys up
# the O(S) streaming kernel wins on both time and memory. With attention
# dropout ON the gap widens further (the xla path adds bernoulli + an
# [S,S] mask; in-kernel hash dropout costs ~2%): measured r3, fwd+bwd
# 8-layer stacks — seq 512: 19.7 vs 32.8 ms; 1024: 23.8 vs 56.1;
# 2048: 25.9 vs 101.3 (PROFILE.md). Overridable with impl="pallas"/"xla".
PALLAS_MIN_SEQ_K = 512


def _pallas_ok(q, k, bias, mask, dropout_active: bool = False):
    if bias is not None:
        return False
    if mask is not None and _as_kv_mask(mask, q.shape[0], k.shape[1]) is None:
        return False
    sq, sk = q.shape[1], k.shape[1]
    if not (sq % 128 == 0 and sk % 128 == 0 and q.shape[-1] in
            (64, 128, 256)):
        return False
    if sk < PALLAS_MIN_SEQ_K:
        return False
    # Odd 128-multiple self-attention lengths (640/768/896/1152) collapse
    # the Q blocks; round 3 measured XLA ahead there and dispatched away.
    # Re-measured in round 4 against the SAME kernels with the explicit
    # padded-flash alternative (tools/probe_pad_dispatch.py, fwd+bwd
    # 8-layer stacks, in-run A/B, ms):
    #   seq   640 off: xla 29.4  pallas 19.2  padded 26.0  -> pallas
    #   seq   768 off: xla 32.7  pallas 18.3  padded 26.1  -> pallas
    #   seq   896 off: xla 45.4  pallas 28.4  padded 26.4  -> ~tie
    #   seq  1152 off: xla 68.5  pallas 38.4  padded 50.6  -> pallas
    #   (dropout ON widens every pallas win by ~2x: xla pays bernoulli +
    #    an [S,S] mask.)
    # The degraded-block kernel now wins every cell (the r3 xla numbers
    # did not survive the round-4 VMEM/compiler-params changes), so the
    # gate admits all 128-multiple lengths; impl="pallas_pad" remains the
    # explicit 512-padded route (marginal winner at 896 only).
    return True


def _padded_flash(q, k, v, *, causal, kv_mask, softmax_scale, dropout_rate,
                  dropout_rng, pad_to: int = 512):
    """Run the flash kernel on sequences padded up to a full-block multiple,
    masking the pad keys and slicing the pad queries off — recovers the
    tuned 512-wide blocks for lengths like 640/768/896/1152 whose own
    divisors collapse the block size (round-3 VERDICT weak #3)."""
    import jax.numpy as jnp

    from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

    b, sq = q.shape[0], q.shape[1]
    sk = k.shape[1]
    tq = -(-sq // pad_to) * pad_to
    tk = -(-sk // pad_to) * pad_to

    def pad_seq(x, t):
        s = x.shape[1]
        if s == t:
            return x
        w = [(0, 0)] * x.ndim
        w[1] = (0, t - s)
        return jnp.pad(x, w)

    if kv_mask is None:
        kv_mask = jnp.ones((b, sk), jnp.float32)
    out = flash_attention(pad_seq(q, tq), pad_seq(k, tk), pad_seq(v, tk),
                          causal=causal, kv_mask=pad_seq(kv_mask, tk),
                          softmax_scale=softmax_scale,
                          dropout_rate=dropout_rate, dropout_rng=dropout_rng)
    return out[:, :sq]


def attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
              causal: bool = False,
              bias: Optional[jax.Array] = None,
              mask: Optional[jax.Array] = None,
              dropout_rate: float = 0.0,
              dropout_rng: Optional[jax.Array] = None,
              deterministic: bool = True,
              softmax_scale: Optional[float] = None,
              mesh=None,
              impl: str = "auto") -> jax.Array:
    """Dispatching attention entry point used by every model family."""
    dropout_active = dropout_rate > 0.0 and not deterministic
    if impl == "auto":
        impl = ("pallas" if _on_tpu() and _pallas_ok(
            q, k, bias, mask, dropout_active) else "xla")
    if impl == "pallas_pad":
        kv_mask = _as_kv_mask(mask, q.shape[0], k.shape[1])
        if bias is not None or (mask is not None and kv_mask is None):
            raise ValueError("impl='pallas_pad' takes only key-padding "
                             "masks, like impl='pallas'")
        rate = dropout_rate if dropout_active else 0.0
        return _padded_flash(q, k, v, causal=causal, kv_mask=kv_mask,
                             softmax_scale=softmax_scale, dropout_rate=rate,
                             dropout_rng=dropout_rng)
    if impl == "pallas":
        kv_mask = _as_kv_mask(mask, q.shape[0], k.shape[1])
        if bias is not None or (mask is not None and kv_mask is None):
            raise ValueError("impl='pallas' flash attention takes only "
                             "key-padding masks ([B, Sk] / [B,1,1,Sk]) — "
                             "use impl='xla' for general masks/bias (or "
                             "sparse attention for layout masks)")
        from deepspeed_tpu.ops.transformer.flash_attention import flash_attention

        rate = dropout_rate if dropout_active else 0.0
        return flash_attention(q, k, v, causal=causal, kv_mask=kv_mask,
                               softmax_scale=softmax_scale,
                               dropout_rate=rate, dropout_rng=dropout_rng)
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal, bias=bias, mask=mask,
                             dropout_rate=dropout_rate, dropout_rng=dropout_rng,
                             deterministic=deterministic,
                             softmax_scale=softmax_scale)
    if impl in ("ring", "ulysses"):
        if bias is not None or mask is not None or (
                dropout_rate > 0.0 and not deterministic):
            raise ValueError(f"impl='{impl}' does not take mask/bias/dropout")
        if mesh is None:
            from deepspeed_tpu.parallel.mesh import get_default_mesh

            mesh = get_default_mesh()
        if mesh is None:
            raise ValueError(f"impl='{impl}' needs a mesh (pass mesh= or "
                             "build the engine first, which registers one)")
        from deepspeed_tpu.parallel.sequence import (ring_attention,
                                                     ulysses_attention)

        if impl == "ring":
            return ring_attention(q, k, v, mesh=mesh, causal=causal,
                                  softmax_scale=softmax_scale)
        return ulysses_attention(q, k, v, mesh=mesh, causal=causal,
                                 softmax_scale=softmax_scale)
    raise ValueError(f"unknown attention impl '{impl}'")
