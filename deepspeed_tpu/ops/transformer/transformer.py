"""DeepSpeedTransformerLayer — the fused transformer layer op.

Reference surface: ``deepspeed/ops/transformer/transformer.py:39``
(``DeepSpeedTransformerConfig``), ``:462`` (``DeepSpeedTransformerLayer``)
over ``csrc/transformer/ds_transformer_cuda.cpp:147,295`` + ~7,400 lines of
hand-fused CUDA (LN, QKV GEMM, strided-batch attention, softmax, dropout,
GELU kernels).

TPU-native fusion strategy — measured, not assumed (see
``ops/transformer/attention.py`` crossover data): XLA already emits the
LN/bias/GELU/dropout/residual chains fused into the surrounding GEMMs on
TPU, and beats a hand-written monolithic kernel below 512 keys; the one
fusion XLA cannot do — O(S) streaming attention — is the Pallas flash
kernel, which the layer routes to automatically from 512 keys. The
reference's memory-saving *kernel options* map onto ``jax.checkpoint``
policies instead of bespoke saved-tensor plumbing:

- ``normalize_invertible``  (don't save LN inputs)      → remat the LNs
- ``attn_dropout_checkpoint`` (recompute attn dropout)  → remat attention
- ``gelu_checkpoint``       (recompute GELU)            → remat the MLP
- ``stochastic_mode``       (fast non-deterministic)    → per-call rng fold
  (numerics may differ run-to-run, the reference's documented contract)

The layer is a flax module whose parameter names match the in-tree BERT
family, so ``bert_partition_rules()`` TP-shards it unchanged.
"""

from dataclasses import dataclass
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.attention import attention


@dataclass
class DeepSpeedTransformerConfig:
    """Reference config surface (ops/transformer/transformer.py:39).

    ``batch_size``/``max_seq_length`` are accepted for API parity but not
    baked into the program — XLA re-specializes per shape, where the CUDA
    layer pre-allocated workspaces.
    """

    batch_size: int = -1
    hidden_size: int = -1
    intermediate_size: int = -1
    heads: int = -1
    attn_dropout_ratio: float = 0.1
    hidden_dropout_ratio: float = 0.1
    num_hidden_layers: int = -1
    initializer_range: float = 0.02
    local_rank: int = -1
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    huggingface: bool = False
    training: bool = True
    max_seq_length: int = 512
    layer_norm_eps: float = 1e-12

    def __post_init__(self):
        if self.intermediate_size in (-1, 0) and self.hidden_size > 0:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def dtype(self):
        return jnp.bfloat16 if self.fp16 else jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.heads


class DeepSpeedTransformerLayer(nn.Module):
    """One transformer layer with the reference kernel's option surface.

    ``__call__(x, attn_mask=None, deterministic=True)`` — x: [B, S, H].
    Parameter tree matches the in-tree ``BertLayer`` naming so the shared
    TP partition rules apply.
    """

    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, x, attn_mask=None, deterministic: bool = True):
        # NB: the math below intentionally mirrors models/bert.py BertLayer
        # (same Dense names / residual / LN structure); the parity tests in
        # tests/test_transformer_layer.py use BertLayer as the oracle, so
        # the two must be edited together.
        cfg = self.config
        d, dt = cfg.hidden_size, cfg.dtype
        init = nn.initializers.normal(cfg.initializer_range)
        out_std = cfg.initializer_range
        if cfg.adjust_init_range and cfg.num_hidden_layers > 0:
            # reference: output projections damped by 1/sqrt(2*L)
            out_std = cfg.initializer_range / (2 * cfg.num_hidden_layers) ** 0.5
        out_init = nn.initializers.normal(out_std)

        site_ids = {"attn": 1, "proj": 2, "mlp": 3}

        def rng_for(name):
            if deterministic:
                return None
            # Distinct stream per dropout site. stochastic_mode (reference:
            # trade run-to-run determinism for speed) is accepted — dropout
            # masks are already drawn fresh per call from the engine's rng,
            # which is the whole behavioral contract of the flag here.
            return jax.random.fold_in(self.make_rng("dropout"),
                                      site_ids[name])

        # All remat'd pieces are module-first lifted functions so flax's
        # scope-aware nn.remat handles param creation inside the
        # recomputed region (a bare jax.checkpoint cannot).
        def attn_fn(mdl, h):
            qkv = nn.Dense(3 * d, dtype=dt, name="c_attn",
                           kernel_init=init)(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            b, s = q.shape[0], q.shape[1]
            shape = (b, s, cfg.heads, cfg.head_dim)
            q, k, v = q.reshape(shape), k.reshape(shape), v.reshape(shape)
            o = attention(q, k, v, causal=False, mask=attn_mask,
                          dropout_rate=cfg.attn_dropout_ratio,
                          dropout_rng=rng_for("attn"),
                          deterministic=deterministic, impl="auto")
            o = o.reshape(b, s, d)
            o = nn.Dense(d, dtype=dt, name="c_proj",
                         kernel_init=out_init)(o)
            return nn.Dropout(cfg.hidden_dropout_ratio,
                              deterministic=deterministic)(
                o, rng=rng_for("proj"))

        def mlp_fn(mdl, h):
            h = nn.Dense(cfg.intermediate_size, dtype=dt, name="c_fc",
                         kernel_init=init)(h)
            h = nn.gelu(h, approximate=True)
            h = nn.Dense(d, dtype=dt, name="mlp_proj",
                         kernel_init=out_init)(h)
            return nn.Dropout(cfg.hidden_dropout_ratio,
                              deterministic=deterministic)(
                h, rng=rng_for("mlp"))

        def norm1_fn(mdl, h):
            return nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                dtype=jnp.float32, name="ln_attn")(h)

        def norm2_fn(mdl, h):
            return nn.LayerNorm(epsilon=cfg.layer_norm_eps,
                                dtype=jnp.float32, name="ln_mlp")(h)

        if cfg.attn_dropout_checkpoint:
            attn_fn = nn.remat(attn_fn)
        if cfg.gelu_checkpoint:
            mlp_fn = nn.remat(mlp_fn)
        if cfg.normalize_invertible:
            norm1_fn = nn.remat(norm1_fn)
            norm2_fn = nn.remat(norm2_fn)

        if cfg.pre_layer_norm:
            x = x + attn_fn(self, norm1_fn(self, x).astype(dt))
            x = x + mlp_fn(self, norm2_fn(self, x).astype(dt))
        else:
            x = norm1_fn(self, (x + attn_fn(self, x)).astype(
                jnp.float32)).astype(dt)
            x = norm2_fn(self, (x + mlp_fn(self, x)).astype(
                jnp.float32)).astype(dt)
        return x
