"""Paged decode attention — Pallas TPU kernel, the second instantiation of
the flash-attention family (``flash_attention.py``) aimed at the serving
tier's hot loop: one (or a handful of) query token(s) per sequence
attending over a **block table** into the paged KV pool
(``serving/kv_cache.py``).

Why a kernel: the fallback decode path gathers the whole padded KV window
— ``pool[block_table]`` materializes ``[B, MB·BS, H, D]`` *per decode
step*, in the compute dtype, before a dense masked attention reads it
again. That is HBM traffic proportional to the table width, paid twice
(gather write + attention read), and with int8 pools it also materializes
the dequantized fp copy. Here the K/V blocks stream **directly from the
pool through VMEM** (the block table rides as scalar prefetch so the DMA
engine chases it), online softmax runs in fp32 scratch, and int8 pools
are dequantized **in-kernel** with their per-(token, head) fp32 scales —
the fp copy of the cache is never materialized anywhere.

Grid: ``(batch, heads, table_width)`` with the table dimension innermost
— each ``(b, h)`` pair walks its row of the block table accumulating
running max / normaliser / fp32 accumulator in VMEM scratch (the same
online-softmax recurrence as the flash forward kernel). Inactive table
entries point at the reserved scratch block 0, so a short sequence's walk
re-reads one hot block instead of streaming cold pool memory — HBM
traffic scales with the *sequence*, not the window.

Masking matches ``PagedLayerCache.update`` exactly: key position ``j``
(table-slot order) is visible to query ``i`` iff ``j <= pos + i`` — the
cached past plus the chunk's causal prefix. The multi-query form
(``num_q > 1``) is what speculative decoding's verification step uses to
score ``k+1`` positions in one dispatch.

``interpret=True`` (automatic off-TPU) runs the same kernel through the
Pallas interpreter so the CPU tier-1 parity suite covers the real kernel
arithmetic — the ``tests/unit/test_cuda_forward.py`` strategy, like the
flash kernel.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.transformer.flash_attention import LANES, NEG_INF

__all__ = ["paged_decode_attention", "paged_decode_ok"]


def _use_interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # pragma: no cover - no backend
        return True


def paged_decode_ok(head_dim: int, block_size: int) -> bool:
    """Auto-dispatch gate (``attention.py`` style): can the compiled
    kernel tile this cache geometry on the MXU/VPU? The lane dim is the
    head_dim (must be a 128-multiple) and each streamed K/V block is a
    ``[block_size, head_dim]`` tile (sublane dim: 8-multiple). Shapes
    that fail fall back to the (capped) gather path — and the interpret
    path used by CPU tier-1 takes any shape, so parity tests force
    ``impl="kernel"`` instead of relying on this gate."""
    return head_dim % 128 == 0 and block_size % 8 == 0


def _decode_kernel(bt_ref, pos_ref, *refs, scale: float, block_size: int,
                   num_q: int, int8: bool):
    if int8:
        q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref, m_scr, l_scr, acc = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc = refs
        ks_ref = vs_ref = None
    bi = pl.program_id(0)
    wi = pl.program_id(2)
    num_w = pl.num_programs(2)

    @pl.when(wi == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc[...] = jnp.zeros_like(acc)

    hi = pl.program_id(1)
    q = q_ref[0, :, 0, :].astype(jnp.float32) * scale        # [S, D]
    k = k_ref[0, :, 0, :].astype(jnp.float32)                # [BS, D]
    v = v_ref[0, :, 0, :].astype(jnp.float32)
    if int8:
        # In-kernel dequant: the pool's per-(token, head) RTNE scales
        # ride as whole-heads [BS, H] blocks (trailing dim equals the
        # array's — mosaic tiling) and this head's column is sliced in
        # kernel. Scale traffic stays proportional to the streamed
        # blocks; the fp K/V copy exists only as this VMEM block.
        ks = jax.lax.dynamic_slice_in_dim(ks_ref[0], hi, 1, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(vs_ref[0], hi, 1, axis=1)
        k = k * ks                                           # [BS, 1]
        v = v * vs

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [S, BS]
    # Visibility matches PagedLayerCache.update: key j (table-slot
    # order) visible to query i iff j <= pos + i. Table slots past the
    # written region point at scratch garbage — masked here exactly like
    # the gather path's kpos <= qpos mask.
    kpos = wi * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (num_q, block_size), 1)
    qpos = pos_ref[bi] + jax.lax.broadcasted_iota(
        jnp.int32, (num_q, block_size), 0)
    s = jnp.where(kpos <= qpos, s, NEG_INF)

    m_prev = m_scr[:, 0]                                     # [S]
    l_prev = l_scr[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    p = jnp.exp(s - m_new[:, None])
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=1)
    acc[...] = acc[...] * alpha[:, None] + jnp.dot(
        p, v, preferred_element_type=jnp.float32)
    m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)

    @pl.when(wi == num_w - 1)
    def _finish():
        l_safe = jnp.maximum(l_scr[:, 0], 1e-30)
        o_ref[0, :, 0, :] = (acc[...] / l_safe[:, None]).astype(o_ref.dtype)


def paged_decode_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array,
                           k_scale: Optional[jax.Array],
                           v_scale: Optional[jax.Array],
                           block_table: jax.Array, pos: jax.Array, *,
                           block_size: int,
                           softmax_scale: Optional[float] = None,
                           interpret: Optional[bool] = None) -> jax.Array:
    """Attention of ``q`` [B, S, H, D] over the paged pool through each
    row's block table.

    ``k_pool``/``v_pool``: [N, BS, H, D] (fp, or int8 with ``k_scale``/
    ``v_scale`` [N, BS, H] fp32 per-(token, head) scales). ``block_table``:
    [B, WB] int32 pool-block ids (the caller may pass a column-sliced
    window — all positions indexed are table-relative). ``pos``: [B]
    int32, the first query's position (queries sit at ``pos..pos+S-1``).
    Returns [B, S, H, D] in ``q.dtype``. The chunk's K/V must already be
    written into the pools (``PagedLayerCache.update_attend`` does both).
    """
    b, s, h, d = q.shape
    wb = block_table.shape[1]
    bs = int(block_size)
    if k_pool.shape[1] != bs:
        raise ValueError(f"pool block size {k_pool.shape[1]} != {bs}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / (d ** 0.5)
    interpret = _use_interpret() if interpret is None else interpret
    int8 = k_scale is not None

    kernel = functools.partial(_decode_kernel, scale=float(scale),
                               block_size=bs, num_q=s, int8=int8)
    in_specs = [
        pl.BlockSpec((1, s, 1, d), lambda bi, hi, wi, bt, p: (bi, 0, hi, 0)),
        pl.BlockSpec((1, bs, 1, d),
                     lambda bi, hi, wi, bt, p: (bt[bi, wi], 0, hi, 0)),
        pl.BlockSpec((1, bs, 1, d),
                     lambda bi, hi, wi, bt, p: (bt[bi, wi], 0, hi, 0)),
    ]
    inputs = [q, k_pool, v_pool]
    if int8:
        # Whole-heads (1, BS, H) scale blocks straight from the pool
        # layout — no relayout of the (donated, per-step-rewritten)
        # scale pools; the kernel slices its head's column. H extra
        # lanes per block is noise next to the [BS, D] K/V stream.
        in_specs += [
            pl.BlockSpec((1, bs, h),
                         lambda bi, hi, wi, bt, p: (bt[bi, wi], 0, 0)),
            pl.BlockSpec((1, bs, h),
                         lambda bi, hi, wi, bt, p: (bt[bi, wi], 0, 0)),
        ]
        inputs += [k_scale, v_scale]

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,        # block table + positions
            grid=(b, h, wb),              # table walk innermost: scratch
                                          # accumulates per (seq, head)
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, s, 1, d), lambda bi, hi, wi, bt, p: (bi, 0, hi, 0)),
            scratch_shapes=[
                pltpu.VMEM((s, LANES), jnp.float32),   # running max
                pltpu.VMEM((s, LANES), jnp.float32),   # normaliser
                pltpu.VMEM((s, d), jnp.float32),       # fp32 accumulator
            ]),
        out_shape=jax.ShapeDtypeStruct((b, s, h, d), q.dtype),
        interpret=interpret,
    )(block_table.astype(jnp.int32), pos.astype(jnp.int32), *inputs)
    return out
