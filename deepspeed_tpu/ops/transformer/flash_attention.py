"""Fused flash attention — Pallas TPU kernel, the framework's answer to the
reference's fused attention CUDA path (``csrc/transformer/softmax_kernels.cu``
+ the strided-batch attention GEMMs in ``ds_transformer_cuda.cpp:147``) with
O(seq) memory instead of materialising the [S, S] score matrix.

Forward: one kernel per (batch·head, q-block): K/V stream through VMEM in
kv-blocks while running max / normaliser / fp32 accumulator live in scratch
(online softmax). Saves the per-row logsumexp for the backward pass.

Backward: custom VJP with two kernels — dq over q-blocks, dk/dv over
kv-blocks — using the standard flash-attention recomputation identity
ds = p ⊙ (dp − delta), delta = rowsum(dO ⊙ O).

All matmuls accumulate in fp32 on the MXU (preferred_element_type); block
sizes are 128-aligned for MXU/VPU tiling. ``interpret=True`` runs the same
kernels through the Pallas interpreter for CPU tests (the kernel-parity
strategy of reference tests/unit/test_cuda_forward.py).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Measured on v5e at seq 4096 (fwd+bwd, d=64): 128x128 blocks run at
# ~1 TF/s (grid/stream overhead dominates) while 512x1024 reaches ~31 TF/s
# — large blocks keep the MXU fed and amortize the per-program K/V stream.
# VMEM check (fp32): q bq·d + k/v 2·bk·d + score block bq·bk —
#   d=64:  (32K + 131K + 524K)·4 B ≈ 2.7 MB
#   d=128: (65K + 262K + 524K)·4 B ≈ 3.4 MB
#   d=256: (131K + 524K + 524K)·4 B ≈ 4.7 MB
# all comfortably inside 16 MB, so the 512x1024 default serves every
# admitted head_dim (r2 VERDICT weak #8: no per-head-dim table needed —
# the score block dominates and is head_dim-independent). Both are
# clamped to the actual sequence lengths for short inputs; sequences that
# are 128-multiples but lack large 128-multiple divisors (e.g. 640)
# degrade to small blocks — pad such inputs to a friendlier length
# upstream (pad_to_block_size) if they are hot.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
LANES = 128   # TPU lane width: per-row scalars (lse/delta) are broadcast
              # across the lane dim so their blocks satisfy (8,128) tiling
NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def fit_block(block: int, seq: int) -> int:
    """Largest 128-multiple <= `block` that divides `seq` (the kernels
    require whole blocks); used by the auto-dispatch gate too — degraded
    blocks lose to XLA (see attention.py crossover notes)."""
    block = min(block, seq)
    if seq % 128 == 0:
        while seq % block:
            block -= 128
    return block


# ---------------------------------------------------------------------------
# Attention dropout — counter-based hash PRNG
# ---------------------------------------------------------------------------
# The reference applies attention dropout inside its fused kernels
# (csrc/transformer/dropout_kernels.cu, ds_transformer_cuda.cpp:168-190).
# Here the keep-mask is a pure function of (seed, batch·head, absolute row,
# absolute col) via a murmur3-style integer hash — vector int ops that run
# identically inside the Mosaic kernel, in the Pallas interpreter, and in
# plain jnp (`dropout_keep_mask` is the oracle the parity tests use). The
# backward kernels regenerate exactly the forward's mask because the hash
# depends only on absolute element coordinates, not the block walk order.

def _hash_u32(x):
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0x27D4EB2F)
    x = x ^ (x >> 16)
    return x


def _dropout_bits(seed, bh, rows, cols):
    """uint32 hash bits for absolute element coordinates. rows/cols are
    int32 arrays broadcastable to the score-block shape."""
    x = (rows.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         + cols.astype(jnp.uint32) * jnp.uint32(0x7FEB352D))
    x = x ^ (jnp.asarray(seed).astype(jnp.uint32) + jnp.uint32(0x165667B1))
    x = x ^ (jnp.asarray(bh).astype(jnp.uint32) * jnp.uint32(0x58F633B5)
             + jnp.uint32(1))
    return _hash_u32(x)


def dropout_keep_mask(seed, bh, rows, cols, rate: float):
    """Boolean keep-mask for attention dropout — the single source of truth
    shared by the kernels and the jnp oracle (tests/test_flash_attention).
    seed: int32 scalar; bh: batch·head index; rows/cols: absolute score
    coordinates (broadcastable int32 arrays)."""
    bits = _dropout_bits(seed, bh, rows, cols)
    # top 24 bits vs an integer threshold — Mosaic has no uint32->float
    # cast, and the int32 compare is cheaper anyway (>>8 keeps it positive).
    thresh = int(float(rate) * (1 << 24))
    return (bits >> 8).astype(jnp.int32) >= thresh


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, *refs, causal: bool, scale: float, block_k: int,
                seq_q: int, seq_k: int, has_mask: bool, dropout_rate: float):
    if has_mask:
        q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
        mask_ref = None
    bh_idx = pl.program_id(0)
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]

    num_kv = seq_k // block_k
    # Bottom-right aligned causality (matches xla_attention's tril offset
    # k = sk - sq): query row i may attend keys j <= i + offset.
    offset = seq_k - seq_q
    if causal:
        hi = jax.lax.div((qi + 1) * block_q + offset + block_k - 1, block_k)
        hi = jnp.clip(hi, 0, num_kv)
    else:
        hi = num_kv

    def body(ki, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # [bq, bk]
        q_idx = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_idx = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            s = jnp.where(q_idx + offset >= k_idx, s, NEG_INF)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        if mask_ref is not None:
            # Key-padding mask (float 0/1, [1, bk]): multiplying p keeps the
            # masked keys out of BOTH the normaliser and the accumulator —
            # exact, and robust even for fully-masked rows (p -> 0, l -> 0).
            km = mask_ref[0, :, pl.ds(ki * block_k, block_k)]
            p = p * km
        alpha = jnp.exp(m_prev - m_new)
        # Dropout applies to the accumulated probabilities only — the
        # normaliser keeps the full softmax mass, matching post-softmax
        # dropout semantics (reference dropout_kernels.cu applies it to the
        # normalised probs; here l normalises first, then D p v sums).
        if dropout_rate > 0.0:
            keep = dropout_keep_mask(seed_ref[0], bh_idx, q_idx, k_idx,
                                     dropout_rate)
            p_acc = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        else:
            p_acc = p
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jnp.dot(
            p_acc, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    init = (jnp.full((block_q,), NEG_INF, jnp.float32),
            jnp.zeros((block_q,), jnp.float32),
            jnp.zeros((block_q, d), jnp.float32))
    m, l, acc = jax.lax.fori_loop(0, hi, body, init)
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse = m + jnp.log(l_safe)
    lse_ref[0] = jnp.broadcast_to(lse[:, None], (block_q, LANES))


def _flash_forward(q, k, v, kv_mask, causal, scale, block_q, block_k,
                   interpret, nheads=1, dropout_rate=0.0, seed=None):
    bh, sq, d = q.shape
    sk = k.shape[1]
    has_mask = kv_mask is not None
    if seed is None:
        seed = jnp.zeros((1,), jnp.int32)
    kernel = functools.partial(_fwd_kernel, causal=causal, scale=scale,
                               block_k=block_k, seq_q=sq, seq_k=sk,
                               has_mask=has_mask, dropout_rate=dropout_rate)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, s: (b, i, 0)),
        pl.BlockSpec((1, sk, d), lambda b, i, s: (b, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda b, i, s: (b, 0, 0)),
    ]
    inputs = [q, k, v]
    if has_mask:
        # Mask rides as [B, 1, Sk] so the (1, 1, Sk) block's trailing dims
        # equal the array's (TPU mosaic tiling constraint for sub-8 rows).
        in_specs.append(
            pl.BlockSpec((1, 1, sk), lambda b, i, s: (b // nheads, 0, 0)))
        inputs.append(kv_mask)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,     # dropout seed rides in SMEM
            grid=(bh, sq // block_q),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, s: (b, i, 0)),
                pl.BlockSpec((1, block_q, LANES), lambda b, i, s: (b, i, 0)),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, LANES), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_vmem_params(
            (2 * sk * d + 2 * block_q * d) * q.dtype.itemsize
            + block_q * LANES * 4 + (4 * sk if has_mask else 0)),
    )(seed, *inputs)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(seed_ref, *refs, causal: bool, scale: float, block_k: int,
                   seq_q: int, seq_k: int, has_mask: bool,
                   dropout_rate: float):
    if has_mask:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
         dq_ref) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref = refs
        mask_ref = None
    bh_idx = pl.program_id(0)
    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    delta = delta_ref[0, :, 0]

    num_kv = seq_k // block_k
    offset = seq_k - seq_q
    if causal:
        hi = jnp.clip(jax.lax.div(
            (qi + 1) * block_q + offset + block_k - 1, block_k), 0, num_kv)
    else:
        hi = num_kv

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        q_idx = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_idx = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            s = jnp.where(q_idx + offset >= k_idx, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if mask_ref is not None:
            p = p * mask_ref[0, :, pl.ds(ki * block_k, block_k)]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        # With dropout D: o = Σ D p̂ v / l, and Σ_j p̂_j D_j dp_j = do·o =
        # delta still holds, so ds = p (D∘dp − delta) — regenerate the
        # forward's exact keep-mask from the hash.
        if dropout_rate > 0.0:
            keep = dropout_keep_mask(seed_ref[0], bh_idx, q_idx, k_idx,
                                     dropout_rate)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = p * (dp - delta[:, None])
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(seed_ref, *refs, causal: bool, scale: float, block_q: int,
                    seq_q: int, seq_k: int, has_mask: bool,
                    dropout_rate: float):
    if has_mask:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
         dk_ref, dv_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref) = refs
        mask_ref = None
    bh_idx = pl.program_id(0)
    ki = pl.program_id(1)
    block_k = k_ref.shape[1]
    d = k_ref.shape[2]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    num_q = seq_q // block_q
    offset = seq_k - seq_q
    if causal:
        lo = jnp.clip(jax.lax.div(ki * block_k - offset, block_q), 0, num_q)
    else:
        lo = 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        q_idx = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_idx = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            s = jnp.where(q_idx + offset >= k_idx, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                       # [bq, bk]
        if mask_ref is not None:
            p = p * mask_ref[0]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = dropout_keep_mask(seed_ref[0], bh_idx, q_idx, k_idx,
                                     dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_acc = jnp.where(keep, p * inv, 0.0)   # dropped probs for dv
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            p_acc = p
        dv = dv + jax.lax.dot_general(p_acc, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(
        lo, num_q, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _vmem_params(est_bytes: int):
    """Raise Mosaic's scoped-VMEM cap (default 16 MiB) when a kernel
    instance's double-buffered working set won't fit — the long-sequence
    backward keeps whole-sequence q/do/lse/delta refs per instance, which
    at seq 4096 overflows the default by ~1 MiB (v5e has 128 MiB VMEM).
    ``est_bytes`` is the single-buffered per-instance sum; ×4 + 16 MiB
    covers double buffering plus the compiler's own stack slack (measured:
    Mosaic asked for ~2% above a bare ×4 at seq 16384)."""
    if est_bytes * 4 <= 16 * 2**20:
        return None
    return pltpu.CompilerParams(
        vmem_limit_bytes=int(min(100 * 2**20, est_bytes * 4 + 16 * 2**20)))


def _flash_backward(res, g, causal, scale, block_q, block_k, interpret,
                    nheads=1, dropout_rate=0.0):
    q, k, v, kv_mask, out, lse, seed = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (LANES,))
    has_mask = kv_mask is not None

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, s: (b, i, 0)),
        pl.BlockSpec((1, sk, d), lambda b, i, s: (b, 0, 0)),
        pl.BlockSpec((1, sk, d), lambda b, i, s: (b, 0, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i, s: (b, i, 0)),
        pl.BlockSpec((1, block_q, LANES), lambda b, i, s: (b, i, 0)),
        pl.BlockSpec((1, block_q, LANES), lambda b, i, s: (b, i, 0)),
    ]
    dq_inputs = [q, k, v, do, lse, delta]
    if has_mask:
        dq_in_specs.append(
            pl.BlockSpec((1, 1, sk), lambda b, i, s: (b // nheads, 0, 0)))
        dq_inputs.append(kv_mask)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          block_k=block_k, seq_q=sq, seq_k=sk,
                          has_mask=has_mask, dropout_rate=dropout_rate),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, sq // block_q),
            in_specs=dq_in_specs,
            out_specs=pl.BlockSpec((1, block_q, d),
                                   lambda b, i, s: (b, i, 0))),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        interpret=interpret,
        compiler_params=_vmem_params(
            (2 * sk * d + 3 * block_q * d) * q.dtype.itemsize
            + 2 * block_q * LANES * 4),
    )(seed, *dq_inputs)

    dkv_in_specs = [
        pl.BlockSpec((1, sq, d), lambda b, i, s: (b, 0, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, s: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, s: (b, i, 0)),
        pl.BlockSpec((1, sq, d), lambda b, i, s: (b, 0, 0)),
        pl.BlockSpec((1, sq, LANES), lambda b, i, s: (b, 0, 0)),
        pl.BlockSpec((1, sq, LANES), lambda b, i, s: (b, 0, 0)),
    ]
    dkv_inputs = [q, k, v, do, lse, delta]
    if has_mask:
        dkv_in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, s: (b // nheads, 0, i)))
        dkv_inputs.append(kv_mask)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, seq_q=sq, seq_k=sk,
                          has_mask=has_mask, dropout_rate=dropout_rate),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, sk // block_k),
            in_specs=dkv_in_specs,
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, i, s: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, s: (b, i, 0)),
            ]),
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        interpret=interpret,
        compiler_params=_vmem_params(
            (2 * sq * d + 4 * block_k * d) * q.dtype.itemsize
            + 2 * sq * LANES * 4),
    )(seed, *dkv_inputs)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public entry — [B, S, H, D] layout, custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_bhsd(q, k, v, seed, causal, scale, block_q, block_k, interpret,
                dropout_rate):
    out, _ = _flash_forward(q, k, v, None, causal, scale, block_q, block_k,
                            interpret, dropout_rate=dropout_rate, seed=seed)
    return out


def _flash_fwd_rule(q, k, v, seed, causal, scale, block_q, block_k,
                    interpret, dropout_rate):
    out, lse = _flash_forward(q, k, v, None, causal, scale, block_q, block_k,
                              interpret, dropout_rate=dropout_rate, seed=seed)
    return out, (q, k, v, None, out, lse, seed)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret, dropout_rate,
                    res, g):
    dq, dk, dv = _flash_backward(res, g, causal, scale, block_q, block_k,
                                 interpret, dropout_rate=dropout_rate)
    import numpy as _np
    return dq, dk, dv, _np.zeros(res[6].shape, jax.dtypes.float0)


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash_bhsd_masked(q, k, v, kv_mask, seed, causal, scale, block_q,
                       block_k, interpret, nheads, dropout_rate):
    out, _ = _flash_forward(q, k, v, kv_mask, causal, scale, block_q,
                            block_k, interpret, nheads,
                            dropout_rate=dropout_rate, seed=seed)
    return out


def _flash_fwd_rule_masked(q, k, v, kv_mask, seed, causal, scale, block_q,
                           block_k, interpret, nheads, dropout_rate):
    out, lse = _flash_forward(q, k, v, kv_mask, causal, scale, block_q,
                              block_k, interpret, nheads,
                              dropout_rate=dropout_rate, seed=seed)
    return out, (q, k, v, kv_mask, out, lse, seed)


def _flash_bwd_rule_masked(causal, scale, block_q, block_k, interpret, nheads,
                           dropout_rate, res, g):
    dq, dk, dv = _flash_backward(res, g, causal, scale, block_q, block_k,
                                 interpret, nheads,
                                 dropout_rate=dropout_rate)
    import numpy as _np
    return (dq, dk, dv, jnp.zeros_like(res[3]),
            _np.zeros(res[6].shape, jax.dtypes.float0))


_flash_bhsd_masked.defvjp(_flash_fwd_rule_masked, _flash_bwd_rule_masked)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False,
                    kv_mask: Optional[jax.Array] = None,
                    softmax_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    dropout_rate: float = 0.0,
                    dropout_rng: Optional[jax.Array] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Flash attention over [batch, seq, heads, head_dim] tensors.

    ``kv_mask``: optional key-padding mask [batch, seq_k], 1/True = attend —
    the fused-kernel answer to the reference's attention-mask input
    (csrc/transformer/softmax_kernels.cu applies it inside attn_softmax).

    ``dropout_rate`` + ``dropout_rng``: in-kernel attention dropout
    (reference dropout_kernels.cu): the keep-mask is regenerated in the
    backward kernels from a counter-based hash (see ``dropout_keep_mask``),
    so no [S, S] mask is ever materialized.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]

    block_q = fit_block(block_q, sq)
    block_k = fit_block(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq lengths ({sq},{sk}) must divide blocks "
                         f"({block_q},{block_k})")
    scale = softmax_scale if softmax_scale is not None else 1.0 / (d ** 0.5)
    interpret = _use_interpret() if interpret is None else interpret
    dropout_rate = float(dropout_rate)
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        kd = jax.random.key_data(dropout_rng).astype(jnp.uint32).reshape(-1)
        seed = (kd[0] ^ (kd[-1] << 1)).astype(jnp.int32)[None]
    else:
        seed = jnp.zeros((1,), jnp.int32)
    # [B,S,H,D] -> [B*H, S, D]
    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    if kv_mask is not None:
        if kv_mask.shape != (b, sk):
            raise ValueError(f"kv_mask shape {kv_mask.shape} != {(b, sk)}")
        out = _flash_bhsd_masked(
            to_bhsd(q), to_bhsd(k), to_bhsd(v),
            kv_mask.astype(jnp.float32)[:, None, :], seed,
            causal, scale, block_q, block_k, interpret, h, dropout_rate)
    else:
        out = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), seed,
                          causal, scale, block_q, block_k, interpret,
                          dropout_rate)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
