"""Environment / capability report — the ``ds_report`` analogue
(reference ``deepspeed/env_report.py``): instead of probing CUDA op
builders, reports the JAX/TPU stack and which framework features are
usable in this environment."""

import importlib
import sys


GREEN_OK = "\033[92m[OK]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _try_import(name):
    try:
        mod = importlib.import_module(name)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return None


def collect_report() -> dict:
    import deepspeed_tpu

    report = {
        "deepspeed_tpu": deepspeed_tpu.__version__,
        "python": sys.version.split()[0],
        "packages": {},
        "devices": [],
        "platform": None,
        "features": {},
    }
    for pkg in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        report["packages"][pkg] = _try_import(pkg)

    try:
        import jax

        report["platform"] = jax.devices()[0].platform
        report["devices"] = [str(d) for d in jax.devices()]
        report["process_count"] = jax.process_count()
    except Exception as e:  # no backend
        report["platform"] = f"unavailable ({e})"

    on_tpu = report["platform"] == "tpu"
    # Enumerated, not a single boolean: which Pallas kernels are LIVE in
    # this environment (compiled on TPU; all of them run through the
    # interpreter for off-TPU parity tests, which is not "live").
    report["pallas_kernels"] = {
        "flash_attention": on_tpu,
        "sparse_attention": on_tpu,
        "paged_decode_attention": on_tpu,
        "chunked_prefill": on_tpu,
        "fused_adam_update": on_tpu,
    }
    report["features"] = {
        "pallas_kernels": ", ".join(
            k for k, ok in report["pallas_kernels"].items() if ok)
        or "none (interpret-only off TPU)",
        "xla_reference_ops": report["packages"]["jax"] is not None,
        "multihost (jax.distributed)": report["packages"]["jax"] is not None,
        "zero_stages_0_3": True,
        "pipeline_parallelism": True,
        "sequence_parallelism (ring/ulysses)": True,
        "onebit_optimizers": True,
    }
    from deepspeed_tpu.ops.registry import list_ops

    report["ops"] = {name: spec.available()
                     for name, spec in sorted(list_ops().items())}
    return report


def main():
    report = collect_report()
    print("-" * 60)
    print("DeepSpeed-TPU environment report")
    print("-" * 60)
    print(f"deepspeed_tpu .......... {report['deepspeed_tpu']}")
    print(f"python ................. {report['python']}")
    for pkg, ver in report["packages"].items():
        mark = GREEN_OK if ver else RED_NO
        print(f"{pkg:22s} {mark} {ver or 'not installed'}")
    print(f"platform ............... {report['platform']}")
    for d in report["devices"]:
        print(f"  device: {d}")
    print("-" * 60)
    print("feature availability")
    for feat, ok in report["features"].items():
        if feat == "pallas_kernels":
            live = [k for k, on in report["pallas_kernels"].items() if on]
            mark = GREEN_OK if live else RED_NO
            print(f"  {mark} pallas_kernels: {ok}")
            continue
        print(f"  {GREEN_OK if ok else RED_NO} {feat}")
    print("-" * 60)
    print("op registry (op_builder analogue)")
    for name, ok in report["ops"].items():
        print(f"  {GREEN_OK if ok else RED_NO} {name}")
    print("-" * 60)


if __name__ == "__main__":
    main()
