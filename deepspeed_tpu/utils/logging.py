"""Logging utilities.

Capability parity with the reference's ``deepspeed/utils/logging.py`` and the
rank-filtered ``log_dist`` helper from ``deepspeed/utils/__init__.py``: a
singleton package logger plus helpers that only emit on selected process ranks.

On TPU the "rank" is the JAX process index (one process per host); we avoid
importing jax at module import time so the logger is usable before
``jax.distributed.initialize``.
"""

import logging
import os
import sys
from typing import Iterable, Optional

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class _LoggerFactory:
    @staticmethod
    def create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
        log = logging.getLogger(name)
        log.setLevel(level)
        log.propagate = False
        if not log.handlers:
            formatter = logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s",
                datefmt="%Y-%m-%d %H:%M:%S",
            )
            # stderr, not stdout: tools in this package (bench.py, CLI
            # scripts) reserve stdout for machine-readable output.
            handler = logging.StreamHandler(stream=sys.stderr)
            handler.setFormatter(formatter)
            log.addHandler(handler)
        return log


logger = _LoggerFactory.create_logger(
    level=LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info").lower(), logging.INFO)
)


def _process_index() -> int:
    """Current process rank without forcing distributed init."""
    # Prefer the env var set by our launcher; fall back to jax if initialised.
    for var in ("DSTPU_RANK", "JAX_PROCESS_INDEX", "RANK"):
        if var in os.environ:
            try:
                return int(os.environ[var])
            except ValueError:
                pass
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (``None`` / ``[-1]`` = all).

    Mirrors the reference's ``deepspeed/utils/__init__.py`` ``log_dist``.
    """
    ranks = list(ranks) if ranks is not None else []
    my_rank = _process_index()
    if not ranks or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        logger.info(message)


def warning_once(message: str, _seen=set()) -> None:  # noqa: B006 - intentional cache
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
