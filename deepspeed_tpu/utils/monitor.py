"""Training monitoring — tensorboard scalar writer.

Reference surface: the engine's ``tensorboard``-gated SummaryWriter calls
(``runtime/engine.py:1340-1416``: Train/Samples/train_loss, lr, loss_scale
at every logging boundary). Uses torch's SummaryWriter when available (torch
is CPU-only in this image, which is all a writer needs); falls back to a
JSONL event log with the same (tag, value, step) schema so monitoring never
silently disappears.
"""

import json
import os
from typing import Optional


class TensorboardMonitor:
    """Scalar writer gated by TensorboardConfig (config/config.py)."""

    def __init__(self, output_path: str, job_name: str = "DeepSpeedTPUJob"):
        self.log_dir = os.path.join(output_path or "runs", job_name)
        os.makedirs(self.log_dir, exist_ok=True)
        self._writer = None
        self._jsonl = None
        try:
            from torch.utils.tensorboard import SummaryWriter
            self._writer = SummaryWriter(log_dir=self.log_dir)
        except Exception:
            self._jsonl = open(os.path.join(self.log_dir, "scalars.jsonl"),
                               "a", buffering=1)

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        if self._writer is not None:
            self._writer.add_scalar(tag, float(value), int(step))
        else:
            self._jsonl.write(json.dumps(
                {"tag": tag, "value": float(value), "step": int(step)}) + "\n")

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
        if self._jsonl is not None:
            self._jsonl.close()


def build_monitor(tb_config) -> Optional[TensorboardMonitor]:
    if tb_config is None or not tb_config.enabled:
        return None
    return TensorboardMonitor(tb_config.output_path, tb_config.job_name)
