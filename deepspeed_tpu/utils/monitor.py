"""Training monitoring — tensorboard scalar writer.

Reference surface: the engine's ``tensorboard``-gated SummaryWriter calls
(``runtime/engine.py:1340-1416``: Train/Samples/train_loss, lr, loss_scale
at every logging boundary). Uses torch's SummaryWriter when available (torch
is CPU-only in this image, which is all a writer needs); falls back to a
JSONL event log with the same (tag, value, step) schema so monitoring never
silently disappears.

:class:`MetricsJSONL` is that fallback schema as a standalone append-only
writer — the resilience subsystem uses it to record checkpoint write
latency, snapshot cost, and recovery counters next to the checkpoints
themselves, so the scalars survive even when tensorboard is disabled (the
auto-resume probe and tests read them back).
"""

import json
import os
import threading
from typing import Optional


class MetricsJSONL:
    """Append-only ``{tag, value, step, [extra]}`` JSONL scalar log.

    Thread-safe (the async checkpoint writer emits from its background
    thread while the engine emits from the step loop) and line-buffered so
    a preemption mid-run loses at most the current line.
    """

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(path, "a", buffering=1)

    def add_scalar(self, tag: str, value: float, step: int, **extra) -> None:
        """Append one row; ``extra`` key/values ride on the same row (the
        ``[extra]`` field of the schema — e.g. ``kind=`` from the telemetry
        registry, attempt counters from the resilience writer)."""
        with self._lock:
            if self._f.closed:
                return
            row = {"tag": tag, "value": float(value), "step": int(step)}
            if extra:
                row.update(extra)
            self._f.write(json.dumps(row) + "\n")

    def flush(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                os.fsync(self._f.fileno())

    def read(self, tag: Optional[str] = None):
        """All recorded rows (optionally one tag) — test/probe convenience."""
        rows = []
        if not os.path.exists(self.path):
            return rows
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                row = json.loads(line)
                if tag is None or row.get("tag") == tag:
                    rows.append(row)
        return rows

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


class TensorboardMonitor:
    """Scalar writer gated by TensorboardConfig (config/config.py)."""

    def __init__(self, output_path: str, job_name: str = "DeepSpeedTPUJob"):
        self.log_dir = os.path.join(output_path or "runs", job_name)
        os.makedirs(self.log_dir, exist_ok=True)
        self._writer = None
        self._jsonl = None
        try:
            from torch.utils.tensorboard import SummaryWriter
            self._writer = SummaryWriter(log_dir=self.log_dir)
        except Exception:
            self._jsonl = MetricsJSONL(
                os.path.join(self.log_dir, "scalars.jsonl"))

    def add_scalar(self, tag: str, value: float, step: int, **extra) -> None:
        if self._writer is not None:
            # SummaryWriter has no extra-field dimension; extras are dropped
            # there but preserved on the JSONL fallback rows.
            self._writer.add_scalar(tag, float(value), int(step))
        else:
            self._jsonl.add_scalar(tag, value, step, **extra)

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()
        if self._jsonl is not None:
            self._jsonl.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
        if self._jsonl is not None:
            self._jsonl.close()


def build_monitor(tb_config) -> Optional[TensorboardMonitor]:
    if tb_config is None or not tb_config.enabled:
        return None
    return TensorboardMonitor(tb_config.output_path, tb_config.job_name)
