"""Version-compat imports for jax API moves.

``shard_map`` was promoted from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace, and its partial-manual/replication-check
kwargs were renamed (``auto``→``axis_names`` complement,
``check_rep``→``check_vma``). The codebase is written against the new
API; this image pins a jax that only has the experimental one, so the
shim translates. Import ``shard_map`` from here at every call site.
"""

try:
    from jax import shard_map  # noqa: F401  (jax >= 0.6)
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=None, **kw):
        if axis_names is not None:
            kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

try:
    from jax.lax import axis_size  # noqa: F401  (jax >= 0.6)
except ImportError:
    def axis_size(axis_name):
        """Size of a named mesh axis inside a shard_map/collective region —
        psum of 1 over the axis, which SPMD folds to a constant."""
        import jax

        return jax.lax.psum(1, axis_name)

import jax as _jax

# True when this jax ships the promoted (top-level) shard_map. Old
# releases lower axis_index inside partial-manual regions to a
# PartitionId HLO their SPMD partitioner rejects (and the ring-attention
# program aborts the XLA CPU compiler outright), so version-sensitive
# tests gate on this.
NATIVE_SHARD_MAP = hasattr(_jax, "shard_map")

try:
    DEVICE_MEMORY_SPACE = _jax.memory.Space.Device  # jax >= 0.6
except AttributeError:
    from jax._src.sharding_impls import TransferToMemoryKind
    DEVICE_MEMORY_SPACE = TransferToMemoryKind("device")

def distributed_is_initialized() -> bool:
    """jax.distributed.is_initialized, which old jax doesn't export —
    there, the private global client being set is the same signal."""
    if hasattr(_jax.distributed, "is_initialized"):
        return _jax.distributed.is_initialized()
    from jax._src import distributed
    return distributed.global_state.client is not None


__all__ = ["shard_map", "axis_size", "DEVICE_MEMORY_SPACE",
           "NATIVE_SHARD_MAP", "distributed_is_initialized"]
