"""Wall-clock and throughput timers.

Capability parity with the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` at :19, ``ThroughputTimer`` at :100). The CUDA
``synchronize()`` barrier becomes a block-until-ready on the JAX default
device: XLA dispatch is async exactly like CUDA streams, so timers must drain
the device queue before reading the host clock.

The barrier is GATED: a timer whose owner is disabled (wall-clock logging
off) reads the host clock without draining the device — a per-step
``block_until_ready`` round-trip is exactly the overhead the timing exists
to measure, so it must not be paid when nobody reads the timings. Probes
that need an exact barrier regardless pass ``force_sync=True``.
``_device_synchronize`` is the single sync primitive for all of telemetry
(the tracer routes through it too), so tests can count every
telemetry-originated sync by patching one function.
"""

import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist


def _device_synchronize() -> None:
    """Drain outstanding device work (the TPU analogue of cuda.synchronize)."""
    try:
        import jax

        # A tiny transfer forces completion of everything already enqueued on
        # the same stream-ordered executor.
        jax.block_until_ready(jax.device_put(0.0))
    except Exception:
        pass


class _Timer:
    def __init__(self, name: str, owner: Optional["SynchronizedWallClockTimer"] = None):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self.start_time = 0.0
        self.count = 0
        self._owner = owner

    def _sync(self, force: bool) -> None:
        if force or self._owner is None or self._owner.enabled:
            _device_synchronize()

    def start(self, force_sync: bool = False) -> None:
        assert not self.started_, f"timer {self.name_} has already been started"
        self._sync(force_sync)
        self.start_time = time.time()
        self.started_ = True

    def stop(self, reset: bool = False, force_sync: bool = False) -> None:
        assert self.started_, f"timer {self.name_} is not started"
        self._sync(force_sync)
        if reset:
            self.elapsed_ = time.time() - self.start_time
        else:
            self.elapsed_ += time.time() - self.start_time
        self.count += 1
        self.started_ = False

    def reset(self) -> None:
        self.elapsed_ = 0.0
        self.started_ = False
        self.count = 0

    def elapsed(self, reset: bool = True) -> float:
        started = self.started_
        if started:
            self.stop()
        elapsed = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return elapsed

    def mean(self) -> float:
        return self.elapsed_ / max(self.count, 1)


class SynchronizedWallClockTimer:
    """Named timers with device synchronisation, used for wall-clock
    breakdown. ``enabled=False`` keeps the timers usable (host clocks only)
    but skips every device barrier — the engine constructs it from
    ``wall_clock_breakdown`` so breakdown-off runs pay zero syncs."""

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name, owner=self)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    @staticmethod
    def memory_usage() -> str:
        """ALL local devices, not just [0] (same aggregation as the
        engine's HBM gauges): a multi-chip host's OOM margin is set by
        its worst chip (max of peaks) and its real footprint is the sum
        of in-use across chips."""
        try:
            import jax

            peaks, in_use = [], []
            for dev in jax.local_devices():
                stats = dev.memory_stats() or {}
                if stats:
                    peaks.append(stats.get("peak_bytes_in_use", 0))
                    in_use.append(stats.get("bytes_in_use", 0))
            if not peaks:
                return "HBM stats unavailable"
            return (f"HBM in-use {sum(in_use) / 1024**3:.2f} GB | "
                    f"peak {max(peaks) / 1024**3:.2f} GB "
                    f"({len(peaks)} devices)")
        except Exception:
            return "HBM stats unavailable"

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False, ranks: Optional[List[int]] = None) -> None:
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        if memory_breakdown:
            string += " | " + self.memory_usage()
        log_dist(string, ranks=ranks or [0])


class ThroughputTimer:
    """Samples/sec tracker, skipping warm-up steps (reference ``timer.py:100``).

    ``sync=False`` skips the per-step device barriers: window durations then
    measure dispatch+queue time, which converges to device step time in
    steady state (the host can't run ahead of a bounded queue) — accurate
    enough for the periodic throughput print, and free. The engine enables
    barriers only when ``wall_clock_breakdown`` asks for exact timings."""

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: Optional[int] = None,
                 monitor_memory: bool = False, sync: bool = True):
        self.start_time = 0.0
        self.end_time = 0.0
        self.started = False
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.sync = bool(sync)

    def update_epoch_count(self) -> None:
        self.epoch_count += 1
        self.micro_step_count = 0

    def start(self) -> None:
        self.started = True
        if self.global_step_count >= self.start_step:
            if self.sync:
                _device_synchronize()
            self.start_time = time.time()

    def stop(self, report_speed: bool = True) -> None:
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        self.global_step_count += 1
        if self.start_time > 0:
            if self.sync:
                _device_synchronize()
            self.end_time = time.time()
            duration = self.end_time - self.start_time
            self.total_elapsed_time += duration
            if report_speed and self.steps_per_output and \
                    self.global_step_count % self.steps_per_output == 0:
                log_dist(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, "
                    f"throughput: {self.avg_samples_per_sec():.2f} samples/sec",
                    ranks=[0])

    def avg_samples_per_sec(self) -> float:
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = (self.global_step_count - self.start_step) * self.batch_size
            return samples / self.total_elapsed_time
        return 0.0  # not yet past warm-up: no measurement, not a sentinel
