"""Training guardrails: anomaly detection, in-memory rollback, and a step
watchdog for unattended runs (docs/RESILIENCE.md "Guardrails").

The resilience tier (PR 1) survives process *death*; telemetry (PR 2) makes
the run *observable*. This subsystem closes the remaining gap for
unattended training — the run that neither dies nor behaves:

- :class:`~deepspeed_tpu.guardrails.detector.AnomalyDetector` — EWMA/
  z-score classification of every step's (loss, global grad norm) into
  ok / skip / spike, plus a nonfinite check that works in bf16 (where the
  engine has no loss-scaler overflow path);
- :class:`~deepspeed_tpu.guardrails.rollback.RollbackPolicy` over a
  :class:`~deepspeed_tpu.guardrails.rollback.SnapshotRing` — restore the
  last good in-memory state after N consecutive spikes, advance the data
  stream past the offending window, optionally decay the LR, escalate to
  the on-disk resilience checkpoint when the ring is empty;
- :class:`~deepspeed_tpu.guardrails.watchdog.StepWatchdog` — a hung step
  (deadlocked collective, stuck host callback) dumps diagnostics and exits
  with a distinct rc that the supervisor maps to an immediate restart;
- :mod:`~deepspeed_tpu.guardrails.retry` — the shared jittered-exponential
  backoff used by the checkpoint writer, distributed init and supervisor.

Cost contract: ``build_guardrails`` returns ``None`` for a disabled block
and every engine hook is behind an ``is None`` check — a guardrails-off run
performs zero added host fetches, zero device syncs, zero snapshots
(asserted by tests/test_guardrails.py the same way the telemetry zero-sync
test does). Enabled, the per-step cost is two scalar host fetches plus an
amortised ring snapshot every ``snapshot_interval`` steps.
"""

import json
import os
from typing import Any, Callable, Optional

from deepspeed_tpu.guardrails.detector import (OK, SKIP, SPIKE,
                                               AnomalyDetector, EWMATracker,
                                               Verdict)
from deepspeed_tpu.guardrails.retry import backoff_delay, retry_call
from deepspeed_tpu.guardrails.rollback import (GuardrailsError,
                                               RollbackPolicy, SnapshotRing,
                                               restore_snapshot,
                                               take_snapshot)
from deepspeed_tpu.guardrails.watchdog import StepWatchdog, is_watchdog_exit
from deepspeed_tpu.utils.logging import logger

__all__ = [
    "OK", "SKIP", "SPIKE", "AnomalyDetector", "EWMATracker", "Verdict",
    "backoff_delay", "retry_call", "GuardrailsError", "RollbackPolicy",
    "SnapshotRing", "restore_snapshot", "take_snapshot", "StepWatchdog",
    "is_watchdog_exit", "Guardrails", "build_guardrails",
]


def _host_fetch(x) -> float:
    """THE device->host scalar fetch of this subsystem. Single site so the
    zero-cost-when-disabled test can count every guardrails-originated
    device sync by patching one name."""
    return float(x)


def _finite(z: float, cap: float = 1e9) -> float:
    """Clamp a z-score for metric emission (inf is not JSON)."""
    return max(-cap, min(cap, z))


class Guardrails:
    """Per-engine facade wiring detector + rollback + watchdog together.

    The engine owns exactly three call sites: ``step_begin``/``step_end``
    bracketing the step (watchdog deadline) and ``after_step`` with the
    step's (loss, overflow, grad-norm) device scalars (detector + policy).
    """

    def __init__(self, cfg, telemetry=None, metrics_path: Optional[str] = None,
                 goodput=None):
        self.cfg = cfg
        self.telemetry = telemetry
        self.goodput = goodput
        self.detector = AnomalyDetector(
            zscore_threshold=cfg.detector.zscore_threshold,
            warmup_steps=cfg.detector.warmup_steps,
            ewma_alpha=cfg.detector.ewma_alpha,
            track_grad_norm=cfg.detector.track_grad_norm)
        self.ring: Optional[SnapshotRing] = None
        self.policy: Optional[RollbackPolicy] = None
        if cfg.rollback.enabled:
            self.ring = SnapshotRing(cfg.rollback.ring_size)
            self.policy = RollbackPolicy(
                self.ring,
                consecutive_spikes=cfg.rollback.consecutive_spikes,
                skip_batches=cfg.rollback.skip_batches,
                lr_decay=cfg.rollback.lr_decay,
                max_rollbacks=cfg.rollback.max_rollbacks,
                escalate_to_disk=cfg.rollback.escalate_to_disk)
        self.watchdog: Optional[StepWatchdog] = None
        if cfg.watchdog.enabled:
            self.watchdog = StepWatchdog(
                timeout=cfg.watchdog.step_timeout_seconds,
                crashdump_dir=cfg.watchdog.crashdump_dir,
                exit_code=cfg.watchdog.exit_code,
                poll_interval=cfg.watchdog.poll_interval_seconds,
                telemetry=telemetry,
                metrics_tail_of=metrics_path).start()
        self._data_skip_fn: Optional[Callable[[int], None]] = None
        self.last_verdict: Optional[Verdict] = None
        # Numerics integration (telemetry/numerics.py): spike verdicts
        # name the worst-offending layer group and leave a bounded number
        # of spike crashdumps naming it (budget from the numerics block).
        self.metrics_path = metrics_path
        self._spike_dumps = 0

    # ------------------------------------------------------------------
    @property
    def lr_scale(self) -> float:
        return self.policy.lr_scale if self.policy is not None else 1.0

    def register_data_skip_fn(self, fn: Callable[[int], None]) -> None:
        self._data_skip_fn = fn

    def step_begin(self, step: int, label: str = "train_step") -> None:
        if self.watchdog is not None:
            self.watchdog.step_begin(step, label)

    def step_end(self) -> None:
        if self.watchdog is not None:
            self.watchdog.step_end()

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()

    # ------------------------------------------------------------------
    def after_step(self, engine, loss: Any, overflow: Any,
                   norm: Any = None) -> bool:
        """Feed one committed step's scalars through detection + policy.
        Returns True when a rollback rewound the engine (the caller then
        skips its own fail-fast numerics check for this step)."""
        step = int(engine.global_steps)
        of = bool(_host_fetch(overflow)) if overflow is not None else False
        lossf = _host_fetch(loss)
        normf = _host_fetch(norm) if norm is not None else None
        verdict = self.detector.observe(step, lossf, grad_norm=normf,
                                        overflow=of)
        self.last_verdict = verdict
        # Numerics observatory (telemetry/numerics.py): a spike names
        # the worst-offending layer group — the first nonfinite grad
        # group, else the largest grad-to-weight ratio. One extra
        # transfer, on (rare) spike verdicts only.
        worst = None
        numerics = getattr(engine, "numerics", None)
        if verdict.kind == SPIKE and numerics is not None:
            try:
                worst = numerics.worst_group()
            except Exception as e:  # noqa: BLE001 — naming is best-effort
                logger.warning("guardrails: numerics worst_group failed: "
                               "%s", e)
        self._emit(step, verdict, worst_group=worst)
        if verdict.kind == SPIKE:
            logger.warning(
                "guardrails: spike verdict at step %d (%s: loss=%.6g "
                "loss_z=%.3g norm_z=%.3g%s, streak %d/%s)", step,
                verdict.reason, lossf, verdict.loss_z, verdict.norm_z,
                f", worst layer group '{worst}'" if worst else "",
                (self.policy.spike_streak + 1) if self.policy else 1,
                self.policy.consecutive_spikes if self.policy else "-")
            if numerics is not None:
                self._write_spike_dump(engine, step, verdict, worst,
                                       numerics)
            if self.policy is not None and self.policy.note_spike():
                # Recovery is not a step: a disk-escalation restore or a
                # long loader skip must not trip the step deadline and
                # convert a cheap rollback into a watchdog kill.
                if self.watchdog is not None:
                    self.watchdog.suspend()
                if self.goodput is not None:
                    # The restore (+ loader skip) is lost time with its own
                    # goodput category; the re-executed steps that follow
                    # are booked as rollback_replay by the engine.
                    with self.goodput.measure("rollback_restore"):
                        summary = self.policy.rollback(engine,
                                                       self._data_skip_fn)
                else:
                    summary = self.policy.rollback(engine, self._data_skip_fn)
                self._emit_rollback(step, summary)
                return True
        elif verdict.kind == OK:
            if self.policy is not None:
                self.policy.note_ok()
            # Prime the ring at the FIRST ok step (a spike before the first
            # interval boundary would otherwise find it empty), then refresh
            # every snapshot_interval steps.
            if self.ring is not None and (
                    len(self.ring) == 0
                    or step % self.cfg.rollback.snapshot_interval == 0):
                self.ring.push(take_snapshot(engine))
                self._counter("guardrails/snapshots", step)
        return False

    # ------------------------------------------------------------------
    def _emit(self, step: int, verdict: Verdict,
              worst_group: Optional[str] = None) -> None:
        tel = self.telemetry
        if tel is None or not tel.enabled:
            return
        reg = tel.registry
        reg.counter(f"guardrails/steps_{verdict.kind}").inc(step=step)
        reg.gauge("guardrails/loss_zscore").set(_finite(verdict.loss_z),
                                                step=step)
        if verdict.norm_z:
            reg.gauge("guardrails/grad_norm_zscore").set(
                _finite(verdict.norm_z), step=step)
        if verdict.kind == SPIKE:
            extra = ({"worst_group": worst_group} if worst_group else {})
            tel.instant("guardrails_spike", step=step, reason=verdict.reason,
                        loss_z=_finite(verdict.loss_z), **extra)

    def _write_spike_dump(self, engine, step: int, verdict: Verdict,
                          worst_group: Optional[str], numerics) -> None:
        """Spike crashdump: the guardrails-format directory naming the
        worst layer group plus the full per-group numerics table —
        "which layer blew up" answered post-mortem, not just in a log
        line. Bounded by ``telemetry.numerics.max_spike_dumps`` (spikes
        can streak; disk must not)."""
        budget = int(getattr(numerics.cfg, "max_spike_dumps", 8))
        if self._spike_dumps >= budget:
            return
        out = os.path.join(self.cfg.watchdog.crashdump_dir,
                           f"spike_step{step}_{os.getpid()}")
        try:
            os.makedirs(out, exist_ok=True)
            info = {
                "kind": "spike",
                "step": int(step),
                "reason": verdict.reason,
                "loss_z": _finite(verdict.loss_z),
                "norm_z": _finite(verdict.norm_z),
                "worst_group": worst_group,
                "groups": numerics.group_table(),
            }
            with open(os.path.join(out, "info.json"), "w") as f:
                json.dump(info, f, indent=1)
            from deepspeed_tpu.telemetry.memory import write_metrics_tail
            write_metrics_tail(out, self.metrics_path)
            self._spike_dumps += 1
            logger.warning("guardrails: spike crashdump written to %s "
                           "(worst layer group: %s)", out, worst_group)
        except Exception as e:  # noqa: BLE001 — group_table's device
            # fetch can raise backend errors exactly when spikes happen
            # (unhealthy device); a diagnostic dump must never take down
            # the training loop it diagnoses.
            logger.warning("guardrails: spike crashdump failed: %s", e)

    def _emit_rollback(self, step: int, summary: dict) -> None:
        tel = self.telemetry
        if tel is None or not tel.enabled:
            return
        tel.registry.counter("guardrails/rollbacks").inc(step=step)
        tel.instant("guardrails_rollback", step=step, **{
            k: v for k, v in summary.items() if v is not None})

    def _counter(self, name: str, step: int) -> None:
        tel = self.telemetry
        if tel is not None and tel.enabled:
            tel.registry.counter(name).inc(step=step)


def build_guardrails(gcfg, telemetry=None,
                     metrics_path: Optional[str] = None,
                     goodput=None) -> Optional[Guardrails]:
    """``None`` for a disabled block — the engine's hooks gate on ``is
    None``, which is the whole zero-cost-when-disabled story."""
    if gcfg is None or not gcfg.enabled:
        return None
    return Guardrails(gcfg, telemetry=telemetry, metrics_path=metrics_path,
                      goodput=goodput)
