"""Per-step anomaly detection over loss and global grad norm.

The fp16 engine already has an in-device overflow path (dynamic loss scaler
skips the update), but a bf16 run has *no* numeric guardrail: a poisoned
batch or an instability NaNs the loss, the NaN gradients commit into the
params, and every later step trains garbage — silently, because nothing on
the step path looks at the loss. This detector is the host-side watchpost:
it classifies every committed step as

- ``ok``    — finite and statistically unremarkable;
- ``skip``  — the engine itself skipped the update (fp16 overflow, or the
  config-gated bf16 nonfinite-grad check): state is untouched, nothing to
  learn from the garbage scalars, so the trackers ignore them;
- ``spike`` — non-finite loss/norm that DID commit, or a finite value whose
  z-score against an exponentially-weighted mean/variance exceeds the
  threshold. State is suspect; :mod:`~deepspeed_tpu.guardrails.rollback`
  decides what to do about it.

EWMA/z-score rather than fixed thresholds: loss scales vary by orders of
magnitude across models and schedules, and the early-training descent is
steep — an absolute "loss > X" rule is either deaf or trigger-happy. The
exponentially-weighted tracker follows the trajectory with O(1) state and
no window buffer; spikes are *excluded* from the update so a genuine
anomaly cannot drag the baseline toward itself and mask its successors.
"""

import math
from dataclasses import dataclass
from typing import Optional

# Verdicts (string enum kept as plain constants: they travel into telemetry
# tags and log lines as-is).
OK = "ok"
SKIP = "skip"
SPIKE = "spike"


@dataclass
class Verdict:
    """One step's classification plus the evidence behind it."""

    kind: str                       # OK | SKIP | SPIKE
    reason: str = ""                # "", "overflow", "nonfinite", "zscore"
    loss_z: float = 0.0
    norm_z: float = 0.0

    def __bool__(self) -> bool:     # truthy == anomalous
        return self.kind == SPIKE


class EWMATracker:
    """Exponentially-weighted mean/variance with a sigma floor.

    Standard EW update (West 1979 form): ``diff = x - mean``;
    ``mean += alpha * diff``; ``var = (1-alpha) * (var + alpha * diff^2)``.
    The sigma floor (``abs_floor + rel_floor * |mean|``) keeps the z-score
    finite on flat-lined signals (a converged loss has sigma -> 0 and any
    wiggle would otherwise read as an infinite spike).
    """

    def __init__(self, alpha: float = 0.02, abs_floor: float = 1e-8,
                 rel_floor: float = 1e-3):
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        self.alpha = float(alpha)
        self.abs_floor = float(abs_floor)
        self.rel_floor = float(rel_floor)
        self.mean = 0.0
        self.var = 0.0
        self.count = 0

    def sigma(self) -> float:
        return math.sqrt(max(self.var, 0.0)) + self.abs_floor + \
            self.rel_floor * abs(self.mean)

    def zscore(self, x: float) -> float:
        if self.count == 0:
            return 0.0
        return (x - self.mean) / self.sigma()

    def update(self, x: float) -> None:
        if self.count == 0:
            self.mean = x
            self.var = 0.0
        else:
            diff = x - self.mean
            self.mean += self.alpha * diff
            self.var = (1.0 - self.alpha) * (self.var +
                                             self.alpha * diff * diff)
        self.count += 1

    def state_dict(self) -> dict:
        return {"mean": self.mean, "var": self.var, "count": self.count}

    def load_state_dict(self, sd: dict) -> None:
        self.mean = float(sd["mean"])
        self.var = float(sd["var"])
        self.count = int(sd["count"])


class AnomalyDetector:
    """Classify per-step (loss, grad_norm, overflow) host scalars.

    ``warmup_steps`` observations are absorbed before any z-score verdict —
    the early-training loss cliff would otherwise read as a run of spikes.
    Non-finite values are spikes at ANY step (warmup included): there is no
    baseline under which NaN is fine.
    """

    def __init__(self,
                 zscore_threshold: float = 6.0,
                 warmup_steps: int = 20,
                 ewma_alpha: float = 0.02,
                 track_grad_norm: bool = True):
        if zscore_threshold <= 0:
            raise ValueError("zscore_threshold must be > 0")
        if warmup_steps < 1:
            raise ValueError("warmup_steps must be >= 1")
        self.zscore_threshold = float(zscore_threshold)
        self.warmup_steps = int(warmup_steps)
        self.track_grad_norm = bool(track_grad_norm)
        self.loss_tracker = EWMATracker(alpha=ewma_alpha)
        self.norm_tracker = EWMATracker(alpha=ewma_alpha)
        self.stats = {OK: 0, SKIP: 0, SPIKE: 0}

    # ------------------------------------------------------------------
    def observe(self, step: int, loss: float,
                grad_norm: Optional[float] = None,
                overflow: bool = False) -> Verdict:
        """One committed (or engine-skipped) step's scalars -> verdict."""
        if overflow:
            # The engine already refused the update; the scalars are the
            # garbage that triggered the refusal — do not learn from them.
            return self._count(Verdict(SKIP, reason="overflow"))
        loss = float(loss)
        nonfinite = not math.isfinite(loss)
        if grad_norm is not None:
            grad_norm = float(grad_norm)
            nonfinite = nonfinite or not math.isfinite(grad_norm)
        if nonfinite:
            return self._count(Verdict(SPIKE, reason="nonfinite",
                                       loss_z=float("inf")))
        loss_z = self.loss_tracker.zscore(loss)
        norm_z = (self.norm_tracker.zscore(grad_norm)
                  if self.track_grad_norm and grad_norm is not None else 0.0)
        warm = self.loss_tracker.count >= self.warmup_steps
        if warm and max(loss_z, norm_z) > self.zscore_threshold:
            # Spikes are excluded from the EWMA so an anomaly cannot pull
            # the baseline toward itself.
            return self._count(Verdict(SPIKE, reason="zscore",
                                       loss_z=loss_z, norm_z=norm_z))
        self.loss_tracker.update(loss)
        if self.track_grad_norm and grad_norm is not None:
            self.norm_tracker.update(grad_norm)
        return self._count(Verdict(OK, loss_z=loss_z, norm_z=norm_z))

    def _count(self, v: Verdict) -> Verdict:
        self.stats[v.kind] += 1
        return v

    # ------------------------------------------------------------------
    def state_dict(self) -> dict:
        return {"loss": self.loss_tracker.state_dict(),
                "norm": self.norm_tracker.state_dict()}

    def load_state_dict(self, sd: dict) -> None:
        self.loss_tracker.load_state_dict(sd["loss"])
        self.norm_tracker.load_state_dict(sd["norm"])
