"""Step watchdog: detect a hung training step and die loudly, with evidence.

On a TPU pod the nastiest failure is not a crash but a *hang*: one worker
stalls in a collective (peer died mid-allreduce, DCN link flap, a stuck
host callback) and every other worker blocks with it — forever, burning
the reservation, while the supervisor sees a perfectly alive process. The
watchdog turns that silence into a distinct, restartable death:

- the engine brackets every step with :meth:`step_begin` / :meth:`step_end`;
- a daemon thread checks, at ``poll_interval``, whether an *armed* step has
  exceeded ``timeout`` (idle time between steps never counts — eval pauses
  and dataset stalls are not hangs);
- on trip it dumps diagnostics to a crashdump dir — faulthandler stacks of
  every thread (the hung collective's frame included), the recent telemetry
  trace events, and the tail of the metrics JSONL — then exits the process
  with a **distinct** exit code (:data:`~deepspeed_tpu.config.constants.
  GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT`), which the resilience supervisor
  maps to an immediate (no-backoff) restart + auto-resume.

``os._exit`` on purpose: a hung step cannot be unwound by exceptions (the
main thread is blocked inside a device wait), and atexit handlers may
themselves be the hung parties. The crashdump is flushed first; the
process must *go*.
"""

import json
import os
import threading
import time
from typing import Any, Callable, Optional

from deepspeed_tpu.config.constants import \
    GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT
from deepspeed_tpu.utils.logging import logger


class StepWatchdog:
    """Deadline monitor for the training step. One per engine."""

    def __init__(self,
                 timeout: float,
                 crashdump_dir: str = "crashdumps",
                 exit_code: int = GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT,
                 poll_interval: Optional[float] = None,
                 telemetry=None,
                 metrics_tail_of: Optional[str] = None,
                 exit_fn: Callable[[int], None] = os._exit):
        if timeout <= 0:
            raise ValueError("watchdog timeout must be > 0 seconds")
        if poll_interval is not None and poll_interval <= 0:
            raise ValueError("watchdog poll_interval must be > 0 seconds "
                             "(non-positive would busy-spin the thread)")
        self.timeout = float(timeout)
        self.crashdump_dir = crashdump_dir
        self.exit_code = int(exit_code)
        self.poll_interval = (float(poll_interval) if poll_interval
                              else max(0.05, min(1.0, self.timeout / 4.0)))
        self.telemetry = telemetry
        self.metrics_tail_of = metrics_tail_of
        self._exit_fn = exit_fn
        self._lock = threading.Lock()
        self._armed_at: Optional[float] = None
        self._depth = 0            # re-entrant: pipe_step wraps train_step
        self._step = 0
        self._label = ""
        self.tripped = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def start(self) -> "StepWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="guardrails-watchdog",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ------------------------------------------------------------------
    def step_begin(self, step: int, label: str = "train_step") -> None:
        """Arm the deadline. Re-entrant: only the outermost bracket arms
        (the pipeline engine wraps the base engine's train_batch)."""
        with self._lock:
            self._depth += 1
            if self._depth == 1:
                self._armed_at = time.monotonic()
                self._step = int(step)
                self._label = label

    def step_end(self) -> None:
        with self._lock:
            self._depth = max(0, self._depth - 1)
            if self._depth == 0:
                self._armed_at = None

    def suspend(self) -> None:
        """Fully disarm at ANY bracket depth. Rollback recovery (disk
        restore, reshard, loader skip) runs inside the step's armed window
        but is not a step — it must not be killed by the step deadline.
        The enclosing step_end finallys re-balance harmlessly (depth
        clamps at 0) and the next step_begin re-arms cleanly."""
        with self._lock:
            self._depth = 0
            self._armed_at = None

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            with self._lock:
                armed_at, step, label = self._armed_at, self._step, self._label
            if armed_at is None:
                continue
            elapsed = time.monotonic() - armed_at
            if elapsed > self.timeout:
                self.trip(step, elapsed, label)
                return

    def trip(self, step: int, elapsed: float, label: str = "") -> None:
        """Deadline exceeded: dump diagnostics and exit with the distinct
        rc. Split out (and ``exit_fn`` injectable) so tests exercise the
        dump without killing the test process."""
        self.tripped = True
        logger.error(
            "guardrails watchdog: %s for step %d exceeded the %.1fs "
            "deadline (%.1fs elapsed) — dumping diagnostics and exiting "
            "rc=%d for supervisor restart", label or "step", step,
            self.timeout, elapsed, self.exit_code)
        try:
            dump = self.dump_diagnostics(step, elapsed, label)
            logger.error("guardrails watchdog: crashdump at %s", dump)
        except Exception as e:  # noqa: BLE001 — dying loudly beats dying twice
            logger.error("guardrails watchdog: diagnostics dump failed: %s", e)
        self._exit_fn(self.exit_code)

    # ------------------------------------------------------------------
    def dump_diagnostics(self, step: int, elapsed: float,
                         label: str = "") -> str:
        """Write the evidence a post-mortem needs into a fresh directory
        under ``crashdump_dir``; every artifact is best-effort."""
        out = os.path.join(self.crashdump_dir,
                           f"watchdog_step{step}_{os.getpid()}")
        os.makedirs(out, exist_ok=True)
        info: dict = {"step": step, "elapsed_sec": round(elapsed, 3),
                      "timeout_sec": self.timeout, "label": label,
                      "pid": os.getpid(), "exit_code": self.exit_code}

        # 1. Thread stacks — the hung collective / callback frame.
        try:
            import faulthandler
            with open(os.path.join(out, "stacks.txt"), "w") as f:
                faulthandler.dump_traceback(file=f, all_threads=True)
            info["stacks"] = "stacks.txt"
        except Exception as e:  # noqa: BLE001
            info["stacks_error"] = repr(e)

        # 2. Recent telemetry trace events (the spans leading into the hang).
        tel = self.telemetry
        if tel is not None and getattr(tel, "enabled", False):
            try:
                events = tel.tracer.events()[-200:]
                with open(os.path.join(out, "trace_tail.json"), "w") as f:
                    json.dump({"traceEvents": events}, f)
                info["trace_tail"] = "trace_tail.json"
            except Exception as e:  # noqa: BLE001
                info["trace_tail_error"] = repr(e)

        # 3. All-device memory stats + headroom (shared artifact with the
        # memory observatory's OOM crashdump): a hung collective under
        # memory pressure (allocator thrash, a peer that OOM-killed
        # mid-allreduce) looks identical to a network hang without this.
        try:
            from deepspeed_tpu.telemetry.memory import \
                collect_memory_snapshot
            with open(os.path.join(out, "memory.json"), "w") as f:
                json.dump(collect_memory_snapshot(), f, indent=1)
            info["memory"] = "memory.json"
        except Exception as e:  # noqa: BLE001
            info["memory_error"] = repr(e)

        # 4. Tail of the metrics JSONL (last scalar lines before the
        # hang) — the shared crashdump artifact (telemetry/memory.py
        # write_metrics_tail, same as the OOM dump).
        try:
            from deepspeed_tpu.telemetry.memory import write_metrics_tail
            name = write_metrics_tail(out, self.metrics_tail_of)
            if name:
                info["metrics_tail"] = name
        except Exception as e:  # noqa: BLE001
            info["metrics_tail_error"] = repr(e)

        with open(os.path.join(out, "info.json"), "w") as f:
            json.dump(info, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        self._emit_trip_telemetry(step)
        return out

    def _emit_trip_telemetry(self, step: int) -> None:
        tel = self.telemetry
        if tel is None or not getattr(tel, "enabled", False):
            return
        try:
            tel.registry.counter("guardrails/watchdog_trips").inc(step=step)
            tel.instant("guardrails_watchdog_trip", step=step)
            tel.flush()
        except Exception:  # noqa: BLE001 — never block the exit on telemetry
            pass


def is_watchdog_exit(rc: Optional[int]) -> bool:
    """Did a child process die by watchdog? (The supervisor's immediate-
    restart predicate; a custom exit_code must be passed to the supervisor
    via ``immediate_restart_rcs``.)"""
    return rc == GUARDRAILS_WATCHDOG_EXIT_CODE_DEFAULT
