"""Shared jittered-exponential-backoff helper.

Every retry loop in the codebase computes the same thing — attempt k waits
``base * factor**k`` — and each had grown its own ad-hoc copy with its own
bugs (the supervisor's delay was unbounded, the checkpoint writer's had no
jitter, distributed init had no retry at all). This module is the single
implementation: a pure delay schedule (:func:`backoff_delay`) plus a
driver (:func:`retry_call`) for call sites that retry a whole callable.

Jitter exists for the fleet, not the host: when a shared dependency (GCS,
the coordinator, a flaky NFS mount) hiccups, every worker retries at the
same instant unless the schedule is de-synchronised. The default ±25%%
multiplicative jitter is enough to spread a pod's retries across a window
while keeping the expected delay equal to the un-jittered schedule.
"""

import random
import time
from typing import Callable, Optional, Tuple, Type

from deepspeed_tpu.utils.logging import logger


def backoff_delay(attempt: int,
                  base: float,
                  factor: float = 2.0,
                  max_delay: Optional[float] = None,
                  jitter: float = 0.25,
                  rng: Optional[random.Random] = None) -> float:
    """Delay (seconds) before retry ``attempt`` (0-based).

    ``base * factor**attempt``, capped at ``max_delay`` (cap applied BEFORE
    jitter so the cap is a true ceiling on the expectation, and a huge
    attempt count can never overflow into an astronomically long sleep),
    then scaled by a uniform factor in ``[1-jitter, 1+jitter]``.
    ``rng`` makes the jitter deterministic for tests.
    """
    if attempt < 0:
        raise ValueError("attempt must be >= 0")
    if base < 0:
        raise ValueError("base must be >= 0")
    # factor**attempt with the cap folded in early: stop multiplying once
    # past the cap instead of computing an unbounded float power.
    delay = float(base)
    for _ in range(int(attempt)):
        delay *= factor
        if max_delay is not None and delay >= max_delay:
            break
    if max_delay is not None:
        delay = min(delay, float(max_delay))
    if jitter:
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        u = (rng.uniform if rng is not None else random.uniform)(
            1.0 - jitter, 1.0 + jitter)
        delay *= u
    return delay


def retry_call(fn: Callable,
               *args,
               max_retries: int = 3,
               base: float = 0.5,
               factor: float = 2.0,
               max_delay: Optional[float] = None,
               jitter: float = 0.25,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               describe: str = "",
               rng: Optional[random.Random] = None,
               sleep: Callable[[float], None] = time.sleep,
               **kwargs):
    """Call ``fn(*args, **kwargs)``; on ``retry_on`` failure, sleep a
    jittered-exponential delay and retry, up to ``max_retries`` retries
    (``max_retries + 1`` total attempts). The terminal failure re-raises.
    """
    if max_retries < 0:
        raise ValueError("max_retries must be >= 0")
    what = describe or getattr(fn, "__name__", "call")
    for attempt in range(max_retries + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if attempt >= max_retries:
                raise
            delay = backoff_delay(attempt, base, factor=factor,
                                  max_delay=max_delay, jitter=jitter, rng=rng)
            logger.warning("%s attempt %d/%d failed (%s); retrying in %.3fs",
                           what, attempt + 1, max_retries + 1, e, delay)
            sleep(delay)
