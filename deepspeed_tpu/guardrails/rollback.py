"""In-memory rollback: a bounded ring of last-good state snapshots plus the
policy that decides when to restore one.

The resilience tier (``resilience/checkpoint.py``) already knows how to
snapshot an engine to host RAM and how to place saved arrays back onto the
engine's shardings — that machinery is reused wholesale here. The delta is
*where* the snapshot lives (a host-RAM ring, never disk) and *why* it is
restored (a numeric anomaly, not a process death): recovering from a NaN
spike via the on-disk path costs a full deserialize + reshard and loses up
to ``checkpoint.interval`` steps; the in-memory ring restores in one
device_put sweep and loses only the steps since the last ring push.

Policy (:class:`RollbackPolicy`): after ``consecutive_spikes`` spike
verdicts in a row, restore the newest ring snapshot, ask the data pipeline
to skip ``skip_batches`` batches (the poisoned window — batches consumed
since the snapshot are already behind the loader and are dropped by
construction), optionally decay the LR, and count the rollback against
``max_rollbacks``. With the ring empty the policy escalates to the newest
on-disk resilience checkpoint; with nothing anywhere it raises — training
on known-poisoned state is the one thing guardrails exist to prevent.
"""

import collections
from typing import Any, Callable, Optional

from deepspeed_tpu.utils.logging import logger


class GuardrailsError(RuntimeError):
    """Anomaly detected and no recovery path remains."""


class SnapshotRing:
    """Bounded ring of host-side engine snapshots (newest wins).

    Entries are the resilience tier's ``_Snapshot`` objects
    (:func:`deepspeed_tpu.resilience.snapshot_engine`) — host numpy copies
    of the full TrainState plus step/scheduler metadata, exactly what an
    in-memory restore needs. Memory is bounded by ``capacity`` full state
    copies; size the ring against host RAM, not ambition (2 is plenty: one
    known-good state plus one older fallback).
    """

    def __init__(self, capacity: int = 2):
        if capacity < 1:
            raise ValueError("snapshot ring capacity must be >= 1")
        self.capacity = int(capacity)
        self._ring = collections.deque(maxlen=self.capacity)
        self.pushes = 0

    def push(self, snap: Any) -> None:
        self._ring.append(snap)
        self.pushes += 1

    def newest(self) -> Optional[Any]:
        return self._ring[-1] if self._ring else None

    def drop_newest(self) -> None:
        """Discard the newest snapshot (it proved bad: restoring it did not
        stop the spikes, so the next rollback should reach further back)."""
        if self._ring:
            self._ring.pop()

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


def _params_finite(engine) -> bool:
    """One host fetch over a stacked per-leaf isfinite reduction — cheap
    relative to the disk restore it sanity-checks."""
    import jax
    import jax.numpy as jnp

    leaves = [x for x in jax.tree_util.tree_leaves(engine.state.params)
              if hasattr(x, "dtype")
              and jnp.issubdtype(x.dtype, jnp.inexact)]
    if not leaves:
        return True
    flags = jax.jit(lambda ls: jnp.stack(
        [jnp.all(jnp.isfinite(x)) for x in ls]))(leaves)
    return bool(jnp.all(flags))


def take_snapshot(engine) -> Any:
    """Host snapshot of the engine's full training state (reuses the
    resilience D2H machinery; no disk I/O)."""
    from deepspeed_tpu.resilience.checkpoint import snapshot_engine

    return snapshot_engine(engine)


def restore_snapshot(engine, snap) -> int:
    """Install a ring snapshot back onto the engine (device placement via
    the resilience restore path). Returns the number of optimizer steps
    rewound."""
    from deepspeed_tpu.resilience.checkpoint import install_state_arrays

    before = int(engine.global_steps)
    install_state_arrays(engine, dict(snap.arrays),
                         step=int(snap.meta["step"]),
                         micro_steps=int(snap.meta["micro_steps"]),
                         lr_scheduler_state=snap.meta.get("lr_scheduler"))
    return before - int(engine.global_steps)


class RollbackPolicy:
    """Spike-streak bookkeeping + the rollback act itself."""

    def __init__(self,
                 ring: SnapshotRing,
                 consecutive_spikes: int = 2,
                 skip_batches: int = 2,
                 lr_decay: float = 1.0,
                 max_rollbacks: int = 3,
                 escalate_to_disk: bool = True):
        if consecutive_spikes < 1:
            raise ValueError("consecutive_spikes must be >= 1")
        if skip_batches < 0:
            raise ValueError("skip_batches must be >= 0")
        if not 0.0 < lr_decay <= 1.0:
            raise ValueError("lr_decay must be in (0, 1]")
        if max_rollbacks < 1:
            raise ValueError("max_rollbacks must be >= 1")
        self.ring = ring
        self.consecutive_spikes = int(consecutive_spikes)
        self.skip_batches = int(skip_batches)
        self.lr_decay = float(lr_decay)
        self.max_rollbacks = int(max_rollbacks)
        self.escalate_to_disk = bool(escalate_to_disk)
        self.spike_streak = 0
        self.rollbacks = 0
        self.lr_scale = 1.0

    # ------------------------------------------------------------------
    def note_ok(self) -> None:
        self.spike_streak = 0

    def note_spike(self) -> bool:
        """Record one spike verdict; True when the streak crossed the
        rollback threshold (the caller then invokes :meth:`rollback`)."""
        self.spike_streak += 1
        return self.spike_streak >= self.consecutive_spikes

    # ------------------------------------------------------------------
    def rollback(self, engine,
                 data_skip_fn: Optional[Callable[[int], None]] = None) -> dict:
        """Restore the last good state and move the data stream past the
        offending window. Returns a summary dict for telemetry/logs."""
        if self.rollbacks >= self.max_rollbacks:
            raise GuardrailsError(
                f"guardrails: rollback budget exhausted "
                f"({self.max_rollbacks}) and loss is still spiking at step "
                f"{engine.global_steps} — the instability is not transient; "
                "aborting rather than training on poisoned state")
        self.rollbacks += 1
        self.spike_streak = 0
        snap = self.ring.newest()
        summary = {"rollbacks": self.rollbacks, "skipped_batches": 0,
                   "steps_rewound": 0, "source": None}
        if snap is not None:
            steps_rewound = restore_snapshot(engine, snap)
            # A re-triggered rollback should not restore this same snapshot
            # again (its trajectory just spiked); fall back one deeper.
            self.ring.drop_newest()
            summary.update(source="memory", steps_rewound=steps_rewound,
                           restored_step=int(engine.global_steps))
        elif self.escalate_to_disk and self._disk_dir(engine):
            from deepspeed_tpu.resilience import restore

            path, _ = restore(engine, self._disk_dir(engine))
            if path is None:
                raise GuardrailsError(
                    "guardrails: spike streak with no in-memory snapshot "
                    "and no complete on-disk checkpoint to escalate to")
            # Digest-valid is not numerics-valid: the engine skips interval
            # saves on spike verdicts, but a checkpoint written before
            # guardrails were enabled (or by an older build) could still
            # hold non-finite params — restoring it would burn the whole
            # rollback budget re-spiking. Fail loudly instead.
            if not _params_finite(engine):
                raise GuardrailsError(
                    f"guardrails: escalated to on-disk checkpoint {path} "
                    "but its params are non-finite — the newest complete "
                    "checkpoint is itself poisoned; restore an older one "
                    "manually")
            summary.update(source="disk", path=path,
                           restored_step=int(engine.global_steps))
        else:
            raise GuardrailsError(
                "guardrails: spike streak with no in-memory snapshot and "
                "disk escalation unavailable (enable resilience "
                "checkpointing or increase guardrails.rollback.ring_size)")
        if self.lr_decay < 1.0:
            self.lr_scale *= self.lr_decay
            summary["lr_scale"] = self.lr_scale
        if data_skip_fn is not None and self.skip_batches:
            data_skip_fn(self.skip_batches)
            summary["skipped_batches"] = self.skip_batches
        elif self.skip_batches:
            logger.warning(
                "guardrails: no data-skip callback registered "
                "(engine.register_data_skip_fn) — the loader will replay "
                "from its current position; if the anomaly is data-borne "
                "the same window may spike again")
        logger.warning("guardrails: rolled back to step %s from %s "
                       "(rollback %d/%d, skipped %d batches)",
                       summary.get("restored_step"), summary["source"],
                       self.rollbacks, self.max_rollbacks,
                       summary["skipped_batches"])
        return summary

    @staticmethod
    def _disk_dir(engine) -> str:
        rcfg = getattr(engine.config, "resilience", None)
        if rcfg is not None and rcfg.enabled:
            return rcfg.checkpoint.dir
        return ""
