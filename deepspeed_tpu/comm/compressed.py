"""Error-compensated 1-bit compressed allreduce — TPU-native.

Re-design of the reference's ``NcclBackend.compressed_allreduce``
(``deepspeed/runtime/comm/nccl.py:47``): sign-compress the compensated
tensor to 1 bit/element (packed 8-per-uint8 — the CuPy ``packbits`` role,
``runtime/compression/cupy.py``), all_to_all the packed chunks so each rank
server-averages one chunk of the tensor, re-compress the average with
server-side error feedback, and all_gather the result. Wire volume per rank
≈ 2 × numel/8 bytes + scales, vs 2 × numel × 4 for fp32 ring allreduce —
the raison d'être is slow DCN links between pod slices.

Runs inside a shard_map manual over one mesh axis (default ``data``); the
packing is plain jnp (a reshape + matmul with powers of two) which XLA
vectorises on the VPU — no custom kernel needed.

Error feedback: both worker and server errors are carried by the caller
(the 1-bit optimizers store them as optimizer state), making the op pure.
"""

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import DATA_AXIS

# numpy, NOT jnp: a module-level jnp value becomes a leaked tracer if this
# module is first imported inside a jit trace (e.g. the sparse-grad VJP's
# lazy `from deepspeed_tpu.comm.sparse import ...`).
import numpy as _np

_POW2 = 2 ** _np.arange(8, dtype=_np.uint8)


def pack_signs(bits: jax.Array) -> jax.Array:
    """bool[..., 8k] -> uint8[..., k]: 8 sign bits per byte."""
    *lead, n = bits.shape
    assert n % 8 == 0, f"pack length {n} not a multiple of 8"
    grouped = bits.reshape(*lead, n // 8, 8).astype(jnp.uint8)
    return jnp.sum(grouped * _POW2, axis=-1, dtype=jnp.uint8)


def unpack_signs(packed: jax.Array, n: int,
                 dtype=jnp.float32) -> jax.Array:
    """uint8[..., k] -> ``dtype``[..., 8k] of ±1. The decompress dtype is
    a parameter so a bf16 error-feedback pipeline stays bf16 end-to-end
    instead of silently upcasting every unpacked sign to fp32."""
    bits = (packed[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    bits = bits.reshape(*packed.shape[:-1], -1)[..., :n]
    return bits.astype(dtype) * 2.0 - 1.0


def _compress(x: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """-> (packed uint8, scale, decompressed). Scale = mean|x| preserves
    the l1 norm under sign compression (the reference's scale choice).
    Scale and decompressed stay in x's dtype — the 1-bit protocol's
    error-feedback arithmetic must not upcast bf16 traffic to fp32."""
    scale = jnp.mean(jnp.abs(x), axis=-1, keepdims=True)
    bits = x >= 0
    decompressed = (bits.astype(x.dtype) * 2.0 - 1.0) * scale
    return pack_signs(bits), scale, decompressed


def compressed_allreduce_local(x: jax.Array,
                               worker_error: jax.Array,
                               server_error: jax.Array,
                               axis: str,
                               n: int):
    """The manual-region body: x is this rank's LOCAL tensor [numel]
    (numel % (8*n) == 0). Returns (averaged [numel], new_worker_error,
    new_server_error [numel/n])."""
    numel = x.shape[0]
    chunk = numel // n

    # -- worker phase: compensate, compress, ship chunks -------------------
    compensated = x + worker_error
    chunks = compensated.reshape(n, chunk)
    packed, scales, decompressed = _compress(chunks)      # [n, chunk/8],[n,1]
    new_worker_error = compensated - decompressed.reshape(numel)
    # all_to_all: rank r receives every rank's r-th chunk.
    recv_packed = jax.lax.all_to_all(packed, axis, split_axis=0,
                                     concat_axis=0, tiled=False)
    recv_scales = jax.lax.all_to_all(scales, axis, split_axis=0,
                                     concat_axis=0, tiled=False)
    # -- server phase: average my chunk across workers, re-compress --------
    # Decompress in x's dtype throughout: the error-feedback state carries
    # the caller's precision and a hard-coded fp32 here used to upcast
    # every bf16 pipeline (jaxpr-level test in tests/test_onebit.py).
    signs = unpack_signs(recv_packed, chunk, dtype=x.dtype)  # [n, chunk] ±1
    avg = jnp.mean(signs * recv_scales, axis=0)           # [chunk]
    served = avg + server_error
    s_packed, s_scale, s_decompressed = _compress(served[None])
    new_server_error = served - s_decompressed[0]
    # -- gather the served chunks back to everyone -------------------------
    all_packed = jax.lax.all_gather(s_packed, axis, axis=0)   # [n,1,chunk/8]
    all_scales = jax.lax.all_gather(s_scale, axis, axis=0)    # [n,1,1]
    result = (unpack_signs(all_packed[:, 0], chunk, dtype=x.dtype) *
              all_scales[:, 0]).reshape(numel)
    return result, new_worker_error, new_server_error


def sync_momentum_compressed(m_local: jax.Array,
                             worker_error: jax.Array,
                             server_error: jax.Array,
                             axis: str,
                             n: int):
    """Shared 1-bit momentum sync used by OneBitAdam/OneBitLamb: pad the
    local momentum into the worker-error's aligned flat layout, run the
    error-compensated allreduce, and reshape back. Must run inside a
    data-manual shard_map region."""
    numel = int(m_local.size)
    flat = jnp.zeros(worker_error.shape[0], m_local.dtype)
    flat = flat.at[:numel].set(m_local.reshape(-1))
    synced, we_new, se_new = compressed_allreduce_local(
        flat, worker_error, server_error, axis, n)
    return synced[:numel].reshape(m_local.shape), we_new, se_new


def compressed_allreduce(x: jax.Array,
                         worker_error: jax.Array,
                         server_error: jax.Array,
                         mesh: Mesh,
                         axis: str = DATA_AXIS):
    """jit-level entry for tests/benchmarks: ``x`` [n, numel] carries each
    rank's local tensor on the leading (sharded) dim."""
    n = mesh.shape.get(axis, 1)
    body = functools.partial(compressed_allreduce_local, axis=axis, n=n)

    def fn(x_l, we_l, se_l):
        out, we, se = body(x_l[0], we_l[0], se_l[0])
        return out[None], we[None], se[None]

    mapped = shard_map(
        fn, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis)),
        axis_names={axis},
        check_vma=False)
    return jax.jit(mapped)(x, worker_error, server_error)
