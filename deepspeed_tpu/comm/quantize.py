"""Blockwise symmetric quantization for cross-slice gradient traffic.

The DCN links between TPU slices are an order of magnitude slower than
ICI, so the bytes a gradient all-reduce puts on them dominate multi-slice
step time. ZeRO++ (arXiv 2306.10209) shows blockwise-quantized gradient
collectives cut that traffic ~4x with negligible quality loss, and EQuARX
(arXiv 2506.17615) demonstrates the same transformation inside XLA. This
module is the numeric half of that design: deterministic int8 round-trips
with per-block fp32 scales. It is the tree's ONE int8 implementation —
consumers: :mod:`deepspeed_tpu.comm.grad_sync` (DCN stage of the
hierarchical gradient sync), :mod:`deepspeed_tpu.inference.quantization`
(int8 weights, one block per (group, output-channel)), and
:mod:`deepspeed_tpu.serving.kv_cache` (int8 KV pools, one block per
(token, head) vector).

Properties the grad-sync protocol relies on (tested in tests/test_dcn.py):

- **deterministic**: round-to-nearest-even, no stochastic rounding — the
  same input always produces the same wire bytes, so replayed steps (the
  resilience/guardrails machinery) stay reproducible.
- **zero-preserving**: an all-zero block quantizes to zeros and
  dequantizes to exact zeros (scale guard, no 0/0).
- **infinity-free**: finite inputs produce finite outputs (values clip to
  the int8 range; scales are finite for finite blocks).
- **overflow-transparent**: a block containing inf/NaN gets a NaN scale,
  so the dequantized block is NaN — ``has_inf_or_nan`` on the synced
  grads still sees the overflow the fp16 loss-scaler must skip on.
- **max-preserving**: the per-block absmax survives the round-trip to
  within one float32 rounding of ``amax`` (the max element maps to ±qmax
  exactly, and dequantizing gives ``qmax * (amax / qmax)``).
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def qmax_for_bits(bits: int) -> int:
    """Largest magnitude representable by a signed ``bits``-wide code."""
    return 2 ** (bits - 1) - 1


def quantize_blockwise(x: jax.Array, block_size: int,
                       bits: int = 8) -> Tuple[jax.Array, jax.Array]:
    """Quantize the last dim of ``x`` in blocks of ``block_size``.

    x: [..., m] float array, m % block_size == 0.
    Returns (q int8 [..., m], scales fp32 [..., m // block_size]).

    The math runs in fp32 regardless of the input dtype (a bf16 absmax /
    divide would add avoidable quantization noise); the caller controls
    the wire dtypes: int8 codes + fp32 scales.
    """
    if bits != 8:
        raise ValueError(f"quantize_blockwise supports bits=8, got {bits}")
    *lead, m = x.shape
    if m % block_size:
        raise ValueError(f"last dim {m} not divisible by block {block_size}")
    qmax = float(qmax_for_bits(bits))
    blocks = x.reshape(*lead, m // block_size, block_size).astype(jnp.float32)
    amax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    finite = jnp.isfinite(amax)
    # Zero blocks: scale 1 so q = round(0/1) = 0 and dequant is exact 0.
    safe = jnp.where(finite & (amax > 0), amax, jnp.float32(1.0))
    scale = safe / qmax
    q = jnp.clip(jnp.round(blocks / scale), -qmax, qmax).astype(jnp.int8)
    # Non-finite blocks poison their scale: dequantize yields NaN, keeping
    # the overflow visible to the loss-scaler's skip logic downstream.
    scale = jnp.where(finite, scale, jnp.float32(jnp.nan))
    return q.reshape(*lead, m), scale[..., 0]


def dequantize_blockwise(q: jax.Array, scales: jax.Array,
                         block_size: int) -> jax.Array:
    """Inverse of :func:`quantize_blockwise` — fp32 output [..., m]."""
    *lead, m = q.shape
    blocks = q.reshape(*lead, m // block_size, block_size).astype(jnp.float32)
    out = blocks * scales[..., None]
    return out.reshape(*lead, m)


def modeled_wire_bytes(num_elems: int, bits: int, block_size: int) -> int:
    """Bytes one direction of a quantized transfer of ``num_elems`` puts
    on the wire: payload codes + per-block fp32 scales. For the bf16/fp32
    passthrough tiers (bits 16/32) there are no scales."""
    if bits == 8:
        return num_elems + 4 * (num_elems // block_size)
    return num_elems * (bits // 8)
