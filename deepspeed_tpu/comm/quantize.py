"""Blockwise symmetric quantization for cross-slice gradient traffic.

The DCN links between TPU slices are an order of magnitude slower than
ICI, so the bytes a gradient all-reduce puts on them dominate multi-slice
step time. ZeRO++ (arXiv 2306.10209) shows blockwise-quantized gradient
collectives cut that traffic ~4x with negligible quality loss, and EQuARX
(arXiv 2506.17615) demonstrates the same transformation inside XLA. This
module is the numeric half of that design: deterministic int8 round-trips
with per-block fp32 scales. It is the tree's ONE int8 implementation —
consumers: :mod:`deepspeed_tpu.comm.grad_sync` (DCN stage of the
hierarchical gradient sync AND the ZeRO++ qwZ param all-gather,
``ParamGatherPlan`` — the lossy *parameter* hop the numerics
observatory's ``numerics/param_quant_rel_err`` measures),
:mod:`deepspeed_tpu.inference.quantization` (int8 weights, one block per
(group, output-channel)), and :mod:`deepspeed_tpu.serving.kv_cache`
(int8 KV pools, one block per (token, head) vector).

Properties the grad-sync protocol relies on (tested in tests/test_dcn.py):

- **deterministic**: round-to-nearest-even, no stochastic rounding — the
  same input always produces the same wire bytes, so replayed steps (the
  resilience/guardrails machinery) stay reproducible.
- **zero-preserving**: an all-zero block quantizes to zeros and
  dequantizes to exact zeros (scale guard, no 0/0).
- **infinity-free**: finite inputs produce finite outputs (values clip to
  the int8 range; scales are finite for finite blocks).
- **overflow-transparent**: a block containing inf/NaN gets a NaN scale,
  so the dequantized block is NaN — ``has_inf_or_nan`` on the synced
  grads still sees the overflow the fp16 loss-scaler must skip on.
- **max-preserving**: the per-block absmax survives the round-trip to
  within one float32 rounding of ``amax`` (the max element maps to ±qmax
  exactly, and dequantizing gives ``qmax * (amax / qmax)``).
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def qmax_for_bits(bits: int) -> int:
    """Largest magnitude representable by a signed ``bits``-wide code."""
    return 2 ** (bits - 1) - 1


def quantize_blockwise(x: jax.Array, block_size: int,
                       bits: int = 8) -> Tuple[jax.Array, jax.Array]:
    """Quantize the last dim of ``x`` in blocks of ``block_size``.

    x: [..., m] float array, m % block_size == 0.
    Returns (q int8 [..., m], scales fp32 [..., m // block_size]).

    The math runs in fp32 regardless of the input dtype (a bf16 absmax /
    divide would add avoidable quantization noise); the caller controls
    the wire dtypes: int8 codes + fp32 scales.
    """
    if bits != 8:
        raise ValueError(f"quantize_blockwise supports bits=8, got {bits}")
    *lead, m = x.shape
    if m % block_size:
        raise ValueError(f"last dim {m} not divisible by block {block_size}")
    qmax = float(qmax_for_bits(bits))
    blocks = x.reshape(*lead, m // block_size, block_size).astype(jnp.float32)
    amax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    finite = jnp.isfinite(amax)
    # Zero blocks: scale 1 so q = round(0/1) = 0 and dequant is exact 0.
    safe = jnp.where(finite & (amax > 0), amax, jnp.float32(1.0))
    scale = safe / qmax
    q = jnp.clip(jnp.round(blocks / scale), -qmax, qmax).astype(jnp.int8)
    # Non-finite blocks poison their scale: dequantize yields NaN, keeping
    # the overflow visible to the loss-scaler's skip logic downstream.
    scale = jnp.where(finite, scale, jnp.float32(jnp.nan))
    return q.reshape(*lead, m), scale[..., 0]


def dequantize_blockwise(q: jax.Array, scales: jax.Array,
                         block_size: int) -> jax.Array:
    """Inverse of :func:`quantize_blockwise` — fp32 output [..., m]."""
    *lead, m = q.shape
    blocks = q.reshape(*lead, m // block_size, block_size).astype(jnp.float32)
    out = blocks * scales[..., None]
    return out.reshape(*lead, m)


def roundtrip_error_parts(x: jax.Array, bits: int = 8,
                          block_size: int = 256
                          ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Raw accumulables of the round-trip error — ``(err_sq, ref_sq,
    max_abs)`` fp32 scalars — so callers inside manual collectives can
    ``psum``/``pmax`` them across shards before forming the relative
    error (the DCN grad-sync gauge does exactly that). ``bits``: 8 is
    the blockwise int8 RTNE round trip, 16 the bf16 cast, >=32 exact
    (zero error). NaN-transparent: a nonfinite input block poisons its
    scale (see :func:`quantize_blockwise`), so err/max propagate NaN
    instead of hiding the overflow."""
    x32 = x.astype(jnp.float32)
    ref_sq = jnp.sum(x32 * x32)
    if bits >= 32:
        zero = jnp.float32(0.0)
        return zero, ref_sq, zero
    if bits == 16:
        dq = x32.astype(jnp.bfloat16).astype(jnp.float32)
    else:
        q, s = quantize_blockwise(x32, block_size, bits=bits)
        dq = dequantize_blockwise(q, s, block_size)
    diff = dq - x32
    return jnp.sum(diff * diff), ref_sq, jnp.max(jnp.abs(diff))


def rel_from_parts(err_sq: jax.Array, ref_sq: jax.Array) -> jax.Array:
    """rel-L2 from (possibly psum'd) round-trip-error accumulables — the
    ONE combine formula, shared by :func:`roundtrip_error` and the DCN
    grad-sync gauge so the two error surfaces can never desynchronize
    (zero reference -> 0, not inf; NaN propagates)."""
    return jnp.sqrt(err_sq) / jnp.sqrt(jnp.maximum(ref_sq, 1e-30))


def roundtrip_error(x: jax.Array, bits: int = 8,
                    block_size: int = 256) -> Tuple[jax.Array, jax.Array]:
    """Round-trip quantization error of ``x``'s last dim in blocks:
    ``(rel_l2, max_abs)`` fp32 scalars, where ``rel_l2 = ||dq(q(x)) -
    x||_2 / ||x||_2`` (0 for an all-zero input — zero blocks round-trip
    exactly) and ``max_abs`` is the worst per-element error (bounded by
    half the per-block scale for finite blocks — RTNE). The shared
    measurement core of the DCN grad-sync and int8 KV-cache error
    gauges (telemetry/numerics.py); NaN-transparent like the parts
    helper."""
    err_sq, ref_sq, max_abs = roundtrip_error_parts(x, bits, block_size)
    return rel_from_parts(err_sq, ref_sq), max_abs


def modeled_wire_bytes(num_elems: int, bits: int, block_size: int) -> int:
    """Bytes one direction of a quantized transfer of ``num_elems`` puts
    on the wire: payload codes + per-block fp32 scales. For the bf16/fp32
    passthrough tiers (bits 16/32) there are no scales. Callers split the
    result by *direction* — grad traffic (``comm/bytes_dcn``/``_ici``)
    vs param traffic (``comm/bytes_dcn_params``/``_ici_params``) — so
    fleet/devicetime attribution can tell the two hops apart."""
    if bits == 8:
        return num_elems + 4 * (num_elems // block_size)
    return num_elems * (bits // 8)
