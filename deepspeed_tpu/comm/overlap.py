"""Bucket-boundary gradient-sync markers — the compute/comm overlap hooks.

ROADMAP item 1 (T3, arXiv 2401.16677; The Big Send-off, 2504.18658): the
hierarchical grad sync's ICI reduce-scatters must start *during* the
backward pass, not after the full gradient tree materializes. XLA's
latency-hiding scheduler can only overlap a collective with compute that
is independent of it — so the per-bucket reduce-scatter has to be
*emitted* where the bucket's gradients become ready, which is mid-way
through the backward trace, one layer group at a time.

This module provides the marker the in-tree models plant on their layer
stacks and the hook protocol the grad-sync plan installs while tracing:

- :func:`grad_sync_boundary` — an identity on a parameter (sub)tree.
  With no hook installed (every non-overlap path: training with
  ``comm.hierarchical`` off, inference, serving, init) it returns its
  input untouched and leaves **zero** trace footprint — lowered programs
  are bit-identical to a model without markers. With a hook installed it
  wraps the subtree in a ``jax.custom_vjp`` whose backward rule passes
  the cotangents (exactly this group's gradients, complete at this point
  of the backward pass) through the hook — which reduce-scatters them
  over the ICI ``data`` axis via a sharding constraint, so the collective
  lands *between* the layer backwards in the traced program instead of
  trailing all of them.

- :func:`install_ici_hook` — the trace-scoped hook installation the
  grad-sync plan wraps around its ``grad_fn`` call inside the
  ``manual={dcn}`` region. The hook MUST only be active inside that
  region: at the GSPMD-auto top level this jax's partitioner mishandles
  the replication bookkeeping of a data-only constraint under an
  unconstrained ``dcn`` axis (measured: grads scaled by the dcn size).
  Inside the region ``dcn`` is manual and the constraint is exactly the
  per-bucket reduce-scatter the non-overlap path already emits — just
  earlier.

- :func:`marked_block` — the one-line flax wrapper the model zoo uses:
  ``map_variables`` applies :func:`grad_sync_boundary` where the block
  *reads* its params, which is the only place a cotangent hook lands
  mid-backward (a wrap at the loss_fn entry would put every marker's
  backward rule after the whole backward pass — all trailing, nothing
  overlapped).

The marker's backward also routes the flattened cotangents through one
``jax.lax.optimization_barrier``: it pins the reduce-scatter against
being algebraically folded away, and it is the greppable anchor the
overlap-scheduling tests (tests/test_dcn.py) assert interleaving with.
"""

import contextlib
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

_TLS = threading.local()

# Hook signature: hook(group_name, cotangent_tree) -> cotangent_tree.
Hook = Callable[[str, Any], Any]


def active_hook() -> Optional[Hook]:
    return getattr(_TLS, "hook", None)


@contextlib.contextmanager
def install_ici_hook(hook: Optional[Hook]):
    """Trace-scoped hook installation (thread-local — jit tracing is
    synchronous per thread). Nestable; ``None`` is a no-op installer so
    callers don't need to branch."""
    prev = getattr(_TLS, "hook", None)
    _TLS.hook = hook
    try:
        yield
    finally:
        _TLS.hook = prev


def grad_sync_boundary(tree: Any, name: str) -> Any:
    """Identity marker on a parameter (sub)tree, planted where a model
    consumes the group ``name``'s params. No active hook => returns
    ``tree`` unchanged (zero trace footprint). With a hook, the backward
    rule hands this group's cotangents to the hook at the point of the
    backward trace where they are complete."""
    hook = active_hook()
    if hook is None:
        return tree

    @jax.custom_vjp
    def marker(t):
        return t

    def fwd(t):
        return t, None

    def bwd(_, ct):
        return (hook(name, ct),)

    marker.defvjp(fwd, bwd)
    return marker(tree)


def marked_block(block_cls, name: str):
    """Wrap a flax module class so reading its ``params`` collection
    passes through :func:`grad_sync_boundary` under the group ``name``
    (the module's top-level param key, e.g. ``h_3``). Identity-valued —
    init trees, checkpoints, and every non-overlap lowering are
    unchanged."""
    import flax.linen as nn

    return nn.map_variables(
        block_cls, "params",
        trans_in_fn=lambda p: grad_sync_boundary(p, name),
        trans_out_fn=lambda p: p,
        init=True, mutable=True)


def ici_scatter_hook(data_sharding, ici_dtype,
                     group_ok: Callable[[str], bool]) -> Hook:
    """The hook the grad-sync plan installs: flatten the group's float
    cotangents, cast to the ICI reduction dtype, constrain to the
    ``data`` axis (XLA lowers the constraint on the not-yet-reduced
    gradient sum to a reduce-scatter over ICI — the same lowering the
    bucket constraints get, emitted mid-backward instead), and slice
    back. Numerically the identity up to the ici-dtype cast the bucket
    build applies anyway; groups that fail ``group_ok`` (fallback
    leaves: non-data shardings cannot take a flat data constraint) pass
    through untouched."""

    def hook(name: str, ct: Any) -> Any:
        if not group_ok(name):
            return ct
        leaves, tdef = jax.tree_util.tree_flatten(ct)
        idx = [i for i, leaf in enumerate(leaves)
               if jnp.issubdtype(leaf.dtype, jnp.floating) and leaf.size]
        if not idx:
            return ct
        flat = jnp.concatenate(
            [leaves[i].reshape(-1).astype(ici_dtype) for i in idx])
        flat = jax.lax.with_sharding_constraint(flat, data_sharding)
        # Anchor: keeps the scatter from folding into the later bucket
        # concat, and is the marker the scheduling tests grep for.
        flat = jax.lax.optimization_barrier(flat)
        out = list(leaves)
        off = 0
        for i in idx:
            n = leaves[i].size
            out[i] = flat[off:off + n].reshape(
                leaves[i].shape).astype(leaves[i].dtype)
            off += n
        return jax.tree_util.tree_unflatten(tdef, out)

    return hook
