"""Communication backends: named collectives, compressed (1-bit)
allreduce, blockwise quantization, and the hierarchical grad-sync
strategy (docs/PERFORMANCE.md)."""

from deepspeed_tpu.comm import collectives
from deepspeed_tpu.comm.compressed import (compressed_allreduce,
                                           compressed_allreduce_local,
                                           pack_signs, unpack_signs)
from deepspeed_tpu.comm.grad_sync import (GradSyncPlan, GradSyncStrategy,
                                          comm_dtype_from_config,
                                          resolve_hierarchical)
from deepspeed_tpu.comm.quantize import (dequantize_blockwise,
                                         quantize_blockwise)

__all__ = ["collectives", "compressed_allreduce",
           "compressed_allreduce_local", "pack_signs", "unpack_signs",
           "GradSyncPlan", "GradSyncStrategy", "comm_dtype_from_config",
           "resolve_hierarchical", "quantize_blockwise",
           "dequantize_blockwise"]
