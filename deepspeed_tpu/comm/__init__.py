"""Communication backends: named collectives + compressed (1-bit) allreduce."""

from deepspeed_tpu.comm import collectives
from deepspeed_tpu.comm.compressed import (compressed_allreduce,
                                           compressed_allreduce_local,
                                           pack_signs, unpack_signs)

__all__ = ["collectives", "compressed_allreduce",
           "compressed_allreduce_local", "pack_signs", "unpack_signs"]
