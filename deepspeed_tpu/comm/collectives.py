"""Named collective wrappers — the torch.distributed surface, TPU-native.

The reference calls ``torch.distributed`` {all_reduce, reduce, all_gather,
all_to_all_single, broadcast, barrier} over NCCL groups (SURVEY.md §2.5).
On TPU the same verbs are XLA collectives over named mesh axes, legal inside
``shard_map`` manual regions; these wrappers fix the naming and the couple
of non-obvious encodings (broadcast as a masked psum, barrier as a token
psum). Outside shard_map, prefer plain sharding annotations — GSPMD inserts
collectives itself; this module is for the manual paths (pipeline, ring,
compressed comm) and for API familiarity.
"""

from typing import Sequence

import jax
import jax.numpy as jnp


def all_reduce(x: jax.Array, axis: str, op: str = "sum") -> jax.Array:
    if op == "sum":
        return jax.lax.psum(x, axis)
    if op == "mean":
        return jax.lax.pmean(x, axis)
    if op == "max":
        return jax.lax.pmax(x, axis)
    if op == "min":
        return jax.lax.pmin(x, axis)
    raise ValueError(f"unknown reduce op '{op}'")


def all_gather(x: jax.Array, axis: str, *, tiled: bool = True,
               gather_dim: int = 0) -> jax.Array:
    return jax.lax.all_gather(x, axis, axis=gather_dim, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: str, *, scatter_dim: int = 0) -> jax.Array:
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_dim,
                                tiled=True)


def all_to_all(x: jax.Array, axis: str, *, split_dim: int,
               concat_dim: int) -> jax.Array:
    return jax.lax.all_to_all(x, axis, split_axis=split_dim,
                              concat_axis=concat_dim, tiled=True)


def broadcast(x: jax.Array, axis: str, root: int = 0) -> jax.Array:
    """Every rank gets root's value — masked psum (the same trick the
    reference uses for pipeline p2p, pipe/p2p.py:31)."""
    rank = jax.lax.axis_index(axis)
    masked = jnp.where(rank == root, x, jnp.zeros_like(x))
    return jax.lax.psum(masked.astype(jnp.float32), axis).astype(x.dtype)


def ppermute(x: jax.Array, axis: str, perm: Sequence) -> jax.Array:
    return jax.lax.ppermute(x, axis, perm)


def send_recv_next(x: jax.Array, axis: str, n: int) -> jax.Array:
    """Shift to the next rank on the ring (pipeline activation transfer)."""
    return jax.lax.ppermute(x, axis, [(i, (i + 1) % n) for i in range(n)])


def send_recv_prev(x: jax.Array, axis: str, n: int) -> jax.Array:
    return jax.lax.ppermute(x, axis, [(i, (i - 1) % n) for i in range(n)])


def barrier(axis: str) -> jax.Array:
    """Synchronisation token: a collective nothing."""
    return jax.lax.psum(jnp.ones((), jnp.int32), axis)
