"""Row-sparse gradient exchange — the CSR embedding-gradient capability.

Reference: ``deepspeed/runtime/engine.py:1530-1586`` (``sparse_gradients``:
embedding grads travel as CSR tensors — ``csr_tensor.py`` — so the
allreduce moves touched rows instead of the full [V, D] table).

TPU framing (see runtime/sparse_tensor.py for the full rationale): XLA AD
always materialises dense gradients, so the ENGINE's automatic grad
allreduce cannot be sparsified behind the user's back. But the capability
itself — exchanging only touched embedding rows across data ranks — is
expressible as an explicit collective for custom training loops: each rank
contributes ``(ids [N], rows [N, D])`` (its microbatch's per-token
gradients, pre-scatter), the exchange is an ``all_gather`` of both
(``2 · n · N · D`` bytes vs ``2 · V · D`` for the dense ring allreduce —
the win whenever ``n·N ≪ V``, i.e. giant vocab, small batch), and the
dense [V, D] gradient is rebuilt locally by scatter-add AFTER the wire.

``row_sparse_allreduce`` runs inside a data-manual shard_map;
``row_sparse_allreduce_jit`` is the jit-level entry used by tests.
"""

from typing import Tuple

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.parallel.mesh import DATA_AXIS


def rows_from_tokens(ids: jax.Array, g_tokens: jax.Array
                     ) -> Tuple[jax.Array, jax.Array]:
    """Flatten per-token embedding grads to (ids [N], rows [N, D]) — the
    CSR-building step (reference csr_tensor.py from dense rows)."""
    d = g_tokens.shape[-1]
    return ids.reshape(-1), g_tokens.reshape(-1, d)


def scatter_rows(ids: jax.Array, rows: jax.Array, vocab: int) -> jax.Array:
    """(ids, rows) -> dense [V, D] gradient by scatter-add."""
    return jnp.zeros((vocab, rows.shape[-1]), rows.dtype).at[ids].add(rows)


def row_sparse_allreduce(ids: jax.Array, rows: jax.Array, vocab: int,
                         axis=DATA_AXIS,
                         mean: bool = True) -> jax.Array:
    """Inside a manual shard_map over ``axis`` (one name or a tuple of
    names): gather every rank's (ids, rows) and scatter-add into the dense
    [V, D] mean gradient — wire bytes scale with touched rows, not
    vocab."""
    all_ids = jax.lax.all_gather(ids, axis, axis=0, tiled=True)
    all_rows = jax.lax.all_gather(rows, axis, axis=0, tiled=True)
    dense = scatter_rows(all_ids, all_rows, vocab)
    if mean:
        dense = dense / jax.lax.psum(1, axis)
    return dense


def row_sparse_allreduce_jit(ids: jax.Array, rows: jax.Array, vocab: int,
                             mesh: Mesh, axis: str = DATA_AXIS,
                             mean: bool = True) -> jax.Array:
    """jit-level entry: ``ids`` [n, N] / ``rows`` [n, N, D] carry each
    rank's contribution on the leading (sharded) dim; returns the dense
    averaged [V, D] gradient, replicated."""
    def body(i, r):
        return row_sparse_allreduce(i[0], r[0], vocab, axis, mean)

    mapped = shard_map(body, mesh=mesh,
                       in_specs=(P(axis), P(axis)),
                       out_specs=P(),
                       axis_names={axis}, check_vma=False)
    return jax.jit(mapped)(ids, rows)


__all__ = ["row_sparse_allreduce", "row_sparse_allreduce_jit",
           "rows_from_tokens", "scatter_rows"]
