"""Hierarchical quantized gradient sync — the explicit grad-sync strategy.

Today the engine leaves all gradient reduction to implicit pjit resharding
in full precision: ``micro_step_inner`` constrains the accumulator to the
ZeRO grad specs and XLA emits whatever collectives make the shardings
true. On a single slice that is optimal; on multi-slice topologies the
same lowering drags full-precision gradient traffic over the slow
inter-slice DCN axis every step. ZeRO++ (arXiv 2306.10209) and EQuARX
(arXiv 2506.17615) show the fix: make the hierarchy explicit and compress
only the slow hop.

The strategy here (``docs/PERFORMANCE.md``):

1. **Bucket**: each micro-step's grad tree is flattened into fixed-size
   flat buckets (``comm.bucket_mb``) so collective launches amortize and
   the DCN stage works on a handful of large transfers instead of one op
   per leaf.
2. **ICI stage**: every bucket is cast to the ICI reduction dtype
   (``communication_data_type``, default the accumulator's native dtype)
   and constrained to the intra-slice ``data`` axis — XLA lowers that to
   a reduce-scatter over fast ICI, and the gradient accumulator carries
   only the 1/data-size scattered shard (the reference's IPG-bucket
   memory shape, stage2.py:701).
3. **DCN stage** (once per optimizer step): the scattered shard is
   all-reduced across slices over the manual ``dcn`` axis with blockwise
   int8 symmetric quantization (``comm/quantize.py``) — all_to_all the
   codes+scales, dequantize-sum-requantize, all_gather back — or a
   bf16 / fp32 passthrough. Wire bytes drop ~4x (int8) vs fp32.
4. **Unbucket**: the reduced buckets are sliced back into the grad tree
   and handed to the unchanged optimizer apply.

Execution model: the fwd/bwd + ICI stage run inside a ``shard_map``
manual over *only* the ``dcn`` axis (every other axis stays GSPMD-auto,
so ZeRO placement and tensor-parallel specs keep composing); the DCN
stage runs in a second region manual over ``{dcn, data}`` — the same
partial-manual shape the 1-bit optimizers already use — because this
jax's partitioner only supports ``all_to_all`` when the data-like axes
are all manual. Leaves whose grad specs shard over non-data axes
(pipeline blocks, tensor-parallel weights) cannot join a flat bucket;
they fall back to a per-leaf fp32 ``psum`` over ``dcn`` (a bf16 all-
reduce under a partial-manual shard_map crashes this XLA CPU backend —
see the psum note in parallel/pipe/pipeline.py).

``hierarchical: off`` (the default) bypasses this module entirely: the
engine builds the exact pre-existing step functions, bit-identical to
main. ``on`` with fp32 passthrough tracks the implicit path to float
reduction-ordering (~1 ulp — an explicit slice-wise sum cannot reproduce
the implicit single-collective summation order bit-for-bit; the parity
rungs in tests/test_dcn.py pin the bound).

**Overlap mode** (``comm.overlap_grad_sync``, default ``auto`` ≡ on
whenever the hierarchical sync engages — ROADMAP item 1, T3 arXiv
2401.16677 / The Big Send-off arXiv 2504.18658): the same wire protocol
rescheduled so gradient communication overlaps compute instead of
serializing after it, along two axes (docs/PERFORMANCE.md "Overlapped
gradient sync"):

1. *Intra-backward ICI overlap* — buckets are leaf-granular and packed
   in reverse traversal order (the order gradients become ready during
   backward), so each bucket's reduce-scatter depends only on its own
   leaves and the latency-hiding scheduler can run bucket k's scatter
   concurrently with layer k-1's backward. In-tree models additionally
   plant :func:`comm.overlap.grad_sync_boundary` markers on their layer
   stacks: a custom_vjp hook per layer group whose backward rule emits
   the group's data-axis scatter constraint *between* the layer
   backwards in the traced program (not all trailing).
2. *Cross-microstep DCN overlap* — instead of one cross-slice
   all-reduce of the accumulated shard at the GAS boundary, microstep
   k's bucket contributions are quantized and dispatched over DCN
   immediately, double-buffered so exactly one reduce is in flight
   while microstep k+1's fwd/bwd runs; the reduced scattered shards
   accumulate at the jit level and only the final microstep's reduce is
   exposed. DCN wire bytes grow by the GAS factor — traded for hiding
   nearly all of them — and the modeled ``comm/exposed_frac`` accounts
   for the overlap (:meth:`GradSyncPlan.modeled_exposed_seconds`).

Overlap off keeps the PR-4 single-boundary schedule byte-for-byte.
"""

import math
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.quantize import (dequantize_blockwise,
                                         modeled_wire_bytes,
                                         quantize_blockwise, rel_from_parts,
                                         roundtrip_error_parts)
from deepspeed_tpu.parallel.mesh import (DATA_AXIS, DCN_AXIS,
                                         axes_size as mesh_axes_size)
from deepspeed_tpu.utils.jax_compat import shard_map
from deepspeed_tpu.utils.logging import log_dist

_MB = 1 << 20

_COMM_DTYPES = {
    None: None,
    "fp32": jnp.float32, "float32": jnp.float32,
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "fp16": jnp.float16, "float16": jnp.float16,
}


def comm_dtype_from_config(name: Optional[str]):
    """Map the ``communication_data_type`` config string to a jnp dtype
    (None ≡ the accumulator's native dtype). Validation happens at config
    parse; this keeps one authoritative mapping."""
    if name is not None and name not in _COMM_DTYPES:
        raise ValueError(
            f"communication_data_type '{name}' not in "
            f"{sorted(k for k in _COMM_DTYPES if k)}")
    return _COMM_DTYPES.get(name)


def resolve_hierarchical(comm_cfg, mesh: Mesh, *,
                         needs_local_grads: bool = False,
                         sparse_gradients: bool = False,
                         pipe_stages: int = 1) -> Tuple[bool, str]:
    """Resolve the ``comm.hierarchical`` tri-state against the runtime
    shape. Returns (enabled, reason). ``on`` raises on genuinely
    incompatible configurations instead of silently degrading; ``auto``
    quietly resolves off for them."""
    from deepspeed_tpu.config.config import ConfigError

    mode = comm_cfg.hierarchical
    dcn = mesh.shape.get(DCN_AXIS, 1)
    blockers = []
    if needs_local_grads:
        blockers.append(
            "1-bit optimizers run their own error-compensated compressed "
            "collective over dcn — the hierarchical grad sync would "
            "double-compress the same hop")
    if sparse_gradients:
        blockers.append(
            "the sparse embedding-grad exchange reduces over the data-like "
            "axes inside its VJP, which cannot trace under the dcn-manual "
            "region the hierarchical sync needs")
    if pipe_stages > 1:
        blockers.append(
            "pipeline stages > 1 compile their own manual region "
            "(parallel/pipe/pipeline.py) and shard_map regions do not "
            "nest on this jax")
    if mode == "off":
        return False, "comm.hierarchical=off"
    if mode == "on":
        if blockers:
            raise ConfigError(
                f"comm.hierarchical=on is incompatible with this "
                f"configuration: {blockers[0]}")
        if dcn <= 1:
            log_dist("comm.hierarchical=on with a single slice (dcn=1): "
                     "the DCN stage is degenerate — quantization cost "
                     "without traffic savings", ranks=[0])
        return True, "comm.hierarchical=on"
    if mode != "auto":
        raise ConfigError(
            f"comm.hierarchical must be auto|on|off, got '{mode}'")
    if dcn <= 1:
        return False, "auto: single slice (no dcn axis to compress)"
    if blockers:
        return False, f"auto: {blockers[0]}"
    return True, f"auto: dcn={dcn} hierarchical mesh"


def resolve_overlap(comm_cfg) -> bool:
    """Resolve ``comm.overlap_grad_sync`` (auto|on|off, default auto) to
    a bool. Overlap is a property of the hierarchical sync's schedule,
    so it only ever takes effect when :func:`resolve_hierarchical`
    engaged the strategy — the incompatible configurations (1-bit,
    pipeline stages > 1, sparse embedding grads) are already excluded
    there and never reach a plan."""
    from deepspeed_tpu.config.config import ConfigError

    mode = str(getattr(comm_cfg, "overlap_grad_sync", "auto")).lower()
    if mode == "off":
        return False
    if mode in ("auto", "on"):
        return True
    raise ConfigError(
        f"comm.overlap_grad_sync must be auto|on|off, got '{mode}'")


def _spec_axes(spec) -> set:
    axes = set()
    for entry in tuple(spec):
        parts = entry if isinstance(entry, tuple) else (entry,)
        axes.update(a for a in parts if a is not None)
    return axes


class GradSyncPlan:
    """A compiled-shape plan binding the strategy to one grad tree.

    Built once per engine at step-construction time; every method that
    touches arrays is pure jnp and traces inside the jitted step. Methods
    marked *stage-1* must be called inside the ``manual={dcn}`` region;
    ``dcn_sync`` wraps its own ``manual={dcn, data}`` region and is
    called at the jit level, on the dcn-stacked buckets stage 1 returns.
    """

    def __init__(self, comm_cfg, mesh: Mesh, grad_template: Any,
                 grad_specs: Any, acc_dtype, ici_dtype=None, gas: int = 1,
                 measure_quant_error: bool = False, overlap: bool = False):
        self.mesh = mesh
        self.dcn_size = int(mesh.shape.get(DCN_AXIS, 1))
        self.data_size = int(mesh.shape.get(DATA_AXIS, 1))
        self.bits = int(comm_cfg.dcn_quant_bits)
        self.block = int(comm_cfg.quant_block_size)
        # Nominal link bandwidths for the modeled device-time attribution
        # (modeled_exposed_seconds / comm/exposed_frac). One source of
        # truth with the config defaults (getattr covers hand-built cfg
        # objects without the fields).
        from deepspeed_tpu.config import constants as _C
        self.ici_gbps = float(getattr(comm_cfg, "ici_gbps",
                                      _C.COMM_ICI_GBPS_DEFAULT))
        self.dcn_gbps = float(getattr(comm_cfg, "dcn_gbps",
                                      _C.COMM_DCN_GBPS_DEFAULT))
        self.acc_dtype = acc_dtype
        self.ici_dtype = ici_dtype if ici_dtype is not None else acc_dtype
        # Numerics observatory (telemetry/numerics.py): when on, the DCN
        # stage also returns per-bucket RTNE round-trip error of the wire
        # payload vs the fp32 shard. Only the lossy tiers measure — the
        # fp32 passthrough has nothing to attribute. Off (the default)
        # the shard_map body is byte-for-byte the pre-numerics one.
        self.measure_quant = (bool(measure_quant_error)
                              and int(comm_cfg.dcn_quant_bits) in (8, 16))
        # Micro-steps per optimizer step THIS plan's region runs: each one
        # reduce-scatters every bucket over ICI, so the modeled ICI bytes
        # scale with it (the pipe engine's single pipelined fwd/bwd is 1).
        self.gas = int(gas)

        leaves, self.treedef = jax.tree_util.tree_flatten(grad_template)
        spec_leaves = self.treedef.flatten_up_to(grad_specs)
        self.num_leaves = len(leaves)
        self.leaf_shapes = [tuple(l.shape) for l in leaves]
        # math.prod(()) == 1 covers scalars; a zero-dim leaf really does
        # contribute 0 elements (forcing it to 1 would desync the bucket
        # layout from the concatenated flat buffer).
        self.leaf_sizes = [int(math.prod(s)) for s in self.leaf_shapes]
        self.bucketed_idx: List[int] = []
        self.fallback_idx: List[int] = []
        for i, (leaf, spec) in enumerate(zip(leaves, spec_leaves)):
            # leaves may be jax arrays or ShapeDtypeStructs (the offload
            # tier plans against an abstract template).
            float_leaf = jnp.issubdtype(leaf.dtype, jnp.floating)
            # Axes of size 1 shard nothing — a pipe=1 block spec or a
            # model=1 TP spec must not exile the whole model to the
            # uncompressed fallback.
            real_axes = {a for a in _spec_axes(spec)
                         if mesh.shape.get(a, 1) > 1}
            if float_leaf and real_axes <= {DATA_AXIS}:
                self.bucketed_idx.append(i)
            else:
                self.fallback_idx.append(i)
        self.fallback_specs = [spec_leaves[i] for i in self.fallback_idx]
        # Constraint specs usable INSIDE the dcn-manual region: values
        # there are slice-local, so any (pathological) dcn entry in a
        # fallback spec must drop — naming a manual axis in an inner
        # constraint is an error.
        self.fallback_inner_specs = [
            self._strip_dcn(s) for s in self.fallback_specs]

        self.total_elems = sum(self.leaf_sizes[i] for i in self.bucketed_idx)
        self.fallback_elems = sum(self.leaf_sizes[i]
                                  for i in self.fallback_idx)
        # Every bucket is padded to a multiple of data*dcn*block so the
        # scattered shard splits evenly into dcn sub-chunks of whole
        # quantization blocks.
        self.overlap = bool(overlap)
        align = self.data_size * self.dcn_size * self.block
        itemsize = jnp.dtype(self.ici_dtype).itemsize
        if self.overlap:
            # Leaf-granular buckets packed in REVERSE traversal order —
            # the order gradients become ready during backward — so
            # bucket k's reduce-scatter depends only on its own leaves
            # (the readiness-ordered dispatch ROADMAP item 1 asks for).
            # A leaf never straddles buckets; an oversized leaf is its
            # own bucket.
            target = max(align, int(comm_cfg.bucket_mb * _MB / itemsize))
            self.bucket_leaf_idx: List[List[int]] = []
            cur: List[int] = []
            cur_sz = 0
            for i in reversed(self.bucketed_idx):
                sz = self.leaf_sizes[i]
                if cur and cur_sz and cur_sz + sz > target:
                    self.bucket_leaf_idx.append(cur)
                    cur, cur_sz = [], 0
                cur.append(i)
                cur_sz += sz
            if cur:
                self.bucket_leaf_idx.append(cur)
            self.bucket_padded = [
                max(align,
                    (sum(self.leaf_sizes[i] for i in b) + align - 1)
                    // align * align)
                for b in self.bucket_leaf_idx]
            self.num_buckets = len(self.bucket_leaf_idx)
            # Back-compat scalar (describe(), jaxpr size assertions):
            # the largest bucket.
            self.bucket_elems = max(self.bucket_padded, default=0)
            self.padded_elems = sum(self.bucket_padded)
        else:
            # PR-4 layout: fixed-size buckets split from one contiguous
            # flat buffer (leaves may straddle boundaries).
            raw = max(align, int(comm_cfg.bucket_mb * _MB / itemsize))
            self.bucket_elems = ((raw + align - 1) // align) * align
            if self.total_elems:
                self.num_buckets = max(
                    1, (self.total_elems + self.bucket_elems - 1)
                    // self.bucket_elems)
                # Shrink a single bucket to the (aligned) payload: tiny
                # models must not pad to a full bucket_mb of zeros.
                if self.num_buckets == 1:
                    self.bucket_elems = (
                        (self.total_elems + align - 1) // align) * align
            else:
                self.num_buckets = 0
            self.padded_elems = self.num_buckets * self.bucket_elems
            self.bucket_leaf_idx = []
            self.bucket_padded = [self.bucket_elems] * self.num_buckets
        # Top-level param group -> all-bucketed? — consulted by the
        # ICI overlap hook (comm/overlap.py): a group with any fallback
        # leaf (non-data sharding) cannot take a flat data constraint.
        self._group_bucketed = {}
        try:
            paths = jax.tree_util.tree_flatten_with_path(grad_template)[0]
        except Exception:  # noqa: BLE001 — exotic pytrees: hooks just no-op
            paths = []
        groups: dict = {}
        for idx, (path, _) in enumerate(paths):
            if not path:
                continue
            k = path[0]
            key = getattr(k, "key", None)
            if key is None:
                key = getattr(k, "name", None)
            if key is None:
                continue
            groups.setdefault(str(key), []).append(idx)
        bucketed_set = set(self.bucketed_idx)
        self._group_bucketed = {
            k: all(i in bucketed_set for i in v) for k, v in groups.items()}
        self._data_sharding = NamedSharding(mesh, P(DATA_AXIS))
        self._dcn_sync_fn = None
        self._dcn_overlap_fn = None

    @staticmethod
    def _strip_dcn(spec) -> P:
        entries = []
        for entry in tuple(spec):
            parts = entry if isinstance(entry, tuple) else (entry,)
            kept = tuple(a for a in parts
                         if a is not None and a != DCN_AXIS)
            entries.append(kept if len(kept) > 1
                           else (kept[0] if kept else None))
        return P(*entries)

    # ------------------------------------------------------------------
    # stage 1 (inside the manual={dcn} region)
    # ------------------------------------------------------------------
    def zero_fallback(self) -> List[jax.Array]:
        return [jnp.zeros(self.leaf_shapes[i], self.acc_dtype)
                for i in self.fallback_idx]

    def zero_buckets(self) -> Tuple[jax.Array, ...]:
        return tuple(
            jax.lax.with_sharding_constraint(
                jnp.zeros((self.bucket_elems,), self.acc_dtype),
                self._data_sharding)
            for _ in range(self.num_buckets))

    def microstep_buckets(self, grads_tree: Any) -> Tuple[jax.Array, ...]:
        """Flatten this micro-step's bucketed leaves into ICI-dtype flat
        buckets, each constrained to the ``data`` axis — the constraint
        is where XLA emits the per-bucket reduce-scatter over ICI."""
        if not self.num_buckets:
            return ()
        leaves = self.treedef.flatten_up_to(grads_tree)
        parts = [leaves[i].reshape(-1).astype(self.ici_dtype)
                 for i in self.bucketed_idx]
        pad = self.padded_elems - self.total_elems
        if pad:
            # Padding joins the concat instead of a jnp.pad: a `pad` HLO
            # inside this partial-manual region trips the old
            # partitioner's manual-subgroup check (fatal, not catchable).
            parts.append(jnp.zeros((pad,), self.ici_dtype))
        flat = jnp.concatenate(parts)
        return tuple(
            jax.lax.with_sharding_constraint(
                flat[b * self.bucket_elems:(b + 1) * self.bucket_elems],
                self._data_sharding)
            for b in range(self.num_buckets))

    def fallback_leaves(self, grads_tree: Any) -> List[jax.Array]:
        leaves = self.treedef.flatten_up_to(grads_tree)
        return [leaves[i] for i in self.fallback_idx]

    def fallback_sync(self, leaves: Sequence[jax.Array]) -> List[jax.Array]:
        """Per-leaf dcn mean for leaves that cannot join a flat bucket
        (non-data sharding). fp32 on the wire: a bf16 all-reduce under a
        partial-manual shard_map crashes this XLA CPU backend (see
        parallel/pipe/pipeline.py)."""
        inv = 1.0 / self.dcn_size
        return [
            (jax.lax.psum(l.astype(jnp.float32), DCN_AXIS) * inv).astype(
                self.acc_dtype)
            for l in leaves]

    # ------------------------------------------------------------------
    # stage 2 (jit level, manual={dcn, data})
    # ------------------------------------------------------------------
    def _dcn_allreduce_local(self, chunk: jax.Array, gather_ici: bool = True):
        """Body of the DCN stage for ONE bucket's local scattered shard
        ``chunk`` [bucket_elems / data_size]: all-reduce it across slices
        with the configured wire dtype, return ``(gathered_bucket
        [bucket_elems], err)`` where ``err`` — when
        ``measure_quant_error`` is on (None otherwise: the lowering is
        then unchanged) — is this shard's local round-trip-error
        accumulables for BOTH lossy hops the wire takes,
        ``(err_sq, ref_sq, max_abs)`` of the outbound payload followed
        by the same triple for the reduced bucket's re-quantization
        before the return all-gather. Measuring only the first hop
        would systematically underreport the end-to-end error (~sqrt(2)x
        for similar hops). Runs inside the manual={dcn, data} region."""
        n = self.dcn_size
        sub = chunk.shape[0] // n
        parts = chunk.reshape(n, sub)
        inv = 1.0 / n
        err1 = (roundtrip_error_parts(parts, self.bits, self.block)
                if self.measure_quant else None)
        err2 = None
        if self.bits == 8:
            q, s = quantize_blockwise(parts, self.block)
            rq = jax.lax.all_to_all(q, DCN_AXIS, split_axis=0,
                                    concat_axis=0, tiled=False)
            rs = jax.lax.all_to_all(s, DCN_AXIS, split_axis=0,
                                    concat_axis=0, tiled=False)
            red = jnp.sum(dequantize_blockwise(rq, rs, self.block),
                          axis=0) * inv
            if self.measure_quant:
                # Second hop: the reduced bucket is re-quantized for the
                # return all-gather — an independent RTNE stage.
                err2 = roundtrip_error_parts(red, self.bits, self.block)
            q2, s2 = quantize_blockwise(red, self.block)
            aq = jax.lax.all_gather(q2, DCN_AXIS, axis=0, tiled=False)
            a_s = jax.lax.all_gather(s2, DCN_AXIS, axis=0, tiled=False)
            mine = dequantize_blockwise(aq, a_s, self.block).reshape(-1)
        else:
            # bits=32 "passthrough" ships the ICI dtype, NOT whatever
            # dtype the caller accumulated in: the runtime engines
            # accumulate buckets in acc_dtype while the pipe engine hands
            # over raw ici_dtype buckets — without this cast the two
            # would put different wire dtypes on DCN for the same config
            # (and modeled_bytes would misreport one of them).
            wire = (jnp.bfloat16 if self.bits == 16
                    else jnp.dtype(self.ici_dtype))
            rp = jax.lax.all_to_all(parts.astype(wire), DCN_AXIS,
                                    split_axis=0, concat_axis=0,
                                    tiled=False)
            red = (jnp.sum(rp.astype(jnp.float32), axis=0) * inv)
            if self.measure_quant:
                # Second hop (bits=16 only measures): the reduced bucket
                # returns over DCN as bf16 — the same cast loss again.
                err2 = roundtrip_error_parts(red, self.bits, self.block)
            ag = jax.lax.all_gather(red.astype(wire), DCN_AXIS, axis=0,
                                    tiled=False)
            mine = ag.astype(jnp.float32).reshape(-1)
        err = (err1 + err2) if self.measure_quant else None
        if not gather_ici:
            # Overlap mode: keep the reduced chunk as this device's data
            # shard — the jit-level double-buffered accumulator stays at
            # 1/data memory and the one all-gather happens at unbucket
            # time, after the final microstep.
            return mine, err
        # All-gather the reduced chunk back over ICI: the bucket leaves
        # this region replicated and the engine's grad-spec constraint
        # re-shards it locally (no further traffic).
        return jax.lax.all_gather(mine, DATA_AXIS, axis=0, tiled=True), err

    def dcn_sync(self, stacked: Tuple[jax.Array, ...]):
        """DCN stage entry: ``stacked`` buckets are [dcn, bucket_elems]
        (stage 1 stacks each slice's partial on a leading dcn dim).
        Returns ``(buckets, qerr)``: fully-reduced fp32 buckets, one HLO
        collective chain per bucket so the scheduler can overlap them,
        plus — when ``measure_quant_error`` is on — a replicated
        ``[num_buckets, 2]`` fp32 array of (rel-L2, max-abs) round-trip
        error per bucket, psum'd/pmax'd over the whole manual region
        (None otherwise). rel-L2 is the root-sum-square of the two RTNE
        hops (outbound payload + reduced-bucket re-quantization) — the
        error-propagation estimate of the END-TO-END error vs an fp32
        all-reduce; max-abs is the two hops' worst-case sum, in
        accumulator units — under fp16 that includes the loss scale."""
        if not stacked:
            return (), None
        if self._dcn_sync_fn is None:
            measure = self.measure_quant

            def body(*bs):
                res = [self._dcn_allreduce_local(b[0]) for b in bs]
                bufs = tuple(r[0] for r in res)
                if not measure:
                    return bufs
                rows = []
                for _, (e1, r1, m1, e2, r2, m2) in res:
                    axes = (DCN_AXIS, DATA_AXIS)
                    rel1 = rel_from_parts(jax.lax.psum(e1, axes),
                                          jax.lax.psum(r1, axes))
                    rel2 = rel_from_parts(jax.lax.psum(e2, axes),
                                          jax.lax.psum(r2, axes))
                    mab = (jax.lax.pmax(m1, axes)
                           + jax.lax.pmax(m2, axes))
                    rows.append(jnp.stack(
                        [jnp.sqrt(rel1 * rel1 + rel2 * rel2), mab]))
                return bufs, jnp.stack(rows)

            out_specs = tuple(P() for _ in stacked)
            if measure:
                out_specs = (out_specs, P())
            self._dcn_sync_fn = shard_map(
                body, mesh=self.mesh,
                in_specs=tuple(P(DCN_AXIS, DATA_AXIS) for _ in stacked),
                out_specs=out_specs,
                axis_names={DCN_AXIS, DATA_AXIS},
                check_vma=False)
        out = self._dcn_sync_fn(*stacked)
        if self.measure_quant:
            return out[0], out[1]
        return out, None

    # ------------------------------------------------------------------
    # overlap mode (comm.overlap_grad_sync; docs/PERFORMANCE.md
    # "Overlapped gradient sync")
    # ------------------------------------------------------------------
    def microstep_buckets_overlap(self, grads_tree: Any
                                  ) -> Tuple[jax.Array, ...]:
        """Per-bucket flat buffers built from ONLY each bucket's own
        leaves (+ its own padding) — every bucket gets an independent
        dependency chain, so its data-axis reduce-scatter can start as
        soon as *its* gradients exist, not when the whole tree does.
        Runs inside the manual={dcn} region like
        :meth:`microstep_buckets`."""
        if not self.num_buckets:
            return ()
        leaves = self.treedef.flatten_up_to(grads_tree)
        out = []
        for lidx, padded in zip(self.bucket_leaf_idx, self.bucket_padded):
            parts = [leaves[i].reshape(-1).astype(self.ici_dtype)
                     for i in lidx if self.leaf_sizes[i]]
            have = sum(self.leaf_sizes[i] for i in lidx)
            if padded - have:
                # Padding joins the concat (jnp.pad trips the old
                # partitioner's manual-subgroup check — see
                # microstep_buckets).
                parts.append(jnp.zeros((padded - have,), self.ici_dtype))
            out.append(jax.lax.with_sharding_constraint(
                jnp.concatenate(parts) if len(parts) > 1 else parts[0],
                self._data_sharding))
        return tuple(out)

    def _dcn_sync_overlap(self, stacked: Tuple[jax.Array, ...]):
        """Overlap-mode DCN stage for ONE microstep's buckets: same wire
        protocol as :meth:`dcn_sync` but the reduced buckets come back
        as data-sharded shards (``gather_ici=False`` — the jit-level
        accumulator keeps the 1/data memory shape and the single
        all-gather happens at unbucket time), and the quantization-error
        accumulables come back raw (``[num_buckets, 6]`` of
        (err_sq, ref_sq, max_abs) x two hops, already psum/pmax'd over
        the region) so the caller can accumulate them across
        microsteps."""
        if not stacked:
            return (), None
        if self._dcn_overlap_fn is None:
            measure = self.measure_quant

            def body(*bs):
                res = [self._dcn_allreduce_local(b[0], gather_ici=False)
                       for b in bs]
                bufs = tuple(r[0] for r in res)
                if not measure:
                    return bufs
                axes = (DCN_AXIS, DATA_AXIS)
                rows = []
                for _, (e1, r1, m1, e2, r2, m2) in res:
                    rows.append(jnp.stack(
                        [jax.lax.psum(e1, axes), jax.lax.psum(r1, axes),
                         jax.lax.pmax(m1, axes),
                         jax.lax.psum(e2, axes), jax.lax.psum(r2, axes),
                         jax.lax.pmax(m2, axes)]))
                return bufs, jnp.stack(rows)

            out_specs = tuple(P(DATA_AXIS) for _ in stacked)
            if measure:
                out_specs = (out_specs, P())
            self._dcn_overlap_fn = shard_map(
                body, mesh=self.mesh,
                in_specs=tuple(P(DCN_AXIS, DATA_AXIS) for _ in stacked),
                out_specs=out_specs,
                axis_names={DCN_AXIS, DATA_AXIS},
                check_vma=False)
        out = self._dcn_overlap_fn(*stacked)
        if self.measure_quant:
            return out[0], out[1]
        return out, None

    def _qerr_from_parts(self, acc: jax.Array) -> jax.Array:
        """Fold microstep-accumulated error parts ``[num_buckets, 6]``
        into the ``[num_buckets, 2]`` (rel-L2, max-abs) rows
        :meth:`dcn_sync` emits: per-hop rel from the summed squares
        (error-propagation across microsteps), hops RSS-combined;
        max-abs sums hops AND microsteps (the worst-case errors of the
        summed contributions add)."""
        rel1 = rel_from_parts(acc[:, 0], acc[:, 1])
        rel2 = rel_from_parts(acc[:, 3], acc[:, 4])
        return jnp.stack(
            [jnp.sqrt(rel1 * rel1 + rel2 * rel2), acc[:, 2] + acc[:, 5]],
            axis=1)

    def _microstep_region(self, *, compute_params, sub, scale, batch,
                          batch_spec, grad_fn, microbatched: bool):
        """ONE microstep's manual={dcn} region: fwd/bwd with the ICI
        overlap hook installed (in-tree models' bucket-boundary markers
        reduce-scatter each layer group's grads mid-backward), per-bucket
        flat buffers with independent dependency chains, per-microstep
        fallback sync, dcn-pmean'd loss. Returns ``(stacked_buckets,
        fb_synced, loss)`` with the buckets dcn-stacked for
        :meth:`_dcn_sync_overlap`."""
        from deepspeed_tpu.comm import overlap as overlap_mod

        hook = overlap_mod.ici_scatter_hook(
            self._data_sharding, self.ici_dtype,
            lambda name: self._group_bucketed.get(name, False))

        def body(cp, sub_, scale_, batch_, slice_id):
            key = jax.random.fold_in(sub_, slice_id[0])
            with overlap_mod.install_ici_hook(hook):
                loss, grads = grad_fn(cp, batch_, key, scale_)
            mb = self.microstep_buckets_overlap(grads)
            fb_synced = self.fallback_sync(self.fallback_leaves(grads))
            loss = jax.lax.pmean(loss, DCN_AXIS)
            return tuple(b[None] for b in mb), fb_synced, loss

        batch_specs = dcn_batch_leaf_specs(
            batch, batch_spec, self.mesh,
            leading_gas_dim=not microbatched)
        rep = P()
        mapped = shard_map(
            body, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: rep,
                                             compute_params),
                      rep, rep, batch_specs, P(DCN_AXIS)),
            out_specs=(tuple(P(DCN_AXIS)
                             for _ in range(self.num_buckets)),
                       [rep] * len(self.fallback_idx), rep),
            axis_names={DCN_AXIS},
            check_vma=False)
        return mapped(compute_params, sub, scale, batch,
                      slice_index_operand(self.mesh))

    def _run_overlap_gas(self, *, batches: Any, batch_spec,
                         compute_params: Any, sub: jax.Array,
                         scale: jax.Array, grad_fn,
                         microbatched: bool = True):
        """The overlapped GAS schedule: microstep k's buckets are
        quantized and dispatched over DCN immediately after its
        backward, double-buffered so exactly ONE reduce is in flight
        while microstep k+1's fwd/bwd runs (its collective chain has no
        data dependency on k+1's compute — the latency-hiding scheduler
        overlaps them; in the traced program the dcn collectives of
        microstep k sit between microstep k's and k+1's compute, not all
        trailing). Only the final microstep's reduce is exposed.
        Returns ``(grads_tree, loss, qerr)``."""
        steps = self.gas if microbatched else 1
        keys = jax.random.split(sub, steps)
        total: Optional[List[jax.Array]] = None
        inflight: Optional[Tuple[jax.Array, ...]] = None
        fb_total: Optional[List[jax.Array]] = None
        err_acc = None
        losses = []
        for k in range(steps):
            batch_k = (jax.tree_util.tree_map(lambda x, k=k: x[k], batches)
                       if microbatched else batches)
            stacked_k, fb_k, loss_k = self._microstep_region(
                compute_params=compute_params, sub=keys[k], scale=scale,
                batch=batch_k, batch_spec=batch_spec, grad_fn=grad_fn,
                microbatched=microbatched)
            losses.append(loss_k)
            fb_total = (list(fb_k) if fb_total is None
                        else [a + b for a, b in zip(fb_total, fb_k)])
            if inflight is not None:
                # Consume the previous microstep's reduce — by now its
                # wire time has been hidden behind this microstep's
                # fwd/bwd. The accumulator holds ONE total plus ONE
                # in-flight buffer (double-buffered), never more.
                total = (list(inflight) if total is None
                         else [t + f for t, f in zip(total, inflight)])
            inflight, parts = self._dcn_sync_overlap(stacked_k)
            if parts is not None:
                err_acc = parts if err_acc is None else err_acc + parts
        if inflight is not None:
            total = (list(inflight) if total is None
                     else [t + f for t, f in zip(total, inflight)])
        grads = self._unbucket_overlap(total or [], fb_total or [])
        loss = jnp.mean(jnp.stack(losses))
        qerr = (self._qerr_from_parts(err_acc)
                if err_acc is not None else None)
        return grads, loss, qerr

    def _unbucket_overlap(self, buckets: Sequence[jax.Array],
                          fb: Sequence[jax.Array]) -> Any:
        """Slice each bucket's (data-sharded) reduced buffer back into
        its own leaves — leaves never straddle buckets in overlap mode —
        and merge the fallback leaves. The accumulated buckets arrive
        data-sharded; GSPMD inserts the one all-gather where the grad
        specs need it (same total ICI bytes as the non-overlap return
        gather)."""
        out: List[Optional[jax.Array]] = [None] * self.num_leaves
        for lidx, flat in zip(self.bucket_leaf_idx, buckets):
            off = 0
            for i in lidx:
                size = self.leaf_sizes[i]
                out[i] = flat[off:off + size].reshape(
                    self.leaf_shapes[i]).astype(self.acc_dtype)
                off += size
        for i, leaf in zip(self.fallback_idx, fb):
            out[i] = leaf
        return jax.tree_util.tree_unflatten(self.treedef, out)

    def gas_sync(self, *, batches: Any, batch_spec, compute_params: Any,
                 sub: jax.Array, scale: jax.Array, grad_fn,
                 microbatched: bool = True):
        """The ONE entry every hierarchical grad path calls: run the GAS
        fwd/bwd + full hierarchical sync under whichever schedule this
        plan resolved (overlapped or the PR-4 boundary sync) and return
        ``(grads_tree, loss, qerr)``."""
        if self.overlap:
            return self._run_overlap_gas(
                batches=batches, batch_spec=batch_spec,
                compute_params=compute_params, sub=sub, scale=scale,
                grad_fn=grad_fn, microbatched=microbatched)
        stacked, fb_synced, loss = self.run_manual_gas(
            batches=batches, batch_spec=batch_spec,
            compute_params=compute_params, sub=sub, scale=scale,
            grad_fn=grad_fn, microbatched=microbatched)
        grads, qerr = self.sync_grads(stacked, fb_synced)
        return grads, loss, qerr

    # ------------------------------------------------------------------
    # jit level
    # ------------------------------------------------------------------
    def run_manual_gas(self, *, batches: Any, batch_spec,
                       compute_params: Any, sub: jax.Array,
                       scale: jax.Array, grad_fn,
                       microbatched: bool = True):
        """The ONE manual={dcn} region every BOUNDARY-schedule (overlap
        off) hierarchical grad path runs — the overlapped schedule uses
        per-microstep regions (:meth:`_microstep_region`) instead:
        fold the slice id into the dropout key, run the (Python-unrolled)
        GAS loop of ``grad_fn(compute_params, batch, key, scale) ->
        (loss, grads)`` calls, bucket+accumulate each micro-step's grads
        (ICI reduce-scatter at the bucket constraints), sync the fallback
        leaves, and return ``(stacked_buckets, fallback_synced, loss)``
        ready for :meth:`dcn_sync` + :meth:`unbucket`.

        ``microbatched=False`` makes one grad_fn call over the whole
        ``batches`` tree (the pipe engine's single pipelined fwd/bwd over
        all microbatches).

        Shared by both engines' three step builders so the two
        old-partitioner landmines stay fixed in one place: the GAS loop
        unrolls in Python (a lax.scan feeding a dcn-sharded region output
        trips a fatal manual-subgroup check) and bucket padding joins the
        concat (``jnp.pad`` trips the same check).
        """
        fallback_inner = [NamedSharding(self.mesh, s)
                          for s in self.fallback_inner_specs]
        steps = self.gas if microbatched else 1

        def body(cp, sub_, scale_, batches_, slice_id):
            # Decorrelate dropout across slices (each slice sees its own
            # batch shard); slice_id is the iota-operand axis_index
            # stand-in (slice_index_operand).
            key = jax.random.fold_in(sub_, slice_id[0])
            buckets = self.zero_buckets()
            fb = self.zero_fallback()
            losses = []
            for i in range(steps):
                if microbatched:
                    batch = jax.tree_util.tree_map(lambda x, i=i: x[i],
                                                   batches_)
                    key, k = jax.random.split(key)
                else:
                    batch, k = batches_, key
                loss, grads = grad_fn(cp, batch, k, scale_)
                mb = self.microstep_buckets(grads)
                buckets = tuple(b + m.astype(b.dtype)
                                for b, m in zip(buckets, mb))
                gf = self.fallback_leaves(grads)
                fb = [jax.lax.with_sharding_constraint(
                        a + g.astype(a.dtype), s)
                      for a, g, s in zip(fb, gf, fallback_inner)]
                losses.append(loss)
            fb_synced = self.fallback_sync(fb)
            loss = jax.lax.pmean(jnp.mean(jnp.stack(losses)), DCN_AXIS)
            return tuple(b[None] for b in buckets), fb_synced, loss

        batch_specs = dcn_batch_leaf_specs(batches, batch_spec, self.mesh,
                                           leading_gas_dim=True)
        rep = P()
        mapped = shard_map(
            body, mesh=self.mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: rep,
                                             compute_params),
                      rep, rep, batch_specs, P(DCN_AXIS)),
            out_specs=(tuple(P(DCN_AXIS)
                             for _ in range(self.num_buckets)),
                       [rep] * len(self.fallback_idx), rep),
            axis_names={DCN_AXIS},
            check_vma=False)
        return mapped(compute_params, sub, scale, batches,
                      slice_index_operand(self.mesh))

    def unbucket(self, synced_buckets: Sequence[jax.Array],
                 synced_fallback: Sequence[jax.Array]) -> Any:
        """Slice the reduced buckets back into the grad tree (accumulator
        dtype) and merge the fallback leaves."""
        out: List[Optional[jax.Array]] = [None] * self.num_leaves
        if synced_buckets:
            flat = jnp.concatenate(synced_buckets)
            off = 0
            for i in self.bucketed_idx:
                size = self.leaf_sizes[i]
                out[i] = flat[off:off + size].reshape(
                    self.leaf_shapes[i]).astype(self.acc_dtype)
                off += size
        for i, leaf in zip(self.fallback_idx, synced_fallback):
            out[i] = leaf
        return jax.tree_util.tree_unflatten(self.treedef, out)

    # ------------------------------------------------------------------
    # modeling / telemetry
    # ------------------------------------------------------------------
    def sync_grads(self, stacked: Tuple[jax.Array, ...],
                   synced_fallback: Sequence[jax.Array]
                   ) -> Tuple[Any, Optional[jax.Array]]:
        """DCN-sync the stage-1 buckets and slice them back into the grad
        tree — the one sequence every hierarchical step runs after
        :meth:`run_manual_gas`. Returns ``(grads_tree, qerr)``; ``qerr``
        is :meth:`dcn_sync`'s per-bucket error array (None unless
        ``measure_quant_error``)."""
        buckets, qerr = self.dcn_sync(stacked)
        return self.unbucket(buckets, synced_fallback), qerr

    def _bucket_dcn_bytes(self, elems: int) -> int:
        """Modeled DCN wire bytes for one bucket of ``elems`` elements
        (both directions) — the ONE formula behind modeled_bytes and the
        per-bucket trace instants, so the gauge and the instants can
        never disagree."""
        shard = elems // self.data_size
        if self.bits == 32:
            # Passthrough ships the bucket's ICI dtype verbatim (bf16
            # communication_data_type also halves the fp32 passthrough).
            return 2 * shard * jnp.dtype(self.ici_dtype).itemsize
        return 2 * modeled_wire_bytes(shard, self.bits, self.block)

    def _per_bucket_dcn_bytes(self) -> int:
        return self._bucket_dcn_bytes(self.bucket_elems)

    def modeled_bytes(self) -> dict:
        """Per-device per-step wire bytes (modeled; self-shard included,
        so an upper bound — ratios between tiers are exact). Overlap
        mode reduces every microstep's contribution over DCN separately
        (that is what hides the wire time behind the next microstep's
        compute), so its DCN bytes — and the fp32 reference on the SAME
        schedule — carry the GAS factor; the compression ratio between
        tiers is schedule-invariant."""
        sync_rounds = self.gas if self.overlap else 1
        dcn_once = (sum(self._bucket_dcn_bytes(e)
                        for e in self.bucket_padded)
                    + 2 * 4 * self.fallback_elems)   # fp32 psum fallback
        bytes_dcn = sync_rounds * dcn_once
        ici_item = jnp.dtype(self.ici_dtype).itemsize
        # One reduce-scatter per MICRO-step (each gas iteration's bucket
        # constraint) in the ICI dtype, plus one fp32 all-gather of the
        # dequantized buckets out of the DCN stage per optimizer step
        # (overlap mode defers it to unbucket time — same bytes).
        bytes_ici = (self.gas * self.padded_elems * ici_item
                     + self.padded_elems * 4)
        fp32_dcn = sync_rounds * (
            sum(2 * 4 * (e // self.data_size) for e in self.bucket_padded)
            + 2 * 4 * self.fallback_elems)
        return {
            "bytes_dcn": int(bytes_dcn),
            "bytes_ici": int(bytes_ici),
            "bytes_dcn_fp32": int(fp32_dcn),
            "compression_ratio": (fp32_dcn / bytes_dcn if bytes_dcn else 1.0),
            "num_buckets": self.num_buckets,
            "bucket_elems": self.bucket_elems,
            "bucketed_elems": self.total_elems,
            "fallback_elems": self.fallback_elems,
            "overlap": int(self.overlap),
        }

    def modeled_wire_seconds(self) -> float:
        """Total modeled collective seconds per optimizer step at the
        nominal link bandwidths — the wire time that exists, overlapped
        or not."""
        m = self.modeled_bytes()
        return (m["bytes_dcn"] / (self.dcn_gbps * 1e9)
                + m["bytes_ici"] / (self.ici_gbps * 1e9))

    def modeled_exposed_seconds(self,
                                overlap_budget_seconds: Optional[float]
                                = None) -> float:
        """Modeled EXPOSED collective seconds per optimizer step — the
        numerator of ``comm/exposed_frac`` and the
        ``goodput/exposed_comm_sec`` sub-attribution.

        Non-overlap schedule: the sync fires at the GAS boundary,
        nothing overlaps it (ROADMAP item 1's premise) — every modeled
        wire byte is exposed.

        Overlap schedule (docs/OBSERVABILITY.md "Gradient-sync
        metrics"): the exposed floor is the final microstep's DCN
        reduce plus the post-sync all-gather (nothing runs behind
        them); everything else is hideable behind backward compute.
        ``overlap_budget_seconds`` is the modeled compute time available
        to hide behind (the engine passes measured step time minus total
        wire time); hidden time is capped by it, so a comm-dominated
        step still reports most of its wire time as exposed. ``None``
        (no step measured yet, tools) reports the optimistic floor.
        Replace with jax.profiler-measured collective time
        (``comm/measured_exposed_frac``) when a profile was captured."""
        total = self.modeled_wire_seconds()
        if not self.overlap:
            return total
        steps = max(1, self.gas)
        m = self.modeled_bytes()
        dcn_final = (m["bytes_dcn"] / steps) / (self.dcn_gbps * 1e9)
        ag_final = (self.padded_elems * 4) / (self.ici_gbps * 1e9)
        floor = min(total, dcn_final + ag_final)
        if overlap_budget_seconds is None:
            return floor
        hidden = min(total - floor, max(0.0, overlap_budget_seconds))
        return total - hidden

    def describe(self) -> str:
        m = self.modeled_bytes()
        if self.overlap:
            shape = "+".join(str(e) for e in self.bucket_padded) or "0"
            buckets = f"{self.num_buckets}[{shape}] overlap"
        else:
            buckets = f"{self.num_buckets}x{self.bucket_elems}"
        return (f"grad_sync: dcn={self.dcn_size} bits={self.bits} "
                f"block={self.block} buckets={buckets} ici_dtype="
                f"{jnp.dtype(self.ici_dtype).name} "
                f"fallback_elems={self.fallback_elems} "
                f"modeled dcn bytes/step {m['bytes_dcn']} "
                f"({m['compression_ratio']:.2f}x vs fp32)")

    def emit_telemetry(self, telemetry, step: int) -> None:
        """Per-step registry gauges + one-time per-bucket annotations.
        Values are modeled from the plan shape (the collectives run inside
        one XLA program — there is no host-observable per-bucket seam),
        so this costs no device sync."""
        if telemetry is None or not getattr(telemetry, "enabled", False):
            return
        m = self.modeled_bytes()
        reg = telemetry.registry
        reg.gauge("comm/bytes_dcn").set(m["bytes_dcn"], step=step)
        reg.gauge("comm/bytes_ici").set(m["bytes_ici"], step=step)
        reg.gauge("comm/compression_ratio").set(m["compression_ratio"],
                                                step=step)
        if not getattr(self, "_buckets_announced", False):
            self._buckets_announced = True
            for b, elems in enumerate(self.bucket_padded):
                telemetry.instant("grad_sync/bucket", index=b,
                                  elems=elems,
                                  bytes_dcn=self._bucket_dcn_bytes(elems),
                                  bits=self.bits,
                                  overlap=int(self.overlap))


# ---------------------------------------------------------------------------
# ZeRO++ weight path: the explicit quantized param all-gather (qwZ/hpZ)
# ---------------------------------------------------------------------------

# The param-hop comm gauges (emitted by ParamGatherPlan.emit_telemetry),
# pinned against docs/OBSERVABILITY.md in BOTH directions by
# tests/test_doc_lint.py so fleet/devicetime attribution can always tell
# parameter traffic from gradient traffic.
COMM_PARAM_METRIC_TAGS = frozenset({
    "comm/bytes_dcn_params",
    "comm/bytes_ici_params",
})


class ParamGatherPlan:
    """The ZeRO++ weight-path wire protocol (arXiv 2306.10209 qwZ/hpZ):
    one explicit blockwise-quantized all-gather replacing the implicit
    full-precision pjit param all-gather for ZeRO stage >= 2.

    Placement comes from the partitioner (runtime/zero/partition.py):
    with ``zeropp.hpz: off`` the primary param/optimizer partition spans
    the full (dcn, data) product and this gather crosses DCN with int8
    codes; with ``hpz: on`` the partition stays intra-slice (the
    hierarchical secondary partition) and the gather rides ICI only —
    zero dcn-axis param collectives, asserted by tests/test_zeropp.py.

    Wire protocol per gathered leaf, inside ONE ``shard_map`` manual over
    the gather axes (everything else — TP specs, the dcn axis under hpZ —
    stays GSPMD-auto):

    - **int8**: flatten the local fp32 master shard, pad to a block
      multiple (padding joins a concat — ``jnp.pad`` trips the old
      partitioner's manual-subgroup check, see ``microstep_buckets``),
      quantize with the ONE deterministic RTNE core
      (:func:`deepspeed_tpu.comm.quantize.quantize_blockwise`),
      all-gather the int8 codes + fp32 scales, dequantize and stitch the
      full leaf back together. ~4x fewer wire bytes than fp32.
    - **bf16**: cast the shard, gather, upcast — 2x.
    - **fp32 passthrough** (``quantized_weights: off`` with hpZ on): a
      tiled fp32 all-gather — *exact*: the gathered tree is elementwise
      equal to the replicated master, so the hpZ-only tier is an
      ulp-parity rung, not a lossy one.

    Leaves below the stage-3 persistence threshold stay replicated
    (never gathered, no wire traffic); leaves sharded over non-data
    axes (TP/pipe) keep the implicit path (XLA gathers them in full
    precision as before — counted as ``fallback_elems``).

    ``measure_quant_error`` (numerics observatory on + a lossy tier):
    the region additionally returns the RTNE round-trip error of the
    wire payload vs the fp32 master — one ``[2]`` (rel-L2, max-abs)
    array psum'd/pmax'd over the manual axes — which the engine routes
    into the step aux and :class:`~deepspeed_tpu.telemetry.numerics.
    NumericsObservatory` emits as ``numerics/param_quant_rel_err`` /
    ``numerics/param_quant_max_abs_err``. Off, the region body is
    byte-for-byte the measurement-free one.

    The fused step builders hoist the gather out of the GAS scan —
    parameters are loop-invariant until the apply — so the modeled
    bytes below are per optimizer step.
    """

    def __init__(self, zeropp_cfg, mesh: Mesh, param_template: Any,
                 param_specs: Any, measure_quant_error: bool = False):
        self.mesh = mesh
        self.bits = int(zeropp_cfg.wire_bits)
        self.block = int(zeropp_cfg.quant_block_size)
        self.hpz = zeropp_cfg.hpz == "on"
        self.dcn_size = int(mesh.shape.get(DCN_AXIS, 1))
        self.data_size = int(mesh.shape.get(DATA_AXIS, 1))
        self.measure_quant = (bool(measure_quant_error)
                              and self.bits in (8, 16))

        leaves, self.treedef = jax.tree_util.tree_flatten(param_template)
        spec_leaves = self.treedef.flatten_up_to(param_specs)
        self.num_leaves = len(leaves)
        # (leaf idx, sharded dim, axes tuple) per explicitly-gathered leaf.
        self.gathered: List[Tuple[int, int, Tuple[str, ...]]] = []
        self.persistent_idx: List[int] = []   # replicated, no wire traffic
        self.fallback_idx: List[int] = []     # non-data sharding: implicit
        self._leaf_shapes = [tuple(getattr(l, "shape", ())) for l in leaves]
        # (leaf idx, ALL sharded axes) per fallback leaf — the hpZ
        # secondary charge still counts them (fallback_leaves()).
        self.fallback_axes: List[Tuple[int, Tuple[str, ...]]] = []
        for i, (leaf, spec) in enumerate(zip(leaves, spec_leaves)):
            entries = tuple(spec) if spec is not None else ()
            float_leaf = jnp.issubdtype(leaf.dtype, jnp.floating)
            dim = None
            dim_axes: Tuple[str, ...] = ()
            all_axes: List[str] = []
            other = False
            for j, e in enumerate(entries):
                parts = e if isinstance(e, tuple) else ((e,) if e else ())
                parts = tuple(a for a in parts if a is not None)
                if not parts:
                    continue
                real = tuple(a for a in parts
                             if self.mesh.shape.get(a, 1) > 1)
                if not real:
                    continue
                all_axes.extend(real)
                if set(real) <= {DCN_AXIS, DATA_AXIS}:
                    dim, dim_axes = j, real
                else:
                    other = True
            if dim is None and not other:
                self.persistent_idx.append(i)   # truly replicated
            elif other or not float_leaf:
                # TP/mixed-axis leaves (the flat-block protocol cannot
                # stitch a second sharded dim back) and sharded
                # non-float leaves: implicit full-precision path — they
                # DO produce wire traffic, so they must count as
                # fallback, never as persistent.
                self.fallback_idx.append(i)
                self.fallback_axes.append((i, tuple(all_axes)))
            else:
                self.gathered.append((i, dim, dim_axes))
        # The region is manual over the data-like axes {dcn, data} even
        # when the gather itself only names `data` (hpZ): this jax's old
        # SPMD partitioner rejects a manual subgroup whose AUTO axes sit
        # OUTSIDE the manual ones in mesh order (manual={data} with dcn
        # auto is the fatal IsManualSubgroup check; manual={dcn, data} is
        # the dcn_sync shape that works). Under hpZ every dcn rank holds
        # the full data-shard (params are dcn-replicated), so the body
        # computes identical values per slice and emits ZERO dcn-axis
        # collectives — the property tests/test_zeropp.py asserts.
        self.manual_axes = sorted(
            {a for _, _, axes in self.gathered for a in axes}
            | ({DCN_AXIS} if self.dcn_size > 1 and self.gathered else set()))
        self.gathered_elems = sum(
            int(math.prod(self._leaf_shapes[i])) for i, _, _ in self.gathered)
        self.fallback_elems = sum(
            int(math.prod(self._leaf_shapes[i])) for i in self.fallback_idx)
        self.persistent_elems = sum(
            int(math.prod(self._leaf_shapes[i]) or 1)
            for i in self.persistent_idx)
        self._gather_fn = None

    # ------------------------------------------------------------------
    def _restricted_spec(self, i: int, dim: int,
                         axes: Tuple[str, ...]) -> P:
        """shard_map in_spec for one gathered leaf: only the manual
        (gather) axes; everything else stays GSPMD-auto."""
        ndim = len(self._leaf_shapes[i])
        entries: List[Any] = [None] * ndim
        entries[dim] = axes if len(axes) > 1 else axes[0]
        return P(*entries)

    def gather(self, params: Any):
        """The explicit gather, traced inside the jitted step: returns
        ``(full_params fp32 tree, qerr)`` where the gathered leaves are
        fully replicated over the gather axes (the engine's precision
        policy casts to the compute dtype afterwards — elementwise, so
        the fp32 passthrough stays exact) and ``qerr`` is the ``[2]``
        (rel-L2, max-abs) wire round-trip error (None unless
        ``measure_quant_error``)."""
        leaves = self.treedef.flatten_up_to(params)
        if not self.gathered:
            return params, None
        if self._gather_fn is None:
            self._gather_fn = self._build_gather_fn()
        out = self._gather_fn(tuple(leaves[i] for i, _, _ in self.gathered))
        if self.measure_quant:
            full, qerr = out
        else:
            full, qerr = out, None
        merged = list(leaves)
        for (i, _, _), f in zip(self.gathered, full):
            merged[i] = f
        return jax.tree_util.tree_unflatten(self.treedef, merged), qerr

    def _build_gather_fn(self):
        measure = self.measure_quant
        bits, block = self.bits, self.block
        mesh = self.mesh
        red_axes = tuple(self.manual_axes)

        def gather_leaf(x, dim, axes):
            name = axes if len(axes) > 1 else axes[0]
            n = mesh_axes_size(mesh.shape, axes)
            if bits == 32:
                # Exact passthrough: one tiled fp32 all-gather stitches
                # the full leaf along the sharded dim directly.
                return jax.lax.all_gather(x, name, axis=dim,
                                          tiled=True), None
            flat = x.reshape(-1).astype(jnp.float32)
            m = flat.shape[0]
            pad = (-m) % block
            if pad:
                # Padding joins the concat instead of jnp.pad (the old
                # partitioner's manual-subgroup check — see
                # microstep_buckets).
                flat = jnp.concatenate(
                    [flat, jnp.zeros((pad,), jnp.float32)])
            err = (roundtrip_error_parts(flat, bits, block)
                   if measure else None)
            if bits == 8:
                q, s = quantize_blockwise(flat, block)
                qg = jax.lax.all_gather(q, name, axis=0, tiled=False)
                sg = jax.lax.all_gather(s, name, axis=0, tiled=False)
                deq = dequantize_blockwise(qg, sg, block)
            else:       # bf16 wire
                wg = jax.lax.all_gather(flat.astype(jnp.bfloat16), name,
                                        axis=0, tiled=False)
                deq = wg.astype(jnp.float32)
            shards = deq[:, :m].reshape((n,) + x.shape)
            full = jnp.moveaxis(shards, 0, dim).reshape(
                x.shape[:dim] + (n * x.shape[dim],) + x.shape[dim + 1:])
            return full, err

        red_size = mesh_axes_size(mesh.shape, red_axes)

        def body(ls):
            outs = []
            err_sq = ref_sq = mab = jnp.float32(0.0)
            for (idx, dim, axes), x in zip(self.gathered, ls):
                full, err = gather_leaf(x, dim, axes)
                outs.append(full)
                if err is not None:
                    e, r, ma = err
                    # The psum below runs over ALL manual axes, but a
                    # leaf gathered over a subset (e.g. a (data,)-only
                    # fallback leaf under the hpz=off global primary, or
                    # every leaf under hpZ where the region is manual
                    # over dcn too) holds REPLICATED shards along the
                    # rest — pre-divide by the replication factor so
                    # each unique shard's error counts exactly once and
                    # mixed trees aren't skewed toward replicated leaves.
                    gather_size = mesh_axes_size(mesh.shape, axes)
                    w = jnp.float32(gather_size / red_size)
                    err_sq = err_sq + e * w
                    ref_sq = ref_sq + r * w
                    mab = jnp.maximum(mab, ma)
            if not measure:
                return tuple(outs)
            rel = rel_from_parts(jax.lax.psum(err_sq, red_axes),
                                 jax.lax.psum(ref_sq, red_axes))
            return tuple(outs), jnp.stack(
                [rel, jax.lax.pmax(mab, red_axes)])

        in_specs = (tuple(self._restricted_spec(i, dim, axes)
                          for i, dim, axes in self.gathered),)
        out_leaf_specs = tuple(
            P(*([None] * len(self._leaf_shapes[i])))
            for i, _, _ in self.gathered)
        out_specs = ((out_leaf_specs, P()) if measure else out_leaf_specs)
        return shard_map(body, mesh=self.mesh, in_specs=in_specs,
                         out_specs=out_specs,
                         axis_names=set(self.manual_axes),
                         check_vma=False)

    # ------------------------------------------------------------------
    # modeling / telemetry
    # ------------------------------------------------------------------
    def gathered_leaves(self, tree: Any = None) -> List[Tuple[Tuple[int, ...], Tuple[str, ...], Any]]:
        """(global shape, gather axes, companion-tree leaf) per
        explicitly-gathered leaf — what the memory ledger sizes the
        gathered compute-tree footprint from (persistent leaves stay
        replicated; fallback leaves ride the implicit path, so neither
        is gathered in full here). ``tree`` is an optional companion
        pytree of the params structure (the engine's base partition
        specs); None yields None companions."""
        comp = ([None] * self.num_leaves if tree is None
                else self.treedef.flatten_up_to(tree))
        return [(self._leaf_shapes[i], axes, comp[i])
                for i, _, axes in self.gathered]

    def fallback_leaves(self, tree: Any = None) -> List[Tuple[Tuple[int, ...], Tuple[str, ...], Any]]:
        """Same triples for the implicit-path (TP/mixed-axis) leaves,
        with ALL their sharded mesh axes. They skip the explicit gather
        but still carry the partitioner's primary placement on their
        free dim — so the hpZ secondary charge must count them alongside
        the gathered leaves (a global (hpz off) primary would spread
        them over dcn too)."""
        comp = ([None] * self.num_leaves if tree is None
                else self.treedef.flatten_up_to(tree))
        return [(self._leaf_shapes[i], axes, comp[i])
                for i, axes in self.fallback_axes]

    def modeled_bytes(self) -> dict:
        """Per-device per-optimizer-step modeled wire bytes of the param
        gather, split by link direction (self-shard included — an upper
        bound; ratios between tiers are exact, the GradSyncPlan
        convention). ``bytes_params_fp32`` is the same gather at fp32
        wire — the compression denominator. Persistent (replicated)
        leaves never hit the wire; fallback (TP-sharded) leaves ride the
        implicit full-precision path and are excluded from the explicit
        totals (reported so the probe can see them)."""
        bytes_dcn = bytes_ici = fp32 = 0.0
        for i, _, axes in self.gathered:
            elems = int(math.prod(self._leaf_shapes[i]))
            wire = modeled_wire_bytes(elems, self.bits, self.block)
            ref = modeled_wire_bytes(elems, 32, self.block)
            dcn_frac = ((self.dcn_size - 1) / self.dcn_size
                        if DCN_AXIS in axes and self.dcn_size > 1 else 0.0)
            bytes_dcn += wire * dcn_frac
            bytes_ici += wire * (1.0 - dcn_frac)
            fp32 += ref
        wire_total = bytes_dcn + bytes_ici
        return {
            "bytes_dcn_params": int(bytes_dcn),
            "bytes_ici_params": int(bytes_ici),
            "bytes_params_fp32": int(fp32),
            "compression_ratio": (fp32 / wire_total if wire_total else 1.0),
            "gathered_elems": self.gathered_elems,
            "fallback_elems": self.fallback_elems,
            "persistent_elems": self.persistent_elems,
            "hpz": int(self.hpz),
            "bits": self.bits,
        }

    def modeled_wire_seconds(self, dcn_gbps: float,
                             ici_gbps: float) -> float:
        """Modeled collective seconds per optimizer step of the explicit
        param gather at the nominal link bandwidths (the engine passes
        the grad plan's comm.dcn_gbps/ici_gbps). The gather runs
        sequentially before the fused fwd/bwd — nothing is scheduled to
        hide it — so callers count ALL of it as exposed
        (``_emit_comm_attribution``: the modeled ``comm/exposed_frac``
        must include this hop or the PR-9 modeled-vs-measured divergence
        warning fires spuriously whenever zeropp rides with the
        hierarchical sync)."""
        m = self.modeled_bytes()
        return (m["bytes_dcn_params"] / (dcn_gbps * 1e9)
                + m["bytes_ici_params"] / (ici_gbps * 1e9))

    def describe(self) -> str:
        m = self.modeled_bytes()
        tier = {8: "int8", 16: "bf16", 32: "fp32"}[self.bits]
        return (f"zeropp: param gather {tier} block={self.block} "
                f"hpz={'on' if self.hpz else 'off'} "
                f"axes={self.manual_axes} leaves={len(self.gathered)} "
                f"({self.gathered_elems} elems; {self.persistent_elems} "
                f"persistent, {self.fallback_elems} fallback) modeled "
                f"dcn/ici bytes {m['bytes_dcn_params']}/"
                f"{m['bytes_ici_params']} "
                f"({m['compression_ratio']:.2f}x vs fp32)")

    def emit_telemetry(self, telemetry, step: int) -> None:
        """The param-hop direction of the comm byte attribution
        (comm/bytes_dcn_params, comm/bytes_ici_params) — modeled from
        the plan shape like the grad gauges, no device sync."""
        if telemetry is None or not getattr(telemetry, "enabled", False):
            return
        m = self.modeled_bytes()
        reg = telemetry.registry
        reg.gauge("comm/bytes_dcn_params").set(m["bytes_dcn_params"],
                                               step=step)
        reg.gauge("comm/bytes_ici_params").set(m["bytes_ici_params"],
                                               step=step)


# The ISSUE-facing name: the plan IS the strategy object the engines wire
# in (one per engine, bound to its grad tree at step-construction time).
GradSyncStrategy = GradSyncPlan


def dcn_batch_leaf_specs(batches: Any, batch_spec, mesh: Mesh,
                         leading_gas_dim: bool = True) -> Any:
    """Per-leaf shard_map in_specs for the manual={dcn} region: keep only
    the dcn entries of the engine's batch spec, truncated to each leaf's
    rank, replicating any leaf whose dims don't divide (mirroring
    ``put_batch``'s graceful degradation — same rule as the 1-bit
    builder's ``batch_leaf_spec``)."""
    def restrict(entry):
        parts = entry if isinstance(entry, tuple) else (entry,)
        kept = tuple(a for a in parts if a == DCN_AXIS)
        return kept if len(kept) > 1 else (kept[0] if kept else None)

    base = tuple(restrict(e) for e in tuple(batch_spec))
    if leading_gas_dim:
        base = (None,) + base

    def leaf_spec(x):
        entries = base[:x.ndim]
        for d, e in zip(x.shape, entries):
            parts = e if isinstance(e, tuple) else ((e,) if e else ())
            n = mesh_axes_size(mesh.shape, parts)
            if n > 1 and d % n:
                return P(*([None] * x.ndim))
        return P(*entries)

    return jax.tree_util.tree_map(leaf_spec, batches)


def slice_index_operand(mesh: Mesh) -> jax.Array:
    """A [dcn]-iota whose single local element inside a manual={dcn}
    region IS the slice id — the ``axis_index`` equivalent that survives
    this jax's partial-manual lowering (axis_index lowers to a
    PartitionId HLO the old SPMD partitioner rejects; same trick as the
    pipeline's rank_arr)."""
    return jnp.arange(mesh.shape.get(DCN_AXIS, 1), dtype=jnp.int32)
