"""Numerics observatory — per-layer-group gradient/update statistics,
dtype-saturation counters, and quantization-error attribution.

The stack measures every second (goodput), byte (comm gauges) and HBM
allocation (memory observatory) — this module measures the *numbers*
(docs/OBSERVABILITY.md "Numerics observatory"). Until it landed, the
guardrails detector saw only scalar loss and one global grad norm, and
both int8 wire paths (the DCN grad all-reduce, the paged KV cache)
shipped off-by-default with their error unmeasured — exactly the
observability ROADMAP item 2 (ZeRO++ qwZ, arXiv 2306.10209) needs before
a quantized *parameter* all-gather can responsibly turn on, and the
accuracy/bandwidth trade EQuARX (arXiv 2506.17615) insists must be
measured, not assumed.

Three tiers behind ``telemetry.numerics`` (default off):

- **In-program statistics** — a :class:`NumericsPlan` groups the param
  pytree by top-level key (capped at ``max_groups``; the overflow rides
  an ``_other`` group) and the jitted step computes ONE small stacked
  ``[groups, 5]`` fp32 aux array: per-group gradient/weight/update
  squared norms plus compute-dtype saturation (finite fp32 grad → inf in
  bf16/fp16) and underflow-to-zero (nonzero fp32 grad → exact zero)
  element counts. All paths — ZeRO 0-3 fused, hierarchical, offload and
  pipeline — ride the same :meth:`NumericsPlan.group_stats`; the engine
  stores the device array per step (no transfer) and ONE
  ``jax.device_get`` at the metrics-flush boundary feeds the
  ``numerics/*`` gauges. The offload tier's optimizer step runs on the
  host, so its update norms are reported as 0 (grad/weight stats and the
  counters are still in-program).
- **Quantization-error attribution** — with ``comm.hierarchical`` int8
  (or bf16) on, the DCN stage additionally emits per-bucket RTNE
  round-trip error of the wire payload against the fp32 shard
  (``numerics/dcn_quant_rel_err`` / ``numerics/dcn_quant_max_abs_err``,
  via :func:`deepspeed_tpu.comm.quantize.roundtrip_error_parts` psum'd
  across the manual region), and the serving engine emits the analogous
  ``numerics/kv_quant_rel_err`` for the int8 KV cache — the measured
  evidence the quantized param all-gather decision needs.
- **Integration** — guardrails spike verdicts name the worst-offending
  layer group (nonfinite grad first, else largest grad/weight norm
  ratio) in the trace instant and a ``spike_step*`` crashdump; the fleet
  vector gains a ``grad_norm`` field so stragglers and numeric
  divergence correlate per host; ``tools/numerics_report.py`` renders
  per-group trend tables and flags monotone update-ratio drift.

Zero-overhead contract (the PR 2/3/5/6/7 gate): default off ⇒
``engine.numerics`` is ``None``, every hook one attribute check, and the
lowered step text is bit-identical to a numerics-less config. Enabled,
the statistics ride the existing jitted step (no extra dispatch, no
per-step host fetch); the single transfer happens at the flush boundary
(:meth:`NumericsObservatory._fetch` is the ONE site, so tests count it).

jax/numpy are imported lazily where possible so the telemetry package
stays importable on jax-less report hosts.
"""

from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

# Columns of the per-group stats matrix — the wire layout of the step
# aux array. Append only.
GRAD_SQ, WEIGHT_SQ, UPDATE_SQ, SATURATED, UNDERFLOWED = range(5)
N_GROUP_STATS = 5

# Name of the overflow group leaves beyond ``max_groups`` collapse into.
OTHER_GROUP = "_other"

# Every metric tag this module's surface can emit — the engine-side
# per-group gauges, the DCN per-bucket quantization-error gauges, and the
# serving engine's KV-cache analogue (emitted from serving/engine.py but
# owned by this surface). Pinned against docs/OBSERVABILITY.md in BOTH
# directions by tests/test_doc_lint.py, like GOODPUT/FLEET/MEMORY tags.
NUMERICS_METRIC_TAGS = frozenset({
    "numerics/grad_norm",
    "numerics/weight_norm",
    "numerics/update_ratio",
    "numerics/saturation_count",
    "numerics/underflow_count",
    "numerics/global_grad_norm",
    "numerics/dcn_quant_rel_err",
    "numerics/dcn_quant_max_abs_err",
    "numerics/kv_quant_rel_err",
    "numerics/kv_quant_max_abs_err",
    "numerics/param_quant_rel_err",
    "numerics/param_quant_max_abs_err",
})


def _top_key(path) -> str:
    """Top-level pytree key of one flattened leaf path."""
    k = path[0]
    return str(getattr(k, "key", getattr(k, "name", getattr(k, "idx", k))))


class NumericsPlan:
    """Trace-time grouping + the in-program stats function.

    Built once per engine from the param template; :meth:`group_stats`
    is pure jnp and traces inside the jitted step functions — it never
    dispatches its own program.
    """

    def __init__(self, params_template: Any, max_groups: int = 16,
                 compute_dtype=None, expert_groups: int = 0):
        import jax

        flat, _ = jax.tree_util.tree_flatten_with_path(params_template)
        keys = [_top_key(path) for path, _ in flat]
        ordered: List[str] = []
        for k in keys:
            if k not in ordered:
                ordered.append(k)
        if len(ordered) > int(max_groups):
            # Cap: keep the first max_groups-1 top-level keys, collapse
            # the tail into _other — the aux array must stay small and
            # its shape static.
            self.group_names = ordered[:int(max_groups) - 1] + [OTHER_GROUP]
        else:
            self.group_names = ordered
        index = {n: i for i, n in enumerate(self.group_names)}
        other = index.get(OTHER_GROUP)
        self.leaf_group = [index.get(k, other) for k in keys]
        self.num_groups = len(self.group_names)
        # MoE: per-expert rows. Expert-stacked FFN leaves (leading dim ==
        # expert_groups, last path key experts_in/experts_out — the
        # moe/layer.py param layout) ALSO contribute one row per expert,
        # appended after the regular groups and exempt from max_groups
        # (they are a fixed-size family, not a pytree-shaped one). Each
        # such leaf still feeds its top-level group so the regular rows
        # stay comparable across MoE/dense runs. expert_groups == 0 (the
        # default, and any moe-less engine) leaves the plan byte-identical
        # — the zero-overhead contract.
        self.expert_groups = int(expert_groups)
        self.expert_leaf_idx: List[int] = []
        self.expert_base = self.num_groups
        if self.expert_groups > 0:
            for li, (path, leaf) in enumerate(flat):
                last = path[-1]
                name = str(getattr(last, "key", getattr(
                    last, "name", getattr(last, "idx", last))))
                shape = getattr(leaf, "shape", ())
                if (name in ("experts_in", "experts_out")
                        and len(shape) >= 1
                        and int(shape[0]) == self.expert_groups):
                    self.expert_leaf_idx.append(li)
            if self.expert_leaf_idx:
                self.group_names = list(self.group_names) + [
                    f"moe_expert_{i}" for i in range(self.expert_groups)]
                self.num_groups = len(self.group_names)
        # Saturation/underflow are measured against this dtype (the
        # engine's mixed-precision compute dtype); None ⇒ pure-fp32 run,
        # counters are structurally zero.
        self.compute_dtype = compute_dtype

    # ------------------------------------------------------------------
    def group_stats(self, grads: Any, params: Any = None,
                    new_params: Any = None, inv_scale=None):
        """The ``[num_groups, N_GROUP_STATS]`` fp32 aux array for one
        optimizer step. ``grads``: the accumulated grad tree (same
        structure as the param template). ``params``/``new_params``:
        pre-/post-update params (``new_params=None`` ⇒ update norms stay
        0 — the offload tier, whose optimizer runs on the host).
        ``inv_scale``: multiplier restoring unscaled grads (the fused
        builders hand over already-unscaled grads and pass None)."""
        import jax
        import jax.numpy as jnp

        g_leaves = jax.tree_util.tree_leaves(grads)
        p_leaves = (jax.tree_util.tree_leaves(params)
                    if params is not None else [None] * len(g_leaves))
        n_leaves = (jax.tree_util.tree_leaves(new_params)
                    if new_params is not None else [None] * len(g_leaves))
        stats = jnp.zeros((self.num_groups, N_GROUP_STATS), jnp.float32)
        cdt = self.compute_dtype
        zero = jnp.float32(0.0)
        for i, g in enumerate(g_leaves):
            gid = self.leaf_group[i]
            g32 = g.astype(jnp.float32)
            if inv_scale is not None:
                g32 = g32 * inv_scale
            p = p_leaves[i]
            w_sq = (jnp.sum(jnp.square(p.astype(jnp.float32)))
                    if p is not None else zero)
            if n_leaves[i] is not None and p is not None:
                d = n_leaves[i].astype(jnp.float32) - p.astype(jnp.float32)
                u_sq = jnp.sum(d * d)
            else:
                u_sq = zero
            if cdt is not None and jnp.dtype(cdt) != jnp.float32:
                gc = g32.astype(cdt)
                sat = jnp.sum((~jnp.isfinite(gc))
                              & jnp.isfinite(g32)).astype(jnp.float32)
                under = jnp.sum((gc == 0)
                                & (g32 != 0)).astype(jnp.float32)
            else:
                sat = under = zero
            stats = stats.at[gid].add(
                jnp.stack([jnp.sum(g32 * g32), w_sq, u_sq, sat, under]))
        # MoE per-expert rows: expert-stacked leaves additionally reduce
        # over all-but-the-leading axis and scatter into the appended
        # moe_expert_* rows (disjoint from the top-level rows above).
        for i in getattr(self, "expert_leaf_idx", ()):
            e = self.expert_groups
            g32 = g_leaves[i].astype(jnp.float32)
            if inv_scale is not None:
                g32 = g32 * inv_scale
            gf = g32.reshape(e, -1)
            g_sq = jnp.sum(gf * gf, axis=1)
            p = p_leaves[i]
            if p is not None:
                pf = p.astype(jnp.float32).reshape(e, -1)
                w_sq = jnp.sum(pf * pf, axis=1)
            else:
                pf = None
                w_sq = jnp.zeros((e,), jnp.float32)
            if n_leaves[i] is not None and pf is not None:
                df = n_leaves[i].astype(jnp.float32).reshape(e, -1) - pf
                u_sq = jnp.sum(df * df, axis=1)
            else:
                u_sq = jnp.zeros((e,), jnp.float32)
            if cdt is not None and jnp.dtype(cdt) != jnp.float32:
                gc = gf.astype(cdt)
                sat = jnp.sum(((~jnp.isfinite(gc)) & jnp.isfinite(gf))
                              .astype(jnp.float32), axis=1)
                under = jnp.sum(((gc == 0) & (gf != 0))
                                .astype(jnp.float32), axis=1)
            else:
                sat = under = jnp.zeros((e,), jnp.float32)
            per_expert = jnp.stack([g_sq, w_sq, u_sq, sat, under], axis=1)
            stats = stats.at[
                self.expert_base:self.expert_base + e].add(per_expert)
        return stats


class NumericsObservatory:
    """Host-side facade: stores each step's device aux (no transfer),
    fetches ONCE at the flush boundary, emits the gauges, and answers the
    guardrails' "which layer group?" question on spike verdicts."""

    def __init__(self, cfg, plan: NumericsPlan, telemetry=None):
        self.cfg = cfg
        self.plan = plan
        self.telemetry = telemetry
        self._last: Optional[Any] = None
        self._last_step = -1
        self._host: Optional[Dict[str, np.ndarray]] = None

    def attach(self, telemetry) -> None:
        """Late telemetry binding: the engine builds the plan before its
        step functions, the telemetry facade after."""
        self.telemetry = telemetry

    # -- step-path hook (no device work) --------------------------------
    def note_step(self, aux: Any, step: int) -> None:
        """Store this step's device aux — a reference hand-off, zero
        syncs; a stored-but-never-flushed aux is simply dropped."""
        self._last = aux
        self._last_step = int(step)
        self._host = None

    # -- the ONE device->host transfer ----------------------------------
    def _fetch(self) -> Optional[Dict[str, np.ndarray]]:
        """THE flush-boundary transfer of this subsystem (single site so
        the zero-sync test can count every numerics-originated fetch)."""
        if self._last is None:
            return None
        if self._host is None:
            import jax
            host = jax.device_get(self._last)
            self._host = {k: np.asarray(v) for k, v in host.items()}
        return self._host

    # -- flush-boundary emission ----------------------------------------
    def flush(self, step: int) -> None:
        tel = self.telemetry
        if tel is None or not getattr(tel, "enabled", False):
            return
        host = self._fetch()
        if host is None:
            return
        groups = np.asarray(host["groups"], np.float64)
        reg = tel.registry
        for gi, name in enumerate(self.plan.group_names):
            g_norm = float(np.sqrt(max(groups[gi, GRAD_SQ], 0.0))
                           if np.isfinite(groups[gi, GRAD_SQ])
                           else groups[gi, GRAD_SQ])
            w_norm = float(np.sqrt(max(groups[gi, WEIGHT_SQ], 0.0)))
            u_norm = float(np.sqrt(max(groups[gi, UPDATE_SQ], 0.0)))
            reg.gauge("numerics/grad_norm").set(g_norm, step=step,
                                                group=name)
            reg.gauge("numerics/weight_norm").set(w_norm, step=step,
                                                  group=name)
            # A relative measure needs a scale: a ~zero-weight group
            # (zero-init bias under LR warmup) would otherwise report a
            # meaningless ~1e9 ratio and trip the report's drift flag.
            reg.gauge("numerics/update_ratio").set(
                u_norm / w_norm if w_norm > 1e-8 else 0.0,
                step=step, group=name)
            reg.gauge("numerics/saturation_count").set(
                float(groups[gi, SATURATED]), step=step, group=name)
            reg.gauge("numerics/underflow_count").set(
                float(groups[gi, UNDERFLOWED]), step=step, group=name)
        total = float(np.sum(groups[:, GRAD_SQ]))
        # The fleet vector reads this gauge (FLEET_FIELDS grad_norm) —
        # keep it finite so a NaN step cannot poison the gather matrix.
        reg.gauge("numerics/global_grad_norm").set(
            float(np.sqrt(total)) if np.isfinite(total) and total >= 0
            else 0.0, step=step)
        qerr = host.get("dcn_qerr")
        if qerr is not None and np.size(qerr):
            qerr = np.asarray(qerr, np.float64)
            for b in range(qerr.shape[0]):
                reg.gauge("numerics/dcn_quant_rel_err").set(
                    float(qerr[b, 0]), step=step, bucket=b)
                reg.gauge("numerics/dcn_quant_max_abs_err").set(
                    float(qerr[b, 1]), step=step, bucket=b)
        # ZeRO++ qwZ: the lossy PARAM hop (comm/grad_sync.py
        # ParamGatherPlan) — one (rel-L2, max-abs) pair per step, the
        # end-to-end round-trip error of the quantized weight all-gather
        # vs the fp32 master. Same opt-in/zero-overhead contract as the
        # DCN pair; absent unless the engine's zeropp tier is lossy.
        pq = host.get("param_qerr")
        if pq is not None and np.size(pq):
            pq = np.asarray(pq, np.float64).reshape(-1)
            reg.gauge("numerics/param_quant_rel_err").set(
                float(pq[0]), step=step)
            reg.gauge("numerics/param_quant_max_abs_err").set(
                float(pq[1]), step=step)

    # -- guardrails integration ------------------------------------------
    def worst_group(self) -> Optional[str]:
        """The layer group a spike verdict should name: the first group
        with a nonfinite gradient norm, else the group with the largest
        grad-to-weight norm ratio (scale-aware — a raw grad-norm argmax
        would always name the biggest layer). Costs one transfer; called
        only on (rare) spike verdicts."""
        host = self._fetch()
        if host is None:
            return None
        groups = np.asarray(host["groups"], np.float64)
        names = self.plan.group_names
        finite = np.isfinite(groups[:, GRAD_SQ])
        if not finite.all():
            return names[int(np.argmin(finite))]
        denom = np.sqrt(np.maximum(groups[:, WEIGHT_SQ], 1e-24))
        score = np.sqrt(np.maximum(groups[:, GRAD_SQ], 0.0)) / denom
        return names[int(np.argmax(score))]

    def group_table(self) -> List[Dict[str, Any]]:
        """Per-group rows for the spike crashdump (floats sanitised for
        JSON: nonfinite values become the string "nonfinite")."""
        host = self._fetch()
        if host is None:
            return []
        groups = np.asarray(host["groups"], np.float64)

        def _f(x):
            x = float(x)
            return x if np.isfinite(x) else "nonfinite"

        rows = []
        for gi, name in enumerate(self.plan.group_names):
            g_sq = groups[gi, GRAD_SQ]
            rows.append({
                "group": name,
                "grad_norm": _f(np.sqrt(g_sq) if np.isfinite(g_sq)
                                and g_sq >= 0 else g_sq),
                "weight_norm": _f(np.sqrt(max(groups[gi, WEIGHT_SQ], 0.0))),
                "update_ratio": _f(
                    np.sqrt(max(groups[gi, UPDATE_SQ], 0.0))
                    / np.sqrt(groups[gi, WEIGHT_SQ])
                    if groups[gi, WEIGHT_SQ] > 1e-16 else 0.0),
                "saturated": int(groups[gi, SATURATED])
                if np.isfinite(groups[gi, SATURATED]) else -1,
                "underflowed": int(groups[gi, UNDERFLOWED])
                if np.isfinite(groups[gi, UNDERFLOWED]) else -1,
                "finite": bool(np.isfinite(g_sq)),
            })
        return rows

    @property
    def last_step(self) -> int:
        return self._last_step


def build_numerics(tcfg, params_template: Any, compute_dtype=None,
                   expert_groups: int = 0) -> Optional[NumericsObservatory]:
    """``None`` unless telemetry AND its numerics block are enabled — the
    engine hooks gate on ``is None`` (the zero-overhead contract, same
    shape as goodput/fleet/memory/devicetime). ``expert_groups``: the
    engine passes ``moe.num_experts`` when the moe block is enabled, so
    expert-stacked FFN leaves get per-expert ``moe_expert_*`` rows; the
    default 0 keeps the plan byte-identical to a moe-less engine."""
    if tcfg is None or not tcfg.enabled or not tcfg.numerics.enabled:
        return None
    try:
        plan = NumericsPlan(params_template,
                            max_groups=tcfg.numerics.max_groups,
                            compute_dtype=compute_dtype,
                            expert_groups=int(expert_groups))
    except Exception as e:  # noqa: BLE001 — observability must never
        # take down the engine it observes
        logger.warning("numerics: plan construction failed: %s", e)
        return None
    return NumericsObservatory(tcfg.numerics, plan)
