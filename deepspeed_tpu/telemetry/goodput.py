"""Goodput accounting — run-level wall-clock attribution + MFU.

The telemetry layer answers *where does step time go* (tracer spans) and
*what happened* (metrics, recompile warnings). This module answers the
question a fleet operator asks about a whole run: **of N hours of
wall-clock, what fraction was productive training, what was lost to which
cause, and what MFU did the productive part achieve?**

:class:`GoodputAccountant` partitions every second of an attempt's wall
clock into one of :data:`CATEGORIES`:

- ``productive_step``   — a committed optimizer step advancing the run;
- ``ckpt_snapshot``     — device→host state copy on the step path;
- ``ckpt_write_stall``  — the step path *blocked* on checkpoint I/O
  (sync-write managers, ``wait()`` drains; async writes cost nothing here);
- ``rollback_restore``  — guardrails restoring a last-good snapshot;
- ``rollback_replay``   — steps re-executed after a rollback rewound the
  step counter (real compute, zero net progress);
- ``data_stall``        — host batch staging + device placement
  (``put_batch``);
- ``recompile``         — a step whose dispatch traced/compiled (the first
  step, and every retrace the detector flags);
- ``init_restore``      — process start → first step: imports, engine
  construction, ``auto_resume`` checkpoint restore;
- ``idle_other``        — everything else (the residual: user code between
  steps, eval batches, logging).

The accounting is **mark-based**: call sites mark phase *boundaries* and
the accountant attributes the elapsed interval, so the categories partition
the timeline exactly by construction (no double counting, no gaps while
the process lives). It performs **zero device syncs and zero host fetches**
— every primitive is ``time.monotonic()`` — so even the *enabled* path
rides free on an async-dispatch runtime; host wall-clock between marks
converges to device time in steady state because the dispatch queue is
bounded (the same argument ``ThroughputTimer(sync=False)`` rests on).
Disabled (``telemetry.goodput: false`` or telemetry off) the engine holds
``goodput = None`` and every hook is one attribute check.

MFU: the engine feeds the accountant the compiled step's XLA
``cost_analysis`` FLOPs once per compiled step function (no per-step
re-analysis); ``engine/mfu`` is then FLOPs / (mean measured step time ×
chips × per-dtype peak) through the shared
:func:`deepspeed_tpu.profiling.flops_profiler.mfu` helper — the same math
``bench.py`` reports.

Run manifest: each attempt persists ``run_manifest.aNNNN.<host>.json``
under the telemetry dir — run id, attempt index (``DSTPU_RESUME_ATTEMPT``),
host, start/end wall+monotonic timestamps, exit rc, restart cause, config
hash, the category totals and MFU. The engine writes it on start, refreshes
it at every metrics flush (so a SIGTERM keeps a recent snapshot) and
finalises it at exit; :func:`finalize_attempt_manifests` lets the
supervisor/launcher stamp the child's exit rc and restart cause after a
death the engine never saw coming. ``tools/goodput_report.py`` merges the
manifests + ``metrics.jsonl`` of every attempt into one run-level report,
turning inter-attempt downtime (backoff, re-init, restore, replay) from
invisible into attributed.
"""

import hashlib
import json
import os
import socket
import threading
import time
from typing import Any, Dict, Optional, Tuple

RUN_ID_ENV = "DSTPU_RUN_ID"
# Overrides the hostname in every per-host telemetry artifact (run
# manifests, host-scoped metrics/trace filenames, fleet rows) — ONE
# convention across goodput and the fleet layer.
TELEMETRY_HOST_ENV = "DSTPU_TELEMETRY_HOST"
# Stamped by the supervisor/launcher at child spawn so the accountant can
# attribute interpreter start-up (imports dwarf engine construction) to
# init_restore instead of leaving it invisible.
ATTEMPT_START_WALL_ENV = "DSTPU_ATTEMPT_START_WALL"

MANIFEST_PREFIX = "run_manifest."
MANIFEST_FORMAT = 1

CATEGORIES = (
    "productive_step",
    "ckpt_snapshot",
    "ckpt_write_stall",
    "rollback_restore",
    "rollback_replay",
    "data_stall",
    "recompile",
    "init_restore",
    # In-process elastic world change (resilience/elastic.py): drain +
    # state gather + mesh/step-fn rebuild + reshard. Mark-based like every
    # other category (the coordinator wraps the whole reshard in ONE
    # measure), so the exact-partition invariant holds and reshard time
    # never leaks into idle_other.
    "elastic_reshard",
    # Startup config search (autotuning/): candidate pruning + in-process
    # measured trials + winner adoption. The tuner quiesces the engine's
    # goodput hooks for the search window and books the WHOLE window with
    # one mark, so trial steps can never masquerade as productive_step
    # and the exact-partition invariant holds.
    "autotune_search",
    "idle_other",
)

_STEP_CATEGORIES = ("productive_step", "rollback_replay")

# Every metric tag this module can emit — the doc-drift lint
# (tests/test_doc_lint.py) checks these against docs/OBSERVABILITY.md in
# BOTH directions.
GOODPUT_METRIC_TAGS = frozenset(
    {f"goodput/{c}_sec" for c in CATEGORIES}
    | {"goodput/wall_sec", "goodput/goodput_frac",
       "goodput/steps_committed", "goodput/pipe_bubble_sec",
       # Sub-attributions riding INSIDE productive_step (aux gauges, not
       # partition categories): modeled exposed-collective time of the
       # hierarchical grad sync, and fleet-level time lost waiting on a
       # straggler host (telemetry/fleet.py).
       "goodput/exposed_comm_sec", "goodput/straggler_sec", "engine/mfu"})


def config_hash(param_dict: Optional[Dict[str, Any]]) -> str:
    """Stable short hash of a raw config dict (ties manifests of the same
    logical run together across attempts)."""
    blob = json.dumps(param_dict or {}, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def default_run_id(run_dir: Optional[str]) -> str:
    """``DSTPU_RUN_ID`` when set; else derived from the run dir path so
    every attempt of a supervised run (same dir) agrees without
    coordination."""
    rid = os.environ.get(RUN_ID_ENV)
    if rid:
        return rid
    basis = os.path.abspath(run_dir) if run_dir else "unknown"
    return hashlib.sha1(basis.encode()).hexdigest()[:12]


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> str:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


class _Measure:
    """Context manager carving a closed interval out of the timeline: the
    measured span is attributed to ``category`` and the mark cursor jumps
    to the exit time, so the enclosing phase's next mark never re-counts
    it. Time pending *before* entry stays pending for the enclosing
    phase's own mark."""

    __slots__ = ("_acc", "_category", "_t0")

    def __init__(self, acc: "GoodputAccountant", category: str):
        self._acc = acc
        self._category = category
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = self._acc._clock()
        return self

    def __exit__(self, *exc):
        now = self._acc._clock()
        dur = now - self._t0
        with self._acc._lock:
            self._acc._attribute_locked(self._category, dur)
            # Shift the cursor forward by exactly the carved duration:
            # time pending before entry stays pending (the enclosing
            # phase's next mark claims it); a mark that ran inside the
            # measured region clamps at `now` (never double-claimed).
            self._acc._last = min(now, self._acc._last + dur)
        return False


class GoodputAccountant:
    """Wall-clock attribution + MFU for ONE attempt of one run.

    Thread-safe (the checkpoint writer may attribute ``ckpt_write_stall``
    from ``wait()`` off the step thread). No jax imports, no device work.
    """

    def __init__(self,
                 registry=None,
                 run_dir: Optional[str] = None,
                 run_id: Optional[str] = None,
                 attempt: Optional[int] = None,
                 host: Optional[str] = None,
                 cfg_hash: str = "",
                 clock=time.monotonic,
                 wall_clock=time.time,
                 env: Optional[Dict[str, str]] = None):
        env = os.environ if env is None else env
        self.registry = registry
        self.run_dir = run_dir
        self.run_id = run_id if run_id is not None else default_run_id(run_dir)
        if attempt is None:
            from deepspeed_tpu.resilience.fault import RESUME_ATTEMPT_ENV
            attempt = int(env.get(RESUME_ATTEMPT_ENV, "0") or 0)
        self.attempt = int(attempt)
        self.host = (host or env.get(TELEMETRY_HOST_ENV)
                     or socket.gethostname().replace(os.sep, "_"))
        self.cfg_hash = cfg_hash
        self.pid = os.getpid()
        self._clock = clock
        self._wall = wall_clock
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
        self._aux: Dict[str, float] = {}
        # Live-elasticity bookkeeping (resilience/elastic.py): world-change
        # timeline entries + eviction decisions, persisted in the run
        # manifest so goodput_report/fleet_report can render them.
        self._elastic: list = []
        self._evictions: list = []
        now_mono, now_wall = clock(), wall_clock()
        # Interpreter start-up happened before this object existed; when the
        # spawner stamped the start wall time, backdate the attempt to it
        # and book the lag as init_restore.
        lag = 0.0
        spawn = env.get(ATTEMPT_START_WALL_ENV)
        if spawn:
            try:
                lag = max(0.0, now_wall - float(spawn))
            except ValueError:
                lag = 0.0
        self.start_wall = now_wall - lag
        self.start_monotonic = now_mono - lag
        self._totals["init_restore"] += lag
        self._last = now_mono
        self._saw_step = False
        self._first_step: Optional[int] = None
        self._steps_committed = 0
        self._step_time_sum = 0.0
        self._step_count = 0
        self._last_step_dt: Optional[float] = None
        # MFU inputs: set once per compiled step fn by the engine.
        self._flops_per_step: Optional[float] = None
        self._bytes_per_step: Optional[float] = None
        self._n_chips = 1
        self._peak_tflops: Optional[float] = None
        self._flops_attempted = False
        self._finalized = False
        if run_dir:
            self.write_manifest()

    # -- attribution ----------------------------------------------------
    def _attribute_locked(self, category: str, seconds: float) -> None:
        if seconds > 0.0:
            self._totals[category] = self._totals.get(category, 0.0) + seconds

    def attribute(self, category: str, seconds: float) -> None:
        """Add ``seconds`` to a category WITHOUT moving the mark cursor
        (for time measured elsewhere). Prefer :meth:`mark`/:meth:`measure`
        — they keep the partition exact."""
        with self._lock:
            self._attribute_locked(category, seconds)

    def mark(self, category: str) -> float:
        """Attribute everything since the previous mark to ``category``
        and advance the cursor. Returns the attributed seconds."""
        now = self._clock()
        with self._lock:
            dt = now - self._last
            self._last = now
            self._attribute_locked(category, dt)
        return dt

    def measure(self, category: str) -> _Measure:
        """``with goodput.measure("init_restore"): ...`` — attribute a
        closed interval (see :class:`_Measure` for cursor semantics)."""
        return _Measure(self, category)

    def mark_gap(self) -> float:
        """The between-steps mark: init_restore until the first step has
        run, idle_other afterwards."""
        return self.mark("init_restore" if not self._saw_step
                         else "idle_other")

    def step_mark(self, category: str, committed_step: int) -> float:
        """End-of-step mark. ``category`` is one of productive_step /
        rollback_replay / recompile; productive and replay step durations
        feed the MFU step-time estimate (recompile steps are
        compile-inflated and excluded)."""
        dt = self.mark(category)
        with self._lock:
            self._saw_step = True
            if self._first_step is None:
                self._first_step = int(committed_step)
            self._steps_committed = max(self._steps_committed,
                                        int(committed_step))
            if category in _STEP_CATEGORIES:
                self._step_time_sum += dt
                self._step_count += 1
                self._last_step_dt = dt
        return dt

    def note_world_change(self, entry: Dict[str, Any]) -> None:
        """Append one world-change timeline entry (epoch, step, world,
        cause, reshard seconds) — rendered by tools/goodput_report.py as
        the per-attempt world-change timeline row."""
        with self._lock:
            self._elastic.append(dict(entry))

    def note_eviction(self, entry: Dict[str, Any]) -> None:
        """Record one straggler-eviction decision (host, z-score,
        projected gain, verdict) for the run manifest —
        tools/fleet_report.py renders these beside the straggler table."""
        with self._lock:
            self._evictions.append(dict(entry))

    def reset_flops(self) -> None:
        """Re-arm the once-per-compiled-step cost analysis — called after
        an in-process elastic reshard, whose rebuilt step function has a
        different FLOPs/chips profile (engine/mfu must not keep the old
        world's denominator)."""
        with self._lock:
            self._flops_attempted = False
            self._flops_per_step = None
            self._bytes_per_step = None

    def note_aux(self, name: str, seconds: float) -> None:
        """Cumulative auxiliary gauge (``goodput/<name>``) that is NOT part
        of the wall-clock partition — e.g. the pipeline engine's analytic
        bubble time, which overlaps productive_step."""
        with self._lock:
            self._aux[name] = self._aux.get(name, 0.0) + float(seconds)

    # -- MFU ------------------------------------------------------------
    @property
    def wants_flops(self) -> bool:
        return not self._flops_attempted

    def flops_failed(self) -> None:
        self._flops_attempted = True

    def set_flops(self, flops_per_step: float, n_chips: int = 1,
                  peak_tflops_per_chip: Optional[float] = None,
                  bytes_per_step: Optional[float] = None) -> None:
        """FLOPs (and, when known, bytes accessed) of ONE compiled global
        step (XLA cost_analysis), the chip count it ran across, and the
        per-chip peak — set once per compiled step function by the
        engine. ``bytes_per_step`` feeds the device-time observatory's
        roofline classification (telemetry/devicetime.py)."""
        self._flops_attempted = True
        if flops_per_step and flops_per_step > 0:
            self._flops_per_step = float(flops_per_step)
            self._n_chips = max(int(n_chips), 1)
            self._peak_tflops = peak_tflops_per_chip
            if bytes_per_step and bytes_per_step > 0:
                self._bytes_per_step = float(bytes_per_step)

    def flops_info(self) -> Optional[Dict[str, Any]]:
        """The cost-analysis record :meth:`set_flops` captured (None until
        the engine has fed it): flops / bytes accessed per step, chip
        count, per-chip peak — the device-time observatory's roofline and
        measured-MFU inputs."""
        if self._flops_per_step is None:
            return None
        return {"flops_per_step": self._flops_per_step,
                "bytes_per_step": self._bytes_per_step,
                "n_chips": self._n_chips,
                "peak_tflops_per_chip": self._peak_tflops}

    def mean_step_time(self) -> Optional[float]:
        with self._lock:
            if self._step_count == 0:
                return None
            return self._step_time_sum / self._step_count

    def last_step_time(self) -> Optional[float]:
        """Duration of the most recent measured (productive/replay) step —
        the denominator of the per-step ``comm/exposed_frac`` gauge."""
        with self._lock:
            return self._last_step_dt

    def step_time_stats(self) -> Tuple[float, int]:
        """(cumulative measured step seconds, count) — the fleet
        aggregator differences these across flushes."""
        with self._lock:
            return self._step_time_sum, self._step_count

    def aux_totals(self) -> Dict[str, float]:
        """Copy of the auxiliary (non-partition) gauge totals."""
        with self._lock:
            return dict(self._aux)

    def mfu(self) -> Optional[float]:
        """Model FLOPs utilisation of the measured (productive+replay)
        steps, through the shared flops_profiler helper — one source of
        truth with bench.py."""
        dt = self.mean_step_time()
        if self._flops_per_step is None or dt is None or dt <= 0:
            return None
        from deepspeed_tpu.profiling.flops_profiler import mfu as _mfu
        return _mfu(self._flops_per_step, dt, n_chips=self._n_chips,
                    peak_tflops_per_chip=self._peak_tflops)

    # -- readout / emission --------------------------------------------
    def totals(self) -> Dict[str, float]:
        """Category seconds + ``wall_sec``. The explicit categories plus
        the idle_other residual sum to wall_sec exactly (the un-marked
        tail since the last mark rides in idle_other)."""
        now = self._clock()
        with self._lock:
            out = dict(self._totals)
            pending = max(0.0, now - self._last)
            gap_cat = "init_restore" if not self._saw_step else "idle_other"
            out[gap_cat] += pending
            out["wall_sec"] = now - self.start_monotonic
        return out

    def emit(self, step: int) -> None:
        """Emit cumulative ``goodput/*`` gauges (attempt-tagged, so merged
        multi-attempt ``metrics.jsonl`` files stay attributable) and
        ``engine/mfu`` when the FLOPs are known."""
        reg = self.registry
        if reg is None:
            return
        t = self.totals()
        wall = t.pop("wall_sec")
        for cat in CATEGORIES:
            reg.gauge(f"goodput/{cat}_sec").set(t[cat], step=step,
                                                attempt=self.attempt)
        reg.gauge("goodput/wall_sec").set(wall, step=step,
                                          attempt=self.attempt)
        reg.gauge("goodput/goodput_frac").set(
            (t["productive_step"] / wall) if wall > 0 else 0.0,
            step=step, attempt=self.attempt)
        reg.gauge("goodput/steps_committed").set(
            self._steps_committed, step=step, attempt=self.attempt)
        with self._lock:
            aux = dict(self._aux)
        for name, sec in aux.items():
            reg.gauge(f"goodput/{name}").set(sec, step=step,
                                             attempt=self.attempt)
        m = self.mfu()
        if m is not None:
            reg.gauge("engine/mfu").set(m, step=step, attempt=self.attempt)

    # -- manifest -------------------------------------------------------
    def manifest_path(self) -> Optional[str]:
        if not self.run_dir:
            return None
        return os.path.join(self.run_dir,
                            f"{MANIFEST_PREFIX}a{self.attempt:04d}."
                            f"{self.host}.json")

    def manifest(self, exit_rc: Optional[int] = None,
                 restart_cause: Optional[str] = None,
                 final: bool = False) -> Dict[str, Any]:
        t = self.totals()
        wall = t.pop("wall_sec")
        return {
            "format": MANIFEST_FORMAT,
            "run_id": self.run_id,
            "attempt": self.attempt,
            "host": self.host,
            "pid": self.pid,
            "config_hash": self.cfg_hash,
            "start_wall": self.start_wall,
            "start_monotonic": self.start_monotonic,
            "end_wall": self._wall() if final else None,
            "end_monotonic": self._clock() if final else None,
            "exit_rc": exit_rc,
            "restart_cause": restart_cause,
            "wall_sec": wall,
            "categories": t,
            "aux": self.aux_totals(),
            "first_step": self._first_step,
            "steps_committed": self._steps_committed,
            "mean_step_time_sec": self.mean_step_time(),
            "mfu": self.mfu(),
            "n_chips": self._n_chips,
            "flops_per_step": self._flops_per_step,
            "elastic": [dict(e) for e in self._elastic],
            "eviction_decisions": [dict(e) for e in self._evictions],
        }

    def write_manifest(self, exit_rc: Optional[int] = None,
                       restart_cause: Optional[str] = None,
                       final: bool = False) -> Optional[str]:
        """Atomic manifest (re)write. Called on construction, at every
        metrics flush (crash-freshness) and from :meth:`finalize`."""
        path = self.manifest_path()
        if path is None:
            return None
        try:
            return _atomic_write_json(
                path, self.manifest(exit_rc=exit_rc,
                                    restart_cause=restart_cause, final=final))
        except OSError as e:  # a full disk must never kill the run
            from deepspeed_tpu.utils.logging import logger
            logger.warning("goodput manifest write failed: %s", e)
            return None

    def finalize(self, exit_rc: Optional[int] = None) -> None:
        """End-of-attempt manifest (idempotent; wired to atexit by
        build_goodput). The engine usually cannot know its own exit rc —
        the supervisor stamps it post-mortem via
        :func:`finalize_attempt_manifests`."""
        if self._finalized:
            return
        self._finalized = True
        self.write_manifest(exit_rc=exit_rc, final=True)


def build_goodput(tcfg, telemetry=None, cfg_hash: str = "",
                  register_atexit: bool = True) -> Optional[GoodputAccountant]:
    """``None`` unless the telemetry block is enabled AND its ``goodput``
    flag is on — the engine's hooks gate on ``is None`` (the zero-cost
    contract, same shape as guardrails)."""
    if tcfg is None or not tcfg.enabled or not getattr(tcfg, "goodput", False):
        return None
    registry = telemetry.registry if telemetry is not None else None
    acc = GoodputAccountant(registry=registry, run_dir=tcfg.dir,
                            cfg_hash=cfg_hash)
    if register_atexit:
        import atexit
        atexit.register(acc.finalize)
    return acc


# ---------------------------------------------------------------------------
# Supervisor-side manifest finalisation
# ---------------------------------------------------------------------------

def classify_exit(rc: int, immediate_restart_rcs=(), oom_rcs=(),
                  warned_rcs=()) -> str:
    """Human-readable restart cause from a child exit code."""
    if rc == 0:
        return "clean"
    if rc in set(oom_rcs or ()):
        # The memory observatory's distinct rc (telemetry/memory.py):
        # deterministic OOM — a config bug, not a preemption.
        return "oom"
    if rc in set(immediate_restart_rcs or ()):
        return "watchdog"
    if rc in set(warned_rcs or ()):
        # The live-elasticity coordinator's distinct rc (resilience/
        # elastic.py): the grace-window SIGTERM arrived and WAS handled —
        # state drained to disk — but no surviving capacity fit a valid
        # elastic world, so the process exited deliberately. Distinct
        # from "preemption" (rc -15: the warning was never caught).
        return "preemption_warned"
    if rc < 0 or rc in (128 + 15, 128 + 9):  # signal deaths (Popen: -sig)
        return "preemption"
    return "crash"


def stamp_eviction_decisions(run_dir: str, attempt: int,
                             decisions: list) -> int:
    """Supervisor-side: stamp straggler-eviction decisions (host,
    z-score, projected gain, verdict) onto every host manifest of one
    attempt — the post-mortem record tools/fleet_report.py renders. The
    child's own in-process decisions (GoodputAccountant.note_eviction)
    already live in the manifest; the supervisor's entries merge after
    them, deduplicated by (host, step). Returns manifests touched."""
    if not decisions:
        return 0
    prefix = f"{MANIFEST_PREFIX}a{attempt:04d}."
    touched = 0
    try:
        entries = sorted(os.listdir(run_dir)) if os.path.isdir(run_dir) else []
    except OSError:
        entries = []
    for name in entries:
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        path = os.path.join(run_dir, name)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        have = doc.get("eviction_decisions") or []
        seen = {(d.get("host"), d.get("step")) for d in have}
        for d in decisions:
            if (d.get("host"), d.get("step")) not in seen:
                have.append(dict(d))
        doc["eviction_decisions"] = have
        _atomic_write_json(path, doc)
        touched += 1
    return touched


def finalize_attempt_manifests(run_dir: str, attempt: int, rc: int,
                               cause: str, start_wall: float,
                               end_wall: float) -> int:
    """Stamp exit rc / restart cause / end time onto every host manifest
    of one attempt (the child may have died without running atexit). A
    child that died before engine construction left no manifest at all —
    write a stub so the attempt still appears in the report. Returns the
    number of manifests touched."""
    prefix = f"{MANIFEST_PREFIX}a{attempt:04d}."
    touched = 0
    try:
        entries = sorted(os.listdir(run_dir)) if os.path.isdir(run_dir) else []
    except OSError:
        entries = []
    for name in entries:
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        path = os.path.join(run_dir, name)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        doc["exit_rc"] = rc
        doc["restart_cause"] = cause
        if doc.get("end_wall") is None:
            doc["end_wall"] = end_wall
            # Best effort: the child's monotonic clock is gone; extend
            # wall_sec to the supervisor-observed lifetime so the report's
            # unattributed tail (death after the last refresh) is visible.
            doc["wall_sec"] = max(float(doc.get("wall_sec") or 0.0),
                                  end_wall - float(doc.get("start_wall")
                                                   or start_wall))
        _atomic_write_json(path, doc)
        touched += 1
    if touched == 0 and run_dir:
        _atomic_write_json(
            os.path.join(run_dir, f"{prefix}unknown.json"),
            {"format": MANIFEST_FORMAT, "run_id": default_run_id(run_dir),
             "attempt": int(attempt), "host": "unknown", "pid": None,
             "config_hash": "", "start_wall": start_wall,
             "start_monotonic": None, "end_wall": end_wall,
             "end_monotonic": None, "exit_rc": rc, "restart_cause": cause,
             "wall_sec": max(0.0, end_wall - start_wall),
             "categories": {}, "steps_committed": 0,
             "mean_step_time_sec": None, "mfu": None, "n_chips": None,
             "flops_per_step": None})
        touched = 1
    return touched
