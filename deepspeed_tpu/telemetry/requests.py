"""Request observatory — per-request SLO accounting for the serve engine.

The serving analogue of the goodput observatory (``telemetry/goodput.py``):
where goodput partitions a TRAINING run's wall clock into an exact category
set, the :class:`RequestAccountant` partitions every serving REQUEST's
lifetime — arrival to finish — into

    queue_wait / prefill / decode_active / preempted_requeue /
    spec_overhead / finish_other

via monotonic marks the ServeEngine and Scheduler place at submission,
admission, prefill completion, every decode step the row is active,
preemption/requeue, and finish. Categories sum to the measured lifetime by
construction (each mark attributes ``now - cursor`` and advances the
cursor), so "where did this request's latency go" is an exact statement,
not a sampled one.

Alongside the per-request ledger, the accountant keeps an **engine-side
serving-time partition** (prefill / decode / scheduler_admission /
host_idle / compile) over the engine's own wall clock — the per-replica
"what fraction of serving time produced tokens" number the ROADMAP's
scale-out router ranks replicas with.

Everything here is host-side ``time.monotonic`` arithmetic: no device
syncs, no extra ``block_until_ready``. The established zero-overhead
off-contract applies — ``build_requests`` returns ``None`` unless
``telemetry.requests.enabled``, every engine hook gates on ``is None``,
and with the accountant off the engine's emitted tag set is byte-identical
to today's.

Outputs:

- registry metrics under ``requests/`` (cumulative per-category seconds,
  the engine partition, TPOT / e2e / queue-wait histograms, prefix-cache
  token savings, preemption counts) — every tag in
  :data:`REQUEST_METRIC_TAGS`, pinned against docs/OBSERVABILITY.md by
  tests/test_doc_lint.py;
- one JSONL record per finished request in host-scoped
  ``requests.<host>.jsonl`` (single-host: ``requests.jsonl``), merged
  across hosts by ``tools/slo_report.py``;
- per-request async tracks in the Perfetto timeline (StepTracer ``b``/"e"
  events) so a request's queue -> prefill -> decode -> preempt -> resume
  arc is visible across the engine's step spans.
"""

import json
import os
import time
from collections import deque
from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger

# The exact partition of one request's lifetime. ``finish_other`` absorbs
# host-side residue (dispatch bookkeeping, the slice of a step a row spent
# waiting on batch-mates, the final finish mark) so the sum is always the
# measured lifetime — nothing is dropped on the floor.
REQUEST_CATEGORIES = (
    "queue_wait",          # submitted, waiting for a slot + blocks
    "prefill",             # admission -> first token (cold or warm tail)
    "decode_active",       # decode steps producing accepted tokens
    "preempted_requeue",   # evicted for KV pressure, waiting to re-admit
    "spec_overhead",       # speculative decode time on rejected drafts
    "finish_other",        # host residue: dispatch, batch skew, finish
)

# The engine-side serving-time partition (one cursor over the engine's own
# wall clock, marked inside ``ServeEngine.step``).
ENGINE_CATEGORIES = (
    "prefill",             # prefill dispatch + first-token fetch
    "decode",              # decode/spec dispatch + token fetch
    "scheduler_admission", # host scheduling: admit, growth, preemption
    "host_idle",           # between steps (caller think time, idle loop)
    "compile",             # steps that grew a jit cache (first traces)
)

# Every metric tag this module can emit — pinned against
# docs/OBSERVABILITY.md in both directions by tests/test_doc_lint.py.
REQUEST_METRIC_TAGS = frozenset(
    {f"requests/{c}_sec" for c in REQUEST_CATEGORIES}
    | {f"requests/engine_{c}_sec" for c in ENGINE_CATEGORIES}
    | {
        "requests/engine_wall_sec",
        "requests/tpot_ms",
        "requests/e2e_ms",
        "requests/queue_wait_ms",
        "requests/prefix_tokens_saved",
        "requests/preemptions",
    })

RECORD_FORMAT = 1


class _ReqState:
    """Per-request mark cursor + partition ledger."""

    __slots__ = ("rid", "last", "totals", "phase", "requeued", "span",
                 "last_token", "last_generated", "tpot_sum_ms", "tpot_n",
                 "prefix_tokens")

    def __init__(self, rid: int, arrival: float):
        self.rid = rid
        self.last = arrival            # the mark cursor (monotonic)
        self.totals = {c: 0.0 for c in REQUEST_CATEGORIES}
        self.phase = "queue"
        self.requeued = False
        self.span: Optional[str] = None   # open async-track span name
        self.last_token: Optional[float] = None
        self.last_generated = 0
        self.tpot_sum_ms = 0.0
        self.tpot_n = 0
        self.prefix_tokens = 0


class RequestAccountant:
    """Mark-based per-request SLO ledger + engine serving-time partition.

    The engine owns exactly one accountant (or ``None``); the scheduler
    holds a back-reference so admission/preemption mark without the
    engine relaying. All hooks are pure host float arithmetic on
    ``time.monotonic`` — no device work, ever.
    """

    def __init__(self, registry=None, tracer=None,
                 run_dir: Optional[str] = None,
                 file: str = "requests.jsonl",
                 window_sec: float = 10.0,
                 host: Optional[str] = None):
        from deepspeed_tpu.telemetry.fleet import (default_host,
                                                   host_scoped_path,
                                                   telemetry_host_component)
        self.registry = registry
        self.tracer = tracer if (tracer is not None
                                 and getattr(tracer, "enabled", False)) \
            else None
        self.window_sec = float(window_sec)
        self.host = host if host is not None else default_host()
        # monotonic -> wall-clock anchor, persisted per record so
        # slo_report can order records across hosts.
        self._wall_offset = time.time() - time.monotonic()
        self.spec_k = 0                # engine sets when spec decode is on
        self._states: Dict[int, _ReqState] = {}
        # Cumulative category seconds over FINISHED requests (the
        # ``requests/<cat>_sec`` gauges).
        self._cat_totals = {c: 0.0 for c in REQUEST_CATEGORIES}
        now = time.monotonic()
        self._eng_totals = {c: 0.0 for c in ENGINE_CATEGORIES}
        self._eng_start = now
        self._eng_last = now
        # Rolling decode-throughput window: (t, tokens, decode_sec).
        self._window: deque = deque()
        self.completed = 0
        self.path: Optional[str] = None
        self._fh = None
        self._write_failed = False
        if run_dir:
            part = telemetry_host_component()
            self.path = os.path.join(run_dir,
                                     host_scoped_path(file, part))

    # -- request lifecycle marks ---------------------------------------
    def _mark(self, st: _ReqState, cat: str, now: float) -> None:
        st.totals[cat] += now - st.last
        st.last = now

    def _trace_to(self, st: _ReqState, name: Optional[str]) -> None:
        tr = self.tracer
        if tr is None:
            return
        if st.span is not None:
            tr.async_end(st.span, st.rid)
        if name is not None:
            tr.async_begin(name, st.rid, rid=st.rid)
        st.span = name

    def on_submit(self, request) -> None:
        """The request entered the waiting queue (cursor = its arrival)."""
        st = _ReqState(request.rid, request.arrival)
        self._states[request.rid] = st
        self._trace_to(st, "req/queue")

    def on_admit(self, seq) -> None:
        """Scheduler granted a slot + blocks; prefill is next. Time since
        the cursor is queue wait — or requeue wait after a preemption."""
        st = self._states.get(seq.request.rid)
        if st is None:
            return
        now = time.monotonic()
        self._mark(st, "preempted_requeue" if st.requeued else "queue_wait",
                   now)
        st.requeued = False
        # The winning admission's adopted head (a warm restart may adopt
        # more than the cold first admission did).
        st.prefix_tokens = seq.shared_len
        st.phase = "prefill"
        self._trace_to(st, "req/prefill")

    def on_prefilled(self, seq) -> None:
        """Prefill (cold or warm-tail) produced the first token."""
        st = self._states.get(seq.request.rid)
        if st is None:
            return
        now = time.monotonic()
        self._mark(st, "prefill", now)
        # TPOT baseline: inter-token intervals start at the first token.
        st.last_token = now
        st.last_generated = seq.generated
        st.phase = "decode"
        self._trace_to(st, "req/decode")

    def _useful_frac(self, appended: int) -> float:
        """Fraction of a decode slice that produced accepted tokens: a
        speculative round runs k+1 positions per row regardless of how
        many survive the accept rule; non-speculative decode is all
        useful."""
        if not self.spec_k:
            return 1.0
        return min(1.0, appended / float(self.spec_k + 1))

    def _observe_tpot(self, st: _ReqState, seq, now: float,
                      step: int) -> int:
        """Attribute inter-token intervals for tokens appended since the
        last mark; returns how many were appended."""
        appended = seq.generated - st.last_generated
        if appended > 0 and st.last_token is not None:
            interval_ms = (now - st.last_token) / appended * 1e3
            st.tpot_sum_ms += interval_ms * appended
            st.tpot_n += appended
            if self.registry is not None:
                hist = self.registry.histogram("requests/tpot_ms")
                for _ in range(appended):
                    hist.observe(interval_ms, step=step)
        if appended > 0:
            st.last_token = now
        st.last_generated = seq.generated
        return appended

    def on_decode_step(self, seqs, dt_decode: float, step: int) -> None:
        """One decode (or speculative) step advanced ``seqs`` (the rows
        still running after the step — finished rows went through
        :meth:`on_finish` already). Per row: the slice since its cursor
        splits into host residue (anything beyond the measured decode
        dispatch) and decode time, the latter apportioned between
        ``decode_active`` and ``spec_overhead`` by the row's accepted
        fraction."""
        now = time.monotonic()
        for seq in seqs:
            st = self._states.get(seq.request.rid)
            if st is None:
                continue
            appended = self._observe_tpot(st, seq, now, step)
            elapsed = now - st.last
            other = max(0.0, elapsed - dt_decode)
            dec = elapsed - other
            frac = self._useful_frac(appended)
            st.totals["decode_active"] += dec * frac
            st.totals["spec_overhead"] += dec * (1.0 - frac)
            st.totals["finish_other"] += other
            st.last = now

    def on_preempt(self, seq) -> None:
        """Evicted for KV pressure: the slice since the cursor is host
        residue; the wait until re-admission becomes
        ``preempted_requeue`` (marked at the next :meth:`on_admit`)."""
        st = self._states.get(seq.request.rid)
        if st is None:
            return
        now = time.monotonic()
        self._mark(st, "finish_other", now)
        st.requeued = True
        st.last_token = None           # restart resets the TPOT baseline
        st.phase = "queue"
        self._trace_to(st, "req/preempted")

    def on_finish(self, seq, step: int,
                  status: str = "finished") -> Optional[Dict[str, Any]]:
        """Close the ledger: final TPOT slice, tail mark, aggregate into
        the cumulative gauges/counters, persist the JSONL record.
        Returns the SLO dict the engine nests into ``results[rid]``.
        ``status`` is the terminal status (``finished`` or a resilience
        terminal: ``deadline_expired`` / ``cancelled`` / ``aborted``) —
        an admitted request reaches this hook whichever way it ends."""
        st = self._states.pop(seq.request.rid, None)
        if st is None:
            return None
        req = seq.request
        now = time.monotonic()
        appended = self._observe_tpot(st, seq, now, step)
        elapsed = now - st.last
        if st.phase == "decode" and appended > 0:
            # Finished mid-decode: the tail slice is that step's decode
            # work for this row (bounded by one step).
            frac = self._useful_frac(appended)
            st.totals["decode_active"] += elapsed * frac
            st.totals["spec_overhead"] += elapsed * (1.0 - frac)
        else:
            st.totals["finish_other"] += elapsed
        st.last = now
        lifetime = now - req.arrival
        self._trace_to(st, None)

        for c in REQUEST_CATEGORIES:
            self._cat_totals[c] += st.totals[c]
        self.completed += 1
        reg = self.registry
        if reg is not None:
            reg.histogram("requests/e2e_ms").observe(lifetime * 1e3,
                                                     step=step)
            reg.histogram("requests/queue_wait_ms").observe(
                st.totals["queue_wait"] * 1e3, step=step)
            if req.preempted_count:
                reg.counter("requests/preemptions").inc(
                    req.preempted_count, step=step)
            if st.prefix_tokens:
                reg.counter("requests/prefix_tokens_saved").inc(
                    st.prefix_tokens, step=step)

        slo = {
            "lifetime_sec": lifetime,
            "tpot_mean_ms": (st.tpot_sum_ms / st.tpot_n
                             if st.tpot_n else None),
            "tpot_obs": st.tpot_n,
            "prefix_tokens_saved": st.prefix_tokens,
            "categories": {c: st.totals[c] for c in REQUEST_CATEGORIES},
        }
        rec = {
            "format": RECORD_FORMAT,
            "rid": req.rid,
            "host": self.host,
            "status": status,
            "admitted": True,
            "prompt_len": len(req.prompt),
            "new_tokens": seq.generated,
            "finish_step": step,
            "arrival_unix": req.arrival + self._wall_offset,
            "e2e_ms": lifetime * 1e3,
            "ttft_ms": ((req.first_token_time - req.arrival) * 1e3
                        if req.first_token_time is not None else None),
            "queue_wait_ms": st.totals["queue_wait"] * 1e3,
            "preempted_count": req.preempted_count,
            **slo,
        }
        self._write(rec)
        return slo

    def on_drop(self, request, status: str, step: int) -> None:
        """A request left the system WITHOUT ever being admitted — shed
        at submit time, cancelled/expired in the queue, or torn down with
        the engine. It still gets a terminal JSONL record (every
        submitted rid reaches one), but contributes NO registry metrics:
        the ``requests/`` tag set must stay byte-identical whether or not
        resilience is on, and never-admitted requests have no latency to
        partition. Shed requests never pass :meth:`on_submit`, so a
        missing state is expected."""
        st = self._states.pop(request.rid, None)
        now = time.monotonic()
        if st is not None:
            self._mark(st, "preempted_requeue" if st.requeued
                       else "queue_wait", now)
            self._trace_to(st, None)
        queue_wait = (st.totals["queue_wait"] if st is not None
                      else 0.0)
        rec = {
            "format": RECORD_FORMAT,
            "rid": request.rid,
            "host": self.host,
            "status": status,
            "admitted": False,
            "prompt_len": len(request.prompt),
            "new_tokens": 0,
            "finish_step": step,
            "arrival_unix": request.arrival + self._wall_offset,
            "e2e_ms": (now - request.arrival) * 1e3,
            "ttft_ms": None,
            "queue_wait_ms": queue_wait * 1e3,
            "preempted_count": request.preempted_count,
        }
        self._write(rec)

    # -- engine serving-time partition ---------------------------------
    def engine_mark(self, cat: str) -> None:
        """Attribute the engine wall clock since the last mark to one
        ``ENGINE_CATEGORIES`` bucket and advance the engine cursor."""
        now = time.monotonic()
        self._eng_totals[cat] += now - self._eng_last
        self._eng_last = now

    # -- rolling decode throughput -------------------------------------
    def rolling_add(self, n_tokens: int, dt_decode: float) -> None:
        now = time.monotonic()
        self._window.append((now, int(n_tokens), float(dt_decode)))
        cutoff = now - self.window_sec
        while self._window and self._window[0][0] < cutoff:
            self._window.popleft()

    def rolling_rate(self) -> Optional[float]:
        """Token-weighted decode tokens/s over the window (None before
        any decode work lands in it)."""
        cutoff = time.monotonic() - self.window_sec
        while self._window and self._window[0][0] < cutoff:
            self._window.popleft()
        tok = sum(n for _, n, _ in self._window)
        sec = sum(s for _, _, s in self._window)
        return tok / sec if sec > 0 else None

    # -- emission / persistence ----------------------------------------
    def emit(self, step: int) -> None:
        """Per-step gauges: cumulative per-category seconds over finished
        requests plus the engine partition. Host floats only."""
        reg = self.registry
        if reg is None:
            return
        for c in REQUEST_CATEGORIES:
            reg.gauge(f"requests/{c}_sec").set(self._cat_totals[c],
                                               step=step)
        for c in ENGINE_CATEGORIES:
            reg.gauge(f"requests/engine_{c}_sec").set(
                self._eng_totals[c], step=step)
        reg.gauge("requests/engine_wall_sec").set(
            time.monotonic() - self._eng_start, step=step)

    def _write(self, rec: Dict[str, Any]) -> None:
        if self.path is None or self._write_failed:
            return
        try:
            if self._fh is None:
                parent = os.path.dirname(self.path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                self._fh = open(self.path, "a", encoding="utf-8")
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        except OSError as e:  # noqa: BLE001 — records must never take
            # down the serving loop they observe
            self._write_failed = True
            logger.warning("request records disabled (%s): %s",
                           self.path, e)

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


def build_requests(tcfg, telemetry=None) -> Optional[RequestAccountant]:
    """Factory honoring the zero-overhead off-contract: returns ``None``
    unless telemetry AND ``telemetry.requests`` are enabled, so every
    engine hook stays a single ``is None`` check."""
    if tcfg is None or not getattr(tcfg, "enabled", False):
        return None
    rcfg = getattr(tcfg, "requests", None)
    if rcfg is None or not rcfg.enabled:
        return None
    return RequestAccountant(
        registry=telemetry.registry if telemetry is not None else None,
        tracer=telemetry.tracer if telemetry is not None else None,
        run_dir=tcfg.dir,
        file=rcfg.file,
        window_sec=rcfg.window_sec)
