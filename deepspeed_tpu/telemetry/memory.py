"""Memory observatory — XLA attribution, capacity planning, OOM forensics.

The observability stack answers every *time* question (tracer spans,
goodput categories, fleet stragglers) but, until this module, no *memory*
question: the engine emitted raw HBM watermarks and nothing else, so an
OOM was a silent restart loop and every ZeRO-stage/offload/microbatch
choice was made blind. `telemetry.memory` (docs/OBSERVABILITY.md "Memory
observatory") adds three tiers:

- **Attribution** — once per compiled step function (cached per
  executable, re-armed by the recompile detector, like ``engine/mfu``)
  the observatory pulls ``compiled.memory_analysis()`` and emits the
  ``memory/xla_*_bytes`` gauges, plus a closed-form **model-state
  ledger** computed from the TrainState pytree + its ZeRO shardings:
  per-device bytes for master params / optimizer moments / grad
  accumulator / compute-dtype params as a function of ZeRO stage,
  offload tier and dtypes — the ZeRO "2+2+K" accounting made concrete
  (params@2 + grads@2 + K=12 for fp32 Adam master+m+v, divided by the
  shard count each stage earns). ``memory/hbm_headroom_bytes``
  (device ``bytes_limit`` − peak, min over local devices) rides the
  per-step HBM gauge fetch, with a ``memory/headroom_low`` trace
  instant below ``headroom_warn_frac``.
- **Capacity planner** — a pre-compile :func:`plan_capacity` projecting
  per-device bytes across ZeRO stages 0–3 × offload × microbatch from
  the same component totals, logged as a startup what-if table and
  persisted as ``memory_plan.json``; the engine warns loudly when the
  *chosen* config projects over HBM.
- **OOM forensics** — the engines wrap their compile/step dispatches in
  :meth:`MemoryObservatory.oom_guard`: a RESOURCE_EXHAUSTED escaping the
  step writes a memory crashdump (all-device ``memory_stats``,
  ``jax.profiler.device_memory_profile`` pprof when available, the
  ledger, the XLA analysis, the plan, a metrics tail) in the guardrails
  crashdump format and exits with a **distinct** rc
  (:data:`~deepspeed_tpu.config.constants.MEMORY_OOM_EXIT_CODE_DEFAULT`)
  that the resilience ``Supervisor`` classifies as ``cause=oom`` and
  does **not** restart — a deterministic OOM is a config bug, not a
  preemption, and a hot restart loop would just re-OOM until the budget
  is gone.

Zero-overhead contract (the PR 2/3/5/6 gate): ``telemetry.memory``
defaults off and :func:`build_memory_observatory` then returns ``None``
— the engine holds ``memory = None``, every hook is one attribute
check, the step jaxpr is bit-identical (the observatory never touches
the jitted step functions), and no extra device syncs or host fetches
happen (asserted in tests/test_memory_observatory.py). Enabled, the
only device-adjacent work is the one AOT lower+compile per step
function (booked as ``recompile`` goodput) and the per-step
``memory_stats`` read the HBM gauges already pay for.

jax is imported lazily so the module stays importable on jax-less
report hosts; ``tools/memory_report.py`` is stdlib-only by the same
rule as the other report tools.
"""

import contextlib
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.config.constants import MEMORY_OOM_EXIT_CODE_DEFAULT
from deepspeed_tpu.parallel.mesh import axes_size as mesh_axes_size
from deepspeed_tpu.telemetry.goodput import _atomic_write_json
from deepspeed_tpu.utils.logging import log_dist, logger

PLAN_FORMAT = 1
LEDGER_FORMAT = 1

HEADROOM_INSTANT = "memory/headroom_low"
OOM_INSTANT = "memory/oom"
OOM_COUNTER = "memory/oom_crashdumps"

# XLA memory_analysis fields surfaced as gauges (per-device bytes of the
# compiled step executable).
_XLA_FIELDS = ("argument", "output", "temp", "alias", "generated_code")

# Ledger components emitted as memory/ledger_<component>_bytes gauges.
# "secondary" is the ZeRO++ hpZ replica charge: with the intra-slice
# secondary partition (zero_optimization.zeropp.hpz) the master+moments
# stay dcn-replicated, and this gauge is the per-device HBM that replica
# costs vs the (dcn x data) global primary partition — an attribution
# overlay on bytes already counted in master/optimizer, NOT an extra
# allocation (so it is excluded from the per-device model-state sum).
_LEDGER_COMPONENTS = ("master", "optimizer", "grads", "compute_params",
                      "scalars", "device", "host", "secondary")

# Every metric tag this module can emit (gauges, the OOM counter and the
# trace-instant names) — pinned against docs/OBSERVABILITY.md in BOTH
# directions by tests/test_doc_lint.py, like GOODPUT/FLEET_METRIC_TAGS.
MEMORY_METRIC_TAGS = frozenset(
    {f"memory/xla_{f}_bytes" for f in _XLA_FIELDS}
    | {f"memory/ledger_{c}_bytes" for c in _LEDGER_COMPONENTS}
    | {"memory/hbm_headroom_bytes", "memory/hbm_limit_bytes",
       HEADROOM_INSTANT, OOM_INSTANT, OOM_COUNTER})


def is_resource_exhausted(err: BaseException) -> bool:
    """Is this exception an XLA allocation failure? jax surfaces device
    OOM as ``XlaRuntimeError('RESOURCE_EXHAUSTED: Out of memory
    allocating …')`` (the class is version-dependent, so match by
    message/status). Deliberately NARROW: the no-restart policy this
    predicate gates is justified by determinism, so a bare
    "out of memory" quoted inside some other error must not trip it —
    only the XLA status code, or an XLA runtime error whose own message
    says out-of-memory."""
    msg = f"{err}".lower()
    if "resource_exhausted" in msg or "resource exhausted" in msg:
        return True
    return ("xlaruntimeerror" in type(err).__name__.lower()
            and "out of memory" in msg)


def collect_memory_snapshot() -> Dict[str, Any]:
    """All-device ``memory_stats`` + per-device headroom — the shared
    ``memory.json`` artifact of the OOM and watchdog crashdumps. Best
    effort: backends without stats (CPU) yield ``stats: null`` rows."""
    devices: List[Dict[str, Any]] = []
    headrooms: List[int] = []
    try:
        import jax
        devs = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend may be gone/absent
        devs = []
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — CPU backends may not report
            stats = None
        row: Dict[str, Any] = {
            "id": getattr(d, "id", None),
            "platform": getattr(d, "platform", None),
            "device_kind": getattr(d, "device_kind", ""),
            "stats": stats,
        }
        if stats and stats.get("bytes_limit"):
            row["headroom_bytes"] = int(stats["bytes_limit"]
                                        - stats.get("peak_bytes_in_use", 0))
            headrooms.append(row["headroom_bytes"])
        devices.append(row)
    return {"devices": devices,
            "min_headroom_bytes": min(headrooms) if headrooms else None}


def min_headroom_bytes() -> Optional[int]:
    """Tightest local device's (bytes_limit − peak), or None when no
    device reports a limit (CPU). Used by bench.py's per-round record."""
    return collect_memory_snapshot()["min_headroom_bytes"]


def write_metrics_tail(out_dir: str, metrics_path: Optional[str],
                       max_bytes: int = 64 * 1024,
                       max_lines: int = 100) -> Optional[str]:
    """Write the tail of a metrics JSONL into ``<out_dir>/
    metrics_tail.jsonl`` — the shared crashdump artifact of the OOM and
    watchdog dumps (the metric trajectory INTO the failure). Returns the
    artifact filename, or None when there is nothing to tail."""
    if not metrics_path or not os.path.exists(metrics_path):
        return None
    with open(metrics_path, "rb") as f:
        f.seek(0, os.SEEK_END)
        f.seek(max(0, f.tell() - max_bytes))
        tail = f.read().decode("utf-8", errors="replace")
    name = "metrics_tail.jsonl"
    with open(os.path.join(out_dir, name), "w") as f:
        f.write("\n".join(tail.splitlines()[-max_lines:]) + "\n")
    return name


# ---------------------------------------------------------------------------
# Model-state ledger: per-device bytes from the TrainState + shardings
# ---------------------------------------------------------------------------

def _leaf_shard_bytes(leaf, spec, mesh_shape: Dict[str, int]) -> int:
    """Per-device bytes of one array under a PartitionSpec — the same
    shard arithmetic XLA uses for argument allocation (ceil per sharded
    dim), so the ledger can be cross-checked against
    ``memory_analysis().argument_size_in_bytes``."""
    shape = tuple(getattr(leaf, "shape", ()) or ())
    itemsize = int(np.dtype(getattr(leaf, "dtype", np.float32)).itemsize)
    entries = tuple(spec) if spec is not None else ()
    elems = 1
    for i, d in enumerate(shape):
        e = entries[i] if i < len(entries) else None
        parts = e if isinstance(e, tuple) else ((e,) if e else ())
        n = mesh_axes_size(mesh_shape, parts)
        elems *= -(-int(d) // max(n, 1))
    return elems * itemsize


def _live_spec(leaf, fallback):
    """The leaf's ACTUAL placement when it is a placed jax.Array (XLA's
    output-sharding propagation may differ from the engine's spec trees
    — e.g. ZeRO-1 keeps post-step params data-sharded, deferring the
    all-gather into the next step's cast), else the engine spec."""
    spec = getattr(getattr(leaf, "sharding", None), "spec", None)
    return spec if spec is not None else fallback


def _tree_shard_bytes(tree, specs, mesh_shape: Dict[str, int],
                      live: bool = True) -> int:
    import jax

    if tree is None:
        return 0
    if specs is None:
        bytes_tree = jax.tree_util.tree_map(
            lambda l: _leaf_shard_bytes(
                l, _live_spec(l, None) if live else None, mesh_shape),
            tree)
    else:
        bytes_tree = jax.tree_util.tree_map(
            lambda l, s: _leaf_shard_bytes(
                l, _live_spec(l, s) if live else s, mesh_shape),
            tree, specs)
    return int(sum(jax.tree_util.tree_leaves(bytes_tree)))


def _tree_full_bytes(tree) -> int:
    return _tree_shard_bytes(tree, None, {})


def model_state_ledger(engine) -> Dict[str, Any]:
    """Closed-form per-device model-state bytes for one engine: master
    params / optimizer moments / grad accumulator / compute-dtype params
    under their actual ZeRO shardings and dtypes, plus the host tiers of
    offloaded configs. Pure host arithmetic over shapes/dtypes/specs —
    no device work, no fetches."""
    import jax

    mesh_shape = {str(k): int(v) for k, v in dict(engine.mesh.shape).items()}
    state = engine.state
    offloaded = hasattr(engine, "offloader")
    pcfg = engine._offload_param_cfg
    ocfg = engine._offload_cfg

    param_template = (engine._compute_params if offloaded else state.params)
    total_params = int(sum(
        int(np.prod(l.shape)) if getattr(l, "shape", ()) else 1
        for l in jax.tree_util.tree_leaves(param_template)))

    scalars = (state.step, state.micro_step, state.loss_scale,
               state.skipped_steps, state.rng)
    scalars_bytes = sum(_tree_full_bytes(s) for s in scalars)

    per_dev = {"master_bytes": 0, "optimizer_bytes": 0, "grads_bytes": 0,
               "compute_params_bytes": 0, "scalars_bytes": int(scalars_bytes)}
    full = {"master_bytes": 0, "optimizer_bytes": 0, "grads_bytes": 0,
            "compute_params_bytes": 0}
    host = {"master_bytes": 0, "optimizer_bytes": 0, "param_tier_bytes": 0}

    compute_dtype = (engine.precision.dtype if engine.precision.mixed
                     else np.float32)
    compute_itemsize = int(np.dtype(compute_dtype).itemsize)

    if offloaded:
        # fp32 master + moments live beside each host (sharded across
        # hosts only through the param tier's storage specs — booked FULL
        # per host here, the conservative bound).
        host["master_bytes"] = (
            _tree_full_bytes(engine.offloader.master)
            if engine.offloader.master is not None
            else total_params * 4)
        host["optimizer_bytes"] = (
            _tree_full_bytes(engine.offloader.opt_state)
            if engine.offloader.opt_state is not None
            else total_params * 8)
        # Device grads: the jitted micro-scan's accumulator (ZeRO-sharded
        # carry) is device-resident for the whole step — exactly when an
        # OOM would fire.
        grad_template = jax.tree_util.tree_map(
            lambda p: np.broadcast_to(
                np.zeros((), engine.grad_accum_dtype), p.shape),
            param_template)
        per_dev["grads_bytes"] = _tree_shard_bytes(
            grad_template, engine.grad_specs, mesh_shape)
        full["grads_bytes"] = _tree_full_bytes(grad_template)
        if pcfg.enabled:
            host["param_tier_bytes"] = (
                total_params * compute_itemsize
                // max(mesh_shape.get("data", 1), 1))
        else:
            compute_specs = jax.tree_util.tree_map(
                lambda s: s.spec, engine._compute_shardings)
            per_dev["compute_params_bytes"] = _tree_shard_bytes(
                param_template, compute_specs, mesh_shape)
            full["compute_params_bytes"] = total_params * compute_itemsize
    else:
        per_dev["master_bytes"] = _tree_shard_bytes(
            state.params, engine.param_specs, mesh_shape)
        full["master_bytes"] = _tree_full_bytes(state.params)
        per_dev["optimizer_bytes"] = _tree_shard_bytes(
            state.opt_state, engine.opt_state_specs_full, mesh_shape)
        full["optimizer_bytes"] = _tree_full_bytes(state.opt_state)
        per_dev["grads_bytes"] = _tree_shard_bytes(
            state.grad_acc, engine.grad_specs, mesh_shape)
        full["grads_bytes"] = _tree_full_bytes(state.grad_acc)
        if engine.precision.mixed:
            # The in-step compute-dtype cast of the params: a transient
            # XLA allocation, but live across the whole fwd/bwd — it
            # belongs in the model-state budget even though it is not an
            # *argument* of the step executable. It inherits the LIVE
            # master sharding (the cast is elementwise).
            live_param_specs = jax.tree_util.tree_map(
                lambda l, s: _live_spec(l, s), state.params,
                engine.param_specs)
            compute_template = jax.tree_util.tree_map(
                lambda p: np.broadcast_to(
                    np.zeros((), compute_dtype), p.shape), state.params)
            per_dev["compute_params_bytes"] = _tree_shard_bytes(
                compute_template, live_param_specs, mesh_shape)
            full["compute_params_bytes"] = total_params * compute_itemsize
        zpp_plan = getattr(engine, "param_gather_plan", None)
        if zpp_plan is not None:
            # ZeRO++: the explicit all-gather materializes each gathered
            # leaf FULL (replicated over its gather axes) in the compute
            # dtype, live across the whole fused fwd/bwd (the gather is
            # hoisted out of the GAS scan). The cast accounting above
            # booked those leaves at their sharded master layout — and a
            # pure-fp32 run booked nothing at all, though its gathered
            # fp32 tree is a real extra full copy.
            g_full = g_shard = 0
            for shape, axes, _ in zpp_plan.gathered_leaves():
                e = int(np.prod(shape))
                n = mesh_axes_size(mesh_shape, axes)
                g_full += e
                g_shard += e // max(n, 1)
            if engine.precision.mixed:
                per_dev["compute_params_bytes"] += (
                    (g_full - g_shard) * compute_itemsize)
            else:
                per_dev["compute_params_bytes"] += g_full * 4
                full["compute_params_bytes"] += g_full * 4

    per_dev["model_state_bytes"] = int(sum(per_dev.values()))
    host["total_bytes"] = int(sum(host.values()))
    # ZeRO++ hpZ secondary-replica charge (runtime/zero/partition.py):
    # the intra-slice partition keeps master+moments dcn-replicated so
    # param gathers never cross DCN; the replica's per-device cost vs the
    # (dcn x data) global primary is (1 - 1/dcn) of the fp32 state. An
    # attribution overlay (the bytes are already in master/optimizer) —
    # deliberately NOT added to model_state_bytes above.
    dcn = int(mesh_shape.get("dcn", 1))
    plan = getattr(engine, "param_gather_plan", None)
    hpz = bool(plan is not None and getattr(plan, "hpz", False) and dcn > 1)
    secondary_bytes = 0
    if hpz:
        # Only leaves a global (hpz off) primary could ACTUALLY shard
        # over dcn are part of the charge — the counterfactual lives
        # beside the placement rules (ZeroPartitioner
        # .hpz_replica_shard_elems), asked per leaf WITH its base
        # partition spec. The charge sums the dcn-shardable leaves'
        # SHARD bytes directly (persistent leaves sit in master_bytes
        # at full replicated weight — a blended fraction would
        # overcharge them), with the moments scaled by the full-tree
        # optimizer/master ratio (moments mirror params elementwise).
        # Implicit-path (TP fallback) leaves count too: they skip the
        # explicit gather but their free dim still carries the primary
        # placement, so the global primary would spread them over dcn.
        base = getattr(engine, "_base_specs", None)
        shard_master_bytes = 4 * engine.partitioner.hpz_replica_shard_elems(
            plan.gathered_leaves(base) + plan.fallback_leaves(base))
        opt_ratio = (full["optimizer_bytes"] / full["master_bytes"]
                     if full["master_bytes"] else 0.0)
        secondary_bytes = int(
            shard_master_bytes * (1.0 + opt_ratio) * (dcn - 1) / dcn)
    return {
        "secondary": {"replica_bytes": secondary_bytes, "hpz": hpz,
                      "dcn": dcn},
        "format": LEDGER_FORMAT,
        "zero_stage": int(engine.config.zero_config.stage),
        "offload_optimizer": (ocfg.device if ocfg.enabled else "none"),
        "offload_param": (pcfg.device if pcfg.enabled else "none"),
        "mesh": mesh_shape,
        "dp_shard": int(mesh_shape.get("data", 1)),
        "total_params": total_params,
        "dtypes": {
            "master": "float32",
            "compute": str(np.dtype(compute_dtype)),
            "grad_acc": str(np.dtype(engine.grad_accum_dtype)),
        },
        "per_device": {k: int(v) for k, v in per_dev.items()},
        "full": {k: int(v) for k, v in full.items()},
        "host": {k: int(v) for k, v in host.items()},
    }


# ---------------------------------------------------------------------------
# Capacity planner: ZeRO stage × offload × microbatch what-if
# ---------------------------------------------------------------------------

def plan_capacity(*, compute_params_bytes: float, grads_bytes: float,
                  master_optim_bytes: float, num_shards: int,
                  microbatch: int = 1, act_bytes_per_sample: float = 0.0,
                  hbm_limit_bytes: Optional[float] = None,
                  chosen_stage: int = 0, chosen_offload: bool = False,
                  offload_compute_params_bytes: Optional[float] = None,
                  total_params: int = 0,
                  hpz_secondary_bytes: float = 0.0) -> Dict[str, Any]:
    """Project per-device bytes for every (ZeRO stage 0–3) × (optimizer
    offload off/on) combination from the model's full-tree component
    totals — the reference stage2/stage3 estimators' arithmetic
    (runtime/zero/partition.py ``estimate_zero_model_states_mem_needs``)
    in bytes, driven by the engine's *actual* dtypes instead of assumed
    ones. ``act_bytes_per_sample`` × microbatch adds the activation term
    (a user-supplied estimate; 0 projects model states only).

    ``offload_compute_params_bytes``: the params term of the OFFLOAD
    rows. A non-offload non-mixed run has no separate compute copy (the
    fp32 master in ``mo`` IS the compute tree ⇒ compute_params_bytes
    0), but an optimizer-offload run always materializes a
    device-resident compute tree while the master lives host-side — so
    its rows need the fp32 copy back. Defaults to
    ``compute_params_bytes`` (correct for mixed precision).

    ``hpz_secondary_bytes``: the ZeRO++ hpZ secondary-replica charge
    from the ledger (per-device bytes the intra-slice replica costs vs
    the global (dcn x data) primary partition). Recorded in the plan —
    with the companion ``hpz_global_primary_savings_bytes`` alias — so
    capacity planning can project the "flip hpz off / widen the primary"
    lever next to the stage/offload/microbatch ones."""
    n = max(int(num_shards), 1)
    c_off = (float(offload_compute_params_bytes)
             if offload_compute_params_bytes is not None
             else float(compute_params_bytes))
    rows = []
    for stage in range(4):
        for offload in (False, True):
            compute = c_off if offload else float(compute_params_bytes)
            grads, mo = float(grads_bytes), float(master_optim_bytes)
            if stage == 0:
                dev = compute + grads + mo
            elif stage == 1:
                dev = compute + grads + mo / n
            elif stage == 2:
                dev = compute + (grads + mo) / n
            else:
                dev = (compute + grads + mo) / n
            host = 0.0
            if offload:
                # stage 0 has no ZeRO sharding to exploit: each host
                # stores the FULL master+moments tier (partition.py
                # estimator semantics); stage >= 1 stores its 1/n shard.
                opt_shard = n if stage >= 1 else 1
                host += mo / opt_shard
                dev -= mo / opt_shard
                if stage == 3:
                    # offload_param: the compute-dtype param partition
                    # leaves HBM too (runtime/zero/param_offload.py).
                    host += compute / n
                    dev -= compute / n
            act = float(act_bytes_per_sample) * max(int(microbatch), 1)
            total = dev + act
            headroom = (float(hbm_limit_bytes) - total
                        if hbm_limit_bytes else None)
            verdict = ("unknown" if headroom is None
                       else ("over" if headroom < 0 else "ok"))
            rows.append({
                "stage": stage,
                "offload": bool(offload),
                "model_state_bytes": int(dev),
                "activation_bytes": int(act),
                "device_bytes": int(total),
                "host_bytes": int(host),
                "headroom_bytes": (int(headroom) if headroom is not None
                                   else None),
                "verdict": verdict,
                "chosen": (stage == int(chosen_stage)
                           and bool(offload) == bool(chosen_offload)),
            })
    micro_proj = []
    if act_bytes_per_sample > 0:
        base = next(r for r in rows if r["chosen"])
        for mult in (1, 2, 4):
            mb = max(int(microbatch), 1) * mult
            total = base["model_state_bytes"] + act_bytes_per_sample * mb
            micro_proj.append({
                "microbatch": mb,
                "device_bytes": int(total),
                "verdict": ("unknown" if not hbm_limit_bytes
                            else ("over" if total > hbm_limit_bytes
                                  else "ok")),
            })
    return {
        "format": PLAN_FORMAT,
        "total_params": int(total_params),
        "num_shards": n,
        "microbatch": int(microbatch),
        "act_bytes_per_sample": float(act_bytes_per_sample),
        "hbm_limit_bytes": (int(hbm_limit_bytes) if hbm_limit_bytes
                            else None),
        "rows": rows,
        "microbatch_projection": micro_proj,
        # ZeRO++ hpZ: what the intra-slice secondary replica costs —
        # equivalently, what widening the primary partition to the full
        # (dcn x data) world would save per device (at the price of
        # quantized param gathers crossing DCN).
        "hpz_secondary_bytes": int(hpz_secondary_bytes),
        "hpz_global_primary_savings_bytes": int(hpz_secondary_bytes),
    }


# ---------------------------------------------------------------------------
# Standalone projection: raw config + parameter SHAPES, no live engine.
# The autotuner's pruning path (autotuning/search.py) projects every
# candidate's HBM before any engine exists; the engine call site
# (_plan_from_engine below) is untouched and keeps feeding plan_capacity
# from the live ledger. tests/test_autotuning.py pins the two paths equal
# on MLP + GPT configs.
# ---------------------------------------------------------------------------

def _shape_tree_params(param_shapes) -> int:
    """Total parameter count of a shape tree — leaves need only
    ``.shape`` (arrays, ShapeDtypeStructs and plain numpy all work)."""
    import jax

    return int(sum(
        int(np.prod(l.shape)) if getattr(l, "shape", ()) else 1
        for l in jax.tree_util.tree_leaves(param_shapes)))


def optimizer_state_full_bytes(optimizer_name, optimizer_params,
                               total_params: int) -> int:
    """Full-tree optimizer-state bytes for a config-named optimizer — the
    closed form of ``_tree_full_bytes(optimizer.init(master))``: Adam/
    AdamW/LAMB carry two fp32 moment trees plus an int32 step scalar;
    SGD carries one fp32 momentum tree (or a bare int32 scalar when
    momentum is 0). Unknown/absent names take the Adam shape — the
    engine's own default (_configure_basic_optimizer)."""
    name = str(optimizer_name or "adam").lower()
    if name == "sgd":
        momentum = float((optimizer_params or {}).get("momentum", 0.0))
        return 4 * total_params if momentum else 4
    # adam / adamw / lamb / cpuadam / unknown: AdamState-shaped
    return 8 * total_params + 4


def state_totals_from_shapes(param_shapes, *, optimizer_name=None,
                             optimizer_params=None,
                             precision_dtype: str = "float32",
                             grad_accum_dtype: str = "float32"
                             ) -> Dict[str, int]:
    """The ledger's ``full`` component totals from a parameter-shape tree
    + config dtypes alone — exactly what :func:`model_state_ledger`
    computes from a live engine's state trees (mixed precision adds the
    compute-dtype copy; a pure-fp32 run has none: the master IS the
    compute tree)."""
    total = _shape_tree_params(param_shapes)
    mixed = str(precision_dtype) != "float32"
    # bf16/fp16 are 2 bytes; resolved by name so the function stays
    # importable without ml_dtypes' numpy registrations.
    compute_itemsize = (2 if str(precision_dtype) in
                        ("bfloat16", "bf16", "float16", "fp16") else 4)
    acc_itemsize = (2 if str(grad_accum_dtype) in ("bfloat16", "bf16")
                    else 4)
    return {
        "total_params": total,
        "master_bytes": 4 * total,
        "optimizer_bytes": int(optimizer_state_full_bytes(
            optimizer_name, optimizer_params, total)),
        "grads_bytes": acc_itemsize * total,
        "compute_params_bytes": (compute_itemsize * total if mixed else 0),
    }


def plan_capacity_from_config(config, param_shapes, *,
                              num_shards: Optional[int] = None,
                              microbatch: Optional[int] = None,
                              act_bytes_per_sample: Optional[float] = None,
                              hbm_limit_bytes: Optional[float] = None
                              ) -> Dict[str, Any]:
    """:func:`plan_capacity` driven from a parsed ``DeepSpeedTPUConfig``
    + a parameter-shape tree — no engine, no devices, no placement. The
    same arithmetic as the engine path (``MemoryObservatory.
    _plan_from_engine``), including its offload-row compute fallback;
    ``num_shards`` defaults to the config's data-parallel size (the
    engine path uses the mesh's ICI-inner ``data`` axis — pass it when a
    multi-slice mesh narrows the ZeRO shard axis below dp)."""
    totals = state_totals_from_shapes(
        param_shapes,
        optimizer_name=getattr(config, "optimizer_name", None),
        optimizer_params=getattr(config, "optimizer_params", None),
        precision_dtype=config.precision_dtype,
        grad_accum_dtype=getattr(config, "grad_accum_dtype", "float32"))
    mo = totals["master_bytes"] + totals["optimizer_bytes"]
    return plan_capacity(
        compute_params_bytes=totals["compute_params_bytes"],
        offload_compute_params_bytes=(totals["compute_params_bytes"]
                                      or totals["master_bytes"]),
        grads_bytes=totals["grads_bytes"],
        master_optim_bytes=mo,
        num_shards=(int(num_shards) if num_shards is not None
                    else int(config.data_parallel_size
                             // max(config.mesh.slices, 1))),
        microbatch=(int(microbatch) if microbatch is not None
                    else int(config.train_micro_batch_size_per_gpu)),
        act_bytes_per_sample=float(
            act_bytes_per_sample
            if act_bytes_per_sample is not None
            else config.telemetry.memory.activation_bytes_per_sample),
        hbm_limit_bytes=hbm_limit_bytes,
        chosen_stage=int(config.zero_config.stage),
        chosen_offload=bool(config.zero_config.offload_optimizer.enabled),
        total_params=totals["total_params"])


def _gb(v) -> str:
    return f"{v / 1024**3:8.3f}" if v is not None else "     n/a"


def render_plan_table(plan: Dict[str, Any]) -> str:
    """The startup what-if table (also rendered, stdlib-side, by
    tools/memory_report.py from the persisted ``memory_plan.json``)."""
    lines = [
        f"memory plan: {plan['total_params'] / 1e6:.1f}M params, "
        f"{plan['num_shards']} ZeRO shard(s), microbatch "
        f"{plan['microbatch']}, HBM limit "
        f"{_gb(plan['hbm_limit_bytes']).strip()} GB",
        f"{'config':<22} {'model GB':>9} {'act GB':>8} {'device GB':>10} "
        f"{'host GB':>8} {'headroom':>9}  verdict",
    ]
    lines.append("-" * len(lines[-1]))
    for r in plan["rows"]:
        name = (f"stage{r['stage']}"
                + ("+offload" if r["offload"] else "")
                + (" *" if r["chosen"] else ""))
        lines.append(
            f"{name:<22} {_gb(r['model_state_bytes']):>9} "
            f"{_gb(r['activation_bytes']):>8} {_gb(r['device_bytes']):>10} "
            f"{_gb(r['host_bytes']):>8} {_gb(r['headroom_bytes']):>9}  "
            f"{r['verdict'].upper() if r['verdict'] == 'over' else r['verdict']}")
    for m in plan.get("microbatch_projection", []):
        lines.append(f"  microbatch {m['microbatch']:<4} -> device "
                     f"{_gb(m['device_bytes']).strip()} GB  {m['verdict']}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# The observatory
# ---------------------------------------------------------------------------

class MemoryObservatory:
    """Per-engine memory observability facade (one per engine, like
    goodput/fleet). All hooks are host-side; the only device-adjacent
    work is the one AOT lower+compile behind :meth:`maybe_attribute`."""

    def __init__(self, cfg, telemetry=None, goodput=None,
                 run_dir: Optional[str] = None,
                 exit_fn=os._exit):
        self.cfg = cfg
        self.telemetry = telemetry
        self.goodput = goodput
        self.run_dir = run_dir
        self.crashdump_dir = cfg.crashdump_dir
        self.exit_code = int(cfg.oom_exit_code)
        self._exit_fn = exit_fn
        self._limit_override = (int(cfg.hbm_limit_gb * 1024**3)
                                if cfg.hbm_limit_gb else None)
        self._xla_attempted = False
        self._headroom_low = False
        self.last_ledger: Optional[Dict[str, Any]] = None
        self.last_xla: Optional[Dict[str, int]] = None
        self.last_plan: Optional[Dict[str, Any]] = None

    # -- engine init: ledger + capacity plan ----------------------------
    def on_engine_init(self, engine) -> None:
        try:
            self.last_ledger = model_state_ledger(engine)
            self._emit_ledger(self.last_ledger)
        except Exception as e:  # noqa: BLE001 — observability must never
            # take down the engine it observes
            logger.warning("memory observatory: ledger failed: %s", e)
        if self.cfg.plan_at_init:
            try:
                self._plan_from_engine(engine)
            except Exception as e:  # noqa: BLE001
                logger.warning("memory observatory: plan failed: %s", e)

    def _emit_ledger(self, ledger: Dict[str, Any]) -> None:
        tel = self.telemetry
        if tel is None or not getattr(tel, "enabled", False):
            return
        reg = tel.registry
        per = ledger["per_device"]
        reg.gauge("memory/ledger_master_bytes").set(per["master_bytes"])
        reg.gauge("memory/ledger_optimizer_bytes").set(
            per["optimizer_bytes"])
        reg.gauge("memory/ledger_grads_bytes").set(per["grads_bytes"])
        reg.gauge("memory/ledger_compute_params_bytes").set(
            per["compute_params_bytes"])
        reg.gauge("memory/ledger_scalars_bytes").set(per["scalars_bytes"])
        reg.gauge("memory/ledger_device_bytes").set(
            per["model_state_bytes"])
        reg.gauge("memory/ledger_host_bytes").set(
            ledger["host"]["total_bytes"])
        reg.gauge("memory/ledger_secondary_bytes").set(
            ledger.get("secondary", {}).get("replica_bytes", 0))

    def hbm_limit_bytes(self) -> Optional[int]:
        """min ``bytes_limit`` over local devices, else the config
        override, else None (CPU without a hint)."""
        snap = collect_memory_snapshot()
        limits = [d["stats"]["bytes_limit"] for d in snap["devices"]
                  if d.get("stats") and d["stats"].get("bytes_limit")]
        if limits:
            return int(min(limits))
        return self._limit_override

    def _plan_from_engine(self, engine) -> None:
        ledger = self.last_ledger or model_state_ledger(engine)
        full = ledger["full"]
        mo = (full["master_bytes"] + full["optimizer_bytes"]) or (
            ledger["host"]["master_bytes"] + ledger["host"]["optimizer_bytes"])
        # compute_params_bytes is 0 for non-mixed runs (no separate
        # compute-dtype copy: the fp32 master in `mo` IS the compute
        # tree) — but the OFFLOAD what-if rows always need a device
        # compute tree (the master moves host-side), so they fall back
        # to the fp32 master size when no mixed-precision copy exists.
        self.last_plan = plan_capacity(
            compute_params_bytes=full["compute_params_bytes"],
            offload_compute_params_bytes=(
                full["compute_params_bytes"]
                or full["master_bytes"]
                or ledger["host"]["master_bytes"]),
            grads_bytes=full["grads_bytes"],
            master_optim_bytes=mo,
            num_shards=ledger["dp_shard"],
            microbatch=int(engine.train_micro_batch_size_per_gpu),
            act_bytes_per_sample=float(self.cfg.activation_bytes_per_sample),
            hbm_limit_bytes=self.hbm_limit_bytes(),
            chosen_stage=ledger["zero_stage"],
            chosen_offload=ledger["offload_optimizer"] != "none",
            total_params=ledger["total_params"],
            hpz_secondary_bytes=float(
                ledger.get("secondary", {}).get("replica_bytes", 0)))
        log_dist("memory observatory what-if:\n"
                 + render_plan_table(self.last_plan), ranks=[0])
        chosen = next(r for r in self.last_plan["rows"] if r["chosen"])
        if chosen["verdict"] == "over":
            logger.warning(
                "memory observatory: the CHOSEN config (stage %d%s) "
                "projects %.2f GB per device against a %.2f GB HBM limit "
                "— this run is expected to OOM; consult the what-if "
                "table above for a fitting stage/offload/microbatch",
                chosen["stage"],
                "+offload" if chosen["offload"] else "",
                chosen["device_bytes"] / 1024**3,
                self.last_plan["hbm_limit_bytes"] / 1024**3)
        if self.run_dir:
            from deepspeed_tpu.telemetry.fleet import (
                host_scoped_path, telemetry_host_component)
            try:
                _atomic_write_json(
                    os.path.join(self.run_dir, host_scoped_path(
                        self.cfg.plan_file, telemetry_host_component())),
                    self.last_plan)
            except OSError as e:
                logger.warning("memory plan write failed: %s", e)

    # -- per-executable XLA attribution ---------------------------------
    def maybe_attribute(self, engine, batches, lr, status) -> None:
        """Pull ``compiled.memory_analysis()`` for the engine's step
        executable — once, re-armed when the recompile detector reports a
        new compile/retrace (same cadence as ``engine/mfu``'s cost
        analysis). The AOT lower+compile is booked as ``recompile``
        goodput; the XLA compilation cache dedupes the binary."""
        if self._xla_attempted and status not in ("compile", "retrace"):
            return
        self._xla_attempted = True
        try:
            # Refresh the ledger from the LIVE state placement first, so
            # ledger and XLA analysis describe the same executable (the
            # post-step placement can differ from the init-time one —
            # see _live_spec).
            self.last_ledger = model_state_ledger(engine)
            self._emit_ledger(self.last_ledger)
        except Exception as e:  # noqa: BLE001
            logger.warning("memory observatory: ledger refresh failed: %s",
                           e)
        try:
            g = self.goodput
            ctx = (g.measure("recompile") if g is not None
                   else contextlib.nullcontext())
            with ctx:
                if engine._train_step is not None:
                    lowered = engine._train_step.lower(
                        engine.state, batches, lr)
                elif getattr(engine, "_offload_micro_scan", None) is not None:
                    lowered = engine._offload_micro_scan.lower(
                        engine._compute_params, engine.state.rng, batches,
                        np.float32(1.0))
                else:
                    return
                stats = lowered.compile().memory_analysis()
            xla = {}
            for f in _XLA_FIELDS:
                v = getattr(stats, f"{f}_size_in_bytes", None)
                if v is not None:
                    xla[f"{f}_bytes"] = int(v)
            self.last_xla = xla
            self._emit_xla(xla, step=engine.global_steps)
        except Exception as e:  # noqa: BLE001 — attribution is best-effort
            logger.warning(
                "memory observatory: XLA memory analysis unavailable: %s", e)

    def _emit_xla(self, xla: Dict[str, int], step: int) -> None:
        tel = self.telemetry
        if tel is None or not getattr(tel, "enabled", False):
            return
        reg = tel.registry
        for f in _XLA_FIELDS:
            v = xla.get(f"{f}_bytes")
            if v is not None:
                reg.gauge(f"memory/xla_{f}_bytes").set(v, step=step)

    # -- per-step headroom (rides the engine's HBM gauge fetch) ---------
    def note_hbm(self, peaks: List[int], limits: List[int],
                 step: int) -> None:
        """Called by ``_emit_step_telemetry`` with the per-device peak /
        ``bytes_limit`` lists it already fetched — no extra device work.
        Emits headroom = min(limit − peak) and a ``memory/headroom_low``
        instant when it first drops below ``headroom_warn_frac``."""
        pairs = [(int(l), int(p)) for l, p in zip(limits, peaks)
                 if l and l > 0]
        if pairs:
            headroom = min(l - p for l, p in pairs)
            limit = min(l for l, _ in pairs)
        elif self._limit_override is not None and peaks:
            limit = self._limit_override
            headroom = limit - max(int(p) for p in peaks)
        else:
            return
        tel = self.telemetry
        if tel is not None and getattr(tel, "enabled", False):
            tel.registry.gauge("memory/hbm_headroom_bytes").set(
                headroom, step=step)
            tel.registry.gauge("memory/hbm_limit_bytes").set(
                limit, step=step)
        low = headroom < float(self.cfg.headroom_warn_frac) * limit
        if low and not self._headroom_low:
            logger.warning(
                "memory observatory: HBM headroom %.2f GB is below %.0f%% "
                "of the %.2f GB limit — the next allocation spike (longer "
                "sequence, retrace, eval batch) may OOM",
                headroom / 1024**3,
                float(self.cfg.headroom_warn_frac) * 100, limit / 1024**3)
            if tel is not None and getattr(tel, "enabled", False):
                tel.instant(HEADROOM_INSTANT, step=step,
                            headroom_bytes=int(headroom),
                            limit_bytes=int(limit))
        self._headroom_low = low

    # -- OOM forensics ---------------------------------------------------
    @contextlib.contextmanager
    def oom_guard(self, engine, label: str = "train_step"):
        """Wraps a compile/step dispatch: RESOURCE_EXHAUSTED → memory
        crashdump → exit with the distinct OOM rc (``os._exit`` by
        default — the allocator state after a device OOM is not worth
        unwinding through; injectable for tests). Everything else
        propagates untouched."""
        try:
            yield
        except Exception as e:  # noqa: BLE001 — filtered below
            if not is_resource_exhausted(e):
                raise
            try:
                path = self.write_crashdump(engine, e, label=label)
                logger.error(
                    "memory observatory: RESOURCE_EXHAUSTED in %s at step "
                    "%d — crashdump at %s; exiting rc=%d (the supervisor "
                    "classifies cause=oom and does NOT restart: a "
                    "deterministic OOM is a config bug — see the what-if "
                    "table in memory_plan.json / tools/memory_report.py)",
                    label, getattr(engine, "global_steps", -1), path,
                    self.exit_code)
            except Exception as dump_err:  # noqa: BLE001 — dying loudly
                # beats dying twice
                logger.error(
                    "memory observatory: OOM crashdump failed: %s", dump_err)
            self._exit_fn(self.exit_code)
            raise  # unreachable with os._exit; reached with injected exit_fn

    def write_crashdump(self, engine, err: BaseException,
                        label: str = "train_step") -> str:
        """The guardrails-format crashdump directory a post-mortem needs:
        every artifact best-effort, ``info.json`` last (fsync'd)."""
        step = int(getattr(engine, "global_steps", 0))
        out = os.path.join(self.crashdump_dir,
                           f"oom_step{step}_{os.getpid()}")
        os.makedirs(out, exist_ok=True)
        info: Dict[str, Any] = {
            "kind": "oom", "step": step, "label": label,
            "pid": os.getpid(), "exit_code": self.exit_code,
            "error": str(err)[:4000],
        }

        # 1. All-device memory stats + headroom (the watchdog dump shares
        # this artifact via collect_memory_snapshot).
        try:
            with open(os.path.join(out, "memory.json"), "w") as f:
                json.dump(collect_memory_snapshot(), f, indent=1)
            info["memory"] = "memory.json"
        except Exception as e:  # noqa: BLE001
            info["memory_error"] = repr(e)

        # 2. The model-state ledger (recomputed if the init-time one is
        # stale/absent; shapes/specs are host state and survive the OOM).
        try:
            ledger = self.last_ledger or model_state_ledger(engine)
            with open(os.path.join(out, "ledger.json"), "w") as f:
                json.dump(ledger, f, indent=1)
            info["ledger"] = "ledger.json"
        except Exception as e:  # noqa: BLE001
            info["ledger_error"] = repr(e)

        # 3. XLA memory analysis + the capacity plan, when known.
        for name, doc in (("xla_memory_analysis.json", self.last_xla),
                          ("plan.json", self.last_plan)):
            if doc:
                try:
                    with open(os.path.join(out, name), "w") as f:
                        json.dump(doc, f, indent=1)
                    info[name.split(".")[0]] = name
                except Exception as e:  # noqa: BLE001
                    info[f"{name}_error"] = repr(e)

        # 4. Device memory profile (pprof) — names the live allocations.
        try:
            import jax.profiler
            prof = jax.profiler.device_memory_profile()
            with open(os.path.join(out, "device_memory.pprof"), "wb") as f:
                f.write(prof)
            info["device_memory_profile"] = "device_memory.pprof"
        except Exception as e:  # noqa: BLE001
            info["device_memory_profile_error"] = repr(e)

        # 5. Metrics tail (the headroom trajectory INTO the OOM) — the
        # same shared artifact the watchdog dump writes.
        tel = self.telemetry
        try:
            name = write_metrics_tail(out, getattr(tel, "metrics_path",
                                                   None))
            if name:
                info["metrics_tail"] = name
        except Exception as e:  # noqa: BLE001
            info["metrics_tail_error"] = repr(e)

        with open(os.path.join(out, "info.json"), "w") as f:
            json.dump(info, f, indent=1)
            f.flush()
            os.fsync(f.fileno())

        if tel is not None and getattr(tel, "enabled", False):
            try:
                tel.registry.counter(OOM_COUNTER).inc(step=step)
                tel.instant(OOM_INSTANT, step=step, label=label)
                tel.flush()
            except Exception:  # noqa: BLE001 — never block the exit
                pass
        g = self.goodput
        if g is not None:
            # The supervisor will stamp the rc post-mortem too, but the
            # engine knows the cause with certainty — record it now.
            g.write_manifest(exit_rc=self.exit_code, restart_cause="oom")
        return out


def build_memory_observatory(tcfg, telemetry=None, goodput=None,
                             exit_fn=os._exit) -> \
        Optional[MemoryObservatory]:
    """``None`` unless telemetry AND its memory block are enabled — the
    engine's hooks gate on ``is None`` (the zero-overhead contract, same
    shape as goodput/fleet/guardrails)."""
    if tcfg is None or not tcfg.enabled or not tcfg.memory.enabled:
        return None
    return MemoryObservatory(tcfg.memory, telemetry=telemetry,
                             goodput=goodput, run_dir=tcfg.dir,
                             exit_fn=exit_fn)
