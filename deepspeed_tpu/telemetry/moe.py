"""MoE observatory: the moe/* gauge family, engine-side.

The model computes the per-step MoE statistics in-program (moe/layer.py
``_dispatch_stats`` — load-balance loss, capacity overflow fraction,
expert utilization, modeled dispatch wire bytes) and the engine's train
step threads them out through its aux output, exactly the numerics
observatory's economy: ``note_step`` stores device-array REFERENCES (no
sync on the step path) and ``flush`` — the telemetry cadence boundary,
``steps_per_print`` — pays ONE ``device_get`` for the whole dict.

``build_moe_monitor`` returns None unless BOTH the ``moe`` config block
and telemetry are enabled; every engine hook is ``is None``-gated, so
the off path adds zero work and the lowered step stays bit-identical
(tests/test_moe.py pins it).
"""

from typing import Any, Dict, Optional

import jax

# Every moe/* tag this module can emit — pinned against
# docs/OBSERVABILITY.md in BOTH directions by tests/test_doc_lint.py,
# like NUMERICS/GOODPUT_METRIC_TAGS.
MOE_METRIC_TAGS = frozenset({
    "moe/load_balance_loss",
    "moe/capacity_overflow_frac",
    "moe/expert_utilization",
    "moe/dispatch_bytes_ici",
})

# The model-output aux keys the engine's step threads through (the
# models/gpt.py moe_stats contract); order irrelevant, names are
# "moe_" + the gauge suffix.
MOE_AUX_KEYS = (
    "moe_load_balance_loss",
    "moe_capacity_overflow_frac",
    "moe_expert_utilization",
    "moe_dispatch_bytes_ici",
)


class MoEMonitor:
    """Engine-side flush point for the moe/* gauges."""

    def __init__(self) -> None:
        self.telemetry = None          # TelemetryFacade, attached late
        self._pending: Optional[Dict[str, Any]] = None
        self._step = -1
        self._gas = 1

    def attach(self, telemetry) -> None:
        self.telemetry = telemetry

    def note_step(self, stats: Dict[str, Any], step: int,
                  gas: int = 1) -> None:
        """Store the step's aux stat references — never a device sync
        (flush pays the one fetch at the cadence boundary)."""
        self._pending = dict(stats)
        self._step = int(step)
        self._gas = max(int(gas), 1)

    def _fetch(self) -> Dict[str, float]:
        fetched = jax.device_get(self._pending)
        self._pending = None
        return {k: float(v) for k, v in fetched.items()}

    def flush(self) -> None:
        if self.telemetry is None or not getattr(
                self.telemetry, "enabled", False) or self._pending is None:
            return
        vals = self._fetch()
        reg = self.telemetry.registry
        for key, v in vals.items():
            if not key.startswith("moe_"):
                continue
            if key == "moe_dispatch_bytes_ici":
                # The model reports per-microstep modeled wire bytes
                # (averaged over the GAS scan of a constant); the gauge
                # is per OPTIMIZER step.
                v *= self._gas
            reg.gauge("moe/" + key[len("moe_"):]).set(v, step=self._step)

    @property
    def last_step(self) -> int:
        return self._step


def build_moe_monitor(config) -> Optional[MoEMonitor]:
    """The engine's single construction point: None — and therefore zero
    step-path work — unless the moe block AND telemetry are enabled."""
    moe = getattr(config, "moe", None)
    tcfg = getattr(config, "telemetry", None)
    if moe is None or not moe.enabled:
        return None
    if tcfg is None or not getattr(tcfg, "enabled", False):
        return None
    return MoEMonitor()
