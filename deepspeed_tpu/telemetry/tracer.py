"""Step tracer — Chrome trace-event spans for the training loop.

Records named spans (dataloader / forward / backward / optimizer_step /
ckpt_snapshot / ckpt_write / ...) as Chrome trace-event JSON, the format
Perfetto and ``chrome://tracing`` open directly, plus instant and counter
events. ``tools/trace_report.py`` renders the same file as a per-span time
breakdown table.

Span semantics on an async-dispatch runtime: XLA queues device work and
returns, so a host-side wall-clock span around a dispatch measures the
*dispatch*, not the compute. When ``sync_spans`` is on (the default for an
enabled tracer), the tracer drains the device queue at every span boundary —
the span then brackets exactly the device work issued inside it, which is
the T3-style "where does step time go" attribution. The sync barrier is
gated on the tracer being enabled: a disabled tracer's ``span()`` is a
reusable no-op context manager that performs **zero** ``block_until_ready``
calls and no allocation beyond one attribute check.

Optional ``jax.profiler`` passthrough: give ``jax_profiler_dir`` and the
tracer starts a profiler session alongside (device-level XLA timeline, for
the cases where host spans aren't enough).
"""

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


def _device_sync() -> None:
    """Drain the device queue. Routed through ``utils.timer`` so the whole
    codebase has ONE sync primitive (tests count calls by patching it)."""
    from deepspeed_tpu.utils import timer as _timer

    _timer._device_synchronize()


class _NullSpan:
    """Reusable no-op context manager for the disabled tracer."""

    __slots__ = ()
    duration = 0.0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "args", "_t0", "duration")

    def __init__(self, tracer: "StepTracer", name: str, args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._t0 = 0.0
        self.duration = 0.0

    def __enter__(self):
        if self._tracer.sync_spans:
            _device_sync()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._tracer.sync_spans:
            _device_sync()
        t1 = time.perf_counter()
        self.duration = t1 - self._t0
        self._tracer._record(self.name, self._t0, t1, self.args)
        return False


class StepTracer:
    """Chrome trace-event recorder. Thread-safe (the checkpoint writer
    thread emits ckpt_write spans concurrently with the step loop).

    Bounded: at most ``max_events`` events are held (a ring — the OLDEST
    are dropped first, keeping the recent window that matters for triage;
    ``dropped_events`` counts evictions and the saved trace carries the
    count as metadata). This caps both host RAM and the cost of each
    ``save()`` rewrite at a constant, so periodic flushing over an
    arbitrarily long run does O(steps × max_events) work, never
    O(steps²). ``save()`` is also skipped when nothing was recorded since
    the last write."""

    def __init__(self, path: Optional[str] = None, enabled: Optional[bool] = None,
                 sync_spans: bool = True,
                 jax_profiler_dir: Optional[str] = None,
                 max_events: int = 200_000,
                 host: Optional[str] = None):
        self.path = path
        self.enabled = bool(path) if enabled is None else bool(enabled)
        # Sync barriers strictly require an enabled tracer — the zero-cost
        # contract of disabled telemetry.
        self.sync_spans = bool(sync_spans) and self.enabled
        self.jax_profiler_dir = jax_profiler_dir
        self.host = host
        self._events = collections.deque(maxlen=int(max_events))
        self.dropped_events = 0
        self._dirty = False
        self._lock = threading.Lock()
        self._epoch = time.perf_counter()
        # Wall-clock anchor of the ts=0 epoch, persisted in the trace
        # metadata so tools/fleet_report.py can clock-align traces from
        # different hosts onto one timeline.
        self._epoch_wall = time.time()
        self._pid = os.getpid()
        self._profiler_active = False
        self._profiler_dir: Optional[str] = None
        self._atexit_registered = False
        if self.enabled:
            self._meta("process_name", {"name": "deepspeed_tpu"})
            if jax_profiler_dir:
                self.start_jax_profiler()

    def _append(self, ev: Dict[str, Any]) -> None:
        """Caller holds the lock."""
        if len(self._events) == self._events.maxlen:
            self.dropped_events += 1
        self._events.append(ev)
        self._dirty = True

    # -- event helpers --------------------------------------------------
    def _us(self, t: float) -> float:
        return (t - self._epoch) * 1e6

    def _meta(self, name: str, args: Dict[str, Any]) -> None:
        with self._lock:
            self._append({"name": name, "ph": "M", "pid": self._pid,
                          "tid": threading.get_ident(), "args": args})

    def _record(self, name: str, t0: float, t1: float,
                args: Dict[str, Any]) -> None:
        ev = {"name": name, "ph": "X", "pid": self._pid,
              "tid": threading.get_ident(), "ts": self._us(t0),
              "dur": (t1 - t0) * 1e6}
        if args:
            ev["args"] = args
        with self._lock:
            self._append(ev)

    # -- public API -----------------------------------------------------
    def span(self, name: str, **args):
        """Context manager timing the enclosed region (no-op when
        disabled). The returned handle exposes ``.duration`` (seconds)
        after exit."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, args)

    def instant(self, name: str, **args) -> None:
        if not self.enabled:
            return
        ev = {"name": name, "ph": "i", "s": "t", "pid": self._pid,
              "tid": threading.get_ident(),
              "ts": self._us(time.perf_counter())}
        if args:
            ev["args"] = args
        with self._lock:
            self._append(ev)

    def counter(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._append({
                "name": name, "ph": "C", "pid": self._pid,
                "tid": threading.get_ident(),
                "ts": self._us(time.perf_counter()),
                "args": {"value": float(value)}})

    def _async(self, ph: str, name: str, aid, cat: str,
               args: Dict[str, Any]) -> None:
        ev = {"name": name, "ph": ph, "cat": cat, "id": str(aid),
              "pid": self._pid, "tid": threading.get_ident(),
              "ts": self._us(time.perf_counter())}
        if args:
            ev["args"] = args
        with self._lock:
            self._append(ev)

    def async_begin(self, name: str, aid, cat: str = "request",
                    **args) -> None:
        """Open an async-track span (Chrome ``ph: b``): async events live
        on their own (cat, id) track, so long-lived arcs — a serving
        request's queue -> prefill -> decode lifecycle — render alongside
        the step spans without nesting inside them. Pair with
        :meth:`async_end` on the same (name, cat, id)."""
        if not self.enabled:
            return
        self._async("b", name, aid, cat, args)

    def async_end(self, name: str, aid, cat: str = "request",
                  **args) -> None:
        if not self.enabled:
            return
        self._async("e", name, aid, cat, args)

    @property
    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def span_names(self) -> set:
        with self._lock:
            return {e["name"] for e in self._events if e.get("ph") == "X"}

    # -- jax.profiler passthrough --------------------------------------
    @property
    def profiler_active(self) -> bool:
        return self._profiler_active

    @staticmethod
    def host_scoped_profile_dir(target: str) -> str:
        """Multi-host capture dirs must not collide on shared storage:
        whenever the run spans processes (or ``DSTPU_TELEMETRY_HOST``
        forces it) the capture lands in a per-host subdir — the same
        convention that host-scopes ``metrics.<host>.jsonl``. Single-host
        paths come back unchanged."""
        try:
            from deepspeed_tpu.telemetry.fleet import \
                telemetry_host_component
            part = telemetry_host_component()
        except Exception:  # noqa: BLE001 — backendless: single-host
            part = None
        return os.path.join(target, part) if part else target

    def start_jax_profiler(self, dir: Optional[str] = None) -> \
            Optional[str]:
        """Start a ``jax.profiler`` capture into ``dir`` (the device-time
        observatory's scheduled captures) or the configured passthrough
        ``jax_profiler_dir``. Returns the host-scoped directory actually
        captured into, or None (already active / no dir / profiler
        unavailable)."""
        target = dir or self.jax_profiler_dir
        if self._profiler_active or not target:
            return None
        try:
            import jax
            target = self.host_scoped_profile_dir(target)
            os.makedirs(target, exist_ok=True)
            jax.profiler.start_trace(target)
            self._profiler_active = True
            self._profiler_dir = target
            # Guarantee stop_trace even when a crash skips close(): an
            # exception between start and stop otherwise leaks the
            # profiler session (and its capture buffer) for the rest of
            # the process. stop is idempotent, so a clean close() +
            # atexit double-fire is harmless.
            if not self._atexit_registered:
                import atexit
                atexit.register(self.stop_jax_profiler)
                self._atexit_registered = True
            return target
        except Exception as e:  # noqa: BLE001 — profiler is best-effort
            from deepspeed_tpu.utils.logging import logger
            logger.warning("jax.profiler passthrough unavailable: %s", e)
            return None

    def stop_jax_profiler(self) -> Optional[str]:
        """Stop the active capture (idempotent). Returns the directory it
        was writing into, or None when nothing was active."""
        if not self._profiler_active:
            return None
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001
            pass
        self._profiler_active = False
        d = getattr(self, "_profiler_dir", None)
        self._profiler_dir = None
        return d

    # -- persistence ----------------------------------------------------
    def save(self) -> Optional[str]:
        """Write the trace file (atomic rename). Cheap to call on a cadence:
        a no-op when nothing was recorded since the last write, and the
        rewrite cost is capped by ``max_events`` — a preemption loses at
        most the events since the previous flush."""
        if not self.enabled or not self.path:
            return None
        with self._lock:
            if not self._dirty:
                return self.path
            events = list(self._events)
            dropped = self.dropped_events
            self._dirty = False
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               # wall_epoch: wall-clock time of ts=0 — the cross-host
               # clock-alignment anchor fleet_report merges on.
               "metadata": {"wall_epoch": self._epoch_wall,
                            "host": self.host}}
        if dropped:
            doc["metadata"]["dropped_events"] = dropped
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, self.path)
        return self.path

    flush = save

    def close(self) -> None:
        self.stop_jax_profiler()
        self.save()
