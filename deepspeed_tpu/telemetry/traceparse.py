"""Shared perfetto / Chrome-trace parsing — ONE module, stdlib only.

Before this module the tree had three divergent parsers of the same two
formats: ``tools/trace_report.py`` (StepTracer Chrome traces),
``tools/fleet_report.py --profile-dir`` (``jax.profiler`` perfetto
captures, measured collective time) and ``tools/profile_gpt2.py`` (ad-hoc
cost prints next to a hand-run capture). All of them — plus the
device-time observatory (``telemetry/devicetime.py``), which turns the
same captures into ``devicetime/*`` gauges — now route through here.

Deliberately **stdlib-only and import-clean** (json, gzip, glob, re — no
jax, no numpy, no package imports): the report tools load this file by
path (``importlib.util.spec_from_file_location``) so they keep running on
hosts without jax installed, exactly as before the consolidation.

Two input families, one vocabulary:

- **StepTracer traces** (``trace.json``): host-side span events. The
  ``load_doc`` / ``load_many`` / ``summarize`` family (formerly
  tools/trace_report.py) renders them as per-span breakdowns.
- **``jax.profiler`` captures** (``**/*.trace.json.gz`` under a profile
  dir): device-level XLA op events. ``parse_capture_dir`` classifies
  every HLO op into an attribution category (:data:`CATEGORIES`),
  computes per-device busy/idle unions and the overlap-aware **exposed
  collective time** (collective device time NOT covered by compute on any
  other stream of the same device — the T3-style measured ground truth
  the modeled ``comm/exposed_frac`` is checked against).

:data:`COLLECTIVE_RE` is the one collective-op-name list in the tree.
"""

import collections
import glob as _glob
import gzip
import json
import os
import re
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Op classification
# ---------------------------------------------------------------------------

# XLA collective op names inside a capture (also matches the -start/-done
# async halves). THE one list: fleet_report, devicetime and the report
# tools all import it from here.
COLLECTIVE_RE = re.compile(
    r"all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute",
    re.IGNORECASE)

# Device-time attribution categories (the order reports render them in).
# "gap" (host-dispatch idle between ops) is computed from the timeline
# union, not from op names, so it is not listed here.
CATEGORIES = ("matmul", "elementwise", "collective", "copy", "other")

# HLO op-name charset: lowercase + digits + [-_.]. Runtime/host events
# (``ThreadpoolListener::StartRegion``, ``PjitFunction(<lambda>)``,
# ``$profiler.py:91 start_trace``) all contain characters outside it and
# are excluded from device-time attribution.
_NON_HLO_CHAR_RE = re.compile(r"[^a-z0-9_.\-]")

_MATMUL_STEMS = frozenset({"dot", "dot-general", "convolution", "conv"})
_COPY_STEMS = frozenset({
    "copy", "copy-start", "copy-done", "transpose", "bitcast", "reshape",
    "pad", "slice", "dynamic-slice", "dynamic-update-slice", "concatenate",
    "gather", "scatter", "broadcast", "reverse",
})
_ELEMENTWISE_STEMS = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "exponential", "exponential-minus-one", "exp", "log", "log-plus-one",
    "tanh", "logistic", "rsqrt", "sqrt", "power", "negate", "abs", "sign",
    "floor", "ceil", "round", "clamp", "compare", "select", "and", "or",
    "xor", "not", "convert", "reduce", "reduce-window", "reduce-precision",
    "map", "iota", "rng", "rng-bit-generator", "sine", "cosine",
    "is-finite", "shift-left", "shift-right-logical",
    "shift-right-arithmetic", "rem", "atan2", "cbrt", "expm1", "log1p",
})


def op_stem(name: str) -> str:
    """``'dot.3'`` -> ``'dot'``; ``'fusion.12.remat'`` -> ``'fusion'``."""
    return name.lstrip("%").split(".")[0]


def classify_op(name: str) -> Optional[str]:
    """Attribution category for one event name, or ``None`` when the name
    is not an HLO op (runtime scaffolding, host python frames)."""
    if not name or _NON_HLO_CHAR_RE.search(name):
        return None
    if COLLECTIVE_RE.search(name):
        return "collective"
    stem = op_stem(name)
    if (stem in _MATMUL_STEMS or "gemm" in stem or "matmul" in stem
            or "einsum" in stem or "attention" in stem):
        # Pallas attention kernels (flash/paged/chunked_prefill) surface
        # as custom-call events named after the kernel fn — their cycles
        # are MXU work.
        return "matmul"
    if stem in _COPY_STEMS:
        return "copy"
    if stem in _ELEMENTWISE_STEMS or "fusion" in stem or "adam" in stem:
        # fused_adam_update_kernel: one VPU pass over the flat blocks.
        return "elementwise"
    return "other"


# ---------------------------------------------------------------------------
# Loading (shared by trace_report / fleet_report / devicetime)
# ---------------------------------------------------------------------------

def open_trace(path: str) -> Dict[str, Any]:
    """Load a Chrome-trace document — plain ``.json`` or gzipped
    ``.json.gz`` — normalising the bare-array variant to a dict."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    if isinstance(doc, list):
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a Chrome trace (dict or list)")
    events = doc.get("traceEvents", [])
    if not isinstance(events, list):
        raise ValueError(f"{path}: traceEvents is not a list")
    return doc


# trace_report's historical name for the same load.
load_doc = open_trace


def load_events(path: str) -> List[Dict[str, Any]]:
    return open_trace(path)["traceEvents"]


def host_label(path: str, doc: Dict[str, Any]) -> str:
    """Source-host label: trace metadata first, then the
    ``<stem>.<host>.json`` filename component, then the file stem."""
    host = (doc.get("metadata") or {}).get("host")
    if host:
        return str(host)
    stem = os.path.basename(path)
    if stem.endswith(".json"):
        stem = stem[:-len(".json")]
    parts = stem.split(".")
    return parts[-1] if len(parts) > 1 else stem


def load_many(paths: List[str]) -> List[Dict[str, Any]]:
    """Load several trace files into one event list, each event's name
    prefixed with its source host."""
    events: List[Dict[str, Any]] = []
    for path in paths:
        doc = open_trace(path)
        label = host_label(path, doc)
        for ev in doc["traceEvents"]:
            if "name" in ev and ev.get("ph") != "M":
                ev = dict(ev)
                ev["name"] = f"{label}:{ev['name']}"
            events.append(ev)
    return events


def expand_paths(args_traces: List[str]) -> List[str]:
    """Expand glob patterns (quoted globs reach us unexpanded) and keep
    explicit paths as-is."""
    out: List[str] = []
    for t in args_traces:
        matches = sorted(_glob.glob(t))
        out.extend(matches if matches else [t])
    return out


# ---------------------------------------------------------------------------
# Span summaries (formerly tools/trace_report.py)
# ---------------------------------------------------------------------------

def percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo)


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-span-name totals / percentiles, counter last-values, instant
    counts — the trace_report table's data."""
    spans: Dict[str, List[float]] = {}
    counters: Dict[str, float] = {}
    instants: Dict[str, int] = {}
    for ev in events:
        ph = ev.get("ph")
        name = ev.get("name", "<unnamed>")
        if ph == "X":
            spans.setdefault(name, []).append(float(ev.get("dur", 0.0)))
        elif ph == "C":
            args = ev.get("args") or {}
            # last write wins: counters carry running totals
            for k, v in args.items():
                counters[name if k == "value" else f"{name}.{k}"] = float(v)
        elif ph == "i" or ph == "I":
            instants[name] = instants.get(name, 0) + 1
    rows = []
    for name, durs in spans.items():
        durs.sort()
        total = sum(durs)
        rows.append({
            "name": name,
            "count": len(durs),
            "total_ms": total / 1e3,
            "mean_ms": total / len(durs) / 1e3,
            "p50_ms": percentile(durs, 50) / 1e3,
            "p99_ms": percentile(durs, 99) / 1e3,
        })
    grand = sum(r["total_ms"] for r in rows) or 1.0
    for r in rows:
        r["share"] = r["total_ms"] / grand
    return {"spans": rows, "counters": counters, "instants": instants}


# ---------------------------------------------------------------------------
# Interval math
# ---------------------------------------------------------------------------

def merge_intervals(ivs: List[Tuple[float, float]]) -> \
        List[Tuple[float, float]]:
    """Sorted union of (start, end) intervals."""
    out: List[Tuple[float, float]] = []
    for s, e in sorted(ivs):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def interval_total(merged: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in merged)


def uncovered_time(iv: Tuple[float, float],
                   merged: List[Tuple[float, float]]) -> float:
    """Length of ``iv`` not covered by the merged interval union — the
    exposed share of one collective against the compute union."""
    s, e = iv
    if e <= s:
        return 0.0
    covered = 0.0
    for ms, me in merged:
        if me <= s:
            continue
        if ms >= e:
            break
        covered += min(e, me) - max(s, ms)
    return (e - s) - covered


def uncovered_segments(iv: Tuple[float, float],
                       merged: List[Tuple[float, float]]
                       ) -> List[Tuple[float, float]]:
    """The contiguous pieces of ``iv`` not covered by the merged
    interval union. ``sum(e - s) == uncovered_time(iv, merged)`` by
    construction; the LONGEST piece is the overlap-quality signal the
    grad-sync A/B probe reads (tools/probe_comm.py): a GAS-boundary
    sync exposes one long contiguous collective block, the overlapped
    schedule splits it into per-microstep slivers."""
    s, e = iv
    if e <= s:
        return []
    out: List[Tuple[float, float]] = []
    cur = s
    for ms, me in merged:
        if me <= cur:
            continue
        if ms >= e:
            break
        if ms > cur:
            out.append((cur, min(ms, e)))
        cur = max(cur, me)
        if cur >= e:
            break
    if cur < e:
        out.append((cur, e))
    return out


# ---------------------------------------------------------------------------
# jax.profiler capture analysis (device-time attribution)
# ---------------------------------------------------------------------------

def _empty_analysis() -> Dict[str, Any]:
    return {
        "categories": {c: 0.0 for c in CATEGORIES},
        "ops": {},
        "busy_sec": 0.0,
        "window_sec": 0.0,
        "gap_sec": 0.0,
        "collective_sec": 0.0,
        "exposed_collective_sec": 0.0,
        "max_exposed_segment_sec": 0.0,
        "n_devices": 0,
        "n_events": 0,
        "captures": [],
    }


def analyze_capture_doc(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Device-time attribution of one capture document.

    Classifies every HLO-op duration event into :data:`CATEGORIES` and
    computes, per device row (pid — a ``/device:...`` process when the
    capture has any, else every process, the CPU-backend layout):

    - ``busy_sec``: union of op intervals across the device's streams
      (device-seconds; concurrent streams don't double-count);
    - ``window_sec``: first-op to last-op span (the capture's device
      timeline);
    - ``gap_sec``: ``window - busy`` — host-dispatch gaps between ops;
    - ``exposed_collective_sec``: the UNION of the device's collective
      intervals minus the union of its *non-collective* op intervals —
      wall time where a collective is on the wire and no compute hides
      it, the measured exposed-comm ground truth. Union semantics (not
      per-event sums) so N streams running the same collective
      concurrently — the CPU backend's one-process-many-shards layout —
      count the wall time once; ``exposed <= window`` by construction.

    Per-category and per-op seconds are straight duration sums
    (device-seconds); all quantities aggregate across devices like the
    fleet's per-host rows sum across chips.
    """
    out = _empty_analysis()
    events = doc.get("traceEvents") or []
    device_pids = set()
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            nm = str((ev.get("args") or {}).get("name", ""))
            if nm.startswith("/device:"):
                device_pids.add(ev.get("pid"))
    per_pid: Dict[Any, List[Tuple[float, float, str, str]]] = \
        collections.defaultdict(list)
    for ev in events:
        if ev.get("ph") != "X":
            continue
        if device_pids and ev.get("pid") not in device_pids:
            continue
        name = ev.get("name", "")
        cat = classify_op(name)
        if cat is None:
            continue
        try:
            ts = float(ev.get("ts", 0.0)) / 1e6
            dur = float(ev.get("dur", 0.0)) / 1e6
        except (TypeError, ValueError):
            continue
        if dur <= 0:
            continue
        per_pid[ev.get("pid")].append((ts, ts + dur, cat, name))
    for pid, rows in per_pid.items():
        compute, everything, collectives = [], [], []
        for s, e, cat, name in rows:
            dur = e - s
            out["categories"][cat] += dur
            op = out["ops"].setdefault(
                name, {"sec": 0.0, "count": 0, "category": cat})
            op["sec"] += dur
            op["count"] += 1
            out["n_events"] += 1
            everything.append((s, e))
            if cat == "collective":
                collectives.append((s, e))
                out["collective_sec"] += dur
            else:
                compute.append((s, e))
        comp_merged = merge_intervals(compute)
        all_merged = merge_intervals(everything)
        busy = interval_total(all_merged)
        span = (all_merged[-1][1] - all_merged[0][0]) if all_merged else 0.0
        out["busy_sec"] += busy
        out["window_sec"] += span
        out["gap_sec"] += max(0.0, span - busy)
        for iv in merge_intervals(collectives):
            for us, ue in uncovered_segments(iv, comp_merged):
                out["exposed_collective_sec"] += ue - us
                out["max_exposed_segment_sec"] = max(
                    out["max_exposed_segment_sec"], ue - us)
    out["n_devices"] = len(per_pid)
    return out


def collective_burstiness(doc: Dict[str, Any], op_filter: str = "all-to-all",
                          win_frac: float = 0.05) -> float:
    """How concentrated the matching collectives' wall time is: the max
    share of their total duration inside any contiguous
    ``win_frac``-of-capture span (windows anchored at each matching
    interval's start).

    The overlap A/B's schedule-geometry signal (tools/probe_comm.py): a
    GAS-boundary grad sync fires its whole DCN stage (`all-to-all`
    chains) in ONE burst — high burstiness — while the overlapped
    schedule spreads it across microsteps. Geometry, not contention: it
    reads event timestamps only, so it stays meaningful on the CPU
    backend where nothing can truly run concurrently. Returns 0.0 when
    no op matches."""
    match: List[Tuple[float, float]] = []
    allops: List[Tuple[float, float]] = []
    for ev in (doc.get("traceEvents") or []):
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "")
        if classify_op(name) is None:
            continue
        try:
            ts = float(ev.get("ts", 0.0)) / 1e6
            dur = float(ev.get("dur", 0.0)) / 1e6
        except (TypeError, ValueError):
            continue
        if dur <= 0:
            continue
        allops.append((ts, ts + dur))
        if op_filter in name:
            match.append((ts, ts + dur))
    if not match:
        return 0.0
    km = merge_intervals(match)
    am = merge_intervals(allops)
    window = am[-1][1] - am[0][0]
    if window <= 0:
        return 0.0
    w = window * win_frac
    total = sum(e - s for s, e in km)
    best = 0.0
    for s0, _ in km:
        inwin = sum(min(e, s0 + w) - max(s, s0)
                    for s, e in km if e > s0 and s < s0 + w)
        best = max(best, inwin / total if total else 0.0)
    return best


def collective_burstiness_dir(profile_dir: str,
                              op_filter: str = "all-to-all",
                              win_frac: float = 0.05) -> float:
    """Max :func:`collective_burstiness` over every ``*.trace.json.gz``
    under ``profile_dir`` (torn captures skipped)."""
    best = 0.0
    pattern = os.path.join(profile_dir, "**", "*.trace.json.gz")
    for path in sorted(_glob.glob(pattern, recursive=True)):
        try:
            best = max(best, collective_burstiness(
                open_trace(path), op_filter=op_filter, win_frac=win_frac))
        except (OSError, EOFError, ValueError, zlib.error):
            continue
    return best


def merge_analyses(analyses: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    out = _empty_analysis()
    for a in analyses:
        for c in CATEGORIES:
            out["categories"][c] += a["categories"].get(c, 0.0)
        for name, op in a["ops"].items():
            tgt = out["ops"].setdefault(
                name, {"sec": 0.0, "count": 0, "category": op["category"]})
            tgt["sec"] += op["sec"]
            tgt["count"] += op["count"]
        for k in ("busy_sec", "window_sec", "gap_sec", "collective_sec",
                  "exposed_collective_sec", "n_events"):
            out[k] += a[k]
        out["max_exposed_segment_sec"] = max(
            out["max_exposed_segment_sec"],
            a.get("max_exposed_segment_sec", 0.0))
        out["n_devices"] = max(out["n_devices"], a["n_devices"])
        out["captures"].extend(a.get("captures", []))
    return out


def parse_capture_path(path: str) -> Dict[str, Any]:
    a = analyze_capture_doc(open_trace(path))
    a["captures"] = [path]
    return a


def parse_capture_dir(profile_dir: str) -> Dict[str, Any]:
    """Merged device-time analysis over every ``*.trace.json.gz`` under
    ``profile_dir`` (recursive — jax.profiler nests
    ``plugins/profile/<date>/``). Torn/empty captures are tolerated: an
    unreadable file is skipped, an empty dir yields the zero analysis."""
    analyses = []
    pattern = os.path.join(profile_dir, "**", "*.trace.json.gz")
    for path in sorted(_glob.glob(pattern, recursive=True)):
        try:
            a = analyze_capture_doc(open_trace(path))
        except (OSError, EOFError, ValueError, zlib.error):
            continue
        a["captures"] = [os.path.relpath(path, profile_dir)]
        analyses.append(a)
    return merge_analyses(analyses)


def top_ops(analysis: Dict[str, Any], k: int = 10) -> List[Dict[str, Any]]:
    """The hottest-op table: top-``k`` ops by total device seconds — the
    Pallas-tier candidate list."""
    rows = [{"name": n, **op} for n, op in analysis["ops"].items()]
    rows.sort(key=lambda r: r["sec"], reverse=True)
    busy = analysis["busy_sec"] or 1.0
    for r in rows[:k]:
        r["share_of_busy"] = r["sec"] / busy
    return rows[:k]


def scan_profile_dir(profile_dir: str) -> Dict[str, Dict[str, float]]:
    """Measured collective vs total device time per capture file — the
    historical ``fleet_report --profile-dir`` output, byte-compatible
    (total = sum of ALL duration events, collective by
    :data:`COLLECTIVE_RE`)."""
    out: Dict[str, Dict[str, float]] = {}
    pattern = os.path.join(profile_dir, "**", "*.trace.json.gz")
    for path in sorted(_glob.glob(pattern, recursive=True)):
        try:
            doc = open_trace(path)
        except (OSError, EOFError, ValueError, zlib.error):
            continue
        total = coll = 0.0
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            dur = float(ev.get("dur", 0.0))
            total += dur
            if COLLECTIVE_RE.search(ev.get("name", "")):
                coll += dur
        rel = os.path.relpath(path, profile_dir)
        out[rel] = {"collective_ms": coll / 1e3, "total_ms": total / 1e3,
                    "collective_frac": (coll / total) if total > 0 else 0.0}
    return out
