"""Metrics registry — counters / gauges / histograms with tags and sinks.

One facade subsuming the two pre-existing scalar writers
(``utils/monitor.py``: ``MetricsJSONL`` and ``TensorboardMonitor``): every
subsystem emits through a :class:`MetricsRegistry` and the registry fans out
to whatever sinks are configured — JSONL (append-only, crash-tolerant),
tensorboard (via the existing monitor), or in-memory (tests/probes). With no
sinks attached every emit is a single attribute check, so an engine with
telemetry disabled pays nothing.

The row schema extends the established ``{tag, value, step}`` JSONL contract
(resilience metrics readers keep working) with ``kind`` and flattened tags,
so one file serves counters, gauges and histogram observations alike.
"""

import bisect
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger


class Sink:
    """Sink interface: receives every metric emission."""

    def emit(self, kind: str, name: str, value: float, step: int,
             tags: Dict[str, Any]) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class JSONLSink(Sink):
    """Append-only JSONL rows ``{tag, value, step, kind, ...tags}`` (the
    ``MetricsJSONL`` schema plus kind/tags — readers of the old schema parse
    these rows unchanged)."""

    def __init__(self, path: str):
        from deepspeed_tpu.utils.monitor import MetricsJSONL
        self._jsonl = MetricsJSONL(path)
        self.path = path

    def emit(self, kind, name, value, step, tags):
        self._jsonl.add_scalar(name, value, step, kind=kind, **tags)

    def flush(self):
        self._jsonl.flush()

    def close(self):
        self._jsonl.close()


class TensorboardSink(Sink):
    """Routes through a ``TensorboardMonitor`` (or any ``add_scalar`` object).
    Tags are folded into the tag path (``name[k=v]``) because TB scalars have
    no tag dimension."""

    def __init__(self, monitor):
        self.monitor = monitor

    def emit(self, kind, name, value, step, tags):
        if tags:
            suffix = ",".join(f"{k}={v}" for k, v in sorted(tags.items()))
            name = f"{name}[{suffix}]"
        self.monitor.add_scalar(name, value, step)

    def flush(self):
        self.monitor.flush()

    def close(self):
        self.monitor.close()


class InMemorySink(Sink):
    """Keeps every emission as a dict row — the test/probe sink."""

    def __init__(self):
        self.rows: List[Dict[str, Any]] = []

    def emit(self, kind, name, value, step, tags):
        row = {"kind": kind, "tag": name, "value": float(value),
               "step": int(step)}
        row.update(tags)
        self.rows.append(row)

    def values(self, name: str) -> List[float]:
        return [r["value"] for r in self.rows if r["tag"] == name]

    def tags(self) -> set:
        return {r["tag"] for r in self.rows}


class _Metric:
    def __init__(self, registry: "MetricsRegistry", name: str,
                 tags: Optional[Dict[str, Any]] = None):
        self._registry = registry
        self.name = name
        self.tags = dict(tags or {})


class Counter(_Metric):
    """Monotonic count; emits the RUNNING TOTAL (so the newest row is the
    current value and JSONL readers need no summing)."""

    def __init__(self, registry, name, tags=None):
        super().__init__(registry, name, tags)
        self.total = 0.0

    def inc(self, n: float = 1.0, step: Optional[int] = None, **tags) -> None:
        self.total += n
        self._registry._emit("counter", self.name, self.total, step,
                             {**self.tags, **tags})


class Gauge(_Metric):
    """Point-in-time value."""

    def __init__(self, registry, name, tags=None):
        super().__init__(registry, name, tags)
        self.value: Optional[float] = None

    def set(self, value: float, step: Optional[int] = None, **tags) -> None:
        self.value = float(value)
        self._registry._emit("gauge", self.name, self.value, step,
                             {**self.tags, **tags})


class Histogram(_Metric):
    """Distribution: every observation is emitted, and a bounded sorted
    reservoir keeps percentiles queryable host-side (``percentile``)."""

    def __init__(self, registry, name, tags=None, max_samples: int = 4096):
        super().__init__(registry, name, tags)
        self._sorted: List[float] = []
        self._max = int(max_samples)
        self.count = 0

    def observe(self, value: float, step: Optional[int] = None,
                **tags) -> None:
        value = float(value)
        self.count += 1
        if len(self._sorted) < self._max:
            bisect.insort(self._sorted, value)
        self._registry._emit("histogram", self.name, value, step,
                             {**self.tags, **tags})

    def percentile(self, q: float) -> float:
        """q in [0, 100]; linear interpolation over the reservoir."""
        if not self._sorted:
            raise ValueError(f"histogram {self.name!r} has no observations")
        s = self._sorted
        if len(s) == 1:
            return s[0]
        pos = (q / 100.0) * (len(s) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        return s[lo] + (s[hi] - s[lo]) * (pos - lo)

    def percentiles(self, qs: Sequence[float]) -> Tuple[float, ...]:
        return tuple(self.percentile(q) for q in qs)

    def reset(self) -> None:
        """Drop the host-side reservoir (e.g. to exclude warmup
        observations from percentiles). Rows already emitted to sinks
        are untouched."""
        self._sorted.clear()
        self.count = 0


class MetricsRegistry:
    """Named metrics + fan-out to sinks. Thread-safe: the checkpoint writer
    thread emits concurrently with the step loop."""

    def __init__(self, sinks: Optional[Iterable[Sink]] = None):
        self._sinks: List[Sink] = list(sinks or [])
        self._metrics: Dict[Tuple[str, str], Any] = {}
        self._lock = threading.Lock()
        self._step = 0

    # -- construction ---------------------------------------------------
    def add_sink(self, sink: Sink) -> Sink:
        self._sinks.append(sink)
        return sink

    @property
    def sinks(self) -> List[Sink]:
        return list(self._sinks)

    def _get(self, kind: str, cls, name: str, **kw):
        key = (kind, name)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = cls(self, name, **kw)
            return m

    def counter(self, name: str, **kw) -> Counter:
        return self._get("counter", Counter, name, **kw)

    def gauge(self, name: str, **kw) -> Gauge:
        return self._get("gauge", Gauge, name, **kw)

    def histogram(self, name: str, **kw) -> Histogram:
        return self._get("histogram", Histogram, name, **kw)

    # -- emission -------------------------------------------------------
    def set_step(self, step: int) -> None:
        """Default step stamped on emissions that don't pass one."""
        self._step = int(step)

    def _emit(self, kind: str, name: str, value: float,
              step: Optional[int], tags: Dict[str, Any]) -> None:
        if not self._sinks:
            return
        step = self._step if step is None else int(step)
        with self._lock:
            for sink in self._sinks:
                try:
                    sink.emit(kind, name, value, step, tags)
                except Exception as e:  # noqa: BLE001 — a broken sink must
                    # never take down the training loop it observes
                    logger.warning("telemetry sink %s failed on %s: %s",
                                   type(sink).__name__, name, e)

    def add_scalar(self, tag: str, value: float, step: int, **extra) -> None:
        """Monitor-compat facade: gauge semantics under the old signature,
        so ``monitor.add_scalar`` call sites migrate by renaming only."""
        self.gauge(tag).set(value, step=step, **extra)

    def flush(self) -> None:
        with self._lock:
            for sink in self._sinks:
                sink.flush()

    def close(self) -> None:
        with self._lock:
            for sink in self._sinks:
                sink.close()
            self._sinks = []
