"""Silent-recompilation detector.

An XLA recompilation is the single most expensive silent failure mode on
TPU: a jitted step that retraces because one input's shape / dtype /
sharding changed costs seconds to minutes of compile time *per occurrence*
and produces no error — the run just mysteriously crawls. The classic
triggers: a ragged final batch, a dataloader that pads to the longest
sequence in the batch, a host scalar passed as a python int (every new
value is a new constant → new program).

The detector fingerprints the *call signature XLA's jit cache keys on* —
every leaf's (path, shape, dtype, sharding) — per named step function:

- the FIRST fingerprint for a function is the expected one-time compile;
- a REPEATED fingerprint is a cache hit (silent, free);
- a NEW fingerprint after the first is a **retrace**: a loud warning names
  the function and the exact leaves that changed, the
  ``telemetry/recompiles`` counter increments, and the tracer gets an
  instant event so the retrace shows up in the Perfetto timeline at the
  step where it happened.

Fingerprinting is host-side tuple hashing over aval metadata — no device
work, no sync — so the per-step cost is linear in batch-tree leaf count
and safe to leave on.
"""

import threading
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

RECOMPILE_COUNTER = "telemetry/recompiles"


def _leaf_sig(path, leaf) -> Tuple[str, str, str, str]:
    """(path, shape, dtype, sharding) — the aval metadata jit keys on."""
    name = "/".join(str(getattr(k, "key", getattr(k, "name",
                                getattr(k, "idx", k)))) for k in path)
    shape = getattr(leaf, "shape", None)
    if shape is None:
        if isinstance(leaf, str):
            # Strings are how callers declare STATIC jit inputs (closure /
            # static_argnums values): the VALUE keys the cache.
            return (name, "static", leaf, "-")
        # Python number scalars: jit traces them weakly-typed; the TYPE is
        # the stable part of the signature (a new float value does not
        # retrace, a float-where-int-was does).
        return (name, "scalar", type(leaf).__name__, "-")
    dtype = str(getattr(leaf, "dtype", "-"))
    sharding = getattr(leaf, "sharding", None)
    spec = str(getattr(sharding, "spec", "-")) if sharding is not None \
        else "host"
    return (name, str(tuple(shape)), dtype, spec)


def tree_signature(*trees) -> Tuple[Tuple[str, str, str, str], ...]:
    import jax

    sig: List[Tuple[str, str, str, str]] = []
    for i, tree in enumerate(trees):
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        for path, leaf in flat:
            name, shape, dtype, spec = _leaf_sig(path, leaf)
            sig.append((f"arg{i}.{name}", shape, dtype, spec))
    return tuple(sig)


class RecompileDetector:
    """Per-function fingerprint cache + retrace accounting."""

    def __init__(self, registry=None, tracer=None, enabled: bool = True,
                 warn: bool = True):
        self.enabled = bool(enabled)
        self.warn = bool(warn)
        self.registry = registry
        self.tracer = tracer
        self._lock = threading.Lock()
        # fn -> {fingerprint-hash: signature-tuple}
        self._seen: Dict[str, Dict[int, Tuple]] = {}
        self.stats: Dict[str, Dict[str, int]] = {}

    def check(self, fn_name: str, *trees, step: Optional[int] = None) -> str:
        """Returns ``"compile"`` (expected first trace), ``"hit"`` (cached)
        or ``"retrace"`` (cache miss after the first — warned loudly)."""
        if not self.enabled:
            return "hit"
        sig = tree_signature(*trees)
        key = hash(sig)
        with self._lock:
            seen = self._seen.setdefault(fn_name, {})
            st = self.stats.setdefault(fn_name,
                                       {"compiles": 0, "retraces": 0})
            if key in seen:
                return "hit"
            first = not seen
            prev = next(reversed(seen.values())) if seen else None
            seen[key] = sig
            st["compiles"] += 1
            if first:
                return "compile"
            st["retraces"] += 1
        self._report(fn_name, prev, sig, step)
        return "retrace"

    def forget(self, fn_name: str) -> None:
        """Drop every fingerprint for ``fn_name`` so its next trace counts
        as the expected one-time compile, not a retrace. For EXPECTED
        recompilations only — today that is the in-process elastic world
        change (resilience/elastic.py), whose rebuilt step functions MUST
        recompile; warning about them would train operators to ignore the
        detector."""
        with self._lock:
            self._seen.pop(fn_name, None)

    # ------------------------------------------------------------------
    def _report(self, fn_name: str, prev: Optional[Tuple], sig: Tuple,
                step: Optional[int]) -> None:
        changed = self._diff(prev, sig)
        if self.registry is not None:
            self.registry.counter(RECOMPILE_COUNTER).inc(step=step,
                                                         fn=fn_name)
        if self.tracer is not None:
            self.tracer.instant("recompile", fn=fn_name,
                                changed=changed[:8])
        if self.warn:
            logger.warning(
                "RECOMPILATION DETECTED: jitted step %r retraced%s — XLA is "
                "recompiling this function (seconds-to-minutes of silent "
                "stall per occurrence). Changed inputs: %s. Stabilize input "
                "shapes/dtypes/shardings (pad ragged batches, drop the "
                "short final batch, pass host scalars as jnp arrays).",
                fn_name,
                f" at step {step}" if step is not None else "",
                "; ".join(changed[:8]) if changed else "<signature length>")

    @staticmethod
    def _diff(prev: Optional[Tuple], sig: Tuple) -> List[str]:
        if prev is None:
            return []
        prev_map = {e[0]: e for e in prev}
        out = []
        for entry in sig:
            old = prev_map.get(entry[0])
            if old is None:
                out.append(f"{entry[0]}: new leaf "
                           f"{entry[1]}/{entry[2]}/{entry[3]}")
            elif old != entry:
                out.append(
                    f"{entry[0]}: {old[1]}/{old[2]}/{old[3]} -> "
                    f"{entry[1]}/{entry[2]}/{entry[3]}")
        new_names = {e[0] for e in sig}
        out.extend(f"{e[0]}: leaf removed" for e in prev
                   if e[0] not in new_names)
        return out

    # ------------------------------------------------------------------
    def compiles(self, fn_name: str) -> int:
        return self.stats.get(fn_name, {}).get("compiles", 0)

    def retraces(self, fn_name: Optional[str] = None) -> int:
        if fn_name is not None:
            return self.stats.get(fn_name, {}).get("retraces", 0)
        return sum(s["retraces"] for s in self.stats.values())
