"""Device-time observatory — measured op-level attribution + roofline.

Every comm/compute number the stack reported before this module was
*modeled*: ``comm/exposed_frac`` comes from the grad-sync plan's bandwidth
model and ``engine/mfu`` from XLA ``cost_analysis`` over host-clock step
times. The ground truth sits in ``jax.profiler`` captures that only
hand-run probe scripts ever parsed. This module closes the loop
(docs/OBSERVABILITY.md "Device-time observatory"):

- **Production capture scheduling** — every ``every_steps`` committed
  steps the observatory starts a ``jax.profiler`` capture through the
  engine's :class:`~deepspeed_tpu.telemetry.tracer.StepTracer`, lets it
  run for ``capture_steps`` steps, stops it, parses the capture through
  the shared ``telemetry/traceparse.py`` and GCs all but the newest
  ``keep_last`` capture dirs — attribution runs unattended instead of via
  hand-run probes. Capture dirs are host-scoped (the PR 6
  ``metrics.<host>.jsonl`` convention) so multi-host captures on shared
  storage never collide.
- **Measured op-level attribution** — every HLO op in the capture lands
  in an attribution category (matmul / elementwise fusions / collectives
  / copies+transposes / other, plus the host-dispatch ``gap`` computed
  from the timeline union), emitted as ``devicetime/*`` gauges; the
  top-K hottest-op table names the Pallas-tier candidates (ROADMAP
  item 5).
- **Roofline classification** — the measured per-category time joins the
  step's ``cost_analysis`` flops/bytes (via the goodput accountant's
  :meth:`flops_info`): the step's operational intensity against the
  chip's ridge point classifies each category compute- vs HBM-bound, and
  ``devicetime/mfu_measured`` (flops over *measured device window* time)
  cross-checks the modeled ``engine/mfu``.
- **Measured comm exposure** — collective device time not overlapped by
  compute on the device's other streams becomes
  ``comm/measured_exposed_frac``; when it diverges from the modeled
  ``comm/exposed_frac`` by more than ``divergence_warn`` the observatory
  warns LOUDLY and drops a ``devicetime/divergence`` trace instant — a
  wrong bandwidth model must not silently steer ROADMAP item 1.

Zero-overhead contract (the PR 2/3/5/6/7 gate): ``telemetry.devicetime``
defaults off and :func:`build_devicetime` then returns ``None`` — the
engine holds ``devicetime = None`` and the hook is one attribute check.
Enabled, the steady-state per-step cost is two integer comparisons; all
real work (profiler start/stop, one device drain at capture close so the
capture brackets the issued work, parse, gauge emission, GC) happens at
capture boundaries, never on the in-between step path. The observatory
never touches the jitted step functions — the lowered step is
bit-identical with the block on or off.
"""

import os
import shutil
from typing import Any, Dict, List, Optional

from deepspeed_tpu.telemetry import traceparse
from deepspeed_tpu.telemetry.goodput import _atomic_write_json
from deepspeed_tpu.utils.logging import logger

BREAKDOWN_FILE = "devicetime_breakdown.json"
BREAKDOWN_FORMAT = 1
CAPTURE_PREFIX = "capture_step"

DIVERGENCE_INSTANT = "devicetime/divergence"

# Every metric tag this module can emit (the per-category gauges, the
# capture counter, the divergence instant and the measured exposed-comm
# gauge) — pinned against docs/OBSERVABILITY.md in BOTH directions by
# tests/test_doc_lint.py, like GOODPUT/FLEET/MEMORY_METRIC_TAGS.
DEVICETIME_METRIC_TAGS = frozenset(
    {f"devicetime/{c}_sec" for c in traceparse.CATEGORIES}
    | {"devicetime/gap_sec", "devicetime/busy_sec", "devicetime/window_sec",
       "devicetime/steps_captured", "devicetime/step_time_sec",
       "devicetime/mfu_measured", "devicetime/captures",
       DIVERGENCE_INSTANT, "comm/measured_exposed_frac"})


def roofline_verdicts(intensity: Optional[float],
                      ridge: float) -> Dict[str, str]:
    """Per-category compute- vs HBM-bound classification: the step's
    measured-time-weighted categories joined with its cost_analysis
    operational intensity (flops/byte) against the chip ridge point.
    Matmul inherits the program's intensity verdict (it owns ~all the
    flops); elementwise fusions and copies are bandwidth traffic by
    construction; collectives are network-bound — their fix is overlap
    (ROADMAP item 1), not arithmetic."""
    matmul = "unknown"
    if intensity is not None and ridge > 0:
        matmul = "compute-bound" if intensity >= ridge else "hbm-bound"
    return {"matmul": matmul, "elementwise": "hbm-bound",
            "copy": "hbm-bound", "collective": "network-bound",
            "other": "mixed"}


class DeviceTimeObservatory:
    """Capture scheduling + measured attribution for one engine.

    ``step_hook(step)`` is called once per committed step (from the
    engine's ``_emit_step_telemetry``); everything else is internal.
    """

    def __init__(self, dcfg, run_dir: str, telemetry=None, goodput=None,
                 host: Optional[str] = None):
        self.cfg = dcfg
        self.telemetry = telemetry
        self.goodput = goodput
        from deepspeed_tpu.telemetry.fleet import (default_host,
                                                   telemetry_host_component)
        self._host_part = host if host is not None \
            else telemetry_host_component()
        self.host = self._host_part or default_host()
        self.capture_root = os.path.join(run_dir, dcfg.dir)
        from deepspeed_tpu.telemetry.fleet import host_scoped_path
        self.breakdown_path = os.path.join(
            run_dir, host_scoped_path(BREAKDOWN_FILE, self._host_part))
        self._capture_dir: Optional[str] = None
        self._capture_start_step: Optional[int] = None
        self._own_dirs: List[str] = []
        self.captures_done = 0
        self.last_analysis: Optional[Dict[str, Any]] = None
        self.last_breakdown: Optional[Dict[str, Any]] = None

    # -- scheduling ------------------------------------------------------
    def step_hook(self, step: int) -> None:
        """Per committed step. Steady state is two int compares; profiler
        start/stop + parse happen only at capture boundaries."""
        if self._capture_dir is not None:
            if step - self._capture_start_step >= int(self.cfg.capture_steps):
                self._finish_capture(step)
        elif step > 0 and step % int(self.cfg.every_steps) == 0:
            self._start_capture(step)

    def _start_capture(self, step: int) -> None:
        tracer = getattr(self.telemetry, "tracer", None)
        if tracer is None or tracer.profiler_active:
            # A passthrough session (telemetry.trace.jax_profiler_dir) is
            # already running — scheduling must not fight it.
            return
        target = os.path.join(self.capture_root,
                              f"{CAPTURE_PREFIX}{step:08d}")
        started = tracer.start_jax_profiler(dir=target)
        if started is None:
            return
        # Track the HOST-SCOPED dir the tracer actually captured into
        # (root/<host> on multi-host runs): parsing/GC'ing the shared
        # root would ingest — and delete — other hosts' captures.
        self._capture_dir = started
        self._capture_start_step = step
        if started not in self._own_dirs:
            self._own_dirs.append(started)

    def _finish_capture(self, step: int) -> None:
        tracer = getattr(self.telemetry, "tracer", None)
        target, start_step = self._capture_dir, self._capture_start_step
        self._capture_dir = None
        self._capture_start_step = None
        try:
            # Drain the dispatch queue so the capture brackets exactly the
            # device work the captured steps issued (one sync per capture
            # close — never on the in-between step path).
            from deepspeed_tpu.utils import timer as _timer
            _timer._device_synchronize()
        except Exception:  # noqa: BLE001 — backend may be torn down
            pass
        if tracer is not None:
            tracer.stop_jax_profiler()
        steps_captured = max(1, step - start_step)
        try:
            analysis = traceparse.parse_capture_dir(target)
        except Exception as e:  # noqa: BLE001 — observability must never
            # take down the step loop it observes
            logger.warning("devicetime: capture parse failed: %s", e)
            return
        if not analysis["captures"] or analysis["window_sec"] <= 0:
            # A torn/empty capture (profiler failed to dump, no parseable
            # device events) must not overwrite the gauges with zeros —
            # and a zero measured_frac against a high modeled fraction
            # would fire a guaranteed-spurious divergence warning.
            logger.warning(
                "devicetime: capture at step %d produced no parseable "
                "device events (%s) — skipping emission", step, target)
            self._gc_captures()
            return
        self.captures_done += 1
        self.last_analysis = analysis
        self._emit(analysis, step, steps_captured)
        self._gc_captures()

    def _gc_captures(self) -> None:
        keep = int(self.cfg.keep_last)
        while len(self._own_dirs) > keep:
            victim = self._own_dirs.pop(0)
            shutil.rmtree(victim, ignore_errors=True)
            # Host-scoped capture: drop the shared per-step root too once
            # every host has GC'd its subdir (rmdir refuses non-empty).
            parent = os.path.dirname(victim)
            if os.path.basename(parent).startswith(CAPTURE_PREFIX):
                try:
                    os.rmdir(parent)
                except OSError:
                    pass

    # -- emission --------------------------------------------------------
    def _flops_info(self) -> Optional[Dict[str, Any]]:
        if self.goodput is None:
            return None
        return self.goodput.flops_info()

    def _gauge_value(self, tag: str) -> Optional[float]:
        tel = self.telemetry
        if tel is None:
            return None
        v = tel.registry.gauge(tag).value
        return float(v) if v is not None else None

    def _emit(self, analysis: Dict[str, Any], step: int,
              steps_captured: int) -> None:
        tel = self.telemetry
        if tel is None or not getattr(tel, "enabled", False):
            return
        reg = tel.registry
        for cat in traceparse.CATEGORIES:
            reg.gauge(f"devicetime/{cat}_sec").set(
                analysis["categories"][cat], step=step)
        reg.gauge("devicetime/gap_sec").set(analysis["gap_sec"], step=step)
        reg.gauge("devicetime/busy_sec").set(analysis["busy_sec"], step=step)
        reg.gauge("devicetime/window_sec").set(analysis["window_sec"],
                                               step=step)
        reg.gauge("devicetime/steps_captured").set(steps_captured, step=step)
        reg.counter("devicetime/captures").inc(step=step)

        # Measured step time: per-device window over the captured steps.
        n_dev = max(analysis["n_devices"], 1)
        step_time = (analysis["window_sec"] / n_dev / steps_captured
                     if analysis["window_sec"] > 0 else None)
        if step_time:
            reg.gauge("devicetime/step_time_sec").set(step_time, step=step)

        # Measured comm exposure vs the modeled gauge.
        window = analysis["window_sec"]
        measured_frac = (analysis["exposed_collective_sec"] / window
                         if window > 0 else 0.0)
        reg.gauge("comm/measured_exposed_frac").set(measured_frac, step=step)
        modeled_frac = self._gauge_value("comm/exposed_frac")
        if (modeled_frac is not None
                and abs(measured_frac - modeled_frac)
                > float(self.cfg.divergence_warn)):
            logger.warning(
                "devicetime: MEASURED exposed-comm fraction %.1f%% diverges "
                "from the modeled comm/exposed_frac %.1f%% by more than "
                "%.0f%% — the comm.ici_gbps/dcn_gbps bandwidth model (or "
                "the overlap assumption) is wrong; trust the capture.",
                100.0 * measured_frac, 100.0 * modeled_frac,
                100.0 * float(self.cfg.divergence_warn))
            tel.instant(DIVERGENCE_INSTANT, step=step,
                        measured=round(measured_frac, 4),
                        modeled=round(modeled_frac, 4))

        # Roofline + measured MFU (cost_analysis join).
        info = self._flops_info()
        mfu_measured = None
        intensity = None
        ridge = None
        if info is not None:
            from deepspeed_tpu.profiling.flops_profiler import (
                mfu as _mfu, peak_hbm_gbps, peak_tflops)
            peak = info.get("peak_tflops_per_chip")
            if peak is None:
                peak = peak_tflops(self._device_kind())
            hbm = float(self.cfg.hbm_gbps) if self.cfg.hbm_gbps \
                else peak_hbm_gbps(self._device_kind())
            ridge = (peak * 1e12) / (hbm * 1e9) if hbm > 0 else 0.0
            if info.get("bytes_per_step"):
                intensity = info["flops_per_step"] / info["bytes_per_step"]
            if step_time:
                mfu_measured = _mfu(info["flops_per_step"], step_time,
                                    n_chips=info["n_chips"],
                                    peak_tflops_per_chip=peak)
                reg.gauge("devicetime/mfu_measured").set(mfu_measured,
                                                         step=step)
        verdicts = roofline_verdicts(intensity, ridge or 0.0)

        hot = traceparse.top_ops(analysis, int(self.cfg.top_k))
        self.last_breakdown = {
            "format": BREAKDOWN_FORMAT,
            "step": int(step),
            "host": self.host,
            "steps_captured": int(steps_captured),
            "n_devices": analysis["n_devices"],
            "categories_sec": dict(analysis["categories"]),
            "gap_sec": analysis["gap_sec"],
            "busy_sec": analysis["busy_sec"],
            "window_sec": analysis["window_sec"],
            "step_time_sec": step_time,
            "top_ops": hot,
            "roofline": {
                "intensity_flops_per_byte": intensity,
                "ridge_flops_per_byte": ridge,
                "verdicts": verdicts,
            },
            "mfu_measured": mfu_measured,
            "mfu_modeled": self._gauge_value("engine/mfu"),
            "exposed_comm": {
                "collective_sec": analysis["collective_sec"],
                "exposed_sec": analysis["exposed_collective_sec"],
                "measured_frac": measured_frac,
                "modeled_frac": modeled_frac,
            },
            "captures": list(analysis.get("captures", [])),
        }
        try:
            _atomic_write_json(self.breakdown_path, self.last_breakdown)
        except OSError as e:
            logger.warning("devicetime breakdown write failed: %s", e)
        self._log_table(hot, verdicts, analysis, step)

    def _log_table(self, hot, verdicts, analysis, step) -> None:
        lines = [f"devicetime @ step {step}: busy "
                 f"{analysis['busy_sec'] * 1e3:.1f} ms, gap "
                 f"{analysis['gap_sec'] * 1e3:.1f} ms "
                 f"({analysis['n_devices']} device row(s))"]
        for cat in traceparse.CATEGORIES:
            sec = analysis["categories"][cat]
            if sec > 0:
                lines.append(f"  {cat:<12} {sec * 1e3:>10.2f} ms "
                             f"[{verdicts.get(cat, '?')}]")
        if hot:
            lines.append("  hottest ops (Pallas-tier candidates):")
            for r in hot:
                lines.append(f"    {r['name']:<32} {r['sec'] * 1e3:>9.2f} ms "
                             f"x{r['count']:<5} {r['category']} "
                             f"({r.get('share_of_busy', 0.0):.1%} of busy)")
        logger.info("%s", "\n".join(lines))

    def _device_kind(self) -> str:
        try:
            import jax
            return getattr(jax.devices()[0], "device_kind", "")
        except Exception:  # noqa: BLE001
            return ""


def build_devicetime(tcfg, telemetry=None, goodput=None) -> \
        Optional[DeviceTimeObservatory]:
    """``None`` unless telemetry AND its devicetime block are enabled —
    the engine's hook gates on ``is None`` (the zero-overhead contract,
    same shape as goodput/fleet/memory)."""
    if tcfg is None or not tcfg.enabled or not tcfg.devicetime.enabled:
        return None
    return DeviceTimeObservatory(tcfg.devicetime, run_dir=tcfg.dir,
                                 telemetry=telemetry, goodput=goodput)
