"""Fleet observability — cross-host aggregation + straggler detection.

PRs 2 and 5 gave every *process* rich telemetry; a multi-host run was
still N blind JSONL files. This module is the fleet-level layer
(docs/OBSERVABILITY.md "Fleet observability"):

- **cross-host metric aggregation** — at flush boundaries (off the step
  path) every host contributes a small fixed vector of scalars
  (:data:`FLEET_FIELDS`: mean step time, goodput category deltas, HBM
  peak, modeled exposed-comm seconds) to one tiny jitted all-gather over
  a dedicated one-axis device mesh (:func:`all_gather_rows` — one owner
  device per process). Host 0 emits ``fleet/*`` min / median / max /
  argmax-host gauges and rewrites the per-host breakdown file
  (``fleet_breakdown.json``) atomically.
- **straggler detection** — per-host step-time skew over a rolling
  window of flushes: a host whose windowed mean sits ``zscore`` robust
  (median/MAD) deviations above the fleet median (with a relative scale
  floor so a uniform fleet never false-positives) is named in a
  ``fleet/straggler`` trace instant,
  counted in ``telemetry/stragglers``, and booked as a
  ``goodput/straggler_sec`` time-lost sub-attribution (the fleet runs at
  the slowest host's pace; the excess over the median is the loss).
  Hosts flagged ``persist`` times are marked *persistent* in the
  breakdown file — the signal the elasticity supervisor (ROADMAP item 4)
  will act on; :func:`read_persistent_stragglers` is its reader.
- **device-time attribution feed** — engines with sync'd spans push the
  measured step-span duration through :meth:`FleetAggregator.
  note_step_time`, overriding the goodput host-clock estimate (the
  "sync'd sub-step spans" fallback for runs without a jax.profiler dir).

Zero-overhead contract (the PR 2/3/5 gate): ``telemetry.fleet`` defaults
off and ``build_fleet`` then returns ``None`` — the engine holds
``fleet = None`` and every hook is one attribute check: no extra device
syncs, no host fetches, no collective. Enabled, all device work happens
at the flush cadence, never on the step path.

jax is imported lazily (gather paths only) so the telemetry package stays
importable on jax-less report hosts.
"""

import collections
import os
import socket
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.telemetry.goodput import (TELEMETRY_HOST_ENV,
                                             _atomic_write_json)
from deepspeed_tpu.utils.logging import logger

# The fixed per-host scalar vector every flush gathers. Order is the wire
# layout — append only.
FLEET_FIELDS = (
    "step_time_sec",      # mean committed-step wall time since last flush
    "data_stall_sec",     # goodput data_stall delta since last flush
    "hbm_peak_bytes",     # max peak over this host's local devices
    "productive_sec",     # goodput productive_step delta since last flush
    "exposed_comm_sec",   # modeled exposed-collective seconds (delta)
    "hbm_headroom_bytes", # memory observatory headroom (min over devices;
                          # 0 = not reported: telemetry.memory off or no
                          # device bytes_limit)
    "grad_norm",          # numerics observatory global grad norm at the
                          # last flush (0 = not reported: telemetry.
                          # numerics off) — lets stragglers and numeric
                          # divergence correlate per host
)

# argmin_host is the headroom field's reason to exist — fleet/
# hbm_headroom_bytes_argmin_host NAMES the tightest host — and rides
# every field (the fastest host is as interesting as the slowest).
_FLEET_STATS = ("min", "median", "max", "argmax_host", "argmin_host")

STRAGGLER_COUNTER = "telemetry/stragglers"
STRAGGLER_INSTANT = "fleet/straggler"
BREAKDOWN_FORMAT = 1

# Every metric tag this module can emit (gauges, the straggler counter and
# the straggler trace-instant name) — pinned against docs/OBSERVABILITY.md
# in BOTH directions by tests/test_doc_lint.py, like GOODPUT_METRIC_TAGS.
FLEET_METRIC_TAGS = frozenset(
    {f"fleet/{f}_{s}" for f in FLEET_FIELDS for s in _FLEET_STATS}
    | {"fleet/hosts", STRAGGLER_INSTANT, STRAGGLER_COUNTER})

# Axis name of the throwaway gather mesh (never collides with model axes).
FLEET_GATHER_AXIS = "fleet_host"

# Hostname bytes gathered once so host 0 can NAME the argmax/straggler
# host instead of reporting an index.
_HOST_NAME_BYTES = 64


def default_host() -> str:
    """One convention with the goodput run manifest."""
    return (os.environ.get(TELEMETRY_HOST_ENV)
            or socket.gethostname().replace(os.sep, "_"))


def host_scoped_path(filename: str, host: Optional[str]) -> str:
    """Insert a ``.<host>.`` component before the extension. ``host=None``
    returns the name unchanged — the single-host compat alias, so
    existing runs/readers keep their stable ``metrics.jsonl`` /
    ``trace.json`` paths."""
    if not host:
        return filename
    root, ext = os.path.splitext(filename)
    return f"{root}.{host}{ext}" if ext else f"{filename}.{host}"


def telemetry_host_component() -> Optional[str]:
    """The ``.<host>.`` filename component for this process: ``None`` on
    single-process runs (bare filenames — the compat alias), the host
    name when the run spans processes (shared-storage outputs must not
    clobber each other) or when ``DSTPU_TELEMETRY_HOST`` forces it."""
    forced = os.environ.get(TELEMETRY_HOST_ENV)
    if forced:
        return forced
    try:
        import jax
        if jax.process_count() > 1:
            return default_host()
    except Exception:  # noqa: BLE001 — no backend: single-host semantics
        pass
    return None


# ---------------------------------------------------------------------------
# The tiny jitted cross-host collective
# ---------------------------------------------------------------------------

def fleet_owner_devices() -> List[Any]:
    """One owner device per process, in process order — the participants
    of the fleet gather (every process computes the same list)."""
    import jax

    per_proc: Dict[int, Any] = {}
    for d in sorted(jax.devices(), key=lambda d: (d.process_index, d.id)):
        per_proc.setdefault(d.process_index, d)
    return [per_proc[p] for p in sorted(per_proc)]


# (mesh, in-sharding, jitted gather) per (owners, n_cols): the jit cache
# lives on the wrapper, so rebuilding the lambda each flush would retrace
# and recompile the collective every time.
_GATHER_CACHE: Dict[Any, Any] = {}


def _gather_fns(owners: tuple, n_cols: int):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    key = (owners, n_cols)
    hit = _GATHER_CACHE.get(key)
    if hit is None:
        mesh = Mesh(np.array(owners, dtype=object), (FLEET_GATHER_AXIS,))
        sharded = NamedSharding(mesh, P(FLEET_GATHER_AXIS))
        gather = jax.jit(lambda x: x,
                         out_shardings=NamedSharding(mesh, P()))
        hit = _GATHER_CACHE[key] = (sharded, gather)
    return hit


def all_gather_rows(owners: Sequence[Any],
                    local_rows: Dict[int, np.ndarray]) -> np.ndarray:
    """All-gather one fixed-size fp32 row per participant through ONE
    jitted collective on a dedicated 1-axis mesh over ``owners`` (one
    device per participant). ``local_rows`` maps participant index ->
    [n] vector for the participants whose owner device is addressable
    from this process (all of them in single-process tests; exactly one
    in a real multi-host run). Returns the [n_hosts, n] matrix. The
    mesh + jitted identity (whose replicated out-sharding IS the
    all-gather) are cached per (owners, n_cols), so the collective
    compiles once and is reused at every flush."""
    import jax

    owners = tuple(owners)
    n_hosts = len(owners)
    rows = {int(i): np.asarray(v, np.float32).reshape(1, -1)
            for i, v in local_rows.items()}
    n_cols = next(iter(rows.values())).shape[1]
    sharded, gather = _gather_fns(owners, n_cols)
    shards = [jax.device_put(rows[i], owners[i]) for i in sorted(rows)]
    arr = jax.make_array_from_single_device_arrays(
        (n_hosts, n_cols), sharded, shards)
    out = gather(arr)
    return np.asarray(out.addressable_shards[0].data)


def _encode_host(name: str) -> np.ndarray:
    raw = name.encode("utf-8", errors="replace")[:_HOST_NAME_BYTES]
    vec = np.zeros((_HOST_NAME_BYTES,), np.float32)
    vec[:len(raw)] = np.frombuffer(raw, np.uint8)
    return vec


def _decode_host(row: np.ndarray) -> str:
    raw = bytes(int(b) for b in row if 0 < b < 256)
    return raw.decode("utf-8", errors="replace") or "unknown"


# ---------------------------------------------------------------------------
# Aggregator
# ---------------------------------------------------------------------------

class FleetAggregator:
    """Cross-host aggregation + straggler detection for one engine.

    ``flush(step)`` (called by the engine at the metrics-flush cadence,
    off the step path) collects this host's :data:`FLEET_FIELDS` deltas
    from the goodput accountant, all-gathers every host's vector, and —
    on the leader (process 0) — emits the ``fleet/*`` gauges, runs the
    straggler z-score, and rewrites the breakdown file. ``ingest`` is the
    gather-independent second half, driven directly by tests with
    synthetic matrices (the documented multi-host-without-multi-host
    seam)."""

    def __init__(self, fcfg, run_dir: Optional[str] = None,
                 telemetry=None, goodput=None, host: Optional[str] = None,
                 owners: Optional[Sequence[Any]] = None,
                 process_index: Optional[int] = None,
                 leader: Optional[bool] = None):
        self.cfg = fcfg
        self.run_dir = run_dir
        self.telemetry = telemetry
        self.goodput = goodput
        self.host = host or default_host()
        self._owners = list(owners) if owners is not None else None
        self._process_index = process_index
        self._leader = leader
        self._host_names: Optional[List[str]] = None
        self._window: collections.deque = collections.deque(
            maxlen=int(fcfg.window))
        self.straggler_counts: Dict[str, int] = {}
        # Cumulative fleet-level seconds lost to each host's skew (the
        # sum of verdict lost_sec) — the eviction cost model's evidence
        # (resilience/elastic.py) and a breakdown-file column.
        self.straggler_lost: Dict[str, float] = {}
        # Newest windowed per-step excess per host — the rate input of
        # the eviction cost model (same units the in-process coordinator
        # reads from verdict["lost_sec_per_step"]).
        self.straggler_rate: Dict[str, float] = {}
        self.last_verdict: Optional[Dict[str, Any]] = None
        self._prev: Optional[Dict[str, float]] = None
        # sync'd-span step-time feed (sum, count) since the last flush —
        # when present it overrides the goodput host-clock estimate.
        self._span_sum = 0.0
        self._span_count = 0

    # -- topology (lazy: first flush, after the backend surely exists) ---
    def _topology(self):
        if self._owners is None:
            self._owners = fleet_owner_devices()
        if self._process_index is None:
            import jax
            self._process_index = jax.process_index()
        if self._leader is None:
            self._leader = self._process_index == 0
        return self._owners, self._process_index

    # -- local collection ------------------------------------------------
    def note_step_time(self, seconds: float) -> None:
        """Feed one sync'd step-span duration (the measured device step
        time) — better than the goodput host-clock delta when available."""
        if seconds and seconds > 0:
            self._span_sum += float(seconds)
            self._span_count += 1

    def collect_local(self) -> Optional[np.ndarray]:
        """This host's :data:`FLEET_FIELDS` vector for the interval since
        the previous flush (None before any committed step). Pure host
        reads — goodput totals, registry gauge values, span feeds."""
        g = self.goodput
        if g is None:
            return None
        totals = g.totals()
        ssum, scount = g.step_time_stats()
        aux = g.aux_totals()
        cur = {
            "step_sum": ssum, "step_count": float(scount),
            "data_stall": totals.get("data_stall", 0.0),
            "productive": totals.get("productive_step", 0.0),
            "exposed": aux.get("exposed_comm_sec", 0.0),
        }
        prev = self._prev or {k: 0.0 for k in cur}
        self._prev = cur
        d_count = cur["step_count"] - prev["step_count"]
        span_count = self._span_count
        if d_count <= 0 and span_count == 0:
            return None                       # nothing stepped since last
        if span_count:
            step_time = self._span_sum / span_count
        else:
            step_time = (cur["step_sum"] - prev["step_sum"]) / d_count
        self._span_sum = 0.0
        self._span_count = 0
        # Committed-step count is authoritative (an engine may note more
        # than one sync'd span per step — e.g. pipe_step + train_step).
        self._steps_delta = d_count if d_count > 0 else 1.0
        hbm = headroom = grad_norm = 0.0
        tel = self.telemetry
        if tel is not None:
            v = tel.registry.gauge("engine/hbm_peak_bytes").value
            hbm = float(v) if v else 0.0
            # Set by the memory observatory (telemetry/memory.py) when
            # telemetry.memory is on AND the devices report bytes_limit;
            # 0 otherwise — the breakdown/report treat 0 as "not
            # reported", never as "no headroom".
            h = tel.registry.gauge("memory/hbm_headroom_bytes").value
            headroom = float(h) if h else 0.0
            # Set by the numerics observatory just before this gather
            # (the engine flushes numerics first); already sanitised to
            # a finite value there, but guard anyway — one NaN row would
            # poison every host's median.
            g = tel.registry.gauge("numerics/global_grad_norm").value
            grad_norm = float(g) if g and np.isfinite(g) else 0.0
        return np.array([
            step_time,
            max(0.0, cur["data_stall"] - prev["data_stall"]),
            hbm,
            max(0.0, cur["productive"] - prev["productive"]),
            max(0.0, cur["exposed"] - prev["exposed"]),
            headroom,
            grad_norm,
        ], np.float32)

    # -- the flush-boundary hook ----------------------------------------
    def flush(self, step: int) -> Optional[Dict[str, Any]]:
        vec = self.collect_local()
        if vec is None:
            return None
        try:
            owners, pidx = self._topology()
            if self._host_names is None:
                names = all_gather_rows(
                    owners, self._addressable_rows(owners, pidx,
                                                   _encode_host(self.host)))
                self._host_names = [_decode_host(r) for r in names]
            matrix = all_gather_rows(
                owners, self._addressable_rows(owners, pidx, vec))
        except Exception as e:  # noqa: BLE001 — observability must never
            # take down the step loop it observes
            logger.warning("fleet gather failed: %s", e)
            return None
        return self.ingest(step, matrix, hosts=self._host_names,
                           steps_delta=getattr(self, "_steps_delta", 1.0))

    def _addressable_rows(self, owners, pidx, vec) -> Dict[int, np.ndarray]:
        """Single-process: every participant's shard is addressable and
        must be supplied (they all carry this host's row — there IS only
        one host). Multi-process: exactly this process's row."""
        addressable = {i for i, d in enumerate(owners)
                       if getattr(d, "process_index", 0) == pidx}
        return {i: vec for i in (addressable or {pidx})}

    # -- aggregation + straggler verdicts (gather-independent) -----------
    def ingest(self, step: int, matrix: np.ndarray,
               hosts: Optional[Sequence[str]] = None,
               steps_delta: float = 1.0) -> Dict[str, Any]:
        matrix = np.asarray(matrix, np.float64)
        n_hosts = matrix.shape[0]
        hosts = (list(hosts) if hosts
                 else [f"host{i}" for i in range(n_hosts)])
        # flush() resolves the topology before calling; a direct ingest
        # (tests, report tooling) defaults to leader semantics.
        leader = True if self._leader is None else bool(self._leader)
        stats: Dict[str, Dict[str, Any]] = {}
        # Tolerate matrices narrower than FLEET_FIELDS: the wire layout
        # is append-only, so rows gathered from an older writer simply
        # lack the trailing fields (no stats for them).
        for j, field in enumerate(FLEET_FIELDS[:matrix.shape[1]]):
            col = matrix[:, j]
            amax = int(np.argmax(col))
            amin = int(np.argmin(col))
            stats[field] = {"min": float(col.min()),
                            "median": float(np.median(col)),
                            "max": float(col.max()),
                            "argmax_host": amax,
                            "argmax_host_name": hosts[amax],
                            "argmin_host": amin,
                            "argmin_host_name": hosts[amin]}
        verdict = self._detect_straggler(step, matrix[:, 0], hosts,
                                         steps_delta)
        if leader:
            self._emit(step, n_hosts, stats, verdict)
            self._write_breakdown(step, matrix, hosts, stats)
        return {"step": step, "hosts": hosts, "stats": stats,
                "straggler": verdict}

    def _detect_straggler(self, step, step_times, hosts, steps_delta):
        self._window.append(np.asarray(step_times, np.float64))
        if (len(self._window) < int(self.cfg.min_window)
                or len(hosts) < 2):
            return None
        means = np.mean(np.stack(list(self._window)), axis=0)
        # Robust (median/MAD) z-score: a population std would include the
        # outlier itself, capping max-z at ~sqrt(n_hosts-1) — a 2x
        # straggler in a 4-host fleet would never cross 3. The relative
        # scale floor (5% of the median step time) keeps a near-uniform
        # fleet from flagging its marginally-slowest host (same idea as
        # the guardrails detector's sigma floor).
        med = float(np.median(means))
        mad = float(np.median(np.abs(means - med))) * 1.4826
        z = (means - med) / max(mad, 0.05 * max(med, 1e-12), 1e-12)
        worst = int(np.argmax(z))
        if z[worst] < float(self.cfg.zscore):
            self.last_verdict = None
            return None
        host = hosts[worst]
        self.straggler_counts[host] = self.straggler_counts.get(host, 0) + 1
        verdict = {"host": host, "index": worst,
                   "zscore": float(z[worst]),
                   "count": self.straggler_counts[host],
                   "persistent": (self.straggler_counts[host]
                                  >= int(self.cfg.persist)),
                   # The fleet steps at the slowest host's pace: excess
                   # over the median, over the flushed steps, is the
                   # fleet-level time lost to this straggler.
                   "lost_sec": float(max(0.0, step_times[worst]
                                         - np.median(step_times))
                                     * max(steps_delta, 1.0)),
                   # The windowed per-step excess — the eviction cost
                   # model's rate input (lost seconds per future step if
                   # the straggler stays).
                   "lost_sec_per_step": float(max(0.0, means[worst] - med))}
        self.straggler_lost[host] = (self.straggler_lost.get(host, 0.0)
                                     + verdict["lost_sec"])
        self.straggler_rate[host] = verdict["lost_sec_per_step"]
        self.last_verdict = verdict
        return verdict

    def _emit(self, step, n_hosts, stats, verdict) -> None:
        tel = self.telemetry
        if tel is None or not getattr(tel, "enabled", False):
            return
        reg = tel.registry
        for field, s in stats.items():
            for stat in _FLEET_STATS:
                reg.gauge(f"fleet/{field}_{stat}").set(float(s[stat]),
                                                       step=step)
        reg.gauge("fleet/hosts").set(n_hosts, step=step)
        if verdict is not None:
            tel.instant(STRAGGLER_INSTANT, host=verdict["host"],
                        zscore=round(verdict["zscore"], 3), step=step,
                        persistent=verdict["persistent"])
            reg.counter(STRAGGLER_COUNTER).inc(step=step,
                                               host=verdict["host"])
            if self.goodput is not None and verdict["lost_sec"] > 0:
                self.goodput.note_aux("straggler_sec", verdict["lost_sec"])

    def _write_breakdown(self, step, matrix, hosts, stats) -> None:
        if not self.run_dir:
            return
        doc = {
            "format": BREAKDOWN_FORMAT,
            "step": int(step),
            "hosts": list(hosts),
            "fields": {f: [float(v) for v in matrix[:, j]]
                       for j, f in enumerate(
                           FLEET_FIELDS[:matrix.shape[1]])},
            "stats": stats,
            "stragglers": {
                h: {"count": c,
                    "persistent": c >= int(self.cfg.persist),
                    "lost_sec": self.straggler_lost.get(h, 0.0),
                    "lost_sec_per_step": self.straggler_rate.get(h, 0.0),
                    "last_zscore": (self.last_verdict["zscore"]
                                    if self.last_verdict is not None
                                    and self.last_verdict["host"] == h
                                    else None)}
                for h, c in self.straggler_counts.items()},
            "window": len(self._window),
            "zscore_threshold": float(self.cfg.zscore),
        }
        try:
            _atomic_write_json(
                os.path.join(self.run_dir, self.cfg.breakdown_file), doc)
        except OSError as e:
            logger.warning("fleet breakdown write failed: %s", e)


def build_fleet(tcfg, telemetry=None, goodput=None) -> \
        Optional[FleetAggregator]:
    """``None`` unless telemetry AND its fleet block are enabled — the
    engine's hooks gate on ``is None`` (the zero-overhead contract, same
    shape as goodput/guardrails). Fleet aggregation reads the goodput
    accountant's deltas; ``TelemetryConfig.from_dict`` already rejects
    ``fleet.enabled`` without goodput, and a hand-built config that
    bypasses validation degrades safely (``collect_local`` returns None
    when ``goodput`` is None, so ``flush`` no-ops)."""
    if tcfg is None or not tcfg.enabled or not tcfg.fleet.enabled:
        return None
    return FleetAggregator(tcfg.fleet, run_dir=tcfg.dir,
                           telemetry=telemetry, goodput=goodput)


def read_straggler_evidence(run_dir: str) -> Dict[str, Dict[str, Any]]:
    """Per-host straggler evidence from the fleet breakdown file(s):
    ``{host: {count, persistent, lost_sec, last_zscore}}`` — what the
    supervisor's eviction decision (resilience/elastic.py cost model)
    and the in-process coordinator read. Best-effort: unreadable files
    are skipped; the newest file's entry wins per host."""
    import glob as _glob
    import json as _json

    out: Dict[str, Dict[str, Any]] = {}
    for path in sorted(_glob.glob(os.path.join(run_dir,
                                               "fleet_breakdown*.json"))):
        try:
            with open(path) as f:
                doc = _json.load(f)
        except (OSError, ValueError):
            continue
        for host, info in (doc.get("stragglers") or {}).items():
            out[host] = {
                "count": int(info.get("count") or 0),
                "persistent": bool(info.get("persistent")),
                "lost_sec": float(info.get("lost_sec") or 0.0),
                "lost_sec_per_step": float(
                    info.get("lost_sec_per_step") or 0.0),
                "last_zscore": info.get("last_zscore"),
            }
    return out


def read_persistent_stragglers(run_dir: str) -> List[str]:
    """Hosts marked persistent in any fleet breakdown file under
    ``run_dir`` — the supervisor's (and, later, the elasticity
    policy's) reader. Best-effort: unreadable files are skipped."""
    import glob as _glob
    import json as _json

    out = set()
    for path in sorted(_glob.glob(os.path.join(run_dir,
                                               "fleet_breakdown*.json"))):
        try:
            with open(path) as f:
                doc = _json.load(f)
        except (OSError, ValueError):
            continue
        for host, info in (doc.get("stragglers") or {}).items():
            if info.get("persistent"):
                out.add(host)
    return sorted(out)
