"""Unified observability layer (docs/OBSERVABILITY.md).

Three pieces behind one facade:

- :class:`MetricsRegistry` — counters / gauges / histograms with tags and
  pluggable sinks (JSONL, tensorboard, in-memory);
- :class:`StepTracer` — Chrome trace-event spans (Perfetto-viewable) with
  device-sync barriers gated on the tracer being enabled;
- :class:`RecompileDetector` — fingerprints jitted-step inputs and warns
  loudly when the same step function silently retraces.

``build_telemetry(config.telemetry, ...)`` wires all three from the
``telemetry`` config block; a disabled block yields the same facade with
every path no-op'd (zero sinks, reusable null span, detector off), so call
sites never branch on "is telemetry on".
"""

import os
from typing import Optional

from deepspeed_tpu.telemetry.devicetime import (DEVICETIME_METRIC_TAGS,
                                                DeviceTimeObservatory,
                                                build_devicetime)
from deepspeed_tpu.telemetry.fleet import (FLEET_METRIC_TAGS, FleetAggregator,
                                           build_fleet, default_host,
                                           host_scoped_path,
                                           telemetry_host_component)
from deepspeed_tpu.telemetry.goodput import (GOODPUT_METRIC_TAGS,
                                             GoodputAccountant,
                                             build_goodput)
from deepspeed_tpu.telemetry.goodput import CATEGORIES as GOODPUT_CATEGORIES
from deepspeed_tpu.telemetry.memory import (MEMORY_METRIC_TAGS,
                                            MemoryObservatory,
                                            build_memory_observatory,
                                            collect_memory_snapshot,
                                            model_state_ledger,
                                            plan_capacity)
from deepspeed_tpu.telemetry.numerics import (NUMERICS_METRIC_TAGS,
                                              NumericsObservatory,
                                              NumericsPlan,
                                              build_numerics)
from deepspeed_tpu.telemetry.requests import (ENGINE_CATEGORIES,
                                              REQUEST_CATEGORIES,
                                              REQUEST_METRIC_TAGS,
                                              RequestAccountant,
                                              build_requests)
from deepspeed_tpu.telemetry.recompile import (RECOMPILE_COUNTER,
                                               RecompileDetector,
                                               tree_signature)
from deepspeed_tpu.telemetry.registry import (Counter, Gauge, Histogram,
                                              InMemorySink, JSONLSink,
                                              MetricsRegistry, Sink,
                                              TensorboardSink)
from deepspeed_tpu.telemetry.tracer import StepTracer

__all__ = [
    "Counter", "DEVICETIME_METRIC_TAGS", "DeviceTimeObservatory",
    "ENGINE_CATEGORIES", "FLEET_METRIC_TAGS", "FleetAggregator", "Gauge",
    "GOODPUT_CATEGORIES", "GOODPUT_METRIC_TAGS", "GoodputAccountant",
    "Histogram", "InMemorySink", "JSONLSink", "MEMORY_METRIC_TAGS",
    "MemoryObservatory", "MetricsRegistry", "NUMERICS_METRIC_TAGS",
    "NumericsObservatory", "NumericsPlan",
    "RecompileDetector", "RECOMPILE_COUNTER",
    "REQUEST_CATEGORIES", "REQUEST_METRIC_TAGS", "RequestAccountant",
    "Sink", "StepTracer",
    "Telemetry", "TensorboardSink", "build_devicetime", "build_fleet",
    "build_goodput", "build_memory_observatory", "build_numerics",
    "build_requests", "build_telemetry",
    "collect_memory_snapshot", "default_host", "host_scoped_path",
    "model_state_ledger", "null_telemetry", "plan_capacity",
    "telemetry_host_component", "tree_signature",
]


class Telemetry:
    """The facade the engines hold: ``.registry``, ``.tracer``,
    ``.recompile`` plus convenience passthroughs."""

    def __init__(self, registry: MetricsRegistry, tracer: StepTracer,
                 recompile: RecompileDetector, enabled: bool = True):
        self.registry = registry
        self.tracer = tracer
        self.recompile = recompile
        self.enabled = bool(enabled)
        # Path of the JSONL metrics sink (None without one) — the
        # authoritative answer now that multi-host runs host-scope the
        # filename; consumers (guardrails crashdump tail) read it instead
        # of re-deriving the path from the config.
        self.metrics_path = next(
            (s.path for s in registry.sinks if isinstance(s, JSONLSink)),
            None)

    # passthroughs used on the hot path — kept one attribute deep
    def span(self, name: str, **args):
        return self.tracer.span(name, **args)

    def check_recompile(self, fn_name: str, *trees,
                        step: Optional[int] = None) -> str:
        return self.recompile.check(fn_name, *trees, step=step)

    def instant(self, name: str, **args) -> None:
        """Trace instant event (guardrails spike/rollback/watchdog markers
        land next to the step spans in the same Perfetto timeline)."""
        self.tracer.instant(name, **args)

    def set_step(self, step: int) -> None:
        self.registry.set_step(step)

    def flush(self) -> None:
        self.registry.flush()
        self.tracer.flush()

    def close(self) -> None:
        self.tracer.close()
        self.registry.close()


def null_telemetry() -> Telemetry:
    """A fully disabled facade (no sinks, no trace, detector off)."""
    return Telemetry(MetricsRegistry(), StepTracer(enabled=False),
                     RecompileDetector(enabled=False), enabled=False)


def build_telemetry(tcfg, monitor=None) -> Telemetry:
    """Build the facade from a parsed ``TelemetryConfig``.

    ``monitor``: an already-built ``TensorboardMonitor`` (the engine's
    ``tensorboard`` block) — attached as a registry sink so legacy
    tensorboard configs receive every registry metric without listing
    "tensorboard" in the telemetry sinks.
    """
    if tcfg is None or not tcfg.enabled:
        tel = null_telemetry()
        if monitor is not None:
            # tensorboard-only legacy setups still get registry fan-out
            tel.registry.add_sink(TensorboardSink(monitor))
        return tel

    # Multi-host runs on shared storage must not clobber each other's
    # outputs: the metrics JSONL and trace file gain a `.<host>.`
    # component (same convention as the goodput run manifest) whenever the
    # run spans processes; single-host filenames stay byte-stable
    # (host_scoped_path(name, None) is the compat alias).
    host_part = telemetry_host_component()
    registry = MetricsRegistry()
    for sink_name in tcfg.metrics.sinks:
        if sink_name == "jsonl":
            registry.add_sink(JSONLSink(os.path.join(
                tcfg.dir, host_scoped_path(tcfg.metrics.file, host_part))))
        elif sink_name == "memory":
            registry.add_sink(InMemorySink())
        elif sink_name == "tensorboard":
            if monitor is not None:
                registry.add_sink(TensorboardSink(monitor))
            else:
                from deepspeed_tpu.utils.monitor import TensorboardMonitor
                registry.add_sink(TensorboardSink(
                    TensorboardMonitor(tcfg.dir, job_name="telemetry")))
    if monitor is not None and "tensorboard" not in tcfg.metrics.sinks:
        registry.add_sink(TensorboardSink(monitor))

    tracer = StepTracer(
        path=(os.path.join(tcfg.dir,
                           host_scoped_path(tcfg.trace.file, host_part))
              if tcfg.trace.enabled else None),
        sync_spans=tcfg.trace.sync_spans,
        jax_profiler_dir=tcfg.trace.jax_profiler_dir,
        host=host_part or default_host())
    recompile = RecompileDetector(registry=registry, tracer=tracer,
                                  enabled=tcfg.recompile_detection)
    return Telemetry(registry, tracer, recompile, enabled=True)
