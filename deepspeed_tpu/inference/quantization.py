"""Int8 weight-only quantization for inference.

TPU-native analogue of the reference's ``WeightQuantization``
(``deepspeed/runtime/weight_quantizer.py:5``) and the int8 inference path of
``replace_module``: weights are stored in HBM as int8 with per-output-channel
(optionally row-groupwise) fp32 scales, halving (vs bf16) or quartering (vs
fp32) weight memory. Dequantization happens *inside* the jitted forward —
XLA fuses the ``int8 → bf16 × scale`` expansion into the consuming matmul's
operand pipeline, so no dequantized copy of the full model ever lives in HBM
at once.

Symmetric linear quantization, matching the reference's quantizer semantics
(``csrc/quantization/quantizer.cu``): ``q = round(w / s)``, ``s = max|w| /
127`` per (group, output-channel).

The numeric core is NOT implemented here: the tree has exactly one RTNE
int8 round-trip — :func:`deepspeed_tpu.comm.quantize.quantize_blockwise`
(the ZeRO++-style DCN gradient compressor, also the serving tier's int8
KV-cache quantizer). This module only reshapes weights so that each
(group, output-channel) column is one quantization block, and inherits
that implementation's tested properties (deterministic RTNE,
zero-preserving, max-preserving, overflow-transparent — see
tests/test_dcn.py).
"""

import re
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm.quantize import quantize_blockwise


@jax.tree_util.register_pytree_node_class
class QuantizedWeight:
    """An int8 tensor + fp32 scales standing in for a float weight leaf.

    Registered as a pytree node so quantized param trees pass through
    ``jax.jit`` boundaries like ordinary trees.
    """

    def __init__(self, q: jax.Array, scale: jax.Array,
                 shape: Tuple[int, ...]):
        self.q = q              # int8, grouped shape [G, rows/G, cols...]
        self.scale = scale      # fp32, [G, 1, cols...]
        self.shape = tuple(shape)

    def tree_flatten(self):
        return (self.q, self.scale), self.shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, aux)

    def dequantize(self, dtype=jnp.bfloat16) -> jax.Array:
        w = self.q.astype(jnp.float32) * self.scale
        return w.reshape(self.shape).astype(dtype)

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape)) + self.scale.size * 4


DEFAULT_QUANT_PATTERN = r".*(kernel|wte|embedding)$"


def _quantize_leaf(w: jax.Array, groups: int) -> QuantizedWeight:
    shape = w.shape
    rows = shape[0]
    g = groups if rows % groups == 0 else 1
    if g != groups:
        from deepspeed_tpu.utils.logging import logger
        logger.warning(
            f"quantize_groups={groups} does not divide leading dim {rows} "
            f"of a {shape} weight; falling back to one scale group for it")
    grouped = jnp.reshape(w.astype(jnp.float32), (g, rows // g) + shape[1:])
    # Delegate to the shared RTNE core (comm/quantize.py): it quantizes
    # last-dim blocks, so move the within-group row axis last and make
    # each (group, output-channel) column exactly one block.
    moved = jnp.moveaxis(grouped, 1, -1)            # [g, cols..., rows/g]
    q, scales = quantize_blockwise(moved, rows // g)
    q = jnp.moveaxis(q, -1, 1)                      # [g, rows/g, cols...]
    scale = jnp.moveaxis(scales, -1, 1)             # [g, 1, cols...]
    return QuantizedWeight(q, scale, shape)


def quantize_params(params: Any, groups: int = 1,
                    pattern: str = DEFAULT_QUANT_PATTERN,
                    min_size: int = 4096) -> Any:
    """Quantize matching ≥2-D leaves of a param tree to int8; other leaves
    pass through unchanged. ``groups`` splits the input (row) dimension into
    independently-scaled groups (the reference's ``quantize_groups``)."""
    rx = re.compile(pattern)

    def path_str(path):
        return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)

    def maybe_quant(path, leaf):
        if (leaf.ndim >= 2 and leaf.size >= min_size
                and rx.search(path_str(path))
                and jnp.issubdtype(leaf.dtype, jnp.floating)):
            return _quantize_leaf(leaf, groups)
        return leaf

    return jax.tree_util.tree_map_with_path(maybe_quant, params)


def dequantize_params(params: Any, dtype=jnp.bfloat16) -> Any:
    """Expand QuantizedWeight leaves back to dense arrays (called inside the
    jitted forward so XLA fuses dequant into each weight's consumer)."""
    return jax.tree_util.tree_map(
        lambda x: x.dequantize(dtype) if isinstance(x, QuantizedWeight) else x,
        params, is_leaf=lambda x: isinstance(x, QuantizedWeight))


def quantized_nbytes(params: Any) -> int:
    """Total HBM bytes of a (possibly partially) quantized tree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedWeight)):
        if isinstance(leaf, QuantizedWeight):
            total += leaf.nbytes
        else:
            total += leaf.size * leaf.dtype.itemsize
    return total
