"""Inference engine — TP-sharded forward + KV-cache generation.

TPU-native re-design of the reference's ``InferenceEngine``
(``deepspeed/inference/engine.py:19``) and ``module_inject`` TP slicing
(``module_inject/replace_module.py:89``, ``replace_policy.py``):

- **TP injection → partition rules.** The reference walks the module tree and
  splits qkv/mlp weights onto ranks with ``ReplaceWithTensorSlicing``. Here
  the same Megatron-style split is declarative: the model family's
  ``(regex → PartitionSpec)`` rules (``models/partition.py``) are applied to
  the param tree and GSPMD inserts the all-reduces — no module surgery.
- **Kernel injection → attention dispatch.** ``replace_with_kernel_inject``
  selects the fused CUDA op in the reference; here the models already route
  through ``ops/transformer/attention`` whose ``auto`` mode picks the Pallas
  flash kernel when profitable.
- **KV cache** (reference ``csrc/transformer/inference`` attention cache):
  static-shape per-layer (k, v) arrays updated via ``dynamic_update_slice``;
  the whole prefill + N-token decode runs as ONE jitted program (prefill +
  ``lax.scan``) — one dispatch per generate call, not per token.
- **Int8 weight quantization** (reference ``runtime/weight_quantizer.py:5``):
  weights live in HBM as int8 + scales; dequant is fused into each consumer
  matmul inside the jitted step. See ``inference/quantization.py``.
"""

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.inference.quantization import (dequantize_params,
                                                  quantize_params,
                                                  quantized_nbytes)
from deepspeed_tpu.models.partition import build_specs
from deepspeed_tpu.utils.logging import log_dist

# Smallest prompt bucket: prompts shorter than this share one compiled
# prefill (the compile-cache floor — a 1-token and a 7-token prompt are
# not worth distinct programs).
MIN_PROMPT_BUCKET = 8


def bucket_length(t: int, floor: int = MIN_PROMPT_BUCKET,
                  cap: Optional[int] = None) -> int:
    """Round ``t`` up to the bucket the jitted prefill compiles for: the
    next power of two, at least ``floor``, clamped to ``cap`` (the usable
    context minus the decode budget) but never below ``t`` itself."""
    b = max(floor, 1 << max(0, (t - 1).bit_length()))
    if cap is not None:
        b = min(b, cap)
    return max(b, t)


def sample_logits(logits, rng, temperature: float, top_k: int):
    """Greedy (``temperature == 0``) or temperature/top-k sampling over
    ``[B, V]`` fp32 logits — shared by ``generate()`` and the serving
    engine's decode program (one sampling implementation in the tree)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


class InferenceConfig:
    """Normalized ``init_inference`` kwargs (reference
    ``deepspeed/__init__.py:227`` signature)."""

    def __init__(self, mp_size: int = 1, dtype: Any = None,
                 quantize: bool = False, quantize_groups: int = 1,
                 replace_with_kernel_inject: bool = True,
                 max_tokens: Optional[int] = None,
                 recompile_detection: bool = True,
                 bucket_prompts: bool = True, **extra):
        self.mp_size = int(mp_size)
        self.dtype = dtype if dtype is not None else jnp.bfloat16
        self.quantize = bool(quantize)
        self.quantize_groups = int(quantize_groups)
        self.replace_with_kernel_inject = bool(replace_with_kernel_inject)
        self.max_tokens = max_tokens
        self.recompile_detection = bool(recompile_detection)
        # Pad prompts (left, masked) to power-of-two buckets so varying
        # prompt lengths hit a bounded set of compiled prefill programs
        # instead of retracing per length.
        self.bucket_prompts = bool(bucket_prompts)
        self.extra = extra


class InferenceEngine:
    """Sharded, jitted inference over a flax module.

    ``model``: a flax module whose ``apply({'params': p}, batch,
    deterministic=True)`` returns a dict with "logits" (the in-tree GPT/BERT
    families). Generation additionally needs the module to accept
    ``cache=``/``pos=`` (GPT) — see ``models/gpt.py``.
    """

    def __init__(self, model, params: Any = None,
                 config: Optional[InferenceConfig] = None,
                 mp_size: int = 1, dtype: Any = None,
                 quantize: bool = False, quantize_groups: int = 1,
                 partition_rules=None, injection_policy=None,
                 mesh: Optional[Mesh] = None,
                 checkpoint: Optional[str] = None,
                 example_batch: Any = None, tracer: Any = None, **kwargs):
        self.module = model
        cfg = config or InferenceConfig(
            mp_size=mp_size, dtype=dtype, quantize=quantize,
            quantize_groups=quantize_groups, **kwargs)
        self.config = cfg
        self.model_cfg = getattr(model, "cfg", None)

        # --- external-model injection (module_inject/replace_policy.py):
        # a recognized HF-Flax model is converted onto the in-tree family
        # so it serves through the TPU kernels + TP rules — the
        # reference's replace_with_kernel_inject for other people's
        # models (replace_module.py:11). ``injection_policy`` may name a
        # policy class explicitly; (regex, dims) partition-rule tuples
        # keep their existing meaning below.
        inject_pol = None
        if (isinstance(injection_policy, type)
                and hasattr(injection_policy, "convert")):
            inject_pol = injection_policy
            injection_policy = None
        if cfg.replace_with_kernel_inject or inject_pol is not None:
            from deepspeed_tpu.module_inject import convert_external_model
            if inject_pol is not None or (hasattr(model, "config")
                                          and self.model_cfg is None):
                conv = convert_external_model(model, params=params,
                                              injection_policy=inject_pol,
                                              dtype=cfg.dtype)
                if conv is not None:
                    src_name = type(model).__name__
                    model, params = conv
                    self.module = model
                    self.model_cfg = model.cfg
                    log_dist(
                        f"kernel injection: converted {src_name} weights "
                        f"onto the in-tree {type(model).__name__} family",
                        ranks=[0])

        if checkpoint is not None and params is None:
            from deepspeed_tpu.runtime.checkpointing import load_module_params
            params = load_module_params(checkpoint)
        if params is None:
            if example_batch is None:
                raise ValueError("init_inference needs params, checkpoint, "
                                 "or example_batch to initialise the module")
            params = model.init({"params": jax.random.PRNGKey(0),
                                 "dropout": jax.random.PRNGKey(1)},
                                example_batch)["params"]

        # --- tensor-parallel mesh + param sharding -----------------------
        self.mesh = mesh
        if self.mesh is None and cfg.mp_size > 1:
            from deepspeed_tpu.parallel.mesh import build_mesh
            self.mesh = build_mesh(model=cfg.mp_size)
        self.mp_size = cfg.mp_size

        rules = partition_rules if partition_rules is not None else \
            injection_policy
        if rules is None:
            rules = self._default_rules()
        if rules is None and cfg.mp_size > 1:
            raise ValueError(
                f"mp_size={cfg.mp_size} requested but "
                f"{type(model).__name__} has no built-in partition rules — "
                f"pass partition_rules=/injection_policy= ((regex, dims) "
                f"pairs, see models/partition.py) or mp_size=1")
        self._param_specs = None
        cast = lambda p: (p.astype(cfg.dtype)
                          if jnp.issubdtype(p.dtype, jnp.floating) else p)
        params = jax.tree_util.tree_map(cast, params)
        if cfg.quantize:
            params = quantize_params(params, groups=cfg.quantize_groups)
            log_dist(f"int8 weight quantization: model weights now "
                     f"{quantized_nbytes(params) / 1e6:.1f} MB", ranks=[0])
        if self.mesh is not None and rules is not None:
            # Specs only need paths + ranks: use shape structs for quantized
            # leaves, never materializing a dense dequantized copy.
            from deepspeed_tpu.inference.quantization import QuantizedWeight
            base = jax.tree_util.tree_map(
                lambda x: (jax.ShapeDtypeStruct(x.shape, jnp.bfloat16)
                           if isinstance(x, QuantizedWeight) else x),
                params, is_leaf=lambda x: isinstance(x, QuantizedWeight))
            self._param_specs = build_specs(base, rules,
                                            mesh_axes=dict(self.mesh.shape))
            params = self._shard_params(params)
        self.params = params

        self._forward_jit = None
        self._generate_jit: Dict = {}
        self._generate_calls = 0
        # Serving-side retrace alarm (telemetry/recompile.py): a ragged
        # prompt length or dtype drift recompiles prefill+decode per
        # request — seconds of silent tail latency the detector names.
        from deepspeed_tpu.telemetry import RecompileDetector, StepTracer
        self.recompile_detector = RecompileDetector(
            enabled=cfg.recompile_detection)
        # Inference spans land in the same Perfetto timeline as training:
        # pass the run's StepTracer (telemetry.tracer) and every
        # forward/generate dispatch is bracketed; without one the span is
        # the reusable zero-cost no-op.
        self.tracer = tracer if tracer is not None else \
            StepTracer(enabled=False)

    # ------------------------------------------------------------------
    def _default_rules(self):
        from deepspeed_tpu.models import (BertModel, GPT,
                                          bert_partition_rules,
                                          gpt_partition_rules)
        if isinstance(self.module, GPT):
            return gpt_partition_rules()
        if isinstance(self.module, BertModel):
            return bert_partition_rules()
        return None

    def _shard_params(self, params):
        """Place each leaf with its TP NamedSharding (QuantizedWeight leaves:
        shard the int8 payload with the same spec, replicate the scales)."""
        from deepspeed_tpu.inference.quantization import QuantizedWeight

        def place(leaf, spec):
            if isinstance(leaf, QuantizedWeight):
                qdims = (None,) + tuple(spec) + (None,) * max(
                    0, leaf.q.ndim - 1 - len(tuple(spec)))
                qspec = PartitionSpec(*qdims[:leaf.q.ndim])
                return QuantizedWeight(
                    jax.device_put(leaf.q,
                                   NamedSharding(self.mesh, qspec)),
                    jax.device_put(leaf.scale,
                                   NamedSharding(self.mesh, PartitionSpec())),
                    leaf.shape)
            return jax.device_put(leaf, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map(
            place, params, self._param_specs,
            is_leaf=lambda x: isinstance(x, QuantizedWeight))

    def _materialized(self, params):
        return (dequantize_params(params, self.config.dtype)
                if self.config.quantize else params)

    # ------------------------------------------------------------------
    def forward(self, batch, **kwargs):
        """Jitted deterministic forward; returns the module's output dict."""
        self.recompile_detector.check("inference.forward", batch)
        if self._forward_jit is None:
            def fwd(params, batch):
                p = self._materialized(params)
                return self.module.apply({"params": p}, batch,
                                         deterministic=True)
            self._forward_jit = jax.jit(fwd)
        with self.tracer.span("inference_forward"):
            return self._forward_jit(self.params, batch)

    __call__ = forward

    # ------------------------------------------------------------------
    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: Optional[int] = None,
                 attention_mask=None):
        """Autoregressive generation with a KV cache.

        ``input_ids``: [B, T0] int32 prompts. Ragged prompts must be
        **left-padded** to a uniform T0 and accompanied by
        ``attention_mask`` ([B, T0], 1 = real token, 0 = pad, pads leading):
        pad slots are masked out of every attention step (prefill and the
        whole decode) and learned positions are re-based per row so each
        row's content starts at position 0. Without a mask, prompts are
        taken as unpadded.

        Greedy when ``temperature == 0``, else temperature sampling with
        optional top-k. Sampling uses ``seed`` when given (byte-identical
        outputs for the same seed); when ``seed`` is None an engine-held
        call counter is mixed in so repeated calls draw fresh samples.
        The whole prefill + ``max_new_tokens``-step decode is one jitted
        program. Returns [B, T0 + max_new_tokens].
        """
        import inspect
        sig = inspect.signature(type(self.module).__call__)
        if self.model_cfg is None or "cache" not in sig.parameters:
            raise ValueError(
                f"generate() needs a cache-capable causal LM whose __call__ "
                f"takes cache=/pos= (the in-tree GPT family); "
                f"{type(self.module).__name__} does not")
        ids = jnp.asarray(input_ids, jnp.int32)
        b, t0 = ids.shape
        total = t0 + int(max_new_tokens)
        limit = getattr(self.model_cfg, "max_seq_len", None)
        if self.config.max_tokens is not None:
            limit = (min(limit, self.config.max_tokens) if limit is not None
                     else self.config.max_tokens)
        if limit is not None and total > limit:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({max_new_tokens}) = "
                f"{total} exceeds the usable context of {limit} "
                f"(model max_seq_len / init_inference max_tokens) — "
                f"positions past it would silently clamp")
        if attention_mask is not None:
            mask = np.asarray(attention_mask)
            if mask.shape != (b, t0):
                raise ValueError(f"attention_mask shape {mask.shape} != "
                                 f"{(b, t0)}")
            if not (np.diff(mask.astype(np.int8), axis=1) >= 0).all():
                raise ValueError("attention_mask must be left-padded "
                                 "(0s before 1s in every row)")
            if (mask.sum(axis=1) == 0).any():
                raise ValueError("attention_mask has a fully-padded row — "
                                 "every prompt needs at least one real "
                                 "token (all-masked softmax is NaN)")
            mask = jnp.asarray(mask, jnp.int32)
        else:
            mask = None
        # --- prompt-length bucketing -----------------------------------
        # A ragged prompt length retraces the whole prefill+decode program
        # (seconds of silent stall per NEW length). Left-pad to the next
        # power-of-two bucket instead: ≤ log2(context) compiled programs
        # ever, and the existing left-pad masking/position-rebase makes
        # the padded call token-identical to the unpadded one. The pad
        # columns are stripped from the returned ids.
        t_pad = 0
        if self.config.bucket_prompts:
            cap = limit - int(max_new_tokens) if limit is not None else None
            bucket = bucket_length(t0, cap=cap)
            t_pad = bucket - t0
            if mask is None:
                # Always run the masked path when bucketing: a mask that
                # appears only for non-power-of-two lengths would split
                # each bucket into two jit signatures.
                mask = jnp.ones((b, t0), jnp.int32)
            if t_pad:
                ids = jnp.pad(ids, ((0, 0), (t_pad, 0)))
                mask = jnp.pad(mask, ((0, 0), (t_pad, 0)))
        if seed is None:
            # Unseeded sampled calls draw fresh samples each time (counter-
            # mixed); greedy decoding ignores the PRNG so the counter only
            # advances for sampled calls. seed=N reproduces the N-th
            # unseeded sampled call byte-for-byte.
            seed = self._generate_calls
            if temperature > 0.0:
                self._generate_calls += 1
        self.recompile_detector.check(
            "inference.generate", ids, mask,
            {"static": f"max_new_tokens={int(max_new_tokens)},"
                       f"temperature={float(temperature)},"
                       f"top_k={int(top_k)}"})
        key = (b, int(ids.shape[1]), int(max_new_tokens),
               float(temperature), int(top_k), mask is not None)
        if key not in self._generate_jit:
            self._generate_jit[key] = jax.jit(functools.partial(
                self._generate_impl, max_new_tokens=int(max_new_tokens),
                temperature=float(temperature), top_k=int(top_k)))
        with self.tracer.span("generate", prompt_len=t0,
                              bucket=int(ids.shape[1]),
                              new_tokens=int(max_new_tokens)):
            out = self._generate_jit[key](self.params, ids, mask,
                                          jax.random.PRNGKey(seed))
        # Strip the bucket's left-pad columns: callers see [B, T0 + new].
        return out[:, t_pad:] if t_pad else out

    def _sample(self, logits, rng, temperature, top_k):
        return sample_logits(logits, rng, temperature, top_k)

    def _generate_impl(self, params, ids, mask, rng, *, max_new_tokens,
                       temperature, top_k):
        from deepspeed_tpu.models.gpt import init_kv_cache

        cfg = self.model_cfg
        b, t0 = ids.shape
        max_len = t0 + max_new_tokens
        cache = init_kv_cache(cfg, b, max_len, dtype=self.config.dtype)

        # Left-padded prompts: one fixed [B, max_len] key-validity mask
        # (pad slots never visible, generated slots always are) and per-row
        # re-based position ids.
        if mask is not None:
            n_pads = (t0 - jnp.sum(mask, axis=1)).astype(jnp.int32)  # [B]
            km = jnp.concatenate(
                [mask, jnp.ones((b, max_new_tokens), jnp.int32)], axis=1)
            prefill = {"input_ids": ids, "attention_mask": km,
                       "position_ids": jnp.clip(
                           jnp.arange(t0)[None] - n_pads[:, None], 0)}
        else:
            n_pads = None
            km = None
            prefill = {"input_ids": ids}

        # Dequant happens inside each traced body (not hoisted out of the
        # scan) so XLA fuses it into the consumer matmuls and no dense copy
        # of the whole quantized model stays live across the decode loop.
        out = self.module.apply({"params": self._materialized(params)},
                                prefill, deterministic=True, cache=cache,
                                pos=0)
        rng, sub = jax.random.split(rng)
        nxt = self._sample(out["logits"][:, -1].astype(jnp.float32), sub,
                           temperature, top_k)

        def step(carry, _):
            tok, cache, pos, rng = carry
            batch = {"input_ids": tok[:, None]}
            if km is not None:
                batch["attention_mask"] = km
                batch["position_ids"] = jnp.clip(
                    pos - n_pads, 0)[:, None]
            out = self.module.apply({"params": self._materialized(params)},
                                    batch, deterministic=True, cache=cache,
                                    pos=pos)
            rng, sub = jax.random.split(rng)
            nxt = self._sample(out["logits"][:, -1].astype(jnp.float32), sub,
                               temperature, top_k)
            return (nxt, out["cache"], pos + 1, rng), nxt

        if max_new_tokens > 1:
            (_, _, _, _), toks = jax.lax.scan(
                step, (nxt, out["cache"], t0, rng), None,
                length=max_new_tokens - 1)
            gen = jnp.concatenate([nxt[:, None], toks.T], axis=1)
        else:
            gen = nxt[:, None]
        return jnp.concatenate([ids, gen], axis=1)
