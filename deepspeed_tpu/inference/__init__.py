"""Inference subsystem — TP-sharded, KV-cached, optionally int8-quantized.

Reference surface: ``deepspeed/inference/engine.py``,
``deepspeed/module_inject/`` and ``runtime/weight_quantizer.py``.
"""

from deepspeed_tpu.inference.engine import InferenceConfig, InferenceEngine
from deepspeed_tpu.inference.quantization import (QuantizedWeight,
                                                  dequantize_params,
                                                  quantize_params,
                                                  quantized_nbytes)

__all__ = [
    "InferenceEngine", "InferenceConfig", "quantize_params",
    "dequantize_params", "QuantizedWeight", "quantized_nbytes",
]
