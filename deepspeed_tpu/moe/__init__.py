"""Mixture-of-Experts + expert parallelism (planned-fresh per SURVEY §2.4;
API follows the later deepspeed.moe.layer.MoE surface)."""

from deepspeed_tpu.moe.dispatch import (alltoall_dispatch,
                                        modeled_dispatch_bytes_ici)
from deepspeed_tpu.moe.layer import MoE, MoEConfig, moe_partition_rules

__all__ = ["MoE", "MoEConfig", "moe_partition_rules",
           "alltoall_dispatch", "modeled_dispatch_bytes_ici"]
