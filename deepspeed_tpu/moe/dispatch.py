"""Explicit all-to-all MoE dispatch/combine — expert parallelism as a
real collective, not an XLA resharding accident.

The einsum and scatter paths in moe/layer.py keep the dense GShard
formulation and leave the token<->expert layout change to XLA's SPMD
partitioner: whatever all-to-all (or worse, all-gather) it decides to
emit is invisible, unmeasurable and unsteerable. This module is the
explicit path: a ``shard_map`` manual region over the data-like + expert
axes (``parallel.mesh.moe_dispatch_axes``) in which every token shard
builds per-destination send buffers and exchanges them with a real
``jax.lax.all_to_all`` over the ``expert`` axis — the same manual-region
collective idiom as comm/grad_sync.py's DCN stage, and the layout the
reference implements with torch.distributed all_to_all over its expert
process groups.

Semantics are EXACTLY the oracle's (moe/layer.py einsum path): routing —
choice/prob/pos/keep — is computed globally outside the region, so the
capacity-drop regime, combine weights and load-balance loss are
bit-comparable across all three dispatch modes. Inside the region:

- Tokens are sharded over data-like x expert (the input arrives sharded
  over data-like only and replicated over ``expert``; the entry reshard
  is a free dynamic-slice). Each grid cell holds a distinct token block
  and ``e_local = E / n_expert_shards`` experts.
- Dispatch: each cell scatters its kept tokens into a flat
  ``[E*C + 1, D]`` buffer at global slot ``choice*C + pos`` (dropped
  tokens hit the sentinel row — built with zeros + scatter, never
  ``jnp.pad``, which partial-manual regions reject), reshapes
  destination-major to ``[n_shards, e_local*C, D]`` and all-to-alls it
  over ``expert``. Receivers SUM over sources: global queue positions
  are unique per (expert, pos), so source contributions land in disjoint
  rows and the sum is a union.
- Experts run on their local ``[e_local, C, D]`` block with the local
  weight slices (in_spec ``P(expert, None, None)``). The expert FFN has
  no biases, so the zero rows contributed by peer columns' tokens stay
  exactly zero through it — each data column combines only its own
  tokens and no cross-column reduction is needed.
- Combine: outputs ride back masked by an ownership map (a 0/1 buffer
  scattered at the same slots and exchanged alongside the payload), the
  source cell flattens the returns destination-major — which IS global
  expert order — and gathers ``prob*keep``-weighted rows per k-round.

The buffers span the GLOBAL capacity ``C`` (positions are global), so a
cell's working set is ``O(E*C*D)`` — the price of exact oracle parity;
a per-column capacity would shrink it but change the drop regime.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import (EXPERT_AXIS, axes_size,
                                         get_default_mesh,
                                         moe_dispatch_axes)
from deepspeed_tpu.utils.jax_compat import shard_map


def _resolve_mesh(mesh):
    if mesh is None:
        mesh = get_default_mesh()
    if mesh is None:
        raise ValueError(
            "MoE alltoall dispatch needs a mesh: pass MoEConfig.mesh or "
            "register one (parallel.mesh.set_default_mesh — the engine "
            "does this at construction)")
    return mesh


def alltoall_dispatch(h, rounds, w_in, w_out, *, capacity: int, dtype,
                      mesh=None):
    """Dispatch ``h`` [T, D] through the stacked experts with an explicit
    all-to-all over the ``expert`` axis. ``rounds`` is moe.layer._route's
    output (global routing); ``w_in`` [E, D, F] / ``w_out`` [E, F, D] are
    the stacked fp32 expert params. Returns y [T, D] in ``dtype``,
    bit-comparable with the einsum oracle's combine."""
    mesh = _resolve_mesh(mesh)
    tokens, d = h.shape
    e = int(w_in.shape[0])
    n_shards = int(mesh.shape.get(EXPERT_AXIS, 1))
    if e % n_shards:
        raise ValueError(
            f"num_experts {e} must divide by the expert mesh axis "
            f"({n_shards})")
    e_local = e // n_shards
    axes = moe_dispatch_axes(mesh)
    cells = axes_size(mesh.shape, axes)
    if tokens % cells:
        raise ValueError(
            f"token count {tokens} must divide by the dispatch grid "
            f"({cells} = {axes} shards) for the manual region")

    # Global routing, stacked [k, T] so the region's in_specs stay flat.
    choice = jnp.stack([r.choice for r in rounds])
    prob = jnp.stack([r.prob for r in rounds])
    pos = jnp.stack([r.pos for r in rounds])
    keep = jnp.stack([r.keep for r in rounds])
    k = len(rounds)
    sentinel = e * capacity

    def body(h_loc, choice, prob, pos, keep, w_in_loc, w_out_loc):
        # [k, T_cell] routing for this cell's tokens; slots are GLOBAL
        # (choice is the global expert id, pos the global queue position).
        slot = jnp.where(keep, choice * capacity + pos, sentinel)
        buf_x = jnp.zeros((e * capacity + 1, d), dtype)
        buf_o = jnp.zeros((e * capacity + 1,), dtype)
        for i in range(k):
            buf_x = buf_x.at[slot[i]].add(h_loc)
            buf_o = buf_o.at[slot[i]].add(keep[i].astype(dtype))
        # Destination-major: row block j holds shard j's experts.
        send_x = buf_x[:-1].reshape(n_shards, e_local * capacity, d)
        send_o = buf_o[:-1].reshape(n_shards, e_local * capacity)
        recv_x = jax.lax.all_to_all(send_x, EXPERT_AXIS, split_axis=0,
                                    concat_axis=0, tiled=False)
        recv_o = jax.lax.all_to_all(send_o, EXPERT_AXIS, split_axis=0,
                                    concat_axis=0, tiled=False)
        # Sources occupy disjoint global queue positions: sum == union.
        xin = jnp.sum(recv_x, axis=0).reshape(e_local, capacity, d)
        hmid = jnp.einsum("ecd,edf->ecf", xin, w_in_loc.astype(dtype))
        hmid = jax.nn.gelu(hmid, approximate=True)
        xout = jnp.einsum("ecf,efd->ecd", hmid, w_out_loc.astype(dtype))
        # Return trip: each source gets back exactly the slots it owns.
        back = recv_o[..., None] * xout.reshape(1, e_local * capacity, d)
        ret = jax.lax.all_to_all(back, EXPERT_AXIS, split_axis=0,
                                 concat_axis=0, tiled=False)
        # Shard-major flatten IS global expert order: row choice*C+pos.
        flat = jnp.concatenate(
            [ret.reshape(e * capacity, d), jnp.zeros((1, d), dtype)],
            axis=0)
        y = jnp.zeros_like(h_loc)
        for i in range(k):
            w = (prob[i] * keep[i]).astype(dtype)
            y = y + w[:, None] * flat[slot[i]]
        return y

    route = P(None, axes)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(axes, None), route, route, route, route,
                  P(EXPERT_AXIS, None, None), P(EXPERT_AXIS, None, None)),
        out_specs=P(axes, None),
        axis_names=set(axes), check_vma=False)
    # jit so the eager path works too (old jax's partial-manual
    # shard_map only lowers under jit; inside an outer jit this inlines).
    return jax.jit(fn)(h, choice, prob, pos, keep, w_in, w_out)


def modeled_dispatch_bytes_ici(*, num_experts: int, capacity: int,
                               hidden: int, dtype, mesh=None,
                               k: int = 1) -> int:
    """Modeled per-layer ICI bytes of the explicit exchange: the payload
    buffer rides the wire twice (dispatch + combine) and the ownership
    map once, with remote fraction ``(n-1)/n`` per cell, summed over the
    whole dispatch grid. Static — the same number for every step, priced
    from shapes alone (the counterpart of grad_sync's modeled_bytes).
    Returns 0 when the expert axis is unsharded (the exchange is local)
    or no mesh is registered; the implicit einsum/scatter reshards are
    XLA's business and deliberately not modeled."""
    del k
    if mesh is None:
        mesh = get_default_mesh()
    if mesh is None:
        return 0
    n_shards = int(mesh.shape.get(EXPERT_AXIS, 1))
    if n_shards <= 1:
        return 0
    cells = axes_size(mesh.shape, moe_dispatch_axes(mesh))
    itemsize = jnp.dtype(dtype).itemsize
    ec = num_experts * capacity
    per_cell = (2 * ec * hidden + ec) * itemsize * (n_shards - 1) / n_shards
    return int(cells * per_cell)
