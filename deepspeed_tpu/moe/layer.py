"""Mixture-of-Experts layer with expert parallelism — TPU-first.

The reference snapshot (v0.4.3) predates DeepSpeed-MoE; SURVEY §2.4 marks
EP as "build must plan fresh". The design here is the GShard/Mesh-TF
formulation that maps natively onto a TPU mesh, matching the *later*
DeepSpeed ``deepspeed.moe.layer.MoE`` public surface (hidden_size,
num_experts, k, capacity_factor, aux-loss) so users of that API land
somewhere familiar:

- Experts are one stacked param tree with leading dim E, sharded over the
  ``expert`` mesh axis (the same stacked-and-sharded pattern as the
  pipeline's block stack).
- Routing is dense one-hot dispatch/combine einsums (GShard): XLA lowers
  the resharding between token-sharded and expert-sharded layouts to the
  all-to-all the reference would issue explicitly over its expert process
  group.
- Top-1 (switch) or top-2 gating with capacity dropping and the standard
  load-balancing auxiliary loss (Shazeer et al.; fraction_dispatched x
  mean_gate x E).

``MoE.__call__(x)`` returns ``(y, aux_loss)``; add ``aux_loss`` (scaled by
your alpha) to the task loss.
"""

import math
from dataclasses import dataclass
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.mesh import EXPERT_AXIS


@dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    num_experts: int = 8
    k: int = 1                        # top-k routing (1 or 2)
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    expert_intermediate: int = 0      # 0 -> 4 * hidden
    dtype: Any = jnp.bfloat16
    router_jitter: float = 0.0        # multiplicative input jitter (train)

    @property
    def d_ff(self) -> int:
        return self.expert_intermediate or 4 * self.hidden_size


class MoE(nn.Module):
    """Switch/top-2 MoE FFN. Input [B, S, D] -> ([B, S, D], aux_loss)."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        if cfg.k not in (1, 2):
            raise ValueError(f"k must be 1 or 2, got {cfg.k}")
        b, s, d = x.shape
        e = cfg.num_experts
        tokens = b * s
        factor = (cfg.capacity_factor if not deterministic
                  else cfg.eval_capacity_factor)
        capacity = max(cfg.min_capacity,
                       int(math.ceil(tokens / e * factor)))

        h = x.reshape(tokens, d)
        if cfg.router_jitter > 0.0 and not deterministic:
            eps = cfg.router_jitter
            h_r = h * jax.random.uniform(self.make_rng("dropout"), h.shape,
                                         h.dtype, 1.0 - eps, 1.0 + eps)
        else:
            h_r = h
        # Router in fp32 (numerics dominate routing stability).
        logits = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                          name="router")(h_r.astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)          # [T, E]

        dispatch, combine, aux = _topk_dispatch(gates, cfg.k, capacity)

        # Stacked expert FFN params: [E, ...] sharded over the expert axis
        # by moe_partition_rules(); dispatch einsum reshards tokens to the
        # expert layout (XLA emits the all-to-all on a real mesh).
        w_in = self.param("experts_in", nn.initializers.normal(0.02),
                          (e, d, cfg.d_ff), jnp.float32)
        w_out = self.param("experts_out", nn.initializers.normal(0.02),
                           (e, cfg.d_ff, d), jnp.float32)

        xin = jnp.einsum("tec,td->ecd", dispatch.astype(cfg.dtype),
                         h.astype(cfg.dtype))            # [E, C, D]
        hmid = jnp.einsum("ecd,edf->ecf", xin, w_in.astype(cfg.dtype))
        hmid = nn.gelu(hmid, approximate=True)
        xout = jnp.einsum("ecf,efd->ecd", hmid, w_out.astype(cfg.dtype))
        y = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), xout)
        return y.reshape(b, s, d), aux


def _topk_dispatch(gates: jax.Array, k: int, capacity: int):
    """GShard dispatch/combine tensors + load-balance loss.

    gates: [T, E] softmax. Returns (dispatch [T, E, C] 0/1,
    combine [T, E, C] float, aux_loss scalar).
    """
    t, e = gates.shape
    # Load-balance loss from the TOP-1 assignment (Switch Transformer eq. 4).
    top1 = jnp.argmax(gates, axis=-1)
    me = jnp.mean(gates, axis=0)                          # mean gate / expert
    ce = jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0)
    aux = jnp.sum(me * ce) * e

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    remaining = gates
    used = jnp.zeros((e,), jnp.int32)  # slots consumed per expert so far
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)           # [T]
        prob = jnp.take_along_axis(remaining, choice[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)
        # Position of each token within its chosen expert's queue,
        # offset by slots already taken in earlier k-rounds.
        pos = jnp.cumsum(onehot, axis=0) - onehot + used[None, :]
        pos_tok = jnp.sum(pos * onehot, axis=-1)          # [T]
        keep = pos_tok < capacity
        disp = (jax.nn.one_hot(choice, e, dtype=jnp.float32)[:, :, None]
                * jax.nn.one_hot(pos_tok, capacity, dtype=jnp.float32)[:, None, :]
                * keep[:, None, None])
        dispatch = dispatch + disp
        combine = combine + disp * prob[:, None, None]
        used = used + jnp.sum(onehot * keep[:, None], axis=0)
        remaining = remaining * (1.0 - jax.nn.one_hot(choice, e))
    if k > 1:
        # Top-2: renormalize combine weights over the kept assignments
        # (GShard). Top-1 keeps the raw gate probability as the combine
        # weight (Switch Transformer: y = p_i * E_i(x)) — normalizing it
        # to 1 would cancel the gate from the output and kill the
        # router's task-loss gradient.
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = jnp.where(denom > 0,
                            combine / jnp.maximum(denom, 1e-9), 0.0)
    return dispatch, combine, aux


def moe_partition_rules() -> Tuple[Tuple[str, Tuple], ...]:
    """Expert-parallel specs: stacked expert dim over the ``expert`` axis,
    router replicated. Compose with a family's rules via concatenation."""
    return (
        (r".*experts_(in|out)$", (EXPERT_AXIS, None, None)),
        (r".*router/kernel$", (None, None)),
    )
