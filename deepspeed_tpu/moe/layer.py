"""Mixture-of-Experts layer with expert parallelism — TPU-first.

The reference snapshot (v0.4.3) predates DeepSpeed-MoE; SURVEY §2.4 marks
EP as "build must plan fresh". The design here is the GShard/Mesh-TF
formulation that maps natively onto a TPU mesh, matching the *later*
DeepSpeed ``deepspeed.moe.layer.MoE`` public surface (hidden_size,
num_experts, k, capacity_factor, aux-loss) so users of that API land
somewhere familiar:

- Experts are one stacked param tree with leading dim E, sharded over the
  ``expert`` mesh axis (the same stacked-and-sharded pattern as the
  pipeline's block stack).
- Routing is dense one-hot dispatch/combine einsums (GShard): XLA lowers
  the resharding between token-sharded and expert-sharded layouts to the
  all-to-all the reference would issue explicitly over its expert process
  group.
- Top-1 (switch) or top-2 gating with capacity dropping and the standard
  load-balancing auxiliary loss (Shazeer et al.; fraction_dispatched x
  mean_gate x E).

``MoE.__call__(x)`` returns ``(y, aux_loss)``; add ``aux_loss`` (scaled by
your alpha) to the task loss.
"""

import math
from dataclasses import dataclass
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.mesh import EXPERT_AXIS


@dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    num_experts: int = 8
    k: int = 1                        # top-k routing (1 or 2)
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    expert_intermediate: int = 0      # 0 -> 4 * hidden
    dtype: Any = jnp.bfloat16
    router_jitter: float = 0.0        # multiplicative input jitter (train)
    # "scatter" (default): slot-indexed scatter/gather dispatch, memory
    # O(T·E ints + E·C·D) — linear in tokens. "einsum": the classic GShard
    # [T,E,C] one-hot einsums — O(T²·factor/E) floats, kept as the
    # numerics oracle and for meshes where the einsum's all-to-all
    # lowering is preferred. "alltoall": the explicit manual-region
    # exchange over the ``expert`` mesh axis (moe/dispatch.py) — same
    # routing and combine semantics, but the collective is a real,
    # measurable jax.lax.all_to_all instead of whatever SPMD infers.
    dispatch: str = "scatter"
    # Mesh for the alltoall path (None -> the ambient default mesh, which
    # the engine registers at construction).
    mesh: Any = None
    # When True, __call__ returns (y, aux, stats) with the moe/* gauge
    # scalars (load_balance_loss, capacity_overflow_frac,
    # expert_utilization, dispatch_bytes_ici) so the engine's MoE monitor
    # can flush them without retracing.
    stats: bool = False

    @property
    def d_ff(self) -> int:
        return self.expert_intermediate or 4 * self.hidden_size


class MoE(nn.Module):
    """Switch/top-2 MoE FFN. Input [B, S, D] -> ([B, S, D], aux_loss)."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.cfg
        if cfg.k not in (1, 2):
            raise ValueError(f"k must be 1 or 2, got {cfg.k}")
        b, s, d = x.shape
        e = cfg.num_experts
        tokens = b * s
        factor = (cfg.capacity_factor if not deterministic
                  else cfg.eval_capacity_factor)
        capacity = max(cfg.min_capacity,
                       int(math.ceil(tokens / e * factor)))

        h = x.reshape(tokens, d)
        if cfg.router_jitter > 0.0 and not deterministic:
            eps = cfg.router_jitter
            h_r = h * jax.random.uniform(self.make_rng("dropout"), h.shape,
                                         h.dtype, 1.0 - eps, 1.0 + eps)
        else:
            h_r = h
        # Router in fp32 (numerics dominate routing stability).
        logits = nn.Dense(e, use_bias=False, dtype=jnp.float32,
                          name="router")(h_r.astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)          # [T, E]

        rounds, aux = _route(gates, cfg.k, capacity)

        # Stacked expert FFN params: [E, ...] sharded over the expert axis
        # by moe_partition_rules().
        w_in = self.param("experts_in", nn.initializers.normal(0.02),
                          (e, d, cfg.d_ff), jnp.float32)
        w_out = self.param("experts_out", nn.initializers.normal(0.02),
                           (e, cfg.d_ff, d), jnp.float32)

        def expert_ffn(xin):                              # [E, C, D]
            hmid = jnp.einsum("ecd,edf->ecf", xin, w_in.astype(cfg.dtype))
            hmid = nn.gelu(hmid, approximate=True)
            return jnp.einsum("ecf,efd->ecd", hmid, w_out.astype(cfg.dtype))

        hc = h.astype(cfg.dtype)
        if cfg.dispatch == "scatter":
            # Slot-indexed dispatch: token t's kept assignment (choice,
            # pos) maps to flat slot choice*C+pos; dropped tokens target a
            # sentinel row. Scatter-add builds the [E, C, D] expert input
            # (transposes to gather in backward); the combine is a plain
            # gather weighted by the kept gate. Nothing [T, E, C]-shaped
            # ever exists — the round-2 VERDICT weak-#4 fix.
            slots = [jnp.where(r.keep, r.choice * capacity + r.pos,
                               e * capacity) for r in rounds]
            xin_flat = jnp.zeros((e * capacity + 1, d), cfg.dtype)
            for slot in slots:
                xin_flat = xin_flat.at[slot].add(hc)
            xout = expert_ffn(xin_flat[:-1].reshape(e, capacity, d))
            xout_flat = jnp.concatenate(
                [xout.reshape(e * capacity, d),
                 jnp.zeros((1, d), cfg.dtype)], axis=0)
            y = jnp.zeros((tokens, d), cfg.dtype)
            for r, slot in zip(rounds, slots):
                w = (r.prob * r.keep).astype(cfg.dtype)
                y = y + w[:, None] * xout_flat[slot]
        elif cfg.dispatch == "einsum":
            # GShard one-hot dispatch/combine einsums (XLA lowers the
            # reshard between token- and expert-layouts to all-to-all).
            dispatch, combine = _onehot_tensors(rounds, e, capacity)
            xin = jnp.einsum("tec,td->ecd", dispatch.astype(cfg.dtype), hc)
            xout = expert_ffn(xin)
            y = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), xout)
        elif cfg.dispatch == "alltoall":
            # Explicit manual-region exchange over the expert axis —
            # same routing/combine (exact oracle parity), real collective.
            from deepspeed_tpu.moe.dispatch import alltoall_dispatch
            y = alltoall_dispatch(hc, rounds, w_in, w_out,
                                  capacity=capacity, dtype=cfg.dtype,
                                  mesh=cfg.mesh)
        else:
            raise ValueError(f"unknown MoE dispatch '{cfg.dispatch}'")
        y = y.reshape(b, s, d)
        if cfg.stats:
            return y, aux, _dispatch_stats(cfg, rounds, e, capacity,
                                           tokens, d, aux)
        return y, aux


def _dispatch_stats(cfg, rounds, e, capacity, tokens, d, aux):
    """The moe/* gauge scalars, computed from the routing rounds every
    dispatch mode shares (telemetry/moe.py names; each a 0-dim fp32)."""
    kept = sum(jnp.sum(r.keep.astype(jnp.float32)) for r in rounds)
    counts = sum(jnp.sum(jax.nn.one_hot(r.choice, e, dtype=jnp.float32)
                         * r.keep[:, None].astype(jnp.float32), axis=0)
                 for r in rounds)
    if cfg.dispatch == "alltoall":
        from deepspeed_tpu.moe.dispatch import modeled_dispatch_bytes_ici
        wire = modeled_dispatch_bytes_ici(
            num_experts=e, capacity=capacity, hidden=d, dtype=cfg.dtype,
            mesh=cfg.mesh)
    else:
        wire = 0  # implicit reshards are XLA's business — not modeled
    return {
        "load_balance_loss": aux.astype(jnp.float32),
        "capacity_overflow_frac":
            1.0 - kept / jnp.float32(tokens * len(rounds)),
        "expert_utilization":
            jnp.mean((counts > 0).astype(jnp.float32)),
        "dispatch_bytes_ici": jnp.float32(wire),
    }


class _Round:
    """One top-k routing round: per-token expert choice, gate prob, queue
    position and capacity-keep flag."""

    __slots__ = ("choice", "prob", "pos", "keep")

    def __init__(self, choice, prob, pos, keep):
        self.choice, self.prob, self.pos, self.keep = choice, prob, pos, keep


def _route(gates: jax.Array, k: int, capacity: int):
    """Top-k routing + capacity assignment + load-balance loss.

    gates: [T, E] softmax. Returns ([_Round] * k, aux_loss). All
    intermediates are [T] or [T, E] — position assignment is the
    cumsum-over-onehot counter (cheaper than an argsort and
    arrival-order-stable, which the einsum oracle shares)."""
    t, e = gates.shape
    # Load-balance loss from the TOP-1 assignment (Switch Transformer eq. 4).
    top1 = jnp.argmax(gates, axis=-1)
    me = jnp.mean(gates, axis=0)                          # mean gate / expert
    ce = jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0)
    aux = jnp.sum(me * ce) * e

    rounds = []
    remaining = gates
    used = jnp.zeros((e,), jnp.int32)  # slots consumed per expert so far
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)           # [T]
        prob = jnp.take_along_axis(remaining, choice[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)
        # Position of each token within its chosen expert's queue,
        # offset by slots already taken in earlier k-rounds.
        pos = jnp.cumsum(onehot, axis=0) - onehot + used[None, :]
        pos_tok = jnp.sum(pos * onehot, axis=-1)          # [T]
        keep = pos_tok < capacity
        rounds.append(_Round(choice, prob, pos_tok, keep))
        used = used + jnp.sum(onehot * keep[:, None], axis=0)
        remaining = remaining * (1.0 - jax.nn.one_hot(choice, e))
    if k > 1:
        # Top-2: renormalize combine weights over the kept assignments
        # (GShard). Top-1 keeps the raw gate probability as the combine
        # weight (Switch Transformer: y = p_i * E_i(x)) — normalizing it
        # to 1 would cancel the gate from the output and kill the
        # router's task-loss gradient.
        denom = sum((r.prob * r.keep for r in rounds), jnp.zeros((t,)))
        denom = jnp.maximum(denom, 1e-9)
        rounds = [_Round(r.choice, r.prob / denom, r.pos, r.keep)
                  for r in rounds]
    return rounds, aux


def _onehot_tensors(rounds, e: int, capacity: int):
    """[T, E, C] dispatch/combine one-hots from routing rounds (the GShard
    einsum formulation — numerics oracle for the scatter path)."""
    dispatch = combine = 0.0
    for r in rounds:
        disp = (jax.nn.one_hot(r.choice, e, dtype=jnp.float32)[:, :, None]
                * jax.nn.one_hot(r.pos, capacity,
                                 dtype=jnp.float32)[:, None, :]
                * r.keep[:, None, None])
        dispatch = dispatch + disp
        combine = combine + disp * r.prob[:, None, None]
    return dispatch, combine


def _topk_dispatch(gates: jax.Array, k: int, capacity: int):
    """GShard [T, E, C] dispatch/combine tensors (einsum-path oracle; the
    hot path routes via _route + slot scatter). Kept as the test surface
    for routing semantics."""
    rounds, aux = _route(gates, k, capacity)
    dispatch, combine = _onehot_tensors(rounds, gates.shape[1], capacity)
    return dispatch, combine, aux


def moe_partition_rules() -> Tuple[Tuple[str, Tuple], ...]:
    """Expert-parallel specs: stacked expert dim over the ``expert`` axis,
    router replicated. Compose with a family's rules via concatenation."""
    return (
        (r".*experts_(in|out)$", (EXPERT_AXIS, None, None)),
        (r".*router/kernel$", (None, None)),
    )
