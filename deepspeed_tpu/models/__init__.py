"""In-tree model families (flagship targets for every subsystem)."""

from deepspeed_tpu.models.adapter import flax_module_loss_fn, supervised_loss_fn
from deepspeed_tpu.models.bert import (BERT_CONFIGS, BertConfig, BertModel,
                                       bert_partition_rules, make_bert)
from deepspeed_tpu.models.gpt import (GPT, GPT_CONFIGS, GPTConfig,
                                      cross_entropy_with_ignore,
                                      gpt_partition_rules, make_gpt)
from deepspeed_tpu.models.partition import build_specs

__all__ = [
    "GPT", "GPTConfig", "GPT_CONFIGS", "make_gpt", "gpt_partition_rules",
    "BertModel", "BertConfig", "BERT_CONFIGS", "make_bert",
    "bert_partition_rules", "build_specs", "flax_module_loss_fn",
    "supervised_loss_fn", "cross_entropy_with_ignore",
]
