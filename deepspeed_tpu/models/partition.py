"""Rule-based tensor-parallel PartitionSpec construction.

The reference shards weights for TP by per-architecture injection policies
(``module_inject/replace_policy.py``, ``ReplaceWithTensorSlicing``
``replace_module.py:11``). The TPU-native analogue is declarative: each model
family publishes (regex → spec) rules over its param-tree paths; ``build_specs``
walks any param pytree and emits the matching ``PartitionSpec`` tree, which the
engine composes with ZeRO data-axis sharding (runtime/zero/partition.py).
"""

import re
from typing import Any, Iterable, Optional, Tuple

import jax
from jax.sharding import PartitionSpec


def transformer_block_rules() -> Tuple[Tuple[str, Optional[Tuple]], ...]:
    """Megatron-style TP rules shared by every in-tree transformer family:
    column-parallel qkv / fc-in (output dim on 'model'), row-parallel
    proj / fc-out (input dim on 'model'), vocab-sharded embedding,
    replicated LayerNorms. Families extend these with their own extras."""
    return (
        (r".*c_attn/kernel$", (None, "model")),
        (r".*c_attn/bias$", ("model",)),
        (r".*c_fc/kernel$", (None, "model")),
        (r".*c_fc/bias$", ("model",)),
        (r".*(c_proj|mlp_proj)/kernel$", ("model", None)),
        (r".*(c_proj|mlp_proj)/bias$", (None,)),
        (r".*wte$", ("model", None)),
        (r".*ln_.*/(scale|bias)$", None),
    )


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def build_specs(params: Any,
                rules: Iterable[Tuple[str, Optional[Tuple]]],
                default: Optional[Tuple] = None,
                mesh_axes: Optional[dict] = None) -> Any:
    """PartitionSpec pytree for ``params`` from (regex, dims) rules.

    dims is a tuple like (None, 'model') naming the mesh axis per tensor dim
    (or None for the whole rule → replicated). Axes of size 1 in ``mesh_axes``
    are dropped to replicated so single-chip runs need no special-casing.
    """
    compiled = [(re.compile(pat), dims) for pat, dims in rules]

    def axis_ok(axis_name):
        if axis_name is None:
            return True
        if mesh_axes is None:
            return True
        return mesh_axes.get(axis_name, 1) > 1

    def spec_for(path, leaf):
        name = _path_str(path)
        for pat, dims in compiled:
            if pat.search(name):
                if dims is None:
                    return PartitionSpec()
                dims = tuple(d if axis_ok(d) else None for d in dims)
                dims = dims[:leaf.ndim] + (None,) * (leaf.ndim - len(dims))
                return PartitionSpec(*dims)
        if default is not None:
            d = tuple(x if axis_ok(x) else None for x in default)
            return PartitionSpec(*d[:leaf.ndim])
        return PartitionSpec()

    return jax.tree_util.tree_map_with_path(spec_for, params)
