"""GPT-2 family — flagship causal-LM models, TPU-first.

The reference ships no model zoo of its own; its flagship benchmarks wrap
Megatron GPT-2 (``tests/model/Megatron_GPT2``, ``docs/_tutorials/megatron.md``).
Here the GPT family is in-tree flax so every subsystem (ZeRO, TP, pipeline,
sequence parallel, kernels) has a first-class target.

TPU-first choices:
- combined QKV projection (one big [D, 3D] matmul for the MXU, the same
  layout the reference's fused kernel uses via ``attn_qkvw``);
- bf16 activations with fp32 LayerNorm/softmax;
- attention goes through ``deepspeed_tpu.ops.transformer.attention`` so the
  Pallas flash kernel is a config flag, not a model rewrite;
- optional ``jax.checkpoint`` (remat) per block — activation checkpointing
  (reference ``runtime/activation_checkpointing/checkpointing.py``) as a
  model-level policy;
- tensor-parallel PartitionSpecs provided by ``gpt_partition_rules()``:
  attention/MLP weights split over the ``model`` axis Megatron-style
  (column-parallel qkv/fc-in, row-parallel proj/fc-out).
"""

from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.attention import attention
from deepspeed_tpu.ops.xent import fused_cross_entropy


from deepspeed_tpu.ops.dropout import dropout_module as _dropout_mod


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50257
    max_seq_len: int = 1024
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16          # activation/compute dtype
    attention_impl: str = "auto"
    remat: bool = False                 # activation checkpointing per block
    tie_embeddings: bool = True
    layer_norm_epsilon: float = 1e-5
    fused_ce: bool = True               # ops/xent.py fused CE head
    # exact fp32-logits numerics inside the fused CE (parity-sensitive
    # bf16 runs; costs the fp32 [N,V] HBM pass the fused op avoids)
    fused_ce_fp32_logits: bool = False
    # None -> 1/sqrt(head_dim); GPT-Neo trains UNSCALED attention (1.0)
    attention_scale: Any = None
    # MXU tiling lever (PROFILE.md r3): pad the wte vocab dim to a multiple
    # (128 pads GPT-2's 50257 -> 50304) so the tied head matmul tiles
    # exactly; pad logits are masked to -1e9 in the CE, so the loss is
    # numerically identical to the unpadded model and pad rows stay at
    # init. 0 = off. Applies to the tied-embedding head (lm_head stays
    # unpadded when untied).
    vocab_pad_multiple: int = 0
    # Embedding-table gradient via one-hot MXU matmul instead of XLA's
    # serialized TPU scatter-add (ops/embedding.py; PROFILE.md r3 lever).
    embed_grad_matmul: bool = False
    # Row-sparse cross-rank embedding-grad exchange (config
    # `sparse_gradients: true` — reference engine.py:1530-1586):
    # (mesh, axes) — what deepspeed_tpu.initialize() bakes in (the
    # ENGINE's mesh, never the ambient default) — or True / a bare axes
    # tuple for custom loops (resolved against the ambient mesh).
    sparse_embedding_grad: Any = None
    # Counter-hash activation dropout (ops/dropout.py) instead of flax's
    # threefry bernoulli — the reference's fused-dropout economy
    # (csrc/transformer/dropout_kernels.cu); measured A/B in PROFILE.md.
    fast_dropout: bool = True
    # Fused LayerNorm+projection Pallas kernel at the two pre-LN sites
    # (LN1+QKV and LN2+fc1+GELU) — the reference's fused-block economy
    # (csrc/transformer/ds_transformer_cuda.cpp:147). OFF by default:
    # measured end-to-end LOSS on v5e despite winning isolated micro A/Bs
    # (r5, tools/probe_fused_r5.py: qkv-only 0.93x, mlp-only 0.95x,
    # both 0.90x of baseline — the pallas_call is an XLA fusion barrier,
    # and the surrounding transposes/adds XLA previously fused into the
    # matmuls become standalone HBM passes; PROFILE.md r5). Values:
    # True/"auto" = both sites, "qkv"/"mlp" = one site, False = unfused.
    fused_ln: Any = False
    # Block-sparse attention config dict (the DeepSpeed `sparse_attention`
    # block: mode/block/num_local_blocks/...). When set, training attention
    # routes through ops.sparse_attention (long-sequence O(s·√s) path);
    # decode (kv_cache) stays dense. deepspeed_tpu.initialize() injects
    # this from the engine config automatically.
    sparse_attention: Any = None
    # MoE-GPT (the GShard/Switch "every other layer is MoE" family): with
    # moe_experts > 0, every moe_layer_freq-th block's FFN becomes a
    # deepspeed_tpu.moe.MoE layer (expert-parallel via moe_partition_rules)
    # and the load-balance aux losses fold into the training loss.
    moe_experts: int = 0
    moe_k: int = 1
    moe_layer_freq: int = 2            # every Nth block is MoE
    moe_capacity_factor: float = 1.25
    moe_aux_alpha: float = 0.01
    moe_eval_capacity_factor: float = 2.0
    moe_min_capacity: int = 4
    moe_router_jitter: float = 0.0     # train-only router input jitter
    # Dispatch mode: "scatter" | "einsum" | "alltoall" (moe/dispatch.py —
    # the explicit expert-axis exchange; needs moe_mesh or the ambient
    # default mesh). deepspeed_tpu.initialize() injects these from the
    # engine's `moe` config block, pinning the ENGINE's mesh like
    # sparse_embedding_grad.
    moe_dispatch: str = "scatter"
    moe_mesh: Any = None
    # When True the model output dict grows moe_* stat scalars (mean over
    # the MoE layers; dispatch bytes summed) for the engine's moe/*
    # gauges (telemetry/moe.py).
    moe_stats: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def padded_vocab(self) -> int:
        m = self.vocab_pad_multiple
        if m <= 1:
            return self.vocab_size
        return (self.vocab_size + m - 1) // m * m

    @property
    def num_params(self) -> int:
        d, l, v = self.hidden_size, self.num_layers, self.vocab_size
        per_layer = 12 * d * d + 13 * d
        return v * d + self.max_seq_len * d + l * per_layer + 2 * d


# Named configurations (sizes follow the public GPT-2 family).
GPT_CONFIGS: Dict[str, GPTConfig] = {
    "tiny": GPTConfig(vocab_size=512, max_seq_len=128, hidden_size=64,
                      num_layers=2, num_heads=4, dropout_rate=0.0),
    "gpt2": GPTConfig(hidden_size=768, num_layers=12, num_heads=12),
    "gpt2-medium": GPTConfig(hidden_size=1024, num_layers=24, num_heads=16),
    "gpt2-large": GPTConfig(hidden_size=1280, num_layers=36, num_heads=20),
    "gpt2-xl": GPTConfig(hidden_size=1600, num_layers=48, num_heads=25),
}


def _use_fused_ln(cfg, x) -> frozenset:
    """Dispatch for the fused LN+projection path (GPTConfig.fused_ln):
    returns the set of fused sites ("qkv", "mlp"). "auto" = both on TPU
    when shapes tile; True forces both (Pallas interpret off-TPU — parity
    tests); "qkv"/"mlp" select one site; False = unfused flax modules.

    Mode validation comes FIRST — a typo must always raise, never silently
    train unfused just because shapes happen not to tile. Each site is then
    shape-gated independently: an untileable mlp dim no longer disables a
    requested (and tileable) qkv fusion, and vice versa."""
    mode = getattr(cfg, "fused_ln", False)
    if mode is False or mode is None:
        return frozenset()
    if mode is not True and mode not in ("auto", "qkv", "mlp"):
        raise ValueError(f"unknown fused_ln value {mode!r}: expected False, "
                         "True, 'auto', 'qkv', or 'mlp'")
    if mode == "auto" and jax.devices()[0].platform != "tpu":
        return frozenset()
    from deepspeed_tpu.ops.transformer.fused import ln_matmul_ok

    n = x.shape[0] * x.shape[1]
    want = ("qkv", "mlp") if mode in (True, "auto") else (mode,)
    out_dim = {"qkv": 3 * cfg.hidden_size,
               "mlp": cfg.mlp_ratio * cfg.hidden_size}
    return frozenset(s for s in want
                     if ln_matmul_ok(n, cfg.hidden_size, out_dim[s]))


class GPTBlock(nn.Module):
    """Pre-LN transformer block (attention + MLP or MoE FFN).

    With ``moe=True`` the dense MLP is replaced by a
    :class:`deepspeed_tpu.moe.MoE` layer and the return value grows a
    trailing load-balance aux-loss scalar."""

    cfg: GPTConfig
    moe: bool = False

    @nn.compact
    def __call__(self, x, attn_mask=None, deterministic: bool = True,
                 kv_cache=None, pos=None):
        """``kv_cache``: optional ``(k, v)`` arrays of shape
        [B, max_len, H, Dh] for incremental decoding (the TPU-native analogue
        of the reference inference kernels' attention cache,
        csrc/transformer/inference/). With a cache, new k/v are written at
        ``pos`` and attention runs over the full cache under a
        position-validity mask (static shapes — jit/scan friendly). A
        non-tuple cache is taken as a paged-cache layer view
        (``serving/kv_cache.PagedLayerCache``): it owns the write/gather
        and per-row positions (continuous batching). Returns
        ``(x, cache')`` in cache mode, plain ``x`` otherwise.
        """
        cfg = self.cfg
        d = cfg.hidden_size
        dt = cfg.dtype
        fused = _use_fused_ln(cfg, x)

        if fused:
            from deepspeed_tpu.ops.transformer.fused import (DenseParams,
                                                             LNParams,
                                                             ln_matmul)
        if "qkv" in fused:
            scale1, lnb1 = LNParams(d, name="ln_1")()
            wk, wb = DenseParams(d, 3 * d, name="c_attn")()
            qkv = ln_matmul(x, scale1, lnb1, wk.astype(dt), wb.astype(dt),
                            eps=cfg.layer_norm_epsilon)
        else:
            h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                             dtype=jnp.float32, name="ln_1")(x).astype(dt)
            qkv = nn.Dense(3 * d, dtype=dt, name="c_attn")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, s = q.shape[0], q.shape[1]
        shape = (b, s, cfg.num_heads, cfg.head_dim)
        q, k, v = q.reshape(shape), k.reshape(shape), v.reshape(shape)
        drop_rng = (None if deterministic or cfg.dropout_rate == 0.0
                    else self.make_rng("dropout"))
        if kv_cache is not None:
            if isinstance(kv_cache, tuple):
                ck, cv = kv_cache
                ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                                  (0, pos, 0, 0))
                cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                                  (0, pos, 0, 0))
                kv_cache = (ck, cv)
                # Key j is visible to query i iff j <= pos + i (cached past
                # plus the causal prefix of this chunk).
                qpos = pos + jnp.arange(s)
                kpos = jnp.arange(ck.shape[1])
                dec_mask = (kpos[None, :] <= qpos[:, None])[None, None]
                if attn_mask is not None:
                    dec_mask = jnp.logical_and(dec_mask, attn_mask)
                o = attention(q, ck, cv, causal=False, mask=dec_mask,
                              deterministic=True, impl="xla",
                              softmax_scale=cfg.attention_scale)
            elif (getattr(kv_cache, "attn_impl", "gather")
                    in ("kernel", "chunked") and attn_mask is None):
                # Paged decode fast path: the Pallas kernel streams K/V
                # blocks from the pool through the block table (int8
                # pools dequantized in-kernel) — the gathered [B, L, H,
                # D] copy is never materialized. Same visibility
                # semantics as the gather branch below (parity-tested).
                # "chunked" is the ragged mixed-batch form: one flat
                # token batch with per-token tables and positions
                # (ChunkedLayerCache; ops/transformer/chunked_prefill.py).
                kv_cache, o = kv_cache.update_attend(
                    q, k, v, softmax_scale=cfg.attention_scale)
            else:
                # Paged decode (serving/kv_cache.py): the cache object
                # scatters this chunk through its block table at per-ROW
                # positions and hands back the gathered static-shape K/V
                # plus its own visibility mask — rows in a continuous
                # batch sit at different sequence lengths, so the scalar
                # ``pos`` is unused here.
                kv_cache, ck, cv, dec_mask = kv_cache.update(k, v)
                if attn_mask is not None:
                    dec_mask = jnp.logical_and(dec_mask, attn_mask)
                o = attention(q, ck, cv, causal=False, mask=dec_mask,
                              deterministic=True, impl="xla",
                              softmax_scale=cfg.attention_scale)
        elif cfg.sparse_attention is not None:
            # Config-driven block-sparse path (reference
            # sparse_attention_utils.py model surgery). Attention-prob
            # dropout is not applied under the sparse executor (the
            # reference's sparse path likewise has none); residual/MLP
            # dropouts still apply.
            from deepspeed_tpu.ops.sparse_attention.utils import \
                get_sparse_self_attention

            ssa = get_sparse_self_attention(cfg.sparse_attention,
                                            cfg.num_heads)
            km = None
            if attn_mask is not None:
                km = attn_mask[:, 0, 0, :]   # [B,1,1,S] -> [B,S] key mask
            o = ssa(q, k, v, causal=True, key_mask=km,
                    softmax_scale=cfg.attention_scale)
        else:
            o = attention(q, k, v, causal=True, mask=attn_mask,
                          dropout_rate=cfg.dropout_rate, dropout_rng=drop_rng,
                          deterministic=deterministic, impl=cfg.attention_impl,
                          softmax_scale=cfg.attention_scale)
        o = o.reshape(b, s, d)
        o = nn.Dense(d, dtype=dt, name="c_proj")(o)
        o = _dropout_mod(cfg)(cfg.dropout_rate, deterministic=deterministic)(o)
        x = x + o

        aux = None
        if "mlp" in fused and not self.moe:
            scale2, lnb2 = LNParams(d, name="ln_2")()
            wf, bf2 = DenseParams(d, cfg.mlp_ratio * d, name="c_fc")()
            h = ln_matmul(x, scale2, lnb2, wf.astype(dt), bf2.astype(dt),
                          eps=cfg.layer_norm_epsilon, activation="gelu")
            h = nn.Dense(d, dtype=dt, name="mlp_proj")(h)
        else:
            h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                             dtype=jnp.float32, name="ln_2")(x).astype(dt)
            if self.moe:
                from deepspeed_tpu.moe import MoE, MoEConfig

                moe_out = MoE(MoEConfig(
                    hidden_size=d, num_experts=cfg.moe_experts, k=cfg.moe_k,
                    capacity_factor=cfg.moe_capacity_factor,
                    eval_capacity_factor=cfg.moe_eval_capacity_factor,
                    min_capacity=cfg.moe_min_capacity,
                    router_jitter=cfg.moe_router_jitter,
                    dispatch=cfg.moe_dispatch, mesh=cfg.moe_mesh,
                    stats=cfg.moe_stats,
                    expert_intermediate=cfg.mlp_ratio * d, dtype=dt),
                    name="moe")(h, deterministic=deterministic)
                if cfg.moe_stats:
                    # Bundle (aux, stats) so the block's return arity
                    # stays fixed; GPT unpacks the pair.
                    h, aux_loss, moe_stats = moe_out
                    aux = (aux_loss, moe_stats)
                else:
                    h, aux = moe_out
            else:
                h = nn.Dense(cfg.mlp_ratio * d, dtype=dt, name="c_fc")(h)
                h = nn.gelu(h, approximate=True)
                h = nn.Dense(d, dtype=dt, name="mlp_proj")(h)
        h = _dropout_mod(cfg)(cfg.dropout_rate, deterministic=deterministic)(h)
        x = x + h
        out = (x, kv_cache) if kv_cache is not None else x
        if self.moe:
            return (out + (aux,)) if isinstance(out, tuple) else (out, aux)
        return out


class GPT(nn.Module):
    """Causal LM. ``__call__(batch)`` returns {"loss", "logits"} so it plugs
    straight into ``deepspeed_tpu.models.adapter.flax_module_loss_fn``.

    batch: {"input_ids": [B,S] int32, optional "labels" (shifted internally if
    absent), optional "attention_mask": [B,S] 1=keep}.
    """

    cfg: GPTConfig

    @nn.compact
    def __call__(self, batch, deterministic: bool = False,
                 cache=None, pos=None):
        """Training/eval: ``__call__(batch)`` → {"loss", "logits"}.

        Incremental decoding (inference engine): pass ``cache`` (per-layer
        tuple of (k, v) arrays from :func:`init_kv_cache`) and the write
        offset ``pos`` → {"logits", "cache"}; no loss is computed.
        """
        cfg = self.cfg
        ids = batch["input_ids"]
        b, s = ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.padded_vocab, cfg.hidden_size), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.01),
                         (cfg.max_seq_len, cfg.hidden_size), jnp.float32)
        pos_ids = batch.get("position_ids") if isinstance(batch, dict) else None
        if pos_ids is not None:
            # Per-row positions [B, S] — left-padded prompts re-base their
            # learned positions so row content starts at position 0.
            pe = jnp.take(wpe, pos_ids, axis=0)
        elif pos is None:
            pe = wpe[:s][None]
        else:
            pe = jnp.take(wpe, pos + jnp.arange(s), axis=0)[None]
        from deepspeed_tpu.ops.embedding import embedding_lookup
        tok = embedding_lookup(
            wte, ids, matmul_grad=cfg.embed_grad_matmul,
            sparse_grad_axes=cfg.sparse_embedding_grad)
        x = tok.astype(cfg.dtype) + pe.astype(cfg.dtype)
        x = _dropout_mod(cfg)(cfg.dropout_rate, deterministic=deterministic)(x)

        attn_mask = None
        if "attention_mask" in batch and batch["attention_mask"] is not None:
            am = batch["attention_mask"]          # [B, S] 1=keep
            if cache is not None:
                # Cache mode: the key axis is the cache length, not this
                # chunk. A [B, cache_len] mask is taken as the full
                # key-validity mask (fixed across decode — pad slots stay
                # masked); a [B, S] mask covers positions pos..pos+S and
                # keys already cached (< pos) stay visible.
                lmax = (cache[0][0].shape[1] if isinstance(cache[0], tuple)
                        else cache[0].key_len)
                if am.shape[1] == lmax:
                    km = am.astype(jnp.bool_)
                else:
                    if not isinstance(cache[0], tuple):
                        # Paged caches hold PER-ROW positions: a [B, S]
                        # chunk mask has no single key offset to land at,
                        # and splicing it at 0 would silently mask the
                        # wrong keys for every row.
                        raise ValueError(
                            f"paged cache mode takes a full [B, "
                            f"{lmax}] key-validity attention_mask; got "
                            f"{tuple(am.shape)} (per-chunk masks cannot "
                            f"be placed on a shared key axis with "
                            f"per-row positions)")
                    km = jnp.ones((b, lmax), jnp.bool_)
                    km = jax.lax.dynamic_update_slice(
                        km, am.astype(jnp.bool_),
                        (0, pos if pos is not None else 0))
                attn_mask = km[:, None, None, :]
            else:
                attn_mask = am[:, None, None, :].astype(jnp.bool_)

        block = GPTBlock
        if cfg.remat:
            block = nn.remat(GPTBlock, static_argnums=(3,))
        # Bucket-boundary grad-sync markers (comm/overlap.py): each block
        # reads its params through an identity marker whose custom_vjp
        # backward reduce-scatters the block's grads over ICI *between*
        # the layer backwards — the intra-backward overlap axis of the
        # overlapped gradient sync (docs/PERFORMANCE.md). Inert (zero
        # trace footprint) unless the engine's grad-sync plan installs
        # its hook; wrapping sits OUTSIDE remat so the scatter is not
        # rematerialized.
        from deepspeed_tpu.comm.overlap import marked_block

        def layer_block(i):
            return marked_block(block, f"h_{i}")(
                cfg, moe=is_moe(i), name=f"h_{i}")
        # Progressive Layer Drop (reference progressive_layer_drop.py +
        # engine hooks): per-step keep prob p_l = 1 - l/L * (1 - theta);
        # the engine injects batch["pld_theta"] when pld.enabled.
        pld_theta = batch.get("pld_theta") if isinstance(batch, dict) else None
        new_cache = []
        aux_total = jnp.float32(0.0)
        moe_layer_stats = []

        def is_moe(i):
            return (cfg.moe_experts > 0
                    and i % cfg.moe_layer_freq == cfg.moe_layer_freq - 1)

        for i in range(cfg.num_layers):
            if cache is not None:
                out = layer_block(i)(x, attn_mask, True, cache[i], pos)
                x, layer_kv = out[0], out[1]   # aux (if any) unused in decode
                new_cache.append(layer_kv)
            else:
                y = layer_block(i)(x, attn_mask, deterministic)
                aux_i = None
                if is_moe(i):
                    y, aux_i = y
                    if cfg.moe_stats:
                        aux_i, stats_i = aux_i
                        moe_layer_stats.append(stats_i)
                if pld_theta is not None and not deterministic:
                    from deepspeed_tpu.runtime.progressive_layer_drop import \
                        pld_keep_gate
                    gate = pld_keep_gate(self.make_rng("dropout"), i,
                                         cfg.num_layers, pld_theta)
                    y = jnp.where(gate, y, x)
                    if aux_i is not None:
                        # a PLD-dropped MoE layer contributed nothing —
                        # its balance loss must not push its router
                        aux_i = jnp.where(gate, aux_i, 0.0)
                if aux_i is not None:
                    aux_total = aux_total + aux_i
                x = y

        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32,
                         name="ln_f")(x)
        if cfg.tie_embeddings:
            logits = jnp.einsum("bsd,vd->bsv", x.astype(cfg.dtype),
                                wte.astype(cfg.dtype),
                                preferred_element_type=jnp.float32)
            if cfg.padded_vocab != cfg.vocab_size:
                logits = logits[..., :cfg.vocab_size]
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                              name="lm_head")(x.astype(cfg.dtype)).astype(jnp.float32)

        if cache is not None:
            return {"logits": logits, "cache": tuple(new_cache)}
        # Loss goes through the fused CE head (ops/xent.py): compute-dtype
        # logits, lse-only residual, backward recompute — the [B,S,V] fp32
        # materializations are the single biggest HBM sink at GPT-2 scale
        # (PROFILE.md). The `logits` output above is untouched; XLA
        # dead-code-eliminates it whenever the caller only uses the loss.
        # (A caller reading BOTH loss and logits pays the head matmul twice
        # — the fp32-logits einsum and the fused op's compute-dtype one
        # can't CSE; acceptable for eval loops, free for training.)
        labels = shift_labels(batch)
        if cfg.tie_embeddings and cfg.fused_ce:
            from deepspeed_tpu.ops.embedding import vocab_pad_mask
            mask = (vocab_pad_mask(cfg.padded_vocab, cfg.vocab_size)
                    if cfg.padded_vocab != cfg.vocab_size else None)
            loss = fused_cross_entropy(x.astype(cfg.dtype),
                                       wte.astype(cfg.dtype), labels,
                                       bias=mask, bias_grad=False,
                                       logits_fp32=cfg.fused_ce_fp32_logits)
        else:
            loss = cross_entropy_with_ignore(logits, labels)
        if cfg.moe_experts > 0:
            loss = loss + cfg.moe_aux_alpha * aux_total
        out = {"loss": loss, "logits": logits}
        if moe_layer_stats:
            # moe_* stat scalars for the engine's moe/* gauges
            # (telemetry/moe.py MOE_AUX_KEYS): mean over the MoE layers,
            # except the modeled wire bytes, which sum.
            n = float(len(moe_layer_stats))
            for key in moe_layer_stats[0]:
                total = sum(s[key] for s in moe_layer_stats)
                out["moe_" + key] = (
                    total if key == "dispatch_bytes_ici" else total / n)
        return out


def init_kv_cache(cfg: GPTConfig, batch_size: int, max_len: int,
                  dtype=None) -> Tuple:
    """Per-layer (k, v) cache arrays [B, max_len, H, Dh] for incremental
    decoding. Static shapes — the decode loop updates in place via
    ``dynamic_update_slice`` so the whole generate fits in one jitted scan."""
    dtype = dtype if dtype is not None else cfg.dtype
    shape = (batch_size, max_len, cfg.num_heads, cfg.head_dim)
    return tuple(
        (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
        for _ in range(cfg.num_layers))


def shift_labels(batch) -> jax.Array:
    """Next-token labels: explicit ``labels`` or input_ids shifted left with
    the trailing position ignored. Shared by the plain and pipeline heads."""
    labels = batch.get("labels")
    if labels is None:
        ids = batch["input_ids"]
        labels = jnp.pad(ids[:, 1:], ((0, 0), (0, 1)), constant_values=-100)
    return labels


def cross_entropy_with_ignore(logits: jax.Array, labels: jax.Array,
                              ignore_index: int = -100) -> jax.Array:
    """Token-mean cross entropy, fp32, ignoring ``ignore_index`` positions."""
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    return nll.sum() / jnp.maximum(valid.sum(), 1)


# ---------------------------------------------------------------------------
# Tensor-parallel partition rules (Megatron-style column/row split)
# ---------------------------------------------------------------------------

def gpt_partition_rules() -> Tuple[Tuple[str, Tuple], ...]:
    """(regex, spec-dims) pairs consumed by models.partition.build_specs —
    the shared Megatron-style block rules plus GPT-specific extras. Mirrors
    the reference's inference TP slicing (module_inject/replace_module.py:11).
    """
    from deepspeed_tpu.models.partition import transformer_block_rules
    from deepspeed_tpu.moe import moe_partition_rules

    return transformer_block_rules() + moe_partition_rules() + (
        (r".*wpe$", (None, None)),
        (r".*lm_head/kernel$", (None, "model")),
    )


def make_gpt(name_or_cfg="tiny", **overrides) -> Tuple[GPT, GPTConfig]:
    cfg = (GPT_CONFIGS[name_or_cfg] if isinstance(name_or_cfg, str)
           else name_or_cfg)
    if overrides:
        cfg = replace(cfg, **overrides)
    return GPT(cfg), cfg
