"""Model adapters.

The engine consumes a pure ``loss_fn(params, batch, rng) -> loss`` (or
``(loss, aux)``). These adapters build one from common model styles, playing
the role of the reference's ``nn.Module`` wrapping (engine holds the module
and calls ``self.module(*inputs)``, engine.py:1102).
"""

from typing import Any, Callable, Optional, Tuple

import jax


def flax_module_loss_fn(module, params: Any = None,
                        example_batch: Any = None,
                        init_rng: Optional[jax.Array] = None,
                        loss_key: str = "loss") -> Tuple[Callable, Any]:
    """Adapt a flax.linen module whose __call__ returns the scalar loss (or a
    dict containing ``loss``). Returns (loss_fn, params).

    The module is applied as ``module.apply({'params': p}, batch,
    rngs={'dropout': rng})``; batches are passed through unchanged.
    """
    if params is None:
        if example_batch is None:
            raise ValueError("need params or example_batch to initialise the module")
        rng = init_rng if init_rng is not None else jax.random.PRNGKey(0)
        variables = module.init({"params": rng, "dropout": rng}, example_batch)
        params = variables["params"]

    def loss_fn(p, batch, rng):
        # Convention: rng=None means evaluation — dropout off. The engine's
        # eval path passes None (engine._eval_step).
        if rng is None:
            out = module.apply({"params": p}, batch, deterministic=True)
        else:
            out = module.apply({"params": p}, batch, rngs={"dropout": rng})
        if isinstance(out, dict):
            loss = out[loss_key]
            aux = {k: v for k, v in out.items() if k != loss_key}
            return loss, aux
        return out

    # Published so config-driven re-derivations (the autotuner's moe
    # capacity/dispatch trials, autotuning/search.py) can rebuild the
    # loss with a replaced module cfg — the engine itself never holds
    # the module.
    loss_fn.module = module
    return loss_fn, params


def supervised_loss_fn(apply_fn: Callable, loss: Callable,
                       inputs_key: Any = 0, labels_key: Any = 1) -> Callable:
    """Build a loss_fn from separate forward + criterion, for (x, y) batches."""

    def loss_fn(p, batch, rng):
        x = batch[inputs_key]
        y = batch[labels_key]
        logits = apply_fn(p, x, rng)
        return loss(logits, y)

    return loss_fn
