"""BERT family — the reference's headline pretraining benchmark target
(BERT-Large, ``docs/_tutorials/bert-pretraining.md``; kernel-parity fixtures
``tests/unit/modeling.py`` / ``modelingpreln.py``).

Supports both post-LN (original BERT, reference ``modeling.py``) and pre-LN
(reference ``modelingpreln.py``, the variant the fused kernel's
``pre_layer_norm`` flag selects). MLM + NSP heads for pretraining parity.
"""

from dataclasses import dataclass, replace
from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt import cross_entropy_with_ignore
from deepspeed_tpu.ops.transformer.attention import attention




from deepspeed_tpu.ops.dropout import dropout_module as _dropout_mod

@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_seq_len: int = 512
    type_vocab_size: int = 2
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_ratio: int = 4
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16
    attention_impl: str = "auto"
    pre_layer_norm: bool = True     # reference fused-kernel default
    remat: bool = False
    layer_norm_epsilon: float = 1e-12
    fused_ce: bool = True               # ops/xent.py fused CE head
    # exact fp32-logits numerics inside the fused CE (parity-sensitive
    # bf16 runs; costs the fp32 [N,V] HBM pass the fused op avoids)
    fused_ce_fp32_logits: bool = False
    # Block-sparse attention config dict (the DeepSpeed `sparse_attention`
    # block); deepspeed_tpu.initialize() injects it from the engine config.
    # The reference's BertSparseSelfAttention surgery, as a config field.
    sparse_attention: Any = None
    # Counter-hash activation dropout (ops/dropout.py) — see GPTConfig.
    fast_dropout: bool = True
    # Row-sparse cross-rank embedding-grad exchange (`sparse_gradients:
    # true`) — see GPTConfig.sparse_embedding_grad.
    sparse_embedding_grad: Any = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


BERT_CONFIGS: Dict[str, BertConfig] = {
    "tiny": BertConfig(vocab_size=512, max_seq_len=128, hidden_size=64,
                       num_layers=2, num_heads=4, dropout_rate=0.0),
    "bert-base": BertConfig(hidden_size=768, num_layers=12, num_heads=12),
    "bert-large": BertConfig(hidden_size=1024, num_layers=24, num_heads=16),
}


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attn_mask=None, deterministic: bool = True):
        cfg = self.cfg
        d, dt = cfg.hidden_size, cfg.dtype
        ln = lambda name: nn.LayerNorm(epsilon=cfg.layer_norm_epsilon,
                                       dtype=jnp.float32, name=name)
        drop_rng = (None if deterministic or cfg.dropout_rate == 0.0
                    else self.make_rng("dropout"))

        def attn(h):
            qkv = nn.Dense(3 * d, dtype=dt, name="c_attn")(h)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            b, s = q.shape[0], q.shape[1]
            shape = (b, s, cfg.num_heads, cfg.head_dim)
            if cfg.sparse_attention is not None:
                # Config-driven block-sparse path — the BertSparseSelfAttention
                # analogue (reference sparse_attention_utils.py:100).
                from deepspeed_tpu.ops.sparse_attention.utils import \
                    get_sparse_self_attention

                ssa = get_sparse_self_attention(cfg.sparse_attention,
                                                cfg.num_heads)
                km = (attn_mask[:, 0, 0, :]
                      if attn_mask is not None else None)
                o = ssa(q.reshape(shape), k.reshape(shape),
                        v.reshape(shape), causal=False, key_mask=km)
            else:
                o = attention(q.reshape(shape), k.reshape(shape),
                              v.reshape(shape),
                              causal=False, mask=attn_mask,
                              dropout_rate=cfg.dropout_rate,
                              dropout_rng=drop_rng,
                              deterministic=deterministic,
                              impl=cfg.attention_impl)
            o = nn.Dense(d, dtype=dt, name="c_proj")(o.reshape(b, s, d))
            return _dropout_mod(cfg)(cfg.dropout_rate, deterministic=deterministic)(o)

        def mlp(h):
            h = nn.Dense(cfg.mlp_ratio * d, dtype=dt, name="c_fc")(h)
            h = nn.gelu(h, approximate=True)
            h = nn.Dense(d, dtype=dt, name="mlp_proj")(h)
            return _dropout_mod(cfg)(cfg.dropout_rate, deterministic=deterministic)(h)

        if cfg.pre_layer_norm:
            x = x + attn(ln("ln_attn")(x).astype(dt))
            x = x + mlp(ln("ln_mlp")(x).astype(dt))
        else:  # post-LN original BERT
            x = ln("ln_attn")(x + attn(x)).astype(dt)
            x = ln("ln_mlp")(x + mlp(x)).astype(dt)
        return x


class BertModel(nn.Module):
    """Pretraining model: encoder + MLM head (+ NSP when nsp labels given).

    batch: {"input_ids" [B,S], "attention_mask" [B,S] (1=keep, optional),
    "token_type_ids" (optional), "labels" (MLM, -100 = unmasked, optional),
    "next_sentence_label" [B] (optional)}.
    """

    cfg: BertConfig

    @nn.compact
    def __call__(self, batch, deterministic: bool = False):
        cfg = self.cfg
        ids = batch["input_ids"]
        b, s = ids.shape
        wte = self.param("wte", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.hidden_size), jnp.float32)
        wpe = self.param("wpe", nn.initializers.normal(0.02),
                         (cfg.max_seq_len, cfg.hidden_size), jnp.float32)
        tte = self.param("tte", nn.initializers.normal(0.02),
                         (cfg.type_vocab_size, cfg.hidden_size), jnp.float32)
        tt = batch.get("token_type_ids")
        tt_emb = tte[tt] if tt is not None else tte[0][None, None]
        from deepspeed_tpu.ops.embedding import embedding_lookup
        tok = embedding_lookup(
            wte, ids, sparse_grad_axes=cfg.sparse_embedding_grad)
        x = (tok + wpe[:s][None] + tt_emb).astype(cfg.dtype)
        if not cfg.pre_layer_norm:
            x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32,
                             name="ln_emb")(x).astype(cfg.dtype)
        x = _dropout_mod(cfg)(cfg.dropout_rate, deterministic=deterministic)(x)

        attn_mask = None
        am = batch.get("attention_mask")
        if am is not None:
            attn_mask = am[:, None, None, :].astype(jnp.bool_)

        layer = BertLayer
        if cfg.remat:
            layer = nn.remat(BertLayer, static_argnums=(3,))
        # Bucket-boundary grad-sync markers (comm/overlap.py): see the
        # GPT stack — identity unless the engine's overlapped grad-sync
        # plan installs its hook, in which case each layer's grads
        # reduce-scatter over ICI mid-backward.
        from deepspeed_tpu.comm.overlap import marked_block
        # Progressive Layer Drop — BERT is the reference's PLD target
        # (progressive_layer_drop.py + the PLD gates in its modeling files):
        # keep prob p_l = 1 - l/L * (1 - theta), theta injected per step by
        # the engine as batch["pld_theta"].
        pld_theta = batch.get("pld_theta")
        for i in range(cfg.num_layers):
            y = marked_block(layer, f"layer_{i}")(
                cfg, name=f"layer_{i}")(x, attn_mask, deterministic)
            if pld_theta is not None and not deterministic:
                from deepspeed_tpu.runtime.progressive_layer_drop import \
                    pld_keep_gate
                gate = pld_keep_gate(self.make_rng("dropout"), i,
                                     cfg.num_layers, pld_theta)
                y = jnp.where(gate, y, x)
            x = y
        if cfg.pre_layer_norm:
            x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32,
                             name="ln_f")(x).astype(cfg.dtype)

        # MLM head: transform + tied decoder (original BERT head shape).
        h = nn.Dense(cfg.hidden_size, dtype=cfg.dtype, name="mlm_transform")(x)
        h = nn.gelu(h, approximate=True)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=jnp.float32,
                         name="mlm_ln")(h)
        mlm_bias = self.param("mlm_bias", nn.initializers.zeros,
                              (cfg.vocab_size,), jnp.float32)
        logits = jnp.einsum("bsd,vd->bsv", h.astype(cfg.dtype),
                            wte.astype(cfg.dtype),
                            preferred_element_type=jnp.float32) + mlm_bias

        out = {"logits": logits}
        loss = jnp.float32(0.0)
        labels = batch.get("labels")
        if labels is not None and cfg.fused_ce:
            # Fused CE head (ops/xent.py): avoids the [B,S,V] fp32
            # log-softmax materializations; `logits` above is DCE'd by XLA
            # when the caller uses only the loss.
            from deepspeed_tpu.ops.xent import fused_cross_entropy
            loss = fused_cross_entropy(h.astype(cfg.dtype),
                                       wte.astype(cfg.dtype), labels,
                                       bias=mlm_bias,
                                       logits_fp32=cfg.fused_ce_fp32_logits)
        elif labels is not None:
            loss = cross_entropy_with_ignore(logits, labels)
        nsp = batch.get("next_sentence_label")
        if nsp is not None:
            pooled = jnp.tanh(nn.Dense(cfg.hidden_size, dtype=cfg.dtype,
                                       name="pooler")(x[:, 0]))
            nsp_logits = nn.Dense(2, dtype=cfg.dtype, name="nsp_head")(pooled)
            nsp_logp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32))
            loss = loss - jnp.mean(
                jnp.take_along_axis(nsp_logp, nsp[:, None], axis=-1))
            out["nsp_logits"] = nsp_logits
        out["loss"] = loss
        return out


def bert_partition_rules() -> Tuple[Tuple[str, Tuple], ...]:
    """Tensor-parallel rules — the shared block rules + BERT extras."""
    from deepspeed_tpu.models.partition import transformer_block_rules

    return transformer_block_rules() + (
        (r".*(wpe|tte)$", (None, None)),
    )


def make_bert(name_or_cfg="tiny", **overrides) -> Tuple[BertModel, BertConfig]:
    cfg = (BERT_CONFIGS[name_or_cfg] if isinstance(name_or_cfg, str)
           else name_or_cfg)
    if overrides:
        cfg = replace(cfg, **overrides)
    return BertModel(cfg), cfg
