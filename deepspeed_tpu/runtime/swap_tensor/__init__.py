"""Tensor swapping tier (host RAM <-> NVMe) — reference
``deepspeed/runtime/swap_tensor/``."""

from deepspeed_tpu.runtime.swap_tensor.aio import (AsyncTensorSwapper,
                                                   PipelinedLeafSwapper)

__all__ = ["AsyncTensorSwapper", "PipelinedLeafSwapper"]
