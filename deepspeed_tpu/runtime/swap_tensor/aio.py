"""Asynchronous tensor disk I/O — the aio tier.

TPU-native analogue of the reference's libaio stack (``csrc/aio/``,
``deepspeed/runtime/swap_tensor/aio_utils`` and
``AsyncTensorSwapper``/``AsyncIOBuilder``): a thread-pool of writers/readers
moving numpy buffers between host RAM and NVMe files, with futures standing
in for aio completion queues. The block transfers run in the native
extension (``csrc/aio/aio.cpp`` — GIL-free POSIX pread/pwrite, JIT-built
like the reference's op_builder) and fall
back to ``np.tofile``/``np.fromfile`` when no toolchain exists; either way
I/O overlaps host compute exactly as the reference overlaps aio submits
with CUDA work (``pipelined_optimizer_swapper.py:60``).

Swap files are one flat binary per tensor under ``base_dir`` — the layout of
the reference's per-parameter swap paths (``partitioned_param_swapper.py``).
"""

import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

ALIGN = 4096  # O_DIRECT alignment unit (pointer, length, file offset)


def aligned_empty(nbytes: int) -> np.ndarray:
    """A uint8 buffer whose data pointer is 4 KiB-aligned and whose length
    is padded up to a 4 KiB multiple — the shape O_DIRECT requires. Swap
    files therefore always hold whole blocks; readers slice the logical
    length back out."""
    cap = ((int(nbytes) + ALIGN - 1) // ALIGN) * ALIGN
    raw = np.empty(cap + ALIGN, np.uint8)
    off = (-raw.ctypes.data) % ALIGN
    return raw[off:off + cap]


class AsyncTensorSwapper:
    """Write/read named numpy tensors to per-name swap files, asynchronously.

    ``swap_out(name, arr)`` and ``swap_in(name)`` return futures;
    ``num_inflight`` and byte counters mirror the reference swapper's
    accounting (swap_out_tensors/AsyncTensorSwapper, optimizer_utils.py).
    """

    def __init__(self, base_dir: str, num_threads: int = 2):
        # Lazy: the native module JIT-builds on first swapper construction,
        # not at package import (workers that never swap pay nothing).
        from deepspeed_tpu.ops.aio_native import load_aio
        self._native = load_aio()
        self.base_dir = base_dir
        self.num_threads = num_threads
        os.makedirs(base_dir, exist_ok=True)
        self._pool = ThreadPoolExecutor(max_workers=num_threads,
                                        thread_name_prefix="dstpu-aio")
        self._meta: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {}
        self._last_write: Dict[str, Future] = {}
        self._lock = threading.Lock()
        self._inflight = 0
        self.bytes_written = 0
        self.bytes_read = 0

    def _path(self, name: str) -> str:
        safe = name.replace("/", "__")
        return os.path.join(self.base_dir, f"{safe}.swp")

    def _done(self, _fut):
        with self._lock:
            self._inflight -= 1

    @property
    def num_inflight(self) -> int:
        with self._lock:
            return self._inflight

    def swap_out(self, name: str, arr: np.ndarray) -> Future:
        """Queue a write of ``arr`` to ``name``'s swap file."""
        arr = np.ascontiguousarray(arr)
        self._meta[name] = (arr.shape, arr.dtype)
        nbytes = arr.nbytes

        def write():
            # Stage into an aligned, block-padded buffer (on the pool
            # thread — the caller's hot path only captures arr) so the
            # native write genuinely takes the O_DIRECT path.
            buf = aligned_empty(nbytes)
            buf[:nbytes] = arr.reshape(-1).view(np.uint8)
            buf[nbytes:] = 0
            if self._native is not None:
                self._native.write_buffer(self._path(name), buf, True)
            else:
                buf.tofile(self._path(name))
            with self._lock:
                self.bytes_written += nbytes
            return name

        with self._lock:
            self._inflight += 1
        fut = self._pool.submit(write)
        self._last_write[name] = fut
        fut.add_done_callback(self._done)
        return fut

    def swap_in(self, name: str) -> Future:
        """Queue a read; the future resolves to the numpy array. A read
        always observes the latest ``swap_out`` of the same name: the read
        task first waits on that name's pending write (aio completion-order
        guarantee)."""
        if name not in self._meta:
            raise KeyError(f"no swapped tensor named '{name}'")
        shape, dtype = self._meta[name]
        pending = self._last_write.get(name)

        def read():
            if pending is not None:
                pending.result()
            nbytes = int(np.prod(shape, dtype=np.int64)) * \
                np.dtype(dtype).itemsize
            buf = aligned_empty(nbytes)
            if self._native is not None:
                got = self._native.read_buffer(self._path(name), buf, True)
                if got < nbytes:
                    raise IOError(f"short read: {got} of {nbytes} bytes "
                                  f"from {self._path(name)}")
            else:
                raw = np.fromfile(self._path(name), dtype=np.uint8)
                if len(raw) < nbytes:
                    raise IOError(
                        f"short read: {len(raw)} of {nbytes} bytes "
                        f"from {self._path(name)}")
                buf[:len(raw)] = raw[:len(buf)]
            out = buf[:nbytes].view(dtype).reshape(shape)
            with self._lock:
                self.bytes_read += nbytes
            return out

        with self._lock:
            self._inflight += 1
        fut = self._pool.submit(read)
        fut.add_done_callback(self._done)
        return fut

    def contains(self, name: str) -> bool:
        return name in self._meta

    def synchronize(self) -> None:
        """Barrier: wait for every queued request (aio wait analogue)."""
        self._pool.shutdown(wait=True)
        self._pool = ThreadPoolExecutor(max_workers=self.num_threads,
                                        thread_name_prefix="dstpu-aio")

    def close(self, remove_files: bool = False) -> None:
        self._pool.shutdown(wait=True)
        if remove_files:
            for name in list(self._meta):
                try:
                    os.remove(self._path(name))
                except OSError:
                    pass
            self._meta.clear()


class PipelinedLeafSwapper:
    """Double-buffered per-leaf streaming over a sequence of named tensors —
    the ``PipelinedOptimizerSwapper`` analogue: while leaf *i* is being
    computed on, leaf *i+1*'s state is already being read from disk and leaf
    *i-1*'s result is being written back."""

    def __init__(self, swapper: AsyncTensorSwapper):
        self.swapper = swapper

    def stream(self, names: Sequence[str], compute_fn):
        """For each name (whose state was previously swapped out), read its
        tensors, call ``compute_fn(name, arr) -> new_arr``, write the result
        back. Reads are prefetched one leaf ahead."""
        if not names:
            return
        pending_read = self.swapper.swap_in(names[0])
        write_fut: Optional[Future] = None
        for i, name in enumerate(names):
            arr = pending_read.result()
            if i + 1 < len(names):
                pending_read = self.swapper.swap_in(names[i + 1])
            new_arr = compute_fn(name, arr)
            if write_fut is not None:
                write_fut.result()
            write_fut = self.swapper.swap_out(name, np.asarray(new_arr))
        if write_fut is not None:
            write_fut.result()
