"""Runtime utilities.

Parity with the reference's ``deepspeed/runtime/utils.py``: overflow checking
(:74), MP-aware global grad norm (:201), ``partition_uniform`` /
``partition_balanced`` layer partitioning (:342, :408), and memory reporting
(:578). All numeric helpers are pure jax functions usable inside jit.
"""

import bisect
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


# ---------------------------------------------------------------------------
# Numeric helpers (pure, jit-safe)
# ---------------------------------------------------------------------------

def global_norm(tree) -> jax.Array:
    """L2 norm over a pytree of gradients, computed in fp32."""
    leaves = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in jax.tree_util.tree_leaves(tree)]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(sum(leaves))


def clip_grad_by_global_norm(tree, max_norm: float, norm: Optional[jax.Array] = None):
    """Scale the whole tree so its global norm is <= max_norm (reference
    ``clip_grad_norm_`` semantics at utils.py:201 without the in-place update)."""
    if norm is None:
        norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), tree)


def has_inf_or_nan(tree) -> jax.Array:
    """Overflow predicate over a grad tree (reference CheckOverflow, utils.py:74).

    Inside jit this folds into the step; across the data axis the grads are
    already identical post-reduction so no extra collective is needed.

    The check runs in each leaf's NATIVE dtype: upcasting to fp32 first
    (the old behaviour) materialised a second full-width copy of every
    half-precision leaf, doubling the predicate's read traffic on large
    grad trees for zero semantic gain — fp16/bf16 -> fp32 is exact, so
    ``isfinite`` answers identically either way. Non-inexact leaves (int
    step counters riding in an opt-state tree) are finite by construction
    and are skipped outright.
    """
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)]
    if not leaves:
        return jnp.zeros((), jnp.bool_)
    flags = [~jnp.isfinite(x).all() for x in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = out | f
    return out


def count_parameters(tree) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(tree))


# ---------------------------------------------------------------------------
# Layer partitioning (pipeline stage assignment) — pure Python
# ---------------------------------------------------------------------------

def partition_uniform(num_items: int, num_parts: int) -> List[int]:
    """Boundaries [p0..pP] splitting num_items as evenly as possible
    (reference utils.py:342)."""
    if num_parts <= 0:
        raise ValueError("num_parts must be positive")
    parts = [0] * (num_parts + 1)
    chunk, remainder = divmod(num_items, num_parts)
    for p in range(1, num_parts + 1):
        parts[p] = parts[p - 1] + chunk + (1 if p <= remainder else 0)
    assert parts[-1] == num_items
    return parts


def prefix_sum_inc(weights: Sequence[float]) -> List[float]:
    out = []
    total = 0.0
    for w in weights:
        total += w
        out.append(total)
    return out


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Boundaries minimising the max part weight, via binary search over the
    bottleneck value (reference utils.py:408 uses the same idea)."""
    n = len(weights)
    if num_parts <= 0:
        raise ValueError("num_parts must be positive")
    if n == 0:
        return [0] * (num_parts + 1)
    prefix = prefix_sum_inc(weights)

    def parts_needed(bottleneck: float) -> Optional[List[int]]:
        """Greedy check: can we split into <= num_parts with each <= bottleneck?"""
        bounds = [0]
        start_sum = 0.0
        while bounds[-1] < n:
            # furthest end such that sum(weights[start:end]) <= bottleneck
            limit = start_sum + bottleneck
            end = bisect.bisect_right(prefix, limit, lo=bounds[-1])
            if end == bounds[-1]:  # single item exceeds bottleneck
                return None
            bounds.append(end)
            start_sum = prefix[end - 1]
            if len(bounds) - 1 > num_parts:
                return None
        return bounds

    lo = max(weights)
    hi = prefix[-1]
    # Binary search over real-valued bottleneck to ~1e-6 relative precision.
    for _ in range(64):
        mid = (lo + hi) / 2
        if parts_needed(mid) is not None:
            hi = mid
        else:
            lo = mid
    bounds = parts_needed(hi)
    assert bounds is not None
    # Pad with empty trailing parts if greedy used fewer than num_parts.
    while len(bounds) - 1 < num_parts:
        bounds.append(n)
    return bounds


# ---------------------------------------------------------------------------
# Memory reporting
# ---------------------------------------------------------------------------

def see_memory_usage(message: str, force: bool = False) -> None:
    """Log device + host memory (reference utils.py:578).

    Aggregates ALL local devices — same convention as the engine's HBM
    gauges and the memory observatory: in-use is the summed host
    footprint, peak is the worst chip (the OOM margin), limit is the
    tightest chip's ``bytes_limit``."""
    if not force:
        return
    try:
        peaks, in_use, limits = [], [], []
        for dev in jax.local_devices():
            stats = dev.memory_stats() or {}
            if stats:
                peaks.append(stats.get("peak_bytes_in_use", 0))
                in_use.append(stats.get("bytes_in_use", 0))
                limits.append(stats.get("bytes_limit", 0))
        if not peaks:
            raise RuntimeError("no device reported memory stats")
        limit = min((l for l in limits if l), default=0)
        logger.info(
            f"{message} | HBM in-use {sum(in_use) / 1024**3:.2f} GB, "
            f"peak {max(peaks) / 1024**3:.2f} GB, "
            f"limit {limit / 1024**3:.2f} GB ({len(peaks)} devices)")
    except Exception:
        logger.info(f"{message} | device memory stats unavailable")
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    rss_gb = int(line.split()[1]) / (1024**2)
                    logger.info(f"{message} | host RSS {rss_gb:.2f} GB")
                    break
    except OSError:
        pass
