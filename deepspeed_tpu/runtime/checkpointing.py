"""Checkpoint save/load.

Parity with the reference's checkpoint subsystem (``engine.save_checkpoint``
``runtime/engine.py:1838``, ``load_checkpoint`` :1638, SURVEY.md §3.5):

- tag-named directories under ``save_dir`` with a ``latest`` pointer file;
- model states and optimizer/ZeRO states are logically separate so a model
  can be loaded without optimizer state (``load_optimizer_states=False``);
- ZeRO-sharded state is saved *distributed* via orbax (each host writes its
  shards — the analogue of per-dp-rank ``zero_pp_rank_*`` files) and can be
  restored onto a different dp world size: orbax re-shards on load, which is
  the reference's ``elastic_checkpoint`` dp-resharding (stage2.py:1921);
- ``consolidate_to_fp32`` mirrors ``zero_to_fp32.py`` (offline shard merge).

client_state round-trips arbitrary user metadata exactly like the reference.
"""

import json
import os
import pickle
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from deepspeed_tpu.utils.logging import log_dist, logger

LATEST_FILE = "latest"
STATE_SUBDIR = "state"
META_FILE = "ds_meta.json"
CLIENT_STATE_FILE = "client_state.pkl"
SCHED_FILE = "lr_scheduler.json"


def _tag_dir(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, str(tag))


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[Dict] = None,
                    save_latest: bool = True) -> str:
    """Write a checkpoint; returns the tag directory path."""
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    path = _tag_dir(save_dir, tag)
    os.makedirs(path, exist_ok=True)

    state = engine.state
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(os.path.join(path, STATE_SUBDIR)),
               _to_saveable(state), force=True)
    ckptr.wait_until_finished()

    if jax.process_index() == 0:
        meta = {
            "global_steps": engine.global_steps,
            "micro_steps": engine.micro_steps,
            "skipped_steps": int(state.skipped_steps),
            "zero_stage": engine.config.zero_config.stage,
            "precision": engine.precision.name,
            "dp_world_size": engine.dp_size,
            "world_size": engine.mesh.size,
            "gradient_accumulation_steps": engine.gradient_accumulation_steps,
            "ds_version": _version(),
        }
        with open(os.path.join(path, META_FILE), "w") as f:
            json.dump(meta, f, indent=2)
        with open(os.path.join(path, CLIENT_STATE_FILE), "wb") as f:
            pickle.dump(client_state or {}, f)
        if engine.lr_scheduler is not None:
            with open(os.path.join(path, SCHED_FILE), "w") as f:
                json.dump(engine.lr_scheduler.state_dict(), f)
        if save_latest:
            with open(os.path.join(save_dir, LATEST_FILE), "w") as f:
                f.write(str(tag))
    log_dist(f"saved checkpoint {path}", ranks=[0])
    return path


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_lr_scheduler_states: bool = True):
    """Restore engine state; returns (path, client_state) like the reference."""
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if not os.path.exists(latest):
            logger.warning(f"no 'latest' file in {load_dir}; nothing restored")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = _tag_dir(load_dir, tag)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint dir not found: {path}")

    abstract_state = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        engine.state)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(os.path.abspath(os.path.join(path, STATE_SUBDIR)),
                             _to_saveable(abstract_state))
    new_state = _from_saveable(engine.state, restored)
    if not load_optimizer_states:
        new_state = new_state._replace(opt_state=engine.state.opt_state)
    engine.state = new_state

    meta_path = os.path.join(path, META_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = int(meta.get("global_steps", 0))
        engine.micro_steps = int(meta.get("micro_steps", 0))
    client_state: Dict[str, Any] = {}
    cs_path = os.path.join(path, CLIENT_STATE_FILE)
    if os.path.exists(cs_path):
        with open(cs_path, "rb") as f:
            client_state = pickle.load(f)
    if load_lr_scheduler_states and engine.lr_scheduler is not None:
        sp = os.path.join(path, SCHED_FILE)
        if os.path.exists(sp):
            with open(sp) as f:
                engine.lr_scheduler.load_state_dict(json.load(f))
    log_dist(f"loaded checkpoint {path}", ranks=[0])
    return path, client_state


def _to_saveable(state):
    """TrainState (NamedTuple of pytrees) -> plain nested dict for orbax.

    Works equally on a tree of arrays or of ShapeDtypeStructs (restore types).
    """
    d = state._asdict() if hasattr(state, "_asdict") else dict(state)
    for k, v in d.items():
        if hasattr(v, "_asdict"):
            d[k] = _to_saveable(v)
    return d


def _from_saveable(template_state, restored: Dict):
    """Plain nested dict -> the template's NamedTuple types."""

    def rebuild(template, node):
        if hasattr(template, "_fields"):
            return type(template)(**{f: rebuild(getattr(template, f), node[f])
                                     for f in template._fields})
        return node

    return rebuild(template_state, restored)


def _version() -> str:
    from deepspeed_tpu.version import __version__

    return __version__


def load_module_params(load_dir: str, tag: Optional[str] = None):
    """Restore only the model param tree from a training checkpoint — the
    inference engine's ``checkpoint=`` loading path (reference
    ``InferenceEngine._load_checkpoint``, inference/engine.py:212). No engine
    or optimizer state is constructed."""
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip()
        elif os.path.isdir(os.path.join(load_dir, STATE_SUBDIR)):
            tag = ""  # load_dir is itself a tag directory
        else:
            raise FileNotFoundError(
                f"no '{LATEST_FILE}' file in {load_dir} and it is not a "
                f"tag directory (no '{STATE_SUBDIR}/' inside); pass tag= "
                f"or point at a checkpoint written by save_checkpoint")
    path = os.path.abspath(os.path.join(_tag_dir(load_dir, tag) if tag
                                        else load_dir, STATE_SUBDIR))
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint state dir not found: {path}")
    # Partial restore of just the params subtree: a TrainState checkpoint is
    # ~4x the param bytes (moments + grad accumulator); inference must not
    # pay that in host RAM or load time.
    meta = ocp.StandardCheckpointer().metadata(path)
    params_meta = meta.item_metadata.tree["params"]
    template = jax.tree_util.tree_map(
        lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype), params_meta)
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(
        path, item={"params": template}, transforms={},
        restore_args=ocp.checkpoint_utils.construct_restore_args(
            {"params": template}))
    return jax.tree_util.tree_map(jax.numpy.asarray, restored["params"])


# ---------------------------------------------------------------------------
# zero_to_fp32 equivalent (reference utils/zero_to_fp32.py)
# ---------------------------------------------------------------------------

def consolidate_to_fp32(checkpoint_dir: str, tag: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Offline: read a (possibly sharded) checkpoint and return a flat dict of
    consolidated fp32 master params, without constructing an engine. orbax
    reassembles shards transparently, which is the whole job of the
    reference's zero_to_fp32.py script."""
    if tag is None:
        with open(os.path.join(checkpoint_dir, LATEST_FILE)) as f:
            tag = f.read().strip()
    path = os.path.abspath(os.path.join(_tag_dir(checkpoint_dir, tag), STATE_SUBDIR))
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(path)
    params = restored["params"]
    flat = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}.{k}" if prefix else str(k), v)
        else:
            flat[prefix] = np.asarray(node, dtype=np.float32)

    walk("", params)
    return flat


def zero_to_fp32_main():
    """Console entry ``zero-to-fp32-tpu`` — the reference's standalone
    ``utils/zero_to_fp32.py`` script: consolidate a (sharded) checkpoint
    into a flat fp32 ``.npz`` without constructing an engine."""
    import argparse

    ap = argparse.ArgumentParser(
        description="Consolidate a deepspeed_tpu checkpoint to fp32")
    ap.add_argument("checkpoint_dir")
    ap.add_argument("output_file", help="destination .npz")
    ap.add_argument("--tag", default=None)
    args = ap.parse_args()
    flat = consolidate_to_fp32(args.checkpoint_dir, tag=args.tag)
    np.savez(args.output_file, **flat)
    total = sum(v.size for v in flat.values())
    print(f"wrote {len(flat)} tensors ({total / 1e6:.1f}M params) "
          f"to {args.output_file}")
