"""Progressive Layer Drop.

Reference: ``deepspeed/runtime/progressive_layer_drop.py:5`` (theta schedule)
+ the engine hooks at ``engine.py:1085,1327`` + the PLD gating inside the
Megatron/BERT modeling files. The schedule is identical:

    theta(t) = (1 - theta_bar) * exp(-gamma * t) + theta_bar

with ``theta_bar`` the configured asymptotic keep probability. Layer *l* of
*L* then keeps its sublayers with probability ``p_l = 1 - l/L * (1 - theta)``
(deeper layers drop more), sampled per step per layer.

TPU-native wiring: theta is a *traced scalar input* to the jitted train step
— the engine injects it into the batch as ``batch["pld_theta"]`` and the
in-tree model families gate each block with a Bernoulli draw from the
dropout rng stream, so the drop pattern changes every step without
recompilation.
"""

import math


def pld_keep_gate(key, layer_idx, num_layers, theta):
    """The per-layer Bernoulli keep gate — ONE implementation shared by
    the flat GPT/BERT families and the pipelined block path so their
    theta schedules cannot drift: keep probability
    ``p_l = 1 - l/L * (1 - theta)`` (deeper layers drop more).
    ``layer_idx`` may be a traced scalar (the pipelined scan's global
    block index). Returns a boolean scalar."""
    import jax
    import jax.numpy as jnp

    frac = jnp.asarray(layer_idx, jnp.float32) / num_layers
    p_keep = 1.0 - frac * (1.0 - theta)
    return jax.random.bernoulli(key, p_keep)


class ProgressiveLayerDrop:
    """Theta schedule (reference progressive_layer_drop.py API parity:
    ``get_state``, ``get_theta``, ``update_state``)."""

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = float(theta)     # theta_bar, asymptotic keep prob
        self.gamma = float(gamma)
        self.current_theta = 1.0

    def get_theta(self, global_step: int = None) -> float:
        if global_step is None:
            return self.current_theta
        return ((1.0 - self.theta) * math.exp(-self.gamma * global_step)
                + self.theta)

    def update_state(self, global_step: int) -> float:
        self.current_theta = self.get_theta(global_step)
        return self.current_theta

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}
