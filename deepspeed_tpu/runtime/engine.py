"""The training engine.

TPU-native re-design of the reference ``DeepSpeedEngine``
(``deepspeed/runtime/engine.py:85``). The torch engine is a stateful
``nn.Module`` wrapper whose ``forward/backward/step`` mutate flat fp16
buffers via autograd hooks; here the same public surface drives three jitted
pure functions over an explicit ``TrainState`` pytree:

- ``_micro_step``  — fwd+bwd of one micro-batch, grads accumulated into a
  (possibly data-sharded) fp32 buffer. Equivalent to engine.forward
  (:1073) + engine.backward (:1144): loss is scaled by the dynamic loss
  scale and divided by gradient_accumulation_steps (engine.py:1158).
- ``_apply_step``  — GAS-boundary optimizer step: overflow check (≡
  CheckOverflow, runtime/utils.py:74), unscale, global-norm clip, Adam/LAMB
  update, loss-scale update, overflow-skip (≡ _take_model_step :1253).
- ``_train_step``  — fused scan over all GAS micro-batches + apply, used by
  ``train_batch`` and the benchmark path (single dispatch per global step).

ZeRO stages are *placement policies* (runtime/zero/partition.py): the same
jitted functions run stages 0-3; only the in/out shardings change, and XLA
emits allreduce / reduce-scatter / all-gather accordingly. Gradient
accumulation therefore happens on the *sharded* grads for stage>=2 — each
device accumulates only its shard, the memory/comm behaviour the reference
builds by hand with IPG buckets (stage2.py:701).

The "model" is a pure ``loss_fn(params, batch, rng) -> loss | (loss, aux)``;
adapters for flax modules live in ``deepspeed_tpu.models.adapter``.
"""

import collections
import contextlib
import os
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.config.config import ConfigError, DeepSpeedTPUConfig
from deepspeed_tpu.config import constants as C
from deepspeed_tpu.ops.adam.fused_adam import FusedAdam, FusedAdamW, HostOffloadAdam
from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb
from deepspeed_tpu.parallel.mesh import (DATA_AXIS, build_mesh,
                                         set_default_mesh as
                                         mesh_lib_set_default)
from deepspeed_tpu.runtime.lr_schedules import build_lr_schedule
from deepspeed_tpu.runtime.precision import (LossScaleState, PrecisionPolicy,
                                             make_loss_scaler)
from deepspeed_tpu.runtime.utils import (clip_grad_by_global_norm, global_norm,
                                         has_inf_or_nan)
from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer


class TrainState(NamedTuple):
    """Everything that evolves during training — one sharded pytree."""

    step: jax.Array            # global (optimizer) steps taken, int32
    micro_step: jax.Array      # micro-batches seen, int32
    params: Any                # fp32 master params (ZeRO-sharded per stage)
    opt_state: Any             # optimizer moments (ZeRO-sharded stage>=1)
    grad_acc: Any              # fp32 grad accumulator (sharded stage>=2)
    loss_scale: LossScaleState
    skipped_steps: jax.Array   # int32, overflow-skipped steps
    rng: jax.Array             # PRNG key threaded through dropout


class SGD:
    """Plain SGD with momentum — keeps the basic-optimizer path complete."""

    def __init__(self, lr: float = 1e-3, momentum: float = 0.0,
                 weight_decay: float = 0.0):
        self.lr, self.momentum, self.weight_decay = float(lr), float(momentum), float(weight_decay)

    def init(self, params):
        if self.momentum == 0.0:
            return jnp.zeros((), jnp.int32)
        return jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr

        def leaf(p, g, m):
            g = g.astype(jnp.float32)
            if self.weight_decay:
                g = g + self.weight_decay * p
            if self.momentum == 0.0:
                return p - lr * g, m
            m = self.momentum * m + g
            return p - lr * m, m

        if self.momentum == 0.0:
            new_p = jax.tree_util.tree_map(lambda p, g: leaf(p, g, None)[0], params, grads)
            return new_p, state
        out = jax.tree_util.tree_map(leaf, params, grads, state)
        new_p = jax.tree_util.tree_map(lambda o: o[0], out,
                                       is_leaf=lambda x: isinstance(x, tuple))
        new_m = jax.tree_util.tree_map(lambda o: o[1], out,
                                       is_leaf=lambda x: isinstance(x, tuple))
        return new_p, new_m


OPTIMIZER_REGISTRY = {
    C.ADAM_OPTIMIZER: FusedAdam,
    C.ADAMW_OPTIMIZER: FusedAdamW,
    C.LAMB_OPTIMIZER: FusedLamb,
    C.CPU_ADAM_OPTIMIZER: HostOffloadAdam,
    C.SGD_OPTIMIZER: SGD,
}


def _tree_where(pred, a, b):
    return jax.tree_util.tree_map(lambda x, y: jnp.where(pred, x, y), a, b)


# ONE process-wide jitted global_norm for the introspection accessor
# (get_global_grad_norm): jax.jit caches per (fn, signature), so a fresh
# wrapper per call — the old `jax.jit(global_norm)` inline — re-traced on
# EVERY invocation. Lazy so importing this module stays backend-free.
_GLOBAL_NORM_JIT = None


def _global_norm_jit():
    global _GLOBAL_NORM_JIT
    if _GLOBAL_NORM_JIT is None:
        _GLOBAL_NORM_JIT = jax.jit(global_norm)
    return _GLOBAL_NORM_JIT


class TPUEngine:
    """The DeepSpeedEngine analogue.

    Construction wires config → mesh → ZeRO placement → optimizer → loss
    scaler → jitted steps, mirroring the reference's __init__ call stack
    (SURVEY.md §3.2).
    """

    # The ZeRO++ weight path (zero_optimization.zeropp) builds its
    # explicit param gather into THIS engine's step builders; engines
    # with their own builders (the pipeline engine) opt out and the
    # config validation below fails loudly instead of silently ignoring
    # the block.
    _supports_zeropp = True

    def __init__(self,
                 loss_fn: Callable,
                 params: Any,
                 config: DeepSpeedTPUConfig,
                 mesh: Optional[Mesh] = None,
                 param_partition_specs: Any = None,
                 optimizer: Any = None,
                 lr_scheduler: Any = None,
                 batch_spec: Optional[PartitionSpec] = None,
                 rng_seed: int = 0,
                 donate_state: bool = True,
                 sparse_gradients_handled: bool = False):
        self.config = config
        self.loss_fn = loss_fn
        self.mesh = mesh if mesh is not None else build_mesh(
            data=-1, model=config.mesh.model, pipe=config.mesh.pipe,
            sequence=config.mesh.sequence, expert=config.mesh.expert,
            slices=config.mesh.slices)
        from deepspeed_tpu.parallel.mesh import DCN_AXIS
        self.dcn_size = self.mesh.shape.get(DCN_AXIS, 1)
        # Global data parallelism spans the DCN-outer slice axis too; ZeRO
        # sharding stays on the ICI-inner `data` axis (partition.py).
        self.dp_size = self.mesh.shape.get(DATA_AXIS, 1) * self.dcn_size
        # Register as the ambient mesh for mesh-needing ops (ring/ulysses
        # attention) — but never steal it from an earlier engine: with two
        # live engines the later construction would silently repoint the
        # first engine's attention to the wrong mesh.
        from deepspeed_tpu.parallel.mesh import get_default_mesh
        if get_default_mesh() is None:
            mesh_lib_set_default(self.mesh)

        # --- precision ------------------------------------------------------
        self.precision = PrecisionPolicy(config.precision_dtype)
        # In-device skip-on-nonfinite-grads for bf16/fp32 runs (satellite
        # of the fp16 overflow path; config-gated, default off — the
        # predicate rides inside the jitted step functions built below).
        self._nonfinite_grad_check = config.guardrails.nonfinite_grad_check
        # GAS accumulator dtype (config data_types.grad_accum_dtype): fp32
        # default; bf16 halves the accumulator's HBM read+write per
        # microbatch — the reference's fp16 engine accumulates in half
        # precision the same way.
        self.grad_accum_dtype = (jnp.bfloat16 if config.grad_accum_dtype in
                                 ("bfloat16", "bf16") else jnp.float32)
        self.loss_scaler = make_loss_scaler(
            fp16_enabled=config.fp16.enabled,
            dynamic=config.fp16.dynamic_loss_scale,
            static_scale=config.fp16.loss_scale or 1.0,
            initial_scale_power=config.fp16.initial_scale_power,
            scale_window=config.fp16.loss_scale_window,
            min_scale=config.fp16.min_loss_scale,
            hysteresis=config.fp16.hysteresis)

        # --- ZeRO placement -------------------------------------------------
        self.partitioner = ZeroPartitioner(self.mesh, config.zero_config)
        self._base_specs = param_partition_specs
        self.param_specs = self.partitioner.param_specs(params, param_partition_specs)
        self.grad_specs = self.partitioner.grad_specs(params, param_partition_specs)
        self.opt_specs = self.partitioner.opt_state_specs(params, param_partition_specs)
        self._custom_batch_spec = batch_spec is not None
        if batch_spec is not None:
            self.batch_spec = batch_spec
        elif self.dcn_size > 1:
            # Batches shard over slices first, then ICI-inner data.
            self.batch_spec = PartitionSpec((DCN_AXIS, DATA_AXIS))
        else:
            self.batch_spec = PartitionSpec(DATA_AXIS)

        # --- optimizer ------------------------------------------------------
        self.optimizer = optimizer if optimizer is not None \
            else self._configure_basic_optimizer()
        self.lr_scheduler = lr_scheduler if lr_scheduler is not None \
            else build_lr_schedule(config.scheduler_name, config.scheduler_params)
        self._base_lr = getattr(self.optimizer, "lr", 1e-3)
        # optimizer.type "cpuadam" implies the host tier even without an
        # explicit offload_optimizer block (reference cpu_adam semantics).
        # Engine-local: must not mutate the caller's (possibly shared) config.
        self._offload_cfg = config.zero_config.offload_optimizer
        if (getattr(self.optimizer, "host_resident", False)
                and not self._offload_cfg.enabled):
            from deepspeed_tpu.runtime.zero.config import ZeroOffloadConfig
            self._offload_cfg = ZeroOffloadConfig(device="cpu")
        # optimizer.fused_update — the Pallas blockwise Adam kernel
        # (ops/adam/fused_update.py): one pass over master+grad+m+v per
        # flat block instead of XLA's elementwise chain. Resolved here,
        # consumed by _make_apply_step — the ONE update site every
        # device-resident ZeRO tier routes through.
        self._fused_update = bool(config.optimizer_fused_update)
        if self._fused_update:
            if not isinstance(self.optimizer, FusedAdam):
                raise ConfigError(
                    "optimizer.fused_update requires the Adam family "
                    f"(got {type(self.optimizer).__name__}): the kernel "
                    "bakes in the Adam recurrence")
            if getattr(self.optimizer, "host_resident", False) \
                    or self._offload_cfg.enabled:
                raise ConfigError(
                    "optimizer.fused_update is a device kernel — it "
                    "cannot combine with the host offload tier "
                    "(offload_optimizer / cpuadam)")
            if getattr(self.optimizer, "needs_local_grads", False):
                raise ConfigError(
                    "optimizer.fused_update cannot combine with 1-bit "
                    "optimizers: the compressed sync replaces the plain "
                    "Adam update the kernel implements")
        # offload_param — the ZeRO-Infinity param tier (reference
        # partitioned_param_swapper.py:36, stage3.py:1084): compute-dtype
        # params live in pinned host memory and the step streams blocks
        # on-device (runtime/zero/param_offload.py). Requires stage 3 and a
        # block-structured (PipeModel-derived) streamed loss_fn — built by
        # deepspeed_tpu.initialize() for in-tree model families.
        self._offload_param_cfg = config.zero_config.offload_param
        if self._offload_param_cfg.enabled:
            if config.zero_config.stage != 3:
                raise ConfigError(
                    "offload_param requires ZeRO stage 3 (the param tier is "
                    "the stage-3 partition, stored in host memory)")
            if self._offload_param_cfg.device not in ("cpu", "nvme"):
                raise ConfigError(
                    f"offload_param.device must be 'cpu' or 'nvme', got "
                    f"'{self._offload_param_cfg.device}'")
            if not self._offload_cfg.enabled:
                # The param tier implies the host optimizer tier: fp32
                # master + moments live beside the streamed compute params
                # (reference ZeRO-Infinity couples them the same way —
                # stage3 offload groups both, stage3.py:1084). With
                # offload_param.device='nvme' the master/moment tier goes to
                # disk; the bf16 streaming copy stays in pinned host RAM
                # (see param_offload.py docstring for the scoping).
                from deepspeed_tpu.runtime.zero.config import ZeroOffloadConfig
                self._offload_cfg = ZeroOffloadConfig(
                    device=self._offload_param_cfg.device,
                    nvme_path=self._offload_param_cfg.nvme_path,
                    buffer_count=int(self._offload_param_cfg.buffer_count))
                log_dist("offload_param: enabling the "
                         f"{self._offload_param_cfg.device} optimizer tier",
                         ranks=[0])

        # --- ZeRO++ weight path (zero_optimization.zeropp) ------------------
        # qwZ: the fwd/bwd param all-gather becomes an explicit blockwise
        # int8/bf16 gather (comm/grad_sync.py ParamGatherPlan); hpZ keeps
        # the partition intra-slice so the gather never crosses DCN; the
        # sharded optimizer apply falls out of the (dcn, data) primary
        # placement (runtime/zero/partition.py). Inactive (the default)
        # => param_gather_plan is None and every builder below lowers
        # bit-identically to a zeropp-less config.
        self.zeropp = config.zero_config.zeropp
        self.param_gather_plan = None
        if self.zeropp.active:
            from deepspeed_tpu.parallel.mesh import PIPE_AXIS as _PIPE
            # The engine check runs FIRST: the pipeline engine forces
            # stage <= 1, so a stage-order check would tell its users
            # "use stage >= 2" — advice its own stage rule then rejects.
            # The real cause must surface, not a contradiction loop.
            if not type(self)._supports_zeropp \
                    or self.mesh.shape.get(_PIPE, 1) > 1:
                raise ConfigError(
                    "zero_optimization.zeropp is built into the "
                    "data-parallel step builders; the pipeline engine "
                    "shards params over the pipe axis and compiles its "
                    "own manual region — drop zeropp or use the plain "
                    "engine")
            if getattr(self.optimizer, "needs_local_grads", False):
                # Same precedent as the hierarchical-sync x 1-bit rule:
                # the compressed momentum protocol owns its wire format
                # and rank-local grads — a quantized weight gather on top
                # would double-compress state the protocol assumes exact.
                raise ConfigError(
                    "zero_optimization.zeropp cannot combine with 1-bit "
                    "optimizers: the error-compensated compressed "
                    "momentum sync needs exact rank-local state; "
                    "quantized weight gathers (qwZ) would stack a second "
                    "lossy wire format on the same step (same rule as "
                    "comm.hierarchical x 1-bit)")
            if config.zero_config.stage < 2:
                raise ConfigError(
                    f"zero_optimization.zeropp requires ZeRO stage >= 2 "
                    f"(stage {config.zero_config.stage} has no param/"
                    f"optimizer partition for qwZ/hpZ to serve)")
            # zeropp x offload_param / offload_optimizer are rejected at
            # config parse (DeepSpeedTPUConfig._validate) for explicit
            # blocks; the HOST-IMPLIED tier (optimizer.type "cpuadam" /
            # any host_resident optimizer object, resolved into
            # self._offload_cfg just above) only exists at engine level,
            # so it needs its own wall — the offload step builders
            # stream params host-side and never run the explicit qwZ/hpZ
            # gather, which would leave the plan's modeled comm gauges
            # and ledger charge describing traffic that does not exist.
            if self._offload_cfg.enabled:
                raise ConfigError(
                    "zero_optimization.zeropp cannot combine with the "
                    "host optimizer tier (offload_optimizer, or a "
                    "host-resident optimizer such as 'cpuadam'): the "
                    "offload step builders keep fp32 state host-side "
                    "and never run the explicit quantized param gather")
        # --- gradient-sync strategy (comm/grad_sync.py) ---------------------
        # Hierarchical quantized sync: bucketed ICI reduce-scatter in the
        # communication_data_type + blockwise-int8 (or bf16/fp32) DCN
        # all-reduce, replacing the implicit full-precision pjit resharding
        # on multi-slice meshes. `off` (and unresolved `auto`) keeps the
        # pre-existing step functions bit-identical.
        from deepspeed_tpu.comm.grad_sync import (comm_dtype_from_config,
                                                  resolve_hierarchical)
        from deepspeed_tpu.parallel.mesh import PIPE_AXIS
        self._comm_dtype = comm_dtype_from_config(
            config.communication_data_type)
        # Stashed for the live-elasticity rebuild path, which re-resolves
        # the sync strategy against the post-change mesh.
        self._sparse_grads_handled = bool(sparse_gradients_handled)
        self._grad_sync_on, sync_reason = resolve_hierarchical(
            config.comm, self.mesh,
            needs_local_grads=getattr(self.optimizer, "needs_local_grads",
                                      False),
            sparse_gradients=(config.sparse_gradients_enabled
                              or sparse_gradients_handled),
            pipe_stages=self.mesh.shape.get(PIPE_AXIS, 1))
        self.grad_sync_plan = None
        if self._grad_sync_on:
            log_dist(f"grad_sync: hierarchical sync enabled ({sync_reason})",
                     ranks=[0])
        elif config.comm.overlap_grad_sync == "on":
            # Explicit opt-in with nothing to overlap: the schedule is a
            # property of the hierarchical sync, and that resolved off.
            log_dist(
                f"comm.overlap_grad_sync=on but the hierarchical grad sync "
                f"is not active ({sync_reason}) — the implicit grad path "
                f"has no explicit collectives to overlap; set "
                f"comm.hierarchical on a multi-slice mesh to engage it",
                ranks=[0])
        if not self._grad_sync_on and (self._comm_dtype is not None
              and not getattr(self.optimizer, "needs_local_grads", False)):
            log_dist(
                "communication_data_type is set but the implicit grad path "
                "is active — it applies to the hierarchical grad sync "
                "(comm.hierarchical) and the 1-bit dense pre-reduction only",
                ranks=[0])

        # --- initial state placement ---------------------------------------
        self.state = self._init_state(params, rng_seed)

        # --- numerics observatory (telemetry/numerics.py) -------------------
        # Built BEFORE the step functions: the per-layer-group statistics
        # ride INSIDE the jitted steps (one small stacked aux array), so
        # the builders below consult `self.numerics`. Disabled (the
        # default) => None and the builders emit the bit-identical
        # pre-numerics programs. The telemetry facade attaches later
        # (construction order), via numerics.attach().
        from deepspeed_tpu.telemetry.numerics import build_numerics
        self.numerics = None
        if not getattr(self.optimizer, "needs_local_grads", False):
            self.numerics = build_numerics(
                config.telemetry, params_template=params,
                compute_dtype=(self.precision.dtype if self.precision.mixed
                               else None),
                # MoE: expert-stacked FFN leaves additionally report
                # per-expert moe_expert_* group rows (router collapse
                # shows up as one expert's norms flatlining).
                expert_groups=(config.moe.num_experts
                               if getattr(config, "moe", None) is not None
                               and config.moe.enabled else 0))
        elif (config.telemetry.enabled
              and config.telemetry.numerics.enabled):
            log_dist(
                "numerics: 1-bit optimizers keep rank-local compressed "
                "grads inside their own manual region — in-program "
                "statistics are unavailable on this path; numerics "
                "observatory disabled", ranks=[0])

        # --- MoE observatory (telemetry/moe.py) -----------------------------
        # Built BEFORE the step functions: the standard builders consult
        # it to thread the model's moe_* aux keys through the GAS scan.
        # None (moe or telemetry off) => the builders emit bit-identical
        # pre-moe programs. Telemetry attaches later, like numerics.
        from deepspeed_tpu.telemetry.moe import build_moe_monitor
        self.moe_monitor = build_moe_monitor(config)

        # --- ZeRO++ param gather plan (after numerics: the plan measures
        # the lossy wire hop only when the observatory is listening) -----
        if self.zeropp.active:
            from deepspeed_tpu.comm.grad_sync import ParamGatherPlan
            self.param_gather_plan = ParamGatherPlan(
                self.zeropp, self.mesh,
                param_template=self.state.params,
                param_specs=self.param_specs,
                measure_quant_error=self.numerics is not None)
            log_dist(self.param_gather_plan.describe(), ranks=[0])

        # --- jitted step functions -----------------------------------------
        self._donate = donate_state
        self._build_step_fns()

        # --- bookkeeping ----------------------------------------------------
        self.gradient_accumulation_steps = config.gradient_accumulation_steps
        self.train_micro_batch_size_per_gpu = config.train_micro_batch_size_per_gpu
        # The config solved the batch triple against jax.device_count(); a
        # custom mesh may dedicate devices to model/pipe/sequence axes, so
        # the authoritative global batch derives from the mesh's dp size.
        self.train_batch_size = (self.train_micro_batch_size_per_gpu *
                                 self.gradient_accumulation_steps * self.dp_size)
        if self.train_batch_size != config.train_batch_size:
            log_dist(
                f"train_batch_size recomputed for mesh dp={self.dp_size}: "
                f"{config.train_batch_size} -> {self.train_batch_size}",
                ranks=[0])
        self.steps_per_print = config.steps_per_print
        self.wall_clock_breakdown = config.wall_clock_breakdown

        # --- aux subsystems driven by their config blocks -------------------
        if config.sparse_gradients_enabled and not sparse_gradients_handled:
            raise ConfigError(
                "sparse_gradients: this loss path does not declare the "
                "row-sparse embedding-grad exchange, and the engine cannot "
                "sparsify behind XLA AD's back (dense cotangents). Either "
                "pass an in-tree GPT/BERT model to deepspeed_tpu."
                "initialize() (wired automatically), or set your model "
                "cfg's sparse_embedding_grad / route the embedding "
                "through ops.embedding.embedding_lookup(sparse_grad_axes="
                "...) and construct the engine with "
                "sparse_gradients_handled=True")
        self.progressive_layer_drop = None
        if config.pld.enabled:
            from deepspeed_tpu.runtime.progressive_layer_drop import \
                ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=config.pld.theta, gamma=config.pld.gamma)
        from deepspeed_tpu.utils.monitor import build_monitor
        self.monitor = build_monitor(config.tensorboard)
        # Unified observability facade (telemetry/; docs/OBSERVABILITY.md):
        # metrics registry + step tracer + recompile detector. A legacy
        # tensorboard block rides as a registry sink, so scalar emission has
        # ONE call site; disabled telemetry is a no-op facade.
        from deepspeed_tpu.telemetry import build_telemetry
        self.telemetry = build_telemetry(config.telemetry,
                                         monitor=self.monitor)
        if self.numerics is not None:
            # Late binding: the numerics plan had to exist before the
            # step builders ran; the registry its flush emits into
            # exists only now.
            self.numerics.attach(self.telemetry)
        if self.moe_monitor is not None:
            # Same late binding for the moe/* flush point (built before
            # the step builders, which consult it to thread the moe_*
            # aux keys through the GAS scan).
            self.moe_monitor.attach(self.telemetry)
        # Goodput accounting (telemetry/goodput.py): attributes every
        # wall-clock second of this attempt to a category and persists the
        # per-attempt run manifest. Disabled => None, and every hook below
        # is one attribute check — zero added syncs/fetches, same contract
        # as guardrails.
        from deepspeed_tpu.telemetry.goodput import (build_goodput,
                                                     config_hash)
        self.goodput = build_goodput(
            config.telemetry, telemetry=self.telemetry,
            cfg_hash=config_hash(getattr(config, "_param_dict", None)))
        # Highest step a rollback rewound past: steps re-committed at or
        # below it are replay (real compute, no net progress).
        self._goodput_replay_until = 0
        # Fleet observability (telemetry/fleet.py): cross-host metric
        # aggregation + straggler detection at flush boundaries. Disabled
        # (the default) => None, every hook is one attribute check — no
        # collective, no host fetch, same contract as goodput.
        from deepspeed_tpu.telemetry.fleet import build_fleet
        self.fleet = build_fleet(config.telemetry, telemetry=self.telemetry,
                                 goodput=self.goodput)
        # Memory observatory (telemetry/memory.py): XLA memory attribution
        # + model-state ledger + capacity planner + OOM forensics.
        # Disabled (the default) => None, every hook one attribute check,
        # and the step jaxpr is bit-identical — the observatory never
        # touches the jitted step functions.
        from deepspeed_tpu.telemetry.memory import build_memory_observatory
        self.memory = build_memory_observatory(
            config.telemetry, telemetry=self.telemetry, goodput=self.goodput)
        # Device-time observatory (telemetry/devicetime.py): scheduled
        # jax.profiler captures parsed into measured devicetime/* op
        # attribution, roofline verdicts and comm/measured_exposed_frac.
        # Disabled (the default) => None, the hook one attribute check;
        # enabled, profiler work happens only at capture boundaries.
        from deepspeed_tpu.telemetry.devicetime import build_devicetime
        self.devicetime = build_devicetime(
            config.telemetry, telemetry=self.telemetry, goodput=self.goodput)
        if self.memory is not None:
            # Pre-compile: ledger gauges + the stage×offload×microbatch
            # what-if table (loud warning when the chosen config projects
            # over HBM) — pure host arithmetic over shapes/specs.
            self.memory.on_engine_init(self)
        # Whether _train_batch_inner's train_step span feeds the fleet
        # step-time estimate. The pipeline engine turns this off and
        # feeds its OUTER pipe_step span instead — otherwise both spans
        # would be averaged and under-report the schedule overhead.
        self._fleet_note_inner_span = True
        # Label an OOM crashdump carries for this engine's fused step
        # (the pipeline engine overrides it with the schedule shape).
        self._memory_oom_label = "train_step"
        self.moq = None
        if config.quantize_training.get("enabled", False):
            if self._offload_cfg.enabled and self._offload_cfg.device == "nvme":
                raise ConfigError(
                    "quantize_training with offload_optimizer.device='nvme' "
                    "is not supported: the master params live on disk and "
                    "the post-step sim-quant would need a full read-modify-"
                    "write sweep; use device='cpu'")
            from deepspeed_tpu.ops.quantizer import MoQConfig, MoQQuantizer
            self.moq = MoQQuantizer(MoQConfig.from_dict(
                config.quantize_training))
        self.flops_profiler = None
        if config.flops_profiler.enabled:
            from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler
            self.flops_profiler = FlopsProfiler(config.flops_profiler)
        # An explicit activation_checkpointing block always (re)configures
        # the module-level policy; absent block leaves it untouched so a
        # later engine's explicit block is never shadowed.
        if config.activation_checkpointing_provided:
            from deepspeed_tpu.runtime import activation_checkpointing as _ac
            _ac.configure(deepspeed_config=config)
        # --- resilience: preemption-aware checkpointing + fault injection ---
        # (resilience/; docs/RESILIENCE.md). The manager writes off the step
        # path; the fault plan deterministically injects preemption / ckpt
        # I/O faults so recovery is testable on CPU.
        from deepspeed_tpu.elasticity import elastic_config_hash
        self.elastic_hash = elastic_config_hash(config.elasticity)
        self.recovery_count = 0
        self.ckpt_manager = None
        self.fault_plan = None
        self._client_state_fn = None
        rcfg = config.resilience
        if (rcfg.enabled or rcfg.fault_injection
                or os.environ.get("DSTPU_FAULT_PLAN")):
            from deepspeed_tpu.resilience import FaultPlan
            self.fault_plan = FaultPlan.resolve(rcfg.fault_injection)
        if rcfg.enabled:
            from deepspeed_tpu.resilience import AsyncCheckpointManager
            self.ckpt_manager = AsyncCheckpointManager(
                rcfg.checkpoint.dir,
                interval=rcfg.checkpoint.interval,
                keep_last=rcfg.checkpoint.keep_last,
                max_retries=rcfg.checkpoint.max_retries,
                backoff=rcfg.checkpoint.backoff_seconds,
                async_write=rcfg.checkpoint.async_write,
                fault_plan=self.fault_plan,
                monitor=self.monitor,
                telemetry=self.telemetry,
                goodput=self.goodput)
        # --- guardrails: anomaly detection + in-memory rollback + watchdog --
        # (guardrails/; docs/RESILIENCE.md "Guardrails"). build_guardrails
        # returns None for a disabled block, and every engine hook gates on
        # `is None` — the disabled step path is bit-for-bit the pre-
        # guardrails one: no host fetches, no syncs, no snapshots.
        from deepspeed_tpu.guardrails import build_guardrails
        self.guardrails = build_guardrails(
            config.guardrails, telemetry=self.telemetry,
            # The facade's JSONL sink path (host-scoped on multi-host
            # runs), not a re-derived config join.
            metrics_path=self.telemetry.metrics_path,
            goodput=self.goodput)
        # Monotonic count of dispatched optimizer-step attempts. Unlike
        # global_steps it never rewinds on rollback: data-borne fault
        # injection (FaultPlan nan_loss/hang) keys on it so a rolled-back
        # window is not re-poisoned forever.
        self.step_attempts = 0
        # --- live elasticity: in-process shrink/grow + straggler eviction --
        # (resilience/elastic.py; docs/RESILIENCE.md "Live elasticity").
        # build_elastic returns None for a disabled block — no SIGTERM
        # handler installed, the step-boundary hook one attribute check,
        # and the lowered step bit-identical (tests/test_elastic.py).
        # World-change epoch: stamped into every checkpoint manifest and
        # the goodput run manifest so post-mortem tooling can line
        # attempts up against world changes.
        self.elastic_epoch = 0
        from deepspeed_tpu.resilience.elastic import build_elastic
        if config.elasticity_live.enabled:
            if self._offload_cfg.enabled:
                # The explicit offload blocks are walled at config parse;
                # the HOST-IMPLIED tier (optimizer.type "cpuadam" / any
                # host_resident optimizer object) resolves only here.
                raise ConfigError(
                    "elasticity.live cannot compose with the host "
                    "optimizer tier (offload_optimizer, or a host-"
                    "resident optimizer such as 'cpuadam'): host master/"
                    "moment state is laid out per-partition and the "
                    "in-process reshard only re-places device state")
            if getattr(self.optimizer, "needs_local_grads", False):
                raise ConfigError(
                    "elasticity.live cannot compose with 1-bit "
                    "optimizers: rank-local error-feedback buffers do "
                    "not survive a world change")
        self.elastic = build_elastic(self)
        # Device-sync barriers in the timers are gated on wall_clock_breakdown:
        # a breakdown-off run must not pay a block_until_ready round-trip per
        # step just to feed timings nobody reads.
        self.timers = SynchronizedWallClockTimer(
            enabled=config.wall_clock_breakdown)
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_batch_size,
            steps_per_output=self.steps_per_print,
            sync=config.wall_clock_breakdown)
        self._micro_in_window = 0
        self._pending_micro = []
        self._last_loss = None
        self.global_steps = 0
        self.micro_steps = 0
        self.losses = collections.deque(maxlen=100)

        log_dist(
            f"TPUEngine initialised: zero_stage={config.zero_config.stage} "
            f"precision={self.precision.name} dp={self.dp_size} "
            f"mesh={dict(self.mesh.shape)} gas={self.gradient_accumulation_steps}",
            ranks=[0])

    # ------------------------------------------------------------------
    def _configure_basic_optimizer(self):
        """Reference _configure_basic_optimizer (engine.py:746)."""
        name = self.config.optimizer_name or C.ADAM_OPTIMIZER
        params = dict(self.config.optimizer_params)
        params.pop(C.MAX_GRAD_NORM, None)  # engine owns clipping, as in reference
        if name in (C.ONEBIT_ADAM_OPTIMIZER, C.ONEBIT_LAMB_OPTIMIZER):
            from deepspeed_tpu.ops.onebit.adam import OneBitAdam
            from deepspeed_tpu.ops.onebit.lamb import OneBitLamb
            from deepspeed_tpu.parallel.mesh import DCN_AXIS
            cls = OneBitAdam if name == C.ONEBIT_ADAM_OPTIMIZER else OneBitLamb
            # On a hierarchical mesh the compression axis defaults to the
            # DCN (slow inter-slice) axis — the bandwidth the 1-bit
            # protocol exists to save (reference runtime/comm/nccl.py:47
            # targets exactly the Ethernet-cluster case); the ICI-inner
            # data reduction stays dense (engine pre-reduces it).
            if self.dcn_size > 1:
                params.setdefault("axis", DCN_AXIS)
            return cls(mesh=self.mesh, **params)
        if name == C.ADAM_OPTIMIZER:
            # reference maps adam+adam_w_mode (default true) to FusedAdam(AdamW)
            adam_w_mode = params.pop("adam_w_mode", True)
            torch_adam = params.pop("torch_adam", False)
            del torch_adam
            return FusedAdam(adamw_mode=adam_w_mode, **params)
        if name not in OPTIMIZER_REGISTRY:
            raise ValueError(f"unknown optimizer '{name}'")
        return OPTIMIZER_REGISTRY[name](**params)

    # ------------------------------------------------------------------
    def _init_state(self, params: Any, rng_seed: int) -> TrainState:
        """Place master params / moments / grad-acc with their ZeRO shardings."""
        if self._offload_cfg.enabled:
            return self._init_offload_state(params, rng_seed)
        mesh = self.mesh

        def shard_like(tree, specs):
            # A jitted identity+cast always materialises NEW buffers; a bare
            # device_put may alias the caller's arrays when the sharding
            # already matches, and the step functions' donation would then
            # delete the user's params out from under them.
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs)
            return jax.jit(
                lambda t: jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.float32), t),
                out_shardings=shardings)(tree)

        with mesh:
            master = shard_like(params, self.param_specs)
            if hasattr(self.optimizer, "configure_partitioning"):
                # 1-bit optimizers lay their error-feedback buffers out per
                # manual (pipe) shard — hand them the base param specs.
                self.optimizer.configure_partitioning(self._base_specs, mesh)
            opt_state_host = self.optimizer.init(master)
            opt_specs_full = self._opt_state_specs(opt_state_host, params)
            self.opt_state_specs_full = opt_specs_full
            opt_state = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(jnp.asarray(x), NamedSharding(mesh, s)),
                opt_state_host, opt_specs_full)
            grad_acc = jax.tree_util.tree_map(
                lambda p, s: jax.device_put(
                    jnp.zeros(p.shape, self.grad_accum_dtype),
                    NamedSharding(mesh, s)),
                master, self.grad_specs)
            rep = NamedSharding(mesh, PartitionSpec())
            return TrainState(
                step=jax.device_put(jnp.zeros((), jnp.int32), rep),
                micro_step=jax.device_put(jnp.zeros((), jnp.int32), rep),
                params=master,
                opt_state=opt_state,
                grad_acc=grad_acc,
                loss_scale=jax.device_put(self.loss_scaler.init(), rep),
                skipped_steps=jax.device_put(jnp.zeros((), jnp.int32), rep),
                rng=jax.device_put(jax.random.PRNGKey(rng_seed), rep))

    def _init_offload_state(self, params: Any, rng_seed: int) -> TrainState:
        """ZeRO-Offload layout: fp32 master + moments live on host (or NVMe);
        the device holds only compute-dtype params. See
        runtime/zero/offload.py for the tier design."""
        from deepspeed_tpu.runtime.zero.offload import (OptimizerOffloader,
                                                        to_host)

        ocfg = self._offload_cfg
        if (self.config.zero_config.stage == 3
                and not self._offload_param_cfg.enabled):
            raise ValueError(
                "offload_optimizer with ZeRO stage 3 requires offload_param "
                "(the stage-3 param partition must also leave HBM — enable "
                "zero_optimization.offload_param); with device-resident "
                "params use stage <= 2")
        mesh = self.mesh
        compute_dtype = (self.precision.dtype if self.precision.mixed
                         else jnp.float32)
        self.offloader = OptimizerOffloader(
            self.optimizer, params, device=ocfg.device,
            nvme_path=ocfg.nvme_path, buffer_count=int(ocfg.buffer_count),
            compute_dtype=compute_dtype,
            aio_threads=int(self.config.aio.thread_count))

        if self._offload_param_cfg.enabled:
            # Param tier: compute-dtype params live in pinned host memory,
            # ZeRO-3-partitioned over `data`; the (streamed) loss_fn fetches
            # blocks on-device inside the step. When the streamed loss was
            # built with TP specs (build_streamed_loss tp_specs=...), it
            # publishes shard-aligned storage specs for the packed blocks —
            # each host then stores its (data x model) shard and the fetch
            # moves 1/(dp*tp) of every block (ZeRO-Infinity x MP, reference
            # stage3.py:590 mpu composition).
            from deepspeed_tpu.runtime.zero import param_offload as po
            # Shard count is the ICI-inner data axis only — dp_size also
            # counts dcn slices, which store their own host partitions.
            specs = po.host_storage_specs(
                params, self.mesh.shape.get(DATA_AXIS, 1))
            overrides = getattr(self.loss_fn,
                                "host_storage_spec_overrides", None)
            if overrides:
                specs = {**specs, **overrides}
            self._compute_shardings = po.host_shardings(mesh, specs)
            self._compute_params = jax.device_put(
                po.cast_host(params, compute_dtype), self._compute_shardings)
        else:
            # Device compute params: TP specs if provided, replicated over
            # data.
            base = self._base_specs if self._base_specs is not None else \
                jax.tree_util.tree_map(lambda _: PartitionSpec(), params)
            self._compute_shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), base)
            self._compute_params = jax.jit(
                lambda t: jax.tree_util.tree_map(
                    lambda a: a.astype(compute_dtype), t),
                out_shardings=self._compute_shardings)(params)

        cpu_master = self.offloader.master          # None for nvme tier
        cpu_opt = self.offloader.opt_state
        placeholder = jnp.zeros((), jnp.float32)
        rep = NamedSharding(mesh, PartitionSpec())
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            micro_step=jnp.zeros((), jnp.int32),
            params=cpu_master if cpu_master is not None else placeholder,
            opt_state=cpu_opt if cpu_opt is not None else placeholder,
            grad_acc=placeholder,
            loss_scale=to_host(self.loss_scaler.init()),
            skipped_steps=jnp.zeros((), jnp.int32),
            rng=jax.device_put(jax.random.PRNGKey(rng_seed), rep))

    def _build_offload_step_fns(self) -> None:
        """Step functions for the offloaded optimizer tier: a device-side
        jitted micro-batch scan producing (sharded) grads + overflow/norm
        scalars, then the host/NVMe optimizer step, then compute-dtype params
        placed back onto the mesh. Prefer ``train_batch()``; reference-
        style forward/backward/step loops work via the stash-and-fuse shim
        (``_compat_forward``) at one extra forward per micro-batch."""
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        fp16 = cfg.fp16.enabled
        precision = self.precision
        mesh = self.mesh

        grad_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self.grad_specs)
        scaled_loss_fn = self._make_scaled_loss_fn()
        # Numerics (telemetry/numerics.py) on the offload tier: grad and
        # weight stats + dtype counters come from the device-side scan
        # (new_params stays None — the optimizer step runs on the host,
        # so update norms are reported as 0). The accumulator is still
        # loss-scaled here; inv_scale restores unscaled grads, the same
        # coefficient _make_apply_step uses.
        nplan = self.numerics.plan if self.numerics is not None else None

        def inv_scale_of(scale):
            inv = 1.0 / scale
            if cfg.prescale_gradients:
                inv = inv * self.dp_size / cfg.gradient_predivide_factor
            return inv

        def finish_scan(acc):
            """Overflow/norm scalars on the fully-reduced accumulator —
            shared by the implicit and hierarchical scan variants."""
            # fp16 always checks (loss-scaler contract); bf16/fp32 check
            # only under the guardrails nonfinite-grad opt-in — no perf
            # tax on the default path.
            overflow = (has_inf_or_nan(acc)
                        if fp16 or self._nonfinite_grad_check
                        else jnp.zeros((), jnp.bool_))
            # norm in fp32 (a bf16 square-sum overflows at scale; the cast
            # fuses into the reduction)
            norm = global_norm(jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), acc))
            return overflow, norm

        def micro_scan(compute_params, rng, batches, scale):
            def body(carry, batch):
                acc, rng = carry
                rng, sub = jax.random.split(rng)
                grad_fn = jax.value_and_grad(scaled_loss_fn, has_aux=True)
                (_, (loss, _)), grads = grad_fn(compute_params, batch, sub,
                                                scale)
                acc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(a.dtype), acc, grads)
                return (acc, rng), loss

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, self.grad_accum_dtype),
                compute_params)
            # Constrain the accumulator BEFORE the scan too: the carry
            # buffer itself must be ZeRO-sharded (1/dp per device), not just
            # the final value.
            zeros = jax.lax.with_sharding_constraint(zeros, grad_shardings)
            (acc, rng), losses = jax.lax.scan(body, (zeros, rng), batches)
            acc = jax.lax.with_sharding_constraint(acc, grad_shardings)
            overflow, norm = finish_scan(acc)
            if nplan is not None:
                aux = {"groups": nplan.group_stats(
                    acc, params=compute_params,
                    inv_scale=inv_scale_of(scale))}
                return acc, rng, jnp.mean(losses), overflow, norm, aux
            return acc, rng, jnp.mean(losses), overflow, norm

        def micro_scan_hierarchical(compute_params, rng, batches, scale):
            """The offload tier's device-side scan with the explicit
            hierarchical grad sync (comm/grad_sync.py): same signature and
            return contract as micro_scan, so _offload_train_batch's
            async D2H pipeline is untouched — it just pulls grads whose
            DCN hop was quantized (overlapped with the next microstep's
            fwd/bwd when comm.overlap_grad_sync resolved on)."""
            plan = self.grad_sync_plan
            rng, sub = jax.random.split(rng)
            acc, loss, qerr = plan.gas_sync(
                batches=batches, batch_spec=self.batch_spec,
                compute_params=compute_params, sub=sub, scale=scale,
                grad_fn=self._make_micro_grad())
            acc = jax.lax.with_sharding_constraint(acc, grad_shardings)
            overflow, norm = finish_scan(acc)
            if nplan is not None:
                aux = {"groups": nplan.group_stats(
                    acc, params=compute_params,
                    inv_scale=inv_scale_of(scale))}
                if qerr is not None:
                    aux["dcn_qerr"] = qerr
                return acc, rng, loss, overflow, norm, aux
            return acc, rng, loss, overflow, norm

        if self._grad_sync_on:
            from deepspeed_tpu.comm.grad_sync import (GradSyncPlan,
                                                      resolve_overlap)
            self.grad_sync_plan = GradSyncPlan(
                cfg.comm, mesh,
                grad_template=jax.tree_util.tree_map(
                    lambda p: jax.ShapeDtypeStruct(
                        p.shape, self.grad_accum_dtype),
                    self._compute_params),
                grad_specs=self.grad_specs,
                acc_dtype=self.grad_accum_dtype,
                ici_dtype=self._comm_dtype, gas=gas,
                measure_quant_error=self.numerics is not None,
                overlap=resolve_overlap(cfg.comm))
            log_dist(self.grad_sync_plan.describe(), ranks=[0])
            self._offload_micro_scan = jax.jit(micro_scan_hierarchical)
        else:
            self._offload_micro_scan = jax.jit(micro_scan)

        def cast_tree(tree):
            dt = (precision.dtype if precision.mixed else jnp.float32)
            return jax.tree_util.tree_map(lambda a: a.astype(dt), tree)

        self._offload_cast = jax.jit(cast_tree, donate_argnums=(0,))

        if self._offload_param_cfg.enabled:
            # Param tier: cast on the host (never a full device copy) and
            # commit back into pinned host memory.
            from deepspeed_tpu.runtime.zero import param_offload as po
            dt = (precision.dtype if precision.mixed else jnp.float32)

            def offload_place(tree):
                return jax.device_put(po.cast_host(tree, dt),
                                      self._compute_shardings)
        else:
            def offload_place(tree):
                placed = jax.device_put(tree, self._compute_shardings)
                return self._offload_cast(placed)

        self._offload_place = offload_place
        loss_fn = self.loss_fn

        def eval_step(compute_params, batch):
            out = loss_fn(compute_params, batch, None)
            loss, aux = (out if isinstance(out, tuple) else (out, None))
            return loss.astype(jnp.float32), aux

        self._offload_eval = jax.jit(eval_step)
        self._micro_step = None
        self._apply_step = None
        self._train_step = None
        self._eval_step = None

    def _offload_train_batch(self, batches) -> jax.Array:
        """One offloaded step. The cpu tier is FULLY ASYNC: the device
        micro-scan, the D2H grad transfer, the XLA:CPU optimizer step and
        the param placement are all queued without a single blocking fetch
        — overflow/norm ride as lazy scalars into the host step (reference
        contrast: pipelined_optimizer_swapper.py:60 hides the same
        latency; round-2 VERDICT weak #5). The nvme tier stays host-driven
        (its leaf streaming synchronises by construction)."""
        from deepspeed_tpu.runtime.zero.offload import to_host

        cfg = self.config
        fp16 = cfg.fp16.enabled
        state = self.state
        scale_f = float(state.loss_scale.scale) if fp16 else 1.0
        self._maybe_profile(self._offload_micro_scan, self._compute_params,
                            state.rng, batches, jnp.float32(scale_f),
                            params=self._compute_params)
        out = self._offload_micro_scan(
            self._compute_params, state.rng, batches, jnp.float32(scale_f))
        acc, rng, loss, overflow_d, norm_d = out[:5]
        if self.numerics is not None:
            # Device-array hand-off only — the transfer happens at the
            # flush boundary (the step this aux belongs to commits below).
            self.numerics.note_step(out[5], self.global_steps + 1)
        grads_h = to_host(acc)
        norm_h = to_host(norm_d)
        overflow_h = (to_host(overflow_d)
                      if fp16 or self._nonfinite_grad_check
                      else jnp.zeros((), jnp.bool_))
        # Unscale (+ compensate prescale_gradients' in-loss pre-division,
        # as _make_apply_step does); clipping happens inside the jitted
        # host step from (norm, coef, clip).
        coef = 1.0 / scale_f
        if cfg.prescale_gradients:
            coef = coef * self.dp_size / cfg.gradient_predivide_factor
        self._offload_last_norm = (norm_h, coef)
        # Guardrails feed: the lazy overflow scalar (fetched only when the
        # detector is enabled — _guardrails_step_hook gates the sync).
        self._offload_last_overflow = overflow_h
        lr = float(self._current_lr())
        compute_h = self.offloader.update(grads_h, lr, coef, overflow_h,
                                          norm=norm_h,
                                          clip=cfg.gradient_clipping)
        self._compute_params = self._offload_place(compute_h)
        new_ls = self.loss_scaler.update(state.loss_scale, overflow_h)
        not_of = 1 - overflow_h.astype(jnp.int32)
        self.state = state._replace(
            step=state.step + not_of,
            micro_step=state.micro_step + cfg.gradient_accumulation_steps,
            params=(self.offloader.master if self.offloader.master is not None
                    else state.params),
            opt_state=(self.offloader.opt_state
                       if self.offloader.opt_state is not None
                       else state.opt_state),
            loss_scale=new_ls, rng=rng,
            skipped_steps=state.skipped_steps + overflow_h.astype(jnp.int32))
        return loss

    def _opt_state_specs(self, opt_state: Any, params: Any) -> Any:
        """Spec tree for the optimizer state: any sub-tree that mirrors the
        param tree structure (moment trees) gets the ZeRO opt-state specs;
        everything else (step counters etc.) is replicated. Optimizers with
        bespoke layouts (1-bit error buffers) provide ``state_specs`` and
        receive the engine's ZeRO opt-state specs for their moment trees."""
        if hasattr(self.optimizer, "state_specs"):
            return self.optimizer.state_specs(params, opt_specs=self.opt_specs)
        params_structure = jax.tree_util.tree_structure(params)

        def specs_for(sub):
            if jax.tree_util.tree_structure(sub) == params_structure:
                return self.opt_specs
            return jax.tree_util.tree_map(lambda _: PartitionSpec(), sub)

        if hasattr(opt_state, "_fields"):  # NamedTuple of sub-trees
            return type(opt_state)(*(specs_for(getattr(opt_state, f))
                                     for f in opt_state._fields))
        return specs_for(opt_state)

    # ------------------------------------------------------------------
    # jitted step construction
    # ------------------------------------------------------------------
    def _make_scaled_loss_fn(self):
        """loss_fn wrapped with the engine's scaling contract — ONE
        definition for every builder (standard, offload, hierarchical):
        fp16 loss scale, /gas for accumulation, optional prescale
        pre-division (undone in _make_apply_step's unscale). Returns
        (scaled, (loss32, aux))."""
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        predivide = cfg.prescale_gradients
        loss_fn = self.loss_fn

        def scaled_loss_fn(compute_params, batch, rng, scale):
            out = loss_fn(compute_params, batch, rng)
            loss, aux = (out if isinstance(out, tuple) else (out, None))
            loss32 = loss.astype(jnp.float32)
            scaled = loss32 * scale / gas
            if predivide:
                scaled = scaled / self.dp_size * cfg.gradient_predivide_factor
            return scaled, (loss32, aux)

        return scaled_loss_fn

    def _make_compute_params(self):
        """The ONE compute-params materialization every builder uses:
        ``fn(master_params) -> (compute_params, param_qerr)``. Without a
        zeropp plan it is exactly the pre-existing precision cast
        (``param_qerr`` None, lowering unchanged); with one, the explicit
        quantized all-gather (comm/grad_sync.py ParamGatherPlan) runs
        first and the precision cast is applied to the gathered fp32
        tree — elementwise, so the fp32-passthrough tier stays exact."""
        plan = self.param_gather_plan
        precision = self.precision

        if plan is None:
            return lambda params: (precision.cast_params(params), None)

        def fn(params):
            full, qerr = plan.gather(params)
            return precision.cast_params(full), qerr

        return fn

    def _make_micro_grad(self):
        """One micro-step's (loss, grads) — the grad_fn the hierarchical
        paths hand to GradSyncPlan.run_manual_gas."""
        scaled_loss_fn = self._make_scaled_loss_fn()

        def micro_grad(compute_params, batch, key, scale):
            grad_fn = jax.value_and_grad(scaled_loss_fn, has_aux=True)
            (_, (loss, _)), grads = grad_fn(compute_params, batch, key,
                                            scale)
            return loss, grads

        return micro_grad

    def _make_apply_step(self):
        """GAS-boundary optimizer apply: unscale → overflow check → clip →
        update → loss-scale update → overflow-skip (≡ reference
        _take_model_step engine.py:1253 + stage2.step :1471). Shared by the
        plain and pipeline engines."""
        cfg = self.config
        fp16 = cfg.fp16.enabled
        clip = cfg.gradient_clipping
        predivide = cfg.prescale_gradients
        optimizer = self.optimizer
        scaler = self.loss_scaler

        nonfinite_check = self._nonfinite_grad_check
        # Numerics observatory (telemetry/numerics.py): with a plan the
        # apply returns a 4th output — the [groups, 5] stats aux — so
        # every builder that routes through this apply (standard,
        # hierarchical, pipe, and the micro/apply API) computes the
        # per-group statistics in ONE place. None => the pre-numerics
        # 3-tuple, bit-identical lowering.
        nplan = self.numerics.plan if self.numerics is not None else None
        fused = self._fused_update
        if fused:
            from deepspeed_tpu.ops.adam.fused_update import fused_adam_apply

        def apply_step(state: TrainState, lr):
            scale = state.loss_scale.scale if fp16 else jnp.float32(1.0)
            inv = 1.0 / scale
            if predivide:
                inv = inv * self.dp_size / cfg.gradient_predivide_factor
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) * inv, state.grad_acc)
            # fp16: the loss-scaler overflow path. bf16/fp32: the same
            # skip-on-nonfinite semantics under the (default-off)
            # guardrails gate — engine.py previously hard-coded
            # overflow = zeros() for bf16, leaving NaN grads to commit.
            overflow = (has_inf_or_nan(grads) if fp16 or nonfinite_check
                        else jnp.zeros((), jnp.bool_))
            norm = global_norm(grads)
            raw_grads = grads        # pre-clip: the stats want raw norms
            if clip > 0.0:
                grads = clip_grad_by_global_norm(grads, clip, norm=norm)
            if fused:
                new_params, new_opt = fused_adam_apply(
                    optimizer, grads, state.opt_state, state.params, lr=lr)
            else:
                new_params, new_opt = optimizer.update(
                    grads, state.opt_state, state.params, lr=lr)
            new_params = _tree_where(overflow, state.params, new_params)
            new_opt = _tree_where(overflow, state.opt_state, new_opt)
            new_ls = scaler.update(state.loss_scale, overflow)
            zero_acc = jax.tree_util.tree_map(jnp.zeros_like, state.grad_acc)
            new_state = state._replace(
                step=state.step + jnp.where(overflow, 0, 1),
                params=new_params, opt_state=new_opt, grad_acc=zero_acc,
                loss_scale=new_ls,
                skipped_steps=state.skipped_steps + overflow.astype(jnp.int32),
            )
            if nplan is None:
                return new_state, overflow, norm
            # Update norms measure the COMMITTED delta (zero on an
            # overflow-skipped step, by the _tree_where selection above).
            stats = nplan.group_stats(raw_grads, params=state.params,
                                      new_params=new_params)
            return new_state, overflow, norm, stats

        return apply_step

    def _build_step_fns(self) -> None:
        if self._offload_cfg.enabled:
            if getattr(self.optimizer, "needs_local_grads", False):
                raise ConfigError(
                    "1-bit optimizers cannot combine with offload_optimizer:"
                    " the compressed sync needs rank-local grads on device, "
                    "the offload tier moves the optimizer step to the host")
            self._build_offload_step_fns()
            return
        if getattr(self.optimizer, "needs_local_grads", False):
            self._build_local_grad_step_fns()
            return
        if self._grad_sync_on:
            self._build_hierarchical_step_fns()
            return
        cfg = self.config
        fp16 = cfg.fp16.enabled
        precision = self.precision
        loss_fn = self.loss_fn
        mesh = self.mesh

        grad_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self.grad_specs)
        scaled_loss_fn = self._make_scaled_loss_fn()
        compute_params_fn = self._make_compute_params()
        from deepspeed_tpu.telemetry.moe import MOE_AUX_KEYS
        moe_keys = MOE_AUX_KEYS if self.moe_monitor is not None else ()

        def micro_step_inner(state: TrainState, batch, compute_params):
            rng, sub = jax.random.split(state.rng)
            scale = state.loss_scale.scale if fp16 else jnp.float32(1.0)
            grad_fn = jax.value_and_grad(scaled_loss_fn, has_aux=True)
            (_, (loss, aux)), grads = grad_fn(compute_params, batch, sub, scale)
            grads = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(a.dtype), state.grad_acc, grads)
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
            return state._replace(micro_step=state.micro_step + 1,
                                  grad_acc=grads, rng=rng), loss, aux

        def micro_step(state: TrainState, batch):
            return micro_step_inner(state, batch,
                                    compute_params_fn(state.params)[0])

        apply_step = self._make_apply_step()

        def train_step(state: TrainState, batches, lr):
            """Fused GAS loop: batches have leading dim == gas. The
            compute-dtype cast of the params — and under zeropp the
            explicit quantized all-gather — is hoisted OUT of the scan:
            params are loop-invariant until the apply, and re-casting every
            micro-step costs a full fp32 param read per microbatch (XLA does
            not reliably hoist large loop-invariant buffers itself)."""
            compute_params, pqerr = compute_params_fn(state.params)

            def body(st, batch):
                st, loss, m_aux = micro_step_inner(st, batch, compute_params)
                # MoE: thread the model's in-program moe_* stats out of
                # the scan (trace-time key check — a moe-less model, or
                # moe_monitor None, stacks nothing and the emitted
                # program is bit-identical to the pre-moe one).
                moe = ({k: m_aux[k] for k in moe_keys if k in m_aux}
                       if moe_keys and isinstance(m_aux, dict) else {})
                return st, (loss, moe)

            state, (losses, moe_stacked) = jax.lax.scan(body, state, batches)
            out = apply_step(state, lr)
            state, overflow, norm = out[0], out[1], out[2]
            step_aux = {}
            if self.numerics is not None:
                step_aux["groups"] = out[3]
                if pqerr is not None:
                    step_aux["param_qerr"] = pqerr
            if moe_stacked:
                step_aux["moe"] = {k: jnp.mean(v.astype(jnp.float32))
                                   for k, v in moe_stacked.items()}
            if step_aux:
                return state, jnp.mean(losses), overflow, norm, step_aux
            return state, jnp.mean(losses), overflow, norm

        def eval_step(state: TrainState, batch):
            # Eval stays on the IMPLICIT full-precision path even under an
            # active zeropp plan: the reference API's forward() probe
            # (_compat_forward -> eval_batch) runs once per microbatch, so
            # routing it through the explicit quantized gather would re-run
            # that collective gas times per optimizer step — the exact
            # traffic the fused-only rule exists to avoid, and unaccounted
            # by the one-gather-per-step comm/bytes_*_params model.
            # Validation losses stay full-precision as a side benefit.
            compute_params = precision.cast_params(state.params)
            out = loss_fn(compute_params, batch, None)  # rng=None ≡ eval mode
            loss, aux = (out if isinstance(out, tuple) else (out, None))
            return loss.astype(jnp.float32), aux

        donate = (0,) if self._donate else ()
        if self.param_gather_plan is not None:
            # ZeRO++ is fused-only like the hierarchical/1-bit/offload
            # tiers: a per-microbatch _micro_step would re-run the
            # explicit param all-gather (a collective, not a cheap cast)
            # once per forward() on the reference API, while the comm
            # gauges model ONE gather per optimizer step — stash-and-
            # fuse keeps the wire protocol and its accounting honest.
            self._micro_step = None
            self._apply_step = None
        else:
            self._micro_step = jax.jit(micro_step, donate_argnums=donate)
            self._apply_step = jax.jit(apply_step, donate_argnums=donate)
        self._train_step = jax.jit(train_step, donate_argnums=donate)
        # eval_step deliberately does NOT donate: the train-path jits all
        # consume `state` and return its successor (the engine reassigns
        # self.state from the output), but eval reads state.params by
        # value and returns only the loss — donating would delete the
        # live self.state buffers the next train step still needs. The
        # batch arg is no safer to donate: put_batch returns caller
        # arrays unchanged when they are already placed, so donation
        # would free buffers the caller may reuse.
        self._eval_step = jax.jit(eval_step)

    def _build_hierarchical_step_fns(self) -> None:
        """Step functions with the explicit hierarchical grad sync
        (comm/grad_sync.py, docs/PERFORMANCE.md): the GAS fwd/bwd scan
        runs inside a shard_map manual over ONLY the `dcn` axis (ZeRO
        placement and TP specs stay GSPMD-auto), accumulating each
        micro-step's grads as flat buckets reduce-scattered over the ICI
        `data` axis in the communication_data_type; at the boundary the
        scattered shards all-reduce across slices with blockwise int8
        (or bf16/fp32 passthrough) quantization in a manual={dcn, data}
        region, all-gather back, and feed the unchanged optimizer apply.

        With ``comm.overlap_grad_sync`` resolved on (the default when the
        strategy engages), the plan runs the overlapped schedule instead:
        one manual={dcn} region per microstep with readiness-ordered
        per-bucket ICI scatters (in-tree models' bucket-boundary vjp
        markers fire inside), and microstep k's DCN reduce double-
        buffered against microstep k+1's fwd/bwd — only the final
        microstep's reduce stays exposed.

        Like the other fused-only tiers (1-bit, offload), reference-style
        forward/backward/step loops ride the stash-and-fuse shim."""
        from deepspeed_tpu.comm.grad_sync import (GradSyncPlan,
                                                  resolve_overlap)

        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        fp16 = cfg.fp16.enabled
        precision = self.precision
        loss_fn = self.loss_fn          # eval_step below
        mesh = self.mesh

        plan = GradSyncPlan(cfg.comm, mesh,
                            grad_template=self.state.grad_acc,
                            grad_specs=self.grad_specs,
                            acc_dtype=self.grad_accum_dtype,
                            ici_dtype=self._comm_dtype, gas=gas,
                            measure_quant_error=self.numerics is not None,
                            overlap=resolve_overlap(cfg.comm))
        self.grad_sync_plan = plan
        log_dist(plan.describe(), ranks=[0])

        grad_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self.grad_specs)
        apply_step = self._make_apply_step()
        compute_params_fn = self._make_compute_params()
        # Note on scaling: inside the dcn-manual region the batch is this
        # slice's shard, so loss_fn's mean carries a dcn-size-times-larger
        # per-sample coefficient; the plan's dcn mean divides it back
        # (exactly, for power-of-two slice counts).
        micro_grad = self._make_micro_grad()

        def train_step(state: TrainState, batches, lr):
            rng, sub = jax.random.split(state.rng)
            scale = state.loss_scale.scale if fp16 else jnp.float32(1.0)
            # Under zeropp the explicit quantized gather runs at the jit
            # level, BEFORE the dcn-manual region — the gathered compute
            # params enter gas_sync replicated, exactly what its rep
            # in_specs expect.
            compute_params, pqerr = compute_params_fn(state.params)
            grads, loss, qerr = plan.gas_sync(
                batches=batches, batch_spec=self.batch_spec,
                compute_params=compute_params, sub=sub, scale=scale,
                grad_fn=micro_grad)
            grads = jax.lax.with_sharding_constraint(grads, grad_shardings)
            state = state._replace(micro_step=state.micro_step + gas,
                                   grad_acc=grads, rng=rng)
            out = apply_step(state, lr)
            state, overflow, norm = out[0], out[1], out[2]
            if self.numerics is not None:
                aux = {"groups": out[3]}
                if qerr is not None:
                    aux["dcn_qerr"] = qerr
                if pqerr is not None:
                    aux["param_qerr"] = pqerr
                return state, loss, overflow, norm, aux
            return state, loss, overflow, norm

        def eval_step(state: TrainState, batch):
            # Implicit full-precision eval — see the note in
            # _build_step_fns.eval_step (the forward() probe must not
            # re-run the explicit zeropp gather per microbatch).
            compute_params = precision.cast_params(state.params)
            out = loss_fn(compute_params, batch, None)
            loss, aux = (out if isinstance(out, tuple) else (out, None))
            return loss.astype(jnp.float32), aux

        donate = (0,) if self._donate else ()
        self._train_step = jax.jit(train_step, donate_argnums=donate)
        # No donation for eval: see the note in _build_step_fns.
        self._eval_step = jax.jit(eval_step)
        self._micro_step = None
        self._apply_step = None

    # -- local-grad (1-bit) path: overridable pieces -----------------------
    def _local_grad_axes(self):
        """(comp_axis, dense_axis, manual_axes): the compression axis (dcn
        on hierarchical meshes, data otherwise) plus — when they differ —
        the ICI-inner data axis, which the engine pre-reduces DENSELY before
        the optimizer's compressed collective (cheap on ICI; the 1-bit
        protocol saves the slow-axis bandwidth only, exactly the reference's
        Ethernet-NCCL positioning, runtime/comm/nccl.py:47)."""
        from deepspeed_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS

        comp_axis = getattr(self.optimizer, "axis", DATA_AXIS)
        if self.dcn_size > 1 and comp_axis != DCN_AXIS:
            raise ValueError(
                f"1-bit compression axis '{comp_axis}' on a hierarchical "
                f"mesh (dcn={self.dcn_size}): grads would never reduce "
                f"across slices — compress over '{DCN_AXIS}' (the default)")
        dense_axis = None   # ICI-inner axis the engine reduces densely
        manual_axes = {comp_axis}
        if comp_axis != DATA_AXIS and self.mesh.shape.get(DATA_AXIS, 1) > 1:
            dense_axis = DATA_AXIS
            manual_axes.add(DATA_AXIS)
        return comp_axis, dense_axis, manual_axes

    def _local_grad_forward_backward(self, comp_axis, dense_axis):
        """fwd/bwd producing rank-LOCAL accumulated grads. Returns
        fn(compute_params, grad_acc, sub, scale, batches) ->
        (grads fp32 unscaled, loss fp32 local-mean)."""
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        loss_fn = self.loss_fn

        def run(compute_params, grad_acc, sub, scale, batches):
            def body(carry, batch):
                acc, key = carry
                key, k = jax.random.split(key)

                def scaled(cp):
                    out = loss_fn(cp, batch, k)
                    loss = (out[0] if isinstance(out, tuple) else out)
                    loss32 = loss.astype(jnp.float32)
                    return loss32 * scale / gas, loss32

                (_, loss), grads = jax.value_and_grad(
                    scaled, has_aux=True)(compute_params)
                acc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(a.dtype), acc, grads)
                return (acc, key), loss

            (acc, _), losses = jax.lax.scan(body, (grad_acc, sub), batches)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) / scale, acc)
            return grads, jnp.mean(losses)

        return run

    def _local_grad_sq(self, grads):
        """This rank's squared-norm contribution (overridden by the
        pipeline engine to psum the pipe-sharded block part)."""
        return global_norm(grads) ** 2

    def _build_local_grad_step_fns(self) -> None:
        """Step functions for communication-efficient optimizers
        (OneBitAdam/OneBitLamb, reference runtime/fp16/onebit/), in two
        phases: the fwd/bwd + compressed momentum sync run inside a
        shard_map manual over the compression axes so the optimizer sees
        LOCAL (unreduced) gradients and performs its own compressed
        collective — the engine's dense grad allreduce is bypassed, exactly
        like the reference disables its own allreduce for 1-bit optimizers
        (onebit/adam.py:98) — and the elementwise optimizer apply runs in
        GSPMD-auto mode, where ZeRO-1 optimizer-state sharding composes as
        an ordinary placement policy. Restrictions: ZeRO stage 0/1,
        Prefer ``train_batch()``; reference-style loops run via the
        stash-and-fuse shim (``_compat_forward``).
        ``gradient_clipping`` applies inside the shard_map via a psum'd
        rank-RMS norm (see below)."""
        cfg = self.config
        if cfg.zero_config.stage > 1:
            raise ValueError(
                "1-bit optimizers require ZeRO stage 0 or 1 (grad/param "
                "sharding would break the rank-local compressed protocol; "
                "compressed comm replaces the grad allreduce)")
        gas = cfg.gradient_accumulation_steps
        fp16 = cfg.fp16.enabled
        precision = self.precision
        mesh = self.mesh
        optimizer = self.optimizer
        scaler = self.loss_scaler
        comp_axis, dense_axis, manual_axes = self._local_grad_axes()
        # Axes the grad statistics reduce over (loss mean, clip norm): the
        # data-like axes only; the pipeline's pipe axis shards *params*,
        # not batch, and is handled by the fwd/bwd hook itself.
        red_axes = tuple(sorted(a for a in manual_axes
                                if a in (comp_axis, dense_axis)))
        all_manual = tuple(sorted(manual_axes))

        from deepspeed_tpu.utils.jax_compat import shard_map

        params_tree = self.state.params
        base_specs = self._base_specs
        if base_specs is None:
            base_specs = jax.tree_util.tree_map(
                lambda _: PartitionSpec(), params_tree)

        def manual_restrict(spec):
            entries = []
            for e in tuple(spec):
                parts = e if isinstance(e, tuple) else (e,)
                kept = tuple(a for a in parts if a in manual_axes)
                entries.append(kept if len(kept) > 1
                               else (kept[0] if kept else None))
            return PartitionSpec(*entries)

        param_in_specs = jax.tree_util.tree_map(manual_restrict, base_specs)
        we_specs = self.opt_state_specs_full.worker_error
        se_specs = self.opt_state_specs_full.server_error
        fwd_bwd = self._local_grad_forward_backward(comp_axis, dense_axis)

        def phase_a(params, grad_acc, m, we, se, step, sub, scale, batches):
            compute_params = precision.cast_params(params)
            rank = jax.lax.axis_index(comp_axis)
            if dense_axis is not None:
                from deepspeed_tpu.utils.jax_compat import axis_size
                rank = (rank * axis_size(dense_axis)
                        + jax.lax.axis_index(dense_axis))
            sub = jax.random.fold_in(sub, rank)
            grads, loss = fwd_bwd(compute_params, grad_acc, sub, scale,
                                  batches)
            if dense_axis is not None:
                # Dense ICI-local reduction; the optimizer's compressed
                # collective then runs over the slow axis only. The wire
                # dtype honors communication_data_type (the ICI reduction
                # dtype — same knob the hierarchical grad sync uses);
                # default keeps the gradient's native dtype.
                comm_dt = self._comm_dtype

                def dense_reduce(g):
                    if comm_dt is not None and g.dtype != comm_dt:
                        return jax.lax.pmean(
                            g.astype(comm_dt), dense_axis).astype(g.dtype)
                    return jax.lax.pmean(g, dense_axis)

                grads = jax.tree_util.tree_map(dense_reduce, grads)
            norm = jnp.float32(0.0)
            if cfg.gradient_clipping > 0.0:
                # Global-norm clip BEFORE the optimizer's own collective
                # (round-2 VERDICT weak #3: the reference composes 1-bit
                # Adam with the fp16 engine's clipping). The grads here are
                # still rank-local along the compression axis, so the norm
                # is the rank-RMS proxy sqrt(mean_r ||g_r||^2): equal to
                # the true averaged-grad norm when ranks agree, an upper
                # bound otherwise — the same coefficient on every rank, so
                # clipping commutes with the later pmean/compressed sync
                # (bias documented in docs/MIGRATING.md).
                clip = cfg.gradient_clipping
                local_sq = self._local_grad_sq(grads)
                nr = 1
                for ax in red_axes:
                    nr *= mesh.shape.get(ax, 1)
                norm = jnp.sqrt(jax.lax.psum(local_sq, red_axes) / nr)
                coef = jnp.minimum(1.0, clip / (norm + 1e-6))
                grads = jax.tree_util.tree_map(lambda g: g * coef, grads)
            if fp16 or self._nonfinite_grad_check:
                local_of = has_inf_or_nan(grads).astype(jnp.int32)
                overflow = jax.lax.pmax(local_of, all_manual) > 0
            else:
                overflow = jnp.zeros((), jnp.bool_)
            m_new, g_dense, we_new, se_new = optimizer.sync_phase(
                grads, m, we, se, step)
            loss_mean = jax.lax.pmean(loss, red_axes)
            return loss_mean, m_new, g_dense, we_new, se_new, overflow, norm

        # Batch spec: honor the engine's batch_spec, keeping only the
        # manual (data-like) axes (other axes stay GSPMD-auto and may not
        # appear in the shard_map's specs). Specs are PER LEAF, truncated
        # to the leaf's rank (mirroring put_batch): a low-rank side input
        # like PLD's per-micro-step theta vector [gas] rides replicated —
        # this is what lets progressive_layer_drop compose with the 1-bit
        # path. The shard_map is therefore constructed at TRACE time,
        # inside the jitted train_step, where the batch tree is known.
        base_batch_entries = (None,) + tuple(manual_restrict(self.batch_spec))
        rep = PartitionSpec()

        def batch_leaf_spec(x):
            entries = base_batch_entries[:x.ndim]
            # Mirror put_batch's graceful degradation: a leaf whose dims
            # don't divide the mesh axes is REPLICATED (put_batch already
            # warned and placed it that way), never given a sharded spec
            # that would fail shard_map's divisibility check at trace time.
            for d, e in zip(x.shape, entries):
                parts = e if isinstance(e, tuple) else ((e,) if e else ())
                n = 1
                for a in parts:
                    n *= mesh.shape.get(a, 1)
                if n > 1 and d % n:
                    return PartitionSpec(*([None] * x.ndim))
            return PartitionSpec(*entries)

        def run_phase_a(params, grad_acc, m, we, se, step, sub, scale,
                        batches):
            batch_specs = jax.tree_util.tree_map(batch_leaf_spec, batches)
            mapped = shard_map(
                phase_a, mesh=mesh,
                in_specs=(param_in_specs, param_in_specs, param_in_specs,
                          we_specs, se_specs, rep, rep, rep, batch_specs),
                out_specs=(rep, param_in_specs, param_in_specs, we_specs,
                           se_specs, rep, rep),
                axis_names=manual_axes,
                check_vma=False)
            return mapped(params, grad_acc, m, we, se, step, sub, scale,
                          batches)

        opt_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self.opt_state_specs_full)
        param_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self.param_specs)
        grad_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), self.grad_specs)

        def train_step(state: TrainState, batches, lr):
            rng, sub = jax.random.split(state.rng)
            scale = state.loss_scale.scale if fp16 else jnp.float32(1.0)
            opt = state.opt_state
            loss, m_new, g_dense, we_new, se_new, overflow, norm = \
                run_phase_a(
                    state.params, state.grad_acc, opt.m, opt.worker_error,
                    opt.server_error, opt.step, sub, scale, batches)
            # GSPMD-auto apply: ZeRO-1 places m/v sharded (opt_specs); the
            # resulting gather/slice collectives ride the ICI data axis.
            new_params, new_opt = optimizer.finish_step(
                state.params, opt, m_new, g_dense, we_new, se_new, lr)
            new_params = _tree_where(overflow, state.params, new_params)
            new_opt = _tree_where(overflow, opt, new_opt)
            new_params = jax.lax.with_sharding_constraint(
                new_params, param_shardings)
            new_opt = jax.lax.with_sharding_constraint(new_opt, opt_shardings)
            new_ls = scaler.update(state.loss_scale, overflow)
            zero_acc = jax.lax.with_sharding_constraint(
                jax.tree_util.tree_map(jnp.zeros_like, state.grad_acc),
                grad_shardings)
            state = state._replace(
                step=state.step + jnp.where(overflow, 0, 1),
                micro_step=state.micro_step + gas,
                params=new_params, opt_state=new_opt, grad_acc=zero_acc,
                loss_scale=new_ls, rng=rng,
                skipped_steps=state.skipped_steps + overflow.astype(jnp.int32))
            return state, loss, overflow, norm

        donate = (0,) if self._donate else ()
        self._train_step = jax.jit(train_step, donate_argnums=donate)
        self._eval_step = self._make_local_grad_eval_step()
        self._micro_step = None
        self._apply_step = None

    def _make_local_grad_eval_step(self):
        loss_fn = self.loss_fn
        precision = self.precision

        def eval_step(state: TrainState, batch):
            compute_params = precision.cast_params(state.params)
            out = loss_fn(compute_params, batch, None)
            loss, aux = (out if isinstance(out, tuple) else (out, None))
            return loss.astype(jnp.float32), aux

        return jax.jit(eval_step)

    # ------------------------------------------------------------------
    # Public API (reference parity: engine(batch) / backward / step)
    # ------------------------------------------------------------------
    def __call__(self, batch):
        return self.forward(batch)

    def _current_lr(self) -> jax.Array:
        if self.lr_scheduler is not None:
            lr = jnp.float32(self.lr_scheduler.lr_at(self.global_steps))
        else:
            lr = jnp.float32(self._base_lr)
        # Rollback-driven LR decay (guardrails.rollback.lr_decay): a
        # multiplicative scale over whatever the schedule says, so decaying
        # after an instability composes with any scheduler.
        gr = self.guardrails
        if gr is not None and gr.lr_scale != 1.0:
            lr = lr * jnp.float32(gr.lr_scale)
        return lr

    def put_batch(self, batch, leading_gas_dim: bool = False):
        """Shard a host batch across the data axis. With ``leading_gas_dim``
        the leaves carry a micro-batch dimension first (train_batch path) and
        the data axis shards dim 1.

        Leaves of lower rank than the batch spec keep the spec's leading
        entries (a [B]-shaped label vector under a (data, sequence) spec
        still data-shards its batch dim — round-2 VERDICT weak #6: the old
        rank test silently replicated it); leaves whose dims don't divide
        the sharding are replicated with a warning."""
        spec = self.batch_spec
        if leading_gas_dim:
            spec = PartitionSpec(None, *tuple(self.batch_spec))
        rep = NamedSharding(self.mesh, PartitionSpec())

        def axis_size(entry):
            parts = entry if isinstance(entry, tuple) else (entry,)
            n = 1
            for a in parts:
                if a is not None:
                    n *= self.mesh.shape.get(a, 1)
            return n

        def put(x):
            if isinstance(x, jax.Array) and not x.is_deleted():
                return x  # already placed
            x = np.asarray(x)
            if x.ndim == 0:
                return jax.device_put(x, rep)
            entries = tuple(spec)[:x.ndim]
            if any(d % axis_size(e) for d, e in zip(x.shape, entries)):
                logger.warning(
                    f"put_batch: leaf shape {x.shape} does not divide the "
                    f"batch spec {spec} — replicating")
                return jax.device_put(x, rep)
            return jax.device_put(
                x, NamedSharding(self.mesh, PartitionSpec(*entries)))

        return jax.tree_util.tree_map(put, batch)

    def forward(self, batch):
        """Compute loss and accumulate grads for one micro-batch.

        Trace attribution: ``_micro_step`` is ONE fused XLA program running
        forward *and* backward, so with sync'd spans the "forward" span
        carries the whole fwd+bwd compute and the "backward" span (emitted
        by :meth:`backward`) records only the host-side API point — XLA
        offers no host-observable seam inside a program; use the
        ``jax_profiler_dir`` passthrough for intra-program breakdown."""
        if self._micro_step is None:
            return self._compat_forward(batch)
        tel = self.telemetry
        g = self.goodput
        if g is not None:
            g.mark_gap()
        if self.wall_clock_breakdown:
            self.timers("forward").start()
        if self.progressive_layer_drop is not None and isinstance(batch, dict):
            theta = self.progressive_layer_drop.update_state(self.global_steps)
            batch = dict(batch)
            batch["pld_theta"] = np.float32(theta)
        if self.wall_clock_breakdown:
            self.timers("dataloader").start()
        with tel.span("dataloader", step=self.global_steps):
            batch = self.put_batch(batch)
        if self.wall_clock_breakdown:
            self.timers("dataloader").stop()
        if g is not None:
            g.mark("data_stall")
        status = tel.check_recompile("engine.micro_step", batch,
                                     step=self.global_steps)
        oom_guard = (self.memory.oom_guard(self, label="micro_step")
                     if self.memory is not None
                     else contextlib.nullcontext())
        with tel.span("forward", step=self.global_steps), oom_guard:
            self.state, loss, _ = self._micro_step(self.state, batch)
        if g is not None:
            # Same classification as _goodput_step_mark: micro-steps
            # re-run after a rollback rewind (the upcoming committed step
            # global_steps+1 is at or below the high-water mark) are
            # replay, not productive — the fwd+bwd here is the dominant
            # share of step time on this API.
            if status in ("compile", "retrace"):
                g.mark("recompile")
            elif self.global_steps < self._goodput_replay_until:
                g.mark("rollback_replay")
            else:
                g.mark("productive_step")
        self._last_loss = loss
        if self.wall_clock_breakdown:
            self.timers("forward").stop()
        return loss

    def _compat_forward(self, batch):
        """Reference-style forward() for fused-only configurations (1-bit
        optimizers, offloaded tiers): the micro-batch is STASHED host-side
        and the real fwd+bwd+sync runs as ONE fused program at the GAS
        boundary inside step() — lifting the former train_batch()-only
        restriction (the reference runs 1-bit under its ordinary engine
        loop, onebit/adam.py). The returned loss is this micro-batch's
        deterministic (dropout-off) forward; the training loss of the
        fused step lands in ``engine._last_loss`` after step()."""
        gas = self.gradient_accumulation_steps
        stashed = jax.tree_util.tree_map(np.asarray, batch)
        if len(self._pending_micro) > self._micro_in_window:
            # The previous forward() was never backward()'d — an eval-style
            # probe (reference loops call engine(batch) for validation too).
            # It contributes no gradient: replace it instead of wedging the
            # window.
            self._pending_micro[-1] = stashed
        elif len(self._pending_micro) >= gas:
            raise RuntimeError(
                f"forward() called more than gradient_accumulation_steps="
                f"{gas} times without an intervening step()")
        else:
            self._pending_micro.append(stashed)
        loss = self.eval_batch(batch)
        self._last_loss = loss
        return loss

    def backward(self, loss=None, allreduce_gradients: bool = True):
        """API-parity no-op: gradients were produced in forward's value_and_grad
        (an XLA program has no separate backward dispatch). Kept so reference
        training loops run unchanged. The backward span/timer records the
        host-side API point (near-zero by construction — see
        :meth:`forward`'s trace-attribution note)."""
        if self.wall_clock_breakdown:
            self.timers("backward").start()
            self.timers("backward").stop()
        with self.telemetry.span("backward", step=self.global_steps):
            pass
        self.micro_steps += 1
        self._micro_in_window += 1
        return loss if loss is not None else self._last_loss

    def is_gradient_accumulation_boundary(self) -> bool:
        return self._micro_in_window >= self.gradient_accumulation_steps

    def step(self):
        """Optimizer step at GAS boundary (reference engine.step :1302)."""
        if not self.is_gradient_accumulation_boundary():
            return
        if self._apply_step is None:
            # Fused-only configuration: run the whole window (stashed by
            # _compat_forward) as one fused program now.
            batches = jax.tree_util.tree_map(
                lambda *xs: np.stack(xs), *self._pending_micro)
            self._pending_micro = []
            self._micro_in_window = 0
            micro_before = self.micro_steps   # backward() already counted
            self.train_batch(batches)
            self.micro_steps = micro_before
            return
        self.step_attempts += 1
        gr = self.guardrails
        if gr is not None:
            gr.step_begin(self.global_steps + 1, label="optimizer_step")
        try:
            fp = self.fault_plan
            if fp is not None and fp.should_hang(self.step_attempts):
                fp.hang()
            if self.wall_clock_breakdown:
                self.timers("step").start()
            lr = self._current_lr()
            oom_guard = (self.memory.oom_guard(self, label="optimizer_step")
                         if self.memory is not None
                         else contextlib.nullcontext())
            with self.telemetry.span("optimizer_step",
                                     step=self.global_steps), oom_guard:
                out = self._apply_step(self.state, lr)
            self.state, overflow, norm = out[0], out[1], out[2]
            self._micro_in_window = 0
            self.global_steps += 1
            if self.numerics is not None:
                self.numerics.note_step({"groups": out[3]},
                                        self.global_steps)
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
            if self.wall_clock_breakdown:
                self.timers("step").stop()
        finally:
            if gr is not None:
                gr.step_end()
        self._goodput_step_mark(None)
        if self.global_steps % self.steps_per_print == 0:
            loss = float(self._last_loss) if self._last_loss is not None else float("nan")
            log_dist(f"step={self.global_steps} loss={loss:.4f} "
                     f"lr={float(lr):.3e} loss_scale={float(self.state.loss_scale.scale):.1f}",
                     ranks=[0])
        self._guardrails_step_hook(self._last_loss, overflow, norm)
        if self._last_loss is not None:
            self._post_step_hooks(self._last_loss)
        self._emit_step_telemetry()
        self._resilience_step_hook()

    def _emit_step_telemetry(self) -> None:
        """Per-step registry emission: HBM watermark gauges (peak +
        in-use, the OOM-margin signal), goodput category gauges, default
        step stamp, and a periodic trace-file + run-manifest flush (atomic
        rewrites at steps_per_print cadence so a preemption keeps a recent
        trace without O(steps^2) rewriting)."""
        tel = self.telemetry
        if not tel.enabled:
            return
        tel.set_step(self.global_steps)
        # ALL local devices, not just [0]: a multi-chip host's OOM margin
        # is set by its worst chip, and total in-use is the host's real
        # footprint. peak = max over devices, in_use = sum; rows carry the
        # device count so dashboards can tell a 1-chip host from an 8-chip.
        peaks, in_use, limits = [], [], []
        try:
            devices = jax.local_devices()
        except Exception:  # noqa: BLE001 — backend may be gone at teardown
            devices = []
        for dev in devices:
            try:
                stats = dev.memory_stats()
            except Exception:  # noqa: BLE001 — CPU backends may not report
                stats = None
            if stats:
                peaks.append(stats.get("peak_bytes_in_use", 0))
                in_use.append(stats.get("bytes_in_use", 0))
                limits.append(stats.get("bytes_limit", 0))
        if peaks:
            tel.registry.gauge("engine/hbm_peak_bytes").set(
                max(peaks), step=self.global_steps, devices=len(peaks))
            tel.registry.gauge("engine/hbm_bytes_in_use").set(
                sum(in_use), step=self.global_steps, devices=len(peaks))
        if self.memory is not None:
            # Headroom gauges ride the SAME stats fetch — no extra device
            # work (telemetry/memory.py note_hbm).
            self.memory.note_hbm(peaks, limits, step=self.global_steps)
        if self.grad_sync_plan is not None:
            # comm/bytes_dcn, comm/bytes_ici, comm/compression_ratio —
            # modeled from the plan shape (no device sync; see
            # docs/OBSERVABILITY.md "Gradient-sync metrics").
            self.grad_sync_plan.emit_telemetry(tel, self.global_steps)
        if self.param_gather_plan is not None:
            # The param-hop direction (comm/bytes_dcn_params,
            # comm/bytes_ici_params) — parameter traffic attributed
            # separately from gradient traffic, same modeled-no-sync
            # contract.
            self.param_gather_plan.emit_telemetry(tel, self.global_steps)
        if (self.grad_sync_plan is not None
                or self.param_gather_plan is not None):
            self._emit_comm_attribution(tel)
        if self.goodput is not None:
            self.goodput.emit(self.global_steps)
        if self.devicetime is not None:
            # Capture scheduler: two int compares in steady state; opens/
            # closes a jax.profiler capture (and parses it into the
            # devicetime/* gauges) only at its configured boundaries.
            self.devicetime.step_hook(self.global_steps)
        if self.global_steps % self.steps_per_print == 0:
            if self.numerics is not None:
                # THE numerics transfer: one device_get of the stacked
                # aux, then per-group gauge emission — before tel.flush()
                # so the rows land in this flush's write, and before the
                # fleet gather so its grad_norm field reads this flush's
                # value.
                self.numerics.flush(self.global_steps)
            if self.moe_monitor is not None:
                # Same economy: ONE device_get of the step's moe_* aux
                # refs, then the moe/* gauge family — inside the cadence
                # block so the step path never pays the fetch.
                self.moe_monitor.flush()
            tel.flush()
            if self.goodput is not None:
                # Crash-freshness: a SIGTERM'd attempt keeps a manifest no
                # older than one flush cadence.
                self.goodput.write_manifest()
            if self.fleet is not None:
                # Cross-host aggregation rides the SAME flush boundary —
                # the one collective + host fetch stays off the step path.
                self.fleet.flush(self.global_steps)

    def _emit_comm_attribution(self, tel) -> None:
        """Device-time comm attribution: ``comm/exposed_frac`` is the
        modeled exposed-collective share of the last measured step, and
        the same seconds feed the ``goodput/exposed_comm_sec``
        sub-attribution. Non-overlap schedule: the sync fires at the GAS
        boundary, so every modeled wire byte is exposed (ROADMAP item
        1's baseline). Overlapped schedule: hidden bucket time is
        discounted against the step's non-wire (compute) time — the
        exposed floor is the final microstep's DCN reduce + the post-
        sync all-gather, and ``comm/overlap_hidden_sec`` reports what
        the overlap is modeled to hide — so the PR-9 modeled-vs-measured
        divergence warning doesn't fire spuriously once overlap lands.
        An active zeropp param gather contributes its full wire time as
        exposed (it runs before the fused fwd/bwd, unhidden) — with or
        without a grad-sync plan. Modeled from the plan shape + nominal
        link bandwidths (comm.ici_gbps / comm.dcn_gbps) — no device
        sync, no host fetch."""
        g = self.goodput
        if g is None:
            return
        dt = g.last_step_time()
        if not dt or dt <= 0:
            return
        # The zeropp explicit param gather (ParamGatherPlan) runs
        # sequentially before the fused fwd/bwd — nothing is scheduled to
        # hide it, so ALL of its wire time counts as exposed. Omitting it
        # would make measured-vs-modeled diverge by construction whenever
        # zeropp rides with the hierarchical sync + devicetime captures.
        pplan = self.param_gather_plan
        comm_cfg = self.config.comm
        p_wire = (pplan.modeled_wire_seconds(comm_cfg.dcn_gbps,
                                             comm_cfg.ici_gbps)
                  if pplan is not None else 0.0)
        plan = self.grad_sync_plan
        if plan is not None:
            wire = min(plan.modeled_wire_seconds() + p_wire, dt)
            budget = max(0.0, dt - wire)  # compute time available to hide in
            exposed = min(
                p_wire + plan.modeled_exposed_seconds(
                    overlap_budget_seconds=budget), dt)
        else:
            wire = exposed = min(p_wire, dt)
        tel.registry.gauge("comm/exposed_frac").set(
            exposed / dt, step=self.global_steps)
        if plan is not None and plan.overlap:
            tel.registry.gauge("comm/overlap_hidden_sec").set(
                max(0.0, wire - exposed), step=self.global_steps)
        g.note_aux("exposed_comm_sec", exposed)

    def _goodput_step_mark(self, status) -> None:
        """End-of-step attribution: recompile when the detector saw this
        dispatch trace/compile, rollback_replay while re-earning ground a
        rollback gave up, productive_step otherwise."""
        g = self.goodput
        if g is None:
            return
        if status in ("compile", "retrace"):
            cat = "recompile"
        elif self.global_steps <= self._goodput_replay_until:
            cat = "rollback_replay"
        else:
            cat = "productive_step"
        g.step_mark(cat, self.global_steps)

    def _maybe_goodput_cost_analysis(self, batches, lr) -> None:
        """Feed the accountant the step function's XLA cost-analysis FLOPs
        — ONCE per engine (re-attempted never, success or fail), so
        ``engine/mfu`` needs no per-step analysis. Uses
        ``Lowered.cost_analysis()`` (HLO-level, no second XLA compile —
        the cost is one host-side re-trace, attributed to the recompile
        category); jax versions without it fall back to the AOT compile,
        whose binary the XLA compilation cache dedupes."""
        g = self.goodput
        if g is None or not g.wants_flops:
            return
        if self._train_step is None:
            g.flops_failed()   # offload tier: no single jitted step fn
            return
        try:
            from deepspeed_tpu.profiling.flops_profiler import peak_tflops
            with g.measure("recompile"):
                lowered = self._train_step.lower(self.state, batches, lr)
                try:
                    cost = lowered.cost_analysis() or {}
                except Exception:  # noqa: BLE001 — older jax: compile path
                    cost = lowered.compile().cost_analysis() or {}
            if isinstance(cost, (list, tuple)):  # older jax returns [dict]
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0))
            bytes_per_step = float(cost.get("bytes accessed", 0.0))
            if self._fused_update:
                # XLA's analysis sees the fused update as an opaque
                # custom call (zero flops, zero bytes) — book the
                # kernel's arithmetic and its single HBM round-trip
                # explicitly so MFU / roofline intensity stay honest.
                from deepspeed_tpu.ops.adam.fused_update import (
                    fused_update_cost)
                k_flops, k_bytes = fused_update_cost(self.state.params)
                flops += k_flops
                bytes_per_step += k_bytes
            dev = jax.devices()[0]
            g.set_flops(flops, n_chips=self.mesh.size,
                        peak_tflops_per_chip=peak_tflops(
                            getattr(dev, "device_kind", ""),
                            dtype=self.precision.name),
                        # bytes feed the devicetime roofline's operational
                        # intensity (telemetry/devicetime.py)
                        bytes_per_step=bytes_per_step)
        except Exception as e:  # noqa: BLE001 — MFU is best-effort
            g.flops_failed()
            logger.warning("goodput: step cost analysis unavailable: %s", e)

    def _maybe_profile(self, fn, *args, params=None):
        """Emit the flops report at profile_step. lower+compile only
        (measure=False): must not execute a donating step on live state."""
        if (self.flops_profiler is None or self.global_steps + 1 !=
                self.flops_profiler.config.profile_step):
            return
        prof = self.flops_profiler.profile_callable(
            fn, *args, params=params,
            detailed=self.flops_profiler.config.detailed, measure=False)
        out_file = self.flops_profiler.config.output_file
        if out_file:
            with open(out_file, "w") as f:
                self.flops_profiler.print_profile(prof, file=f)
        else:
            self.flops_profiler.print_profile(prof)

    def _stash_moq_probe(self, batches):
        if (self.moq is not None
                and self.moq.cfg.eigenvalue.get("enabled", False)
                and isinstance(batches, dict)):
            # one micro-batch, host-side, for the one-shot eigenvalue probe
            self._moq_probe_batch = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[0], batches)
        return batches

    def _inject_pld(self, batches):
        if self.progressive_layer_drop is None or not isinstance(batches, dict):
            return batches
        theta = self.progressive_layer_drop.update_state(self.global_steps)
        batches = dict(batches)
        # leading GAS dim so the micro-batch scan can carry it (one scalar
        # per micro-step)
        batches["pld_theta"] = np.full(
            (self.gradient_accumulation_steps,), theta, np.float32)
        return batches

    def _maybe_moq_eigenvalues(self):
        """Compute per-layer Hessian eigenvalues once at the schedule
        offset and hand them to the quantizer (reference engine eigenvalue
        hook: sensitive layers keep precision longer)."""
        ev_cfg = self.moq.cfg.eigenvalue
        if (not ev_cfg.get("enabled", False) or self.moq.eigenvalues
                or self.global_steps < self.moq.cfg.schedule_offset
                or getattr(self, "_moq_probe_batch", None) is None):
            return
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

        ev = Eigenvalue(verbose=ev_cfg.get("verbose", False),
                        max_iter=int(ev_cfg.get("max_iter", 100)),
                        tol=float(ev_cfg.get("tol", 1e-2)),
                        stability=float(ev_cfg.get("stability", 1e-6)))
        compute = (self._compute_params if hasattr(self, "offloader")
                   else self.precision.cast_params(self.state.params))
        vals = ev.compute_eigenvalue(self.loss_fn, compute,
                                     self._moq_probe_batch,
                                     jax.random.PRNGKey(23))
        self.moq.set_eigenvalues(vals)
        log_dist(f"MoQ eigenvalues: { {k: round(v, 4) for k, v in vals.items()} }",
                 ranks=[0])

    def _post_step_hooks(self, loss):
        if self.moq is not None:
            self._maybe_moq_eigenvalues()
            key = jax.random.fold_in(jax.random.PRNGKey(17), self.global_steps)
            if hasattr(self, "offloader"):
                self.offloader.master = self.moq.quantize_tree(
                    self.offloader.master, self.global_steps, key)
                self.state = self.state._replace(params=self.offloader.master)
                self._compute_params = self._offload_place(
                    jax.tree_util.tree_map(np.asarray, self.offloader.master))
            else:
                self.state = self.state._replace(params=self.moq.quantize_tree(
                    self.state.params, self.global_steps, key))
        # Scalar emission goes through the telemetry registry, which fans
        # out to every configured sink (a legacy tensorboard block rides as
        # a sink — build_telemetry). The sink check also gates the host
        # fetches: float(loss) forces a device sync nobody needs when no
        # sink listens.
        reg = self.telemetry.registry
        if reg.sinks:
            reg.add_scalar("Train/Samples/train_loss", float(loss),
                           self.global_steps)
            reg.add_scalar("Train/Samples/lr", float(self._current_lr()),
                           self.global_steps)
            if self.config.fp16.enabled:
                reg.add_scalar("Train/Samples/loss_scale",
                               float(self.state.loss_scale.scale),
                               self.global_steps)

    def train_batch(self, batches) -> jax.Array:
        """Fused full step: ``batches`` is a pytree whose leaves have leading
        dim gradient_accumulation_steps (one entry per micro-batch)."""
        self._pending_micro = []   # direct call supersedes any stashed loop
        self.step_attempts += 1
        fp = self.fault_plan
        if fp is not None and fp.should_nan_loss(self.step_attempts):
            batches = fp.poison_batch(batches)
        gr = self.guardrails
        if gr is not None:
            gr.step_begin(self.global_steps + 1)
        # RESOURCE_EXHAUSTED in compile or dispatch => memory crashdump +
        # distinct OOM rc (telemetry/memory.py). The pipeline engine
        # overrides the label so an OOM mid-pipe names the schedule
        # shape, like the watchdog bracket.
        oom_guard = (self.memory.oom_guard(self,
                                           label=self._memory_oom_label)
                     if self.memory is not None
                     else contextlib.nullcontext())
        try:
            with oom_guard:
                return self._train_batch_inner(batches)
        finally:
            if gr is not None:
                gr.step_end()

    def _train_batch_inner(self, batches) -> jax.Array:
        tel = self.telemetry
        g = self.goodput
        if g is not None:
            g.mark_gap()
        self.tput_timer.start()
        if self.wall_clock_breakdown:
            self.timers("dataloader").start()
        with tel.span("dataloader", step=self.global_steps):
            batches = self.put_batch(
                self._inject_pld(self._stash_moq_probe(batches)),
                leading_gas_dim=True)
        if self.wall_clock_breakdown:
            self.timers("dataloader").stop()
        if g is not None:
            g.mark("data_stall")
        status = tel.check_recompile("engine.train_step", batches,
                                     step=self.global_steps)
        fp = self.fault_plan
        if fp is not None and fp.should_hang(self.step_attempts):
            # In the armed watchdog window, before the step program: the
            # deadlocked-collective shape a real hang takes.
            fp.hang()
        if self._train_step is None:  # offloaded optimizer tier
            with tel.span("train_step", step=self.global_steps) as sp:
                loss = self._offload_train_batch(batches)
            self.global_steps += 1
            self.micro_steps += self.gradient_accumulation_steps
            if self.lr_scheduler is not None:
                self.lr_scheduler.step()
            self.tput_timer.stop()
            self._last_loss = loss
            self._goodput_step_mark(status)
            if self.memory is not None:
                # Offload tier: attribute the device-side micro-scan
                # executable (the host optimizer step has no HBM story).
                self.memory.maybe_attribute(self, batches, None, status)
            if (self.fleet is not None and sp.duration
                    and self._fleet_note_inner_span
                    and tel.tracer.sync_spans):
                self.fleet.note_step_time(sp.duration)
            # Feed the UNSCALED grad norm (norm_h is pre-unscale; coef is
            # the same factor get_global_grad_norm applies) so the offload
            # tier gets the same grad-norm anomaly coverage as the device
            # tiers. The tiny host-side multiply is built only when a
            # detector is listening.
            norm = None
            if self.guardrails is not None:
                norm_h, coef = self._offload_last_norm
                norm = norm_h * coef
            rolled_back = self._guardrails_step_hook(
                loss, getattr(self, "_offload_last_overflow", None), norm)
            if self.config.check_numerics and not rolled_back:
                self._check_numerics(loss, overflow=False)
            self._post_step_hooks(loss)
            self._emit_step_telemetry()
            self._resilience_step_hook()
            return loss
        lr = self._current_lr()
        self._maybe_profile(self._train_step, self.state, batches, lr,
                            params=self.state.params)
        with tel.span("train_step", step=self.global_steps) as sp:
            out = self._train_step(self.state, batches, lr)
        self.state, loss, overflow, norm = out[:4]
        self.global_steps += 1
        step_aux = out[4] if len(out) > 4 else {}
        if self.numerics is not None:
            # A reference hand-off of the in-program stats aux — the
            # device->host transfer happens at the flush boundary only.
            self.numerics.note_step(
                {k: v for k, v in step_aux.items() if k != "moe"},
                self.global_steps)
        if self.moe_monitor is not None and "moe" in step_aux:
            # Same reference hand-off for the model's moe_* stats; the
            # monitor pays its one device_get at the flush boundary.
            self.moe_monitor.note_step(
                step_aux["moe"], self.global_steps,
                gas=self.gradient_accumulation_steps)
        self.micro_steps += self.gradient_accumulation_steps
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self.tput_timer.stop()
        self._last_loss = loss
        self._goodput_step_mark(status)
        if (self.fleet is not None and sp.duration
                and self._fleet_note_inner_span
                and tel.tracer.sync_spans):
            # Sync'd span duration ≈ measured device step time — the
            # fleet aggregator prefers it over goodput's host-clock delta
            # (the "sync'd sub-step spans" device-time fallback). Without
            # sync_spans the span brackets only the async dispatch, so
            # the goodput fallback is the honest estimate.
            self.fleet.note_step_time(sp.duration)
        self._maybe_goodput_cost_analysis(batches, lr)
        if self.memory is not None:
            # Once per compiled step fn (re-armed on retrace): XLA
            # memory_analysis gauges for this executable.
            self.memory.maybe_attribute(self, batches, lr, status)
        rolled_back = self._guardrails_step_hook(loss, overflow, norm)
        if self.config.check_numerics and not rolled_back:
            self._check_numerics(loss, overflow=bool(overflow))
        self._post_step_hooks(loss)
        self._emit_step_telemetry()
        self._resilience_step_hook()
        return loss

    def _check_numerics(self, loss, overflow: bool = False) -> None:
        """`check_numerics` debug mode: fail fast (with the step number and
        the offending leaves) instead of training on silently, the debug
        lever SURVEY §5 asks the TPU build to provide. Costs one extra host
        sync per step — keep it off in production runs. fp16's dynamic
        loss scaler legitimately produces non-finite losses on overflow
        steps (the update is SKIPPED and state rolled back), so those skip
        the loss check; the committed params are always checked, with ONE
        device->host sync for the whole tree (leaf names resolved only on
        failure)."""
        if not overflow and not bool(np.isfinite(np.asarray(loss))):
            raise FloatingPointError(
                f"check_numerics: non-finite loss {float(loss)} at global "
                f"step {self.global_steps} (skipped_steps="
                f"{int(self.state.skipped_steps)})")
        flags = jax.jit(lambda t: jnp.stack([
            jnp.all(jnp.isfinite(x.astype(jnp.float32)))
            for x in jax.tree_util.tree_leaves(t)]))(self.state.params)
        if bool(jnp.all(flags)):
            return
        finite = np.asarray(flags)
        paths = [("/".join(str(getattr(k, "key", k)) for k in path))
                 for path, _ in jax.tree_util.tree_flatten_with_path(
                     self.state.params)[0]]
        bad = [p for p, ok in zip(paths, finite) if not ok]
        raise FloatingPointError(
            f"check_numerics: non-finite params after global step "
            f"{self.global_steps}: {bad[:8]}"
            f"{' ...' if len(bad) > 8 else ''}")

    def eval_batch(self, batch):
        batch = self.put_batch(batch)
        self.telemetry.check_recompile("engine.eval_step", batch)
        if self._eval_step is None:  # offload tier: params already compute-dtype
            loss, _ = self._offload_eval(self._compute_params, batch)
            return loss
        loss, _ = self._eval_step(self.state, batch)
        return loss

    # ------------------------------------------------------------------
    # Introspection / parity getters
    # ------------------------------------------------------------------
    @property
    def module_params(self):
        """Compute-precision view of the parameters."""
        if hasattr(self, "offloader"):
            return self._compute_params
        return self.precision.cast_params(self.state.params)

    def get_global_grad_norm(self) -> float:
        if hasattr(self, "offloader"):
            # grads never persist in state.grad_acc under offload; report the
            # unscaled norm of the last step's accumulated grads. Stored
            # lazily as (scaled_norm_array, coef) — only THIS accessor
            # forces the fetch, keeping the hot path sync-free.
            last = getattr(self, "_offload_last_norm", 0.0)
            if isinstance(last, tuple):
                return float(last[0]) * last[1]
            return float(last)
        # One cached jitted fn for the life of the process: a fresh
        # jax.jit(global_norm) per call built a new wrapper each time,
        # re-tracing (and re-compiling) on every invocation. The detector
        # check makes the regression visible: a retrace under this name
        # after the first call is a bug (tests/test_numerics.py pins it).
        self.telemetry.check_recompile("engine.global_norm",
                                       self.state.grad_acc)
        with self.mesh:
            return float(_global_norm_jit()(self.state.grad_acc))

    def zero_optimization(self) -> bool:
        return self.config.zero_enabled

    def zero_optimization_stage(self) -> int:
        return self.config.zero_config.stage

    def get_lr(self):
        return [float(self._current_lr())]

    @property
    def skipped_steps(self) -> int:
        return int(self.state.skipped_steps)

    def loss_scale(self) -> float:
        return float(self.state.loss_scale.scale)

    # ------------------------------------------------------------------
    # Resilience — preemption-aware async checkpointing + auto-resume
    # (resilience/; docs/RESILIENCE.md)
    # ------------------------------------------------------------------
    def _resilience_step_hook(self) -> None:
        """After every committed optimizer step: enqueue an async checkpoint
        at the configured interval (the write happens on the manager's
        background thread — off the step path) and deliver any injected
        preemption. Save first, then preempt: the interrupted write is
        exactly the torn-checkpoint case the manifest protocol handles.

        With guardrails on, a step the detector just called a SPIKE is
        numerically suspect (in bf16 its NaN grads COMMITTED) — writing it
        would make the newest on-disk checkpoint the poisoned one, which is
        exactly what rollback escalation and post-watchdog auto-resume
        restore. Skip the interval save for spike steps; the next ok step
        saves as usual."""
        gr = self.guardrails
        suspect = (gr is not None and gr.last_verdict is not None
                   and bool(gr.last_verdict))
        mgr = self.ckpt_manager
        if (mgr is not None and not suspect
                and self.global_steps % mgr.interval == 0):
            self.save_checkpoint_async()
        fp = self.fault_plan
        if fp is not None and fp.should_preempt(self.global_steps):
            fp.preempt(self.global_steps)
        if fp is not None and fp.should_slice_preempt(self.step_attempts):
            # The advance-warning shape: SIGTERM WITHOUT resetting the
            # handler, so the live-elasticity coordinator (when enabled)
            # catches it; without one the default disposition kills us —
            # a plain preemption, exactly the contrast the chaos test
            # wants reproducible.
            fp.slice_preempt()
        el = self.elastic
        if el is not None:
            # Step boundary: pending shrink (caught advance warning),
            # rejoin rendezvous, or eviction check. One attribute check
            # plus a couple of flag reads in steady state.
            el.step_boundary(self)

    def register_client_state_fn(self, fn: Callable[[], Dict]) -> None:
        """Callable whose result rides every auto-checkpoint as
        client_state (e.g. ``loader.state_dict`` for dataloader replay)."""
        self._client_state_fn = fn

    # ------------------------------------------------------------------
    # Guardrails — anomaly detection, in-memory rollback, step watchdog
    # (guardrails/; docs/RESILIENCE.md "Guardrails")
    # ------------------------------------------------------------------
    def register_data_skip_fn(self, fn: Callable[[int], int]) -> None:
        """Callable(n) advancing the data stream past n batches — the
        rollback policy uses it to move past a poisoned window (pass
        ``RepeatingLoader.skip_batches``). No-op without a guardrails
        block (nothing else consumes it)."""
        if self.guardrails is not None:
            self.guardrails.register_data_skip_fn(fn)

    def _guardrails_step_hook(self, loss, overflow, norm) -> bool:
        """Post-step detector feed. Returns True when a rollback rewound
        the engine this step (the caller then skips its own fail-fast
        numerics raise — the anomaly was HANDLED). Disabled guardrails is
        one attribute check: no host fetch, no device sync."""
        gr = self.guardrails
        if gr is None or loss is None:
            return False
        step_before = self.global_steps
        rolled = gr.after_step(self, loss, overflow, norm)
        if rolled:
            # Steps up to the pre-rollback high-water mark are re-executed
            # ground: the goodput accountant books them as rollback_replay,
            # not productive_step.
            self._goodput_replay_until = max(self._goodput_replay_until,
                                             step_before)
        return rolled

    def save_checkpoint_async(self,
                              client_state: Optional[Dict] = None) -> None:
        """Snapshot now, write in the background (resilience manager)."""
        if self.ckpt_manager is None:
            raise RuntimeError(
                "save_checkpoint_async requires the resilience block: "
                '{"resilience": {"enabled": true, "checkpoint": {"dir": ...}}}')
        if client_state is None and self._client_state_fn is not None:
            client_state = self._client_state_fn()
        self.ckpt_manager.save(self, client_state=client_state)

    def auto_resume(self):
        """Restore from the newest complete resilience checkpoint under the
        configured dir, resharding onto this engine's (possibly different
        elastic) world. Returns (path, client_state) — (None, {}) means
        fresh start."""
        from deepspeed_tpu.resilience import restore

        rcfg = self.config.resilience
        if not (rcfg.enabled and rcfg.auto_resume):
            return None, {}
        if self.goodput is not None:
            with self.goodput.measure("init_restore"):
                return restore(self, rcfg.checkpoint.dir)
        return restore(self, rcfg.checkpoint.dir)

    def _elastic_rebuild(self, *, devices, slices: int, micro_batch: int,
                         gas: int, arrays: Dict[str, Any],
                         meta: Dict[str, Any]) -> None:
        """In-process elastic world change (resilience/elastic.py): rebuild
        mesh → ZeRO placement → batch triple → state placement → jitted
        step functions over ``devices``, then install the gathered host
        ``arrays`` through the existing ``install_state_arrays`` reshard
        path. No process restart, no ``init_restore`` — the coordinator
        wraps the whole call in ONE goodput ``elastic_reshard`` measure.

        Only the data-parallel fused tiers rebuild (config validation
        walls off pipe/offload/1-bit/zeropp before an engine with live
        elasticity can exist). Mutates the batch keys of ``self.config``
        — the elastic ladder owns them by contract, and the step builders
        read them at build time."""
        from deepspeed_tpu.comm.grad_sync import resolve_hierarchical
        from deepspeed_tpu.parallel.mesh import (DCN_AXIS, PIPE_AXIS,
                                                 build_mesh,
                                                 get_default_mesh)
        from deepspeed_tpu.resilience.checkpoint import (_flatten_named,
                                                         install_state_arrays)

        cfg = self.config
        # Host params template for the new placement, reconstructed from
        # the gathered snapshot (full arrays — the reshard-by-construction
        # property of the PR-1 checkpoint format).
        named, params_def = _flatten_named(self.state.params)
        missing = [n for n, _ in named if f"params.{n}" not in arrays]
        if missing:
            raise ConfigError(
                f"elastic rebuild: snapshot lacks param leaves "
                f"{missing[:5]} — was it written by a different model?")
        params_host = jax.tree_util.tree_unflatten(
            params_def, [np.asarray(arrays[f"params.{n}"])
                         for n, _ in named])

        old_mesh = self.mesh
        mesh = build_mesh(data=-1, model=cfg.mesh.model, pipe=cfg.mesh.pipe,
                          sequence=cfg.mesh.sequence, expert=cfg.mesh.expert,
                          slices=slices, devices=list(devices))
        self.mesh = mesh
        self.dcn_size = mesh.shape.get(DCN_AXIS, 1)
        self.dp_size = mesh.shape.get(DATA_AXIS, 1) * self.dcn_size
        if get_default_mesh() is old_mesh:
            # Keep the ambient mesh (mesh-needing attention ops) in step
            # with the live engine, but never steal another engine's.
            mesh_lib_set_default(mesh)
        self.partitioner = ZeroPartitioner(mesh, cfg.zero_config)
        self.param_specs = self.partitioner.param_specs(
            params_host, self._base_specs)
        self.grad_specs = self.partitioner.grad_specs(
            params_host, self._base_specs)
        self.opt_specs = self.partitioner.opt_state_specs(
            params_host, self._base_specs)
        if not self._custom_batch_spec:
            self.batch_spec = (PartitionSpec((DCN_AXIS, DATA_AXIS))
                               if self.dcn_size > 1
                               else PartitionSpec(DATA_AXIS))

        # The elastic ladder owns the batch triple (config._apply_
        # elasticity wrote the originals the same way): same global batch,
        # re-split for the new world.
        cfg.gradient_accumulation_steps = int(gas)
        cfg.train_micro_batch_size_per_gpu = int(micro_batch)
        cfg.train_batch_size = int(micro_batch) * int(gas) * self.dp_size
        self.gradient_accumulation_steps = int(gas)
        self.train_micro_batch_size_per_gpu = int(micro_batch)
        self.train_batch_size = cfg.train_batch_size
        self.tput_timer.batch_size = self.train_batch_size

        # Re-resolve the grad-sync strategy: a shrink to one slice has no
        # DCN axis left for the hierarchical sync to serve (and a rejoin
        # brings it back).
        self._grad_sync_on, sync_reason = resolve_hierarchical(
            cfg.comm, mesh, needs_local_grads=False,
            sparse_gradients=(cfg.sparse_gradients_enabled
                              or self._sparse_grads_handled),
            pipe_stages=mesh.shape.get(PIPE_AXIS, 1))
        self.grad_sync_plan = None
        log_dist(f"elastic rebuild: hierarchical grad sync "
                 f"{'on' if self._grad_sync_on else 'off'} ({sync_reason})",
                 ranks=[0])

        # Fresh placement on the new mesh (moments re-initialised as
        # templates only), then the snapshot's values land on it through
        # the one shared host-arrays→engine path.
        self.state = self._init_state(params_host, rng_seed=0)
        # ZeRO++ weight path: re-derive the plan from the (possibly
        # rebuilt) config against the new placement. Live elasticity
        # walls zeropp off at config parse, so this only ever fires on
        # the autotuner's trial rebuilds (autotuning/search.py), whose
        # candidate configs flip the block on/off per trial.
        self.zeropp = cfg.zero_config.zeropp
        self.param_gather_plan = None
        if self.zeropp.active:
            from deepspeed_tpu.comm.grad_sync import ParamGatherPlan
            self.param_gather_plan = ParamGatherPlan(
                self.zeropp, mesh,
                param_template=self.state.params,
                param_specs=self.param_specs,
                measure_quant_error=self.numerics is not None)
            log_dist(self.param_gather_plan.describe(), ranks=[0])
        install_state_arrays(
            self, arrays, step=int(meta["step"]),
            micro_steps=int(meta["micro_steps"]),
            lr_scheduler_state=meta.get("lr_scheduler"))
        self._build_step_fns()

        # The rebuilt step functions MUST recompile — that is the point —
        # so the detector's next trace is the expected one-time compile,
        # not a loud retrace warning operators would learn to ignore; the
        # MFU cost analysis re-arms for the new world's FLOPs/chips.
        for fn in ("engine.train_step", "engine.eval_step",
                   "engine.micro_step", "engine.global_norm"):
            self.telemetry.recompile.forget(fn)
        if self.goodput is not None:
            self.goodput.reset_flops()
        if self.memory is not None:
            # Ledger + capacity projections are per-mesh; re-derive them
            # (pure host arithmetic over shapes/specs).
            self.memory.on_engine_init(self)
        log_dist(
            f"elastic rebuild: world={mesh.size} mesh={dict(mesh.shape)} "
            f"micro={micro_batch} gas={gas} global_batch="
            f"{self.train_batch_size} at step {self.global_steps}",
            ranks=[0])

    def _snapshot_state(self) -> TrainState:
        """The state tree a resilience snapshot serialises — swapped tiers
        are read back into host RAM first (same prologue as
        save_checkpoint)."""
        if self._offload_nvme():
            master, opt = self.offloader.export_state()
            return self.state._replace(params=master, opt_state=opt)
        return self.state

    def _apply_restored_state(self, state: TrainState) -> None:
        """Install a restored TrainState, pushing host tiers back into the
        offloader when one exists (mirrors load_checkpoint's epilogue)."""
        if self._offload_nvme():
            self.offloader.import_state(state.params, state.opt_state)
            self._compute_params = self._offload_place(
                jax.tree_util.tree_map(np.asarray, state.params))
            # nvme placeholders stay; scalars (step/loss_scale/rng/...) land.
            self.state = self.state._replace(
                step=state.step, micro_step=state.micro_step,
                loss_scale=state.loss_scale,
                skipped_steps=state.skipped_steps, rng=state.rng)
            return
        self.state = state
        if hasattr(self, "offloader"):
            self.offloader.master = state.params
            self.offloader.opt_state = state.opt_state
            self._compute_params = self._offload_place(
                jax.tree_util.tree_map(np.asarray, state.params))

    # ------------------------------------------------------------------
    # Checkpointing — delegates to runtime.checkpointing
    # ------------------------------------------------------------------
    def _offload_nvme(self) -> bool:
        return (hasattr(self, "offloader")
                and getattr(self.offloader, "tier", None) == "nvme")

    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None,
                        save_latest: bool = True) -> str:
        from deepspeed_tpu.runtime import checkpointing as ckpt

        if self._offload_nvme():
            # Read the swapped (master, moments) tier back into host RAM
            # for the duration of the save — the reference's
            # save_checkpoint_prologue (stage3.py:3250) does the same
            # swap-in before serialising.
            master, opt = self.offloader.export_state()
            old_state = self.state
            self.state = self.state._replace(params=master, opt_state=opt)
            try:
                return ckpt.save_checkpoint(self, save_dir, tag=tag,
                                            client_state=client_state or {},
                                            save_latest=save_latest)
            finally:
                self.state = old_state
        return ckpt.save_checkpoint(self, save_dir, tag=tag,
                                    client_state=client_state or {},
                                    save_latest=save_latest)

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True):
        from deepspeed_tpu.runtime import checkpointing as ckpt

        if self._offload_nvme():
            # Restore into host RAM against an abstract template (the real
            # trees live on disk), then write them back onto the NVMe tier.
            params_abs, opt_abs = self.offloader.abstract_state()
            placeholder_state = self.state
            self.state = self.state._replace(params=params_abs,
                                             opt_state=opt_abs)
            try:
                out = ckpt.load_checkpoint(
                    self, load_dir, tag=tag,
                    load_optimizer_states=load_optimizer_states,
                    load_lr_scheduler_states=load_lr_scheduler_states)
                if out[0] is not None:
                    opt = self.state.opt_state
                    if not load_optimizer_states:
                        # keep the on-disk moments, replace only the master
                        _, opt = self.offloader.export_state()
                    self.offloader.import_state(self.state.params, opt)
                    self._compute_params = self._offload_place(
                        jax.tree_util.tree_map(np.asarray,
                                               self.state.params))
            finally:
                # Revert ONLY the nvme placeholders — the restored step /
                # loss_scale / rng / skipped_steps scalars must survive
                # (they drive overflow-skip, dropout streams, schedules).
                self.state = self.state._replace(
                    params=placeholder_state.params,
                    opt_state=placeholder_state.opt_state)
            return out
        out = ckpt.load_checkpoint(self, load_dir, tag=tag,
                                   load_optimizer_states=load_optimizer_states,
                                   load_lr_scheduler_states=load_lr_scheduler_states)
        if hasattr(self, "offloader") and out[0] is not None:
            # Push restored host state back into the offload tier and
            # refresh the device compute params from the new master.
            self.offloader.master = self.state.params
            self.offloader.opt_state = self.state.opt_state
            self._compute_params = self._offload_place(
                jax.tree_util.tree_map(np.asarray, self.state.params))
        return out
