"""ZeRO partitioning as sharding rules.

The TPU-native realisation of ZeRO stages 1-3 (reference
``runtime/zero/stage1.py``, ``stage2.py``, ``stage3.py``,
``partition_parameters.py``): instead of flat fp16 buffers, autograd hooks and
hand-rolled reduce/allgather, each stage is a *placement policy* — a mapping
from every array in the train state to a ``PartitionSpec`` over the ``data``
mesh axis. pjit/GSPMD then emits exactly the collectives the reference
hand-codes:

- stage 0: params/grads/opt-state replicated; grads ``psum`` (≡ allreduce).
- stage 1: optimizer state (fp32 master + moments) sharded over ``data``
  (≡ optimizer state partitioning, stage1.py). Grad allreduce, then each
  shard updates its slice, params all-gathered — emitted automatically from
  the sharding mismatch.
- stage 2: grads *also* sharded over ``data``: XLA lowers the grad psum with a
  sharded output to a reduce-scatter (≡ stage2.py:769 average_tensor's
  rank-sliced dist.reduce), and the post-step param update all-gathers
  (≡ stage2.py:1583).
- stage 3: parameters themselves sharded over ``data`` (≡ FSDP /
  partition_parameters.py); XLA inserts per-use all-gathers and re-partitions
  afterwards; with remat the gather happens again in backward, matching the
  fetch/release economy of PartitionedParameterCoordinator.

Sharding a tensor means picking one dimension to split. We pick the largest
dimension divisible by the axis size (best collective granularity and layout
friendliness); tensors too small to split stay replicated — the analogue of
stage 3's ``param_persistence_threshold`` (stage3.py:1406).
"""

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import (DATA_AXIS, DCN_AXIS, EXPERT_AXIS,
                                         axes_size as mesh_axes_size)
from deepspeed_tpu.runtime.zero.config import ZeroConfig


@dataclass(frozen=True)
class ZeroPolicy:
    """Which state groups are sharded along the data axis."""

    shard_params: bool
    shard_grads: bool
    shard_optimizer_state: bool

    @classmethod
    def for_stage(cls, stage: int) -> "ZeroPolicy":
        if stage == 0:
            return cls(False, False, False)
        if stage == 1:
            return cls(False, False, True)
        if stage == 2:
            return cls(False, True, True)
        if stage == 3:
            return cls(True, True, True)
        raise ValueError(f"invalid ZeRO stage {stage}")


def _shardable_dim(shape: Tuple[int, ...], axis_size: int,
                   min_size: int) -> Optional[int]:
    """Largest dim divisible by axis_size; None if tensor too small/unsplittable."""
    if axis_size <= 1:
        return None
    size = int(np.prod(shape)) if shape else 0
    if size < min_size:
        return None
    best = None
    best_len = 0
    for i, d in enumerate(shape):
        if d % axis_size == 0 and d > best_len:
            best, best_len = i, d
    return best


class ZeroPartitioner:
    """Computes PartitionSpecs for params / grads / optimizer state.

    ``extra_axes``: model-parallel specs already attached to a param (e.g. a
    tensor-parallel 'model' sharding from the model definition) are composed
    with — not overwritten by — the ZeRO data-axis sharding, giving 2D
    (data × model) sharding like ZeRO+Megatron in the reference.
    """

    def __init__(self, mesh: Mesh, config: ZeroConfig,
                 persistence_threshold: Optional[int] = None):
        self.mesh = mesh
        self.config = config
        self.policy = ZeroPolicy.for_stage(config.stage)
        self.data_size = mesh.shape.get(DATA_AXIS, 1)
        self.dcn_size = mesh.shape.get(DCN_AXIS, 1)
        self.persistence_threshold = int(
            persistence_threshold if persistence_threshold is not None
            else config.param_persistence_threshold)
        # ZeRO++ weight path (zeropp block; arXiv 2306.10209 / 2004.13336):
        # with the block active and stage >= 2, params ALWAYS carry the
        # explicit partition (the implicit stage-2 post-apply all-gather
        # becomes the explicit quantized fwd gather — comm/grad_sync.py
        # ParamGatherPlan). hpz=off spans the PRIMARY partition over the
        # full (dcn, data) product — maximal master/optimizer HBM savings,
        # param gathers cross DCN (quantized); hpz=on keeps the partition
        # intra-slice (the hierarchical SECONDARY partition): gathers ride
        # ICI only, the dcn replica's HBM cost is charged to the memory
        # ledger. Inactive (the default) all axes stay (data,) and every
        # spec below is byte-identical to the pre-zeropp partitioner.
        zpp = config.zeropp
        self._zeropp_shard_params = bool(zpp.active and config.stage >= 2)
        if zpp.active and zpp.hpz == "off" and self.dcn_size > 1:
            self.primary_axes: Tuple[str, ...] = (DCN_AXIS, DATA_AXIS)
        else:
            self.primary_axes = (DATA_AXIS,)

    # -- spec computation ---------------------------------------------------
    def _axes_size(self, axes: Tuple[str, ...]) -> int:
        return mesh_axes_size(self.mesh.shape, axes)

    def _shard_spec(self, shape: Tuple[int, ...],
                    base_spec: Optional[PartitionSpec],
                    axes: Tuple[str, ...],
                    min_size: int = 1) -> PartitionSpec:
        """Add an ``axes`` sharding to base_spec on the best free
        dimension (the generalized ``_data_shard_spec`` — (data,) for the
        classic ZeRO partition, (dcn, data) for the zeropp global primary
        partition)."""
        base = tuple(base_spec) if base_spec is not None else ()
        base = base + (None,) * (len(shape) - len(base))
        # A base spec may already place one of the target axes (e.g.
        # TiledLinear's stage-3 kernel spec places data) — adding it again
        # would duplicate the axis.
        for s in base:
            parts = s if isinstance(s, tuple) else (s,)
            if any(a in parts for a in axes):
                return PartitionSpec(*base)
        axes_size = self._axes_size(axes)
        # Dimensions already taken by model/sequence axes are not available.
        free_dims = [i for i, s in enumerate(base) if s is None]
        candidates = []
        for i in free_dims:
            d = shape[i]
            # the dim must divide by the axes product AFTER any existing
            # sharding on other dims (existing specs shard other dims, so
            # d is intact)
            if d % axes_size == 0:
                candidates.append((d, i))
        if not candidates or int(np.prod(shape)) < min_size:
            return PartitionSpec(*base) if any(s is not None for s in base) else PartitionSpec()
        _, dim = max(candidates)
        new = list(base)
        new[dim] = axes if len(axes) > 1 else axes[0]
        return PartitionSpec(*new)

    def _data_shard_spec(self, shape: Tuple[int, ...],
                         base_spec: Optional[PartitionSpec],
                         min_size: int = 1) -> PartitionSpec:
        """Add a data-axis sharding to base_spec on the best free dimension."""
        return self._shard_spec(shape, base_spec, (DATA_AXIS,),
                                min_size=min_size)

    @staticmethod
    def _places(spec: PartitionSpec, axes: Tuple[str, ...]) -> bool:
        for s in tuple(spec):
            parts = s if isinstance(s, tuple) else (s,)
            if any(a in parts for a in axes):
                return True
        return False

    def _primary_spec(self, shape: Tuple[int, ...],
                      base_spec: Optional[PartitionSpec],
                      min_size: int = 1) -> PartitionSpec:
        """Primary-partition spec. Under the zeropp global primary a
        leaf whose free dims divide ``data`` but not ``dcn * data``
        (e.g. dim 12 on a dcn2 x data4 mesh) must fall back to the
        intra-slice (data,) partition, NOT to full replication — plain
        stage 3 sharded such leaves over data and the "maximal HBM
        savings" mode can never do worse; the leaf then behaves like an
        hpZ leaf (data-sharded, dcn-replicated, ICI-only gather).

        Expert-stacked leaves (a base spec placing the ``expert`` axis —
        moe_partition_rules) are ALWAYS kept intra-slice: expert params
        are the all-to-all dispatch path's working set every microstep,
        and a dcn-spanning primary would put their gather on the
        cross-slice wire. They take the hpZ treatment unconditionally —
        (data,) on the free dim, dcn-replicated, ICI-only collectives —
        which tests/test_moe.py pins at the spec and jaxpr level."""
        if base_spec is not None and self._places(base_spec,
                                                 (EXPERT_AXIS,)):
            return self._shard_spec(shape, base_spec, (DATA_AXIS,),
                                    min_size=min_size)
        spec = self._shard_spec(shape, base_spec, self.primary_axes,
                                min_size=min_size)
        if len(self.primary_axes) > 1 \
                and not self._places(spec, self.primary_axes):
            return self._shard_spec(shape, base_spec, (DATA_AXIS,),
                                    min_size=min_size)
        return spec

    def param_spec(self, shape: Tuple[int, ...],
                   base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        if self.policy.shard_params or self._zeropp_shard_params:
            # Small params stay resident/replicated — the stage-3
            # param_persistence_threshold (stage3.py:1406).
            return self._primary_spec(shape, base_spec,
                                      min_size=self.persistence_threshold)
        return base_spec if base_spec is not None else PartitionSpec()

    def hpz_replica_shard_elems(self, gathered_leaves) -> int:
        """ZeRO++ hpZ secondary-charge support (telemetry/memory.py):
        the per-device master-shard ELEMS of the gathered leaves a
        global (hpz off) primary could actually spread over dcn — the
        replica bytes flipping hpz off would save. Leaves the global
        primary cannot shard over dcn (base-pinned data axis, dims not
        divisible by dcn x data) contribute nothing: they keep the same
        (data,) partition either way. Lives HERE, beside the placement
        rules it mirrors, so the counterfactual can never drift from
        real placement. ``gathered_leaves``: (shape, sharded axes,
        base_spec) triples from ``ParamGatherPlan.gathered_leaves()`` —
        plus its ``fallback_leaves()``, whose free dim carries the same
        primary placement despite riding the implicit gather path."""
        from dataclasses import replace
        zpp = replace(self.config.zeropp, hpz="off")
        if not zpp.active:
            # fp32-passthrough tier: flipping hpz alone would make the
            # block inert; placement only depends on active, not on the
            # wire dtype.
            zpp = replace(zpp, quantized_weights="bf16")
        glob = ZeroPartitioner(
            self.mesh, replace(self.config, zeropp=zpp),
            persistence_threshold=self.persistence_threshold)
        total = 0
        for shape, axes, base in gathered_leaves:
            if not self._places(glob.param_spec(shape, base),
                                (DCN_AXIS,)):
                continue
            n = self._axes_size(axes)
            total += int(np.prod(shape)) // max(n, 1)
        return total

    def grad_spec(self, shape: Tuple[int, ...],
                  base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        # Grads stay on the ICI-inner data axis in every configuration —
        # including zeropp (active only at stage >= 2, where shard_grads
        # already holds): the grad-sync machinery (implicit,
        # hierarchical, overlapped) reduces over dcn and scatters over
        # data, and a dcn-sharded accumulator would break that contract.
        if self.policy.shard_grads or self.policy.shard_params:
            return self._data_shard_spec(shape, base_spec)
        return base_spec if base_spec is not None else PartitionSpec()

    def opt_state_spec(self, shape: Tuple[int, ...],
                       base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        if self.policy.shard_optimizer_state:
            # Under the zeropp global primary the moments follow the
            # (dcn, data) partition — the sharded optimizer apply
            # (2004.13336) then updates each rank's primary shard only —
            # with the same data-axis fallback as param_spec so moments
            # never shard differently from their master leaf.
            return self._primary_spec(shape, base_spec)
        return base_spec if base_spec is not None else PartitionSpec()

    # -- tree-level helpers -------------------------------------------------
    def param_specs(self, params: Any, base_specs: Any = None) -> Any:
        return self._tree_specs(params, base_specs, self.param_spec)

    def grad_specs(self, params: Any, base_specs: Any = None) -> Any:
        return self._tree_specs(params, base_specs, self.grad_spec)

    def opt_state_specs(self, params: Any, base_specs: Any = None) -> Any:
        return self._tree_specs(params, base_specs, self.opt_state_spec)

    def _tree_specs(self, params: Any, base_specs: Any, fn) -> Any:
        def leaf_spec(p, base):
            shape = tuple(p.shape) if hasattr(p, "shape") else ()
            return fn(shape, base)

        if base_specs is None:
            return jax.tree_util.tree_map(lambda p: leaf_spec(p, None), params)
        return jax.tree_util.tree_map(leaf_spec, params, base_specs)

    def param_shardings(self, params: Any, base_specs: Any = None) -> Any:
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), self.param_specs(params, base_specs))

    def opt_state_shardings(self, params: Any, base_specs: Any = None) -> Any:
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), self.opt_state_specs(params, base_specs))


# ---------------------------------------------------------------------------
# Memory estimation (reference stage2.py:2005-2106, stage3 estimators)
# ---------------------------------------------------------------------------

def estimate_zero_model_states_mem_needs(total_params: int,
                                         num_devices: int,
                                         stage: int,
                                         cpu_offload: bool = False,
                                         param_dtype_bytes: int = 2,
                                         master_dtype_bytes: int = 4,
                                         optim_states_per_param: int = 2):
    """Per-device HBM and host bytes for model states under a ZeRO stage
    (reference ``estimate_zero2_model_states_mem_needs`` stage2.py:2005 and
    ``estimate_zero3_model_states_mem_needs`` stage3.py:3272, re-framed
    per-device for the placement-policy design).

    Model states = params (bf16) + grads (bf16/fp32) + master params (fp32)
    + optimizer moments (2×fp32 for Adam).

    ``cpu_offload`` models this engine's offload tiers: the fp32
    master+moments move to host, sharded over devices for stage >= 1 and
    FULL per host at stage 0 (no ZeRO sharding to exploit). At stage 3 the
    offload_optimizer tier requires offload_param (runtime/engine.py), so
    the compute-dtype param partition leaves HBM too — the reference's
    18-vs-16-bytes/param distinction between its zero-3 offload_params and
    zero-2 offload estimates.
    """
    gb = 1024**3
    p = total_params
    master_and_optim = (master_dtype_bytes + optim_states_per_param * 4) * p
    grads = param_dtype_bytes * p
    params = param_dtype_bytes * p
    if stage == 0:
        hbm = params + grads + master_and_optim
    elif stage == 1:
        hbm = params + grads + master_and_optim / num_devices
    elif stage == 2:
        hbm = params + (grads + master_and_optim) / num_devices
    else:
        hbm = (params + grads + master_and_optim) / num_devices
    host = 0
    if cpu_offload:
        opt_shard = num_devices if stage >= 1 else 1
        host = master_and_optim / opt_shard
        hbm -= master_and_optim / opt_shard
        if stage == 3:
            # offload_param: the bf16 param partition lives host-side and
            # streams on demand (runtime/zero/param_offload.py).
            host += params / num_devices
            hbm -= params / num_devices
    return {"hbm_gb": hbm / gb, "host_gb": host / gb}
