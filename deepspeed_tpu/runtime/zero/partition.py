"""ZeRO partitioning as sharding rules.

The TPU-native realisation of ZeRO stages 1-3 (reference
``runtime/zero/stage1.py``, ``stage2.py``, ``stage3.py``,
``partition_parameters.py``): instead of flat fp16 buffers, autograd hooks and
hand-rolled reduce/allgather, each stage is a *placement policy* — a mapping
from every array in the train state to a ``PartitionSpec`` over the ``data``
mesh axis. pjit/GSPMD then emits exactly the collectives the reference
hand-codes:

- stage 0: params/grads/opt-state replicated; grads ``psum`` (≡ allreduce).
- stage 1: optimizer state (fp32 master + moments) sharded over ``data``
  (≡ optimizer state partitioning, stage1.py). Grad allreduce, then each
  shard updates its slice, params all-gathered — emitted automatically from
  the sharding mismatch.
- stage 2: grads *also* sharded over ``data``: XLA lowers the grad psum with a
  sharded output to a reduce-scatter (≡ stage2.py:769 average_tensor's
  rank-sliced dist.reduce), and the post-step param update all-gathers
  (≡ stage2.py:1583).
- stage 3: parameters themselves sharded over ``data`` (≡ FSDP /
  partition_parameters.py); XLA inserts per-use all-gathers and re-partitions
  afterwards; with remat the gather happens again in backward, matching the
  fetch/release economy of PartitionedParameterCoordinator.

Sharding a tensor means picking one dimension to split. We pick the largest
dimension divisible by the axis size (best collective granularity and layout
friendliness); tensors too small to split stay replicated — the analogue of
stage 3's ``param_persistence_threshold`` (stage3.py:1406).
"""

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import DATA_AXIS
from deepspeed_tpu.runtime.zero.config import ZeroConfig


@dataclass(frozen=True)
class ZeroPolicy:
    """Which state groups are sharded along the data axis."""

    shard_params: bool
    shard_grads: bool
    shard_optimizer_state: bool

    @classmethod
    def for_stage(cls, stage: int) -> "ZeroPolicy":
        if stage == 0:
            return cls(False, False, False)
        if stage == 1:
            return cls(False, False, True)
        if stage == 2:
            return cls(False, True, True)
        if stage == 3:
            return cls(True, True, True)
        raise ValueError(f"invalid ZeRO stage {stage}")


def _shardable_dim(shape: Tuple[int, ...], axis_size: int,
                   min_size: int) -> Optional[int]:
    """Largest dim divisible by axis_size; None if tensor too small/unsplittable."""
    if axis_size <= 1:
        return None
    size = int(np.prod(shape)) if shape else 0
    if size < min_size:
        return None
    best = None
    best_len = 0
    for i, d in enumerate(shape):
        if d % axis_size == 0 and d > best_len:
            best, best_len = i, d
    return best


class ZeroPartitioner:
    """Computes PartitionSpecs for params / grads / optimizer state.

    ``extra_axes``: model-parallel specs already attached to a param (e.g. a
    tensor-parallel 'model' sharding from the model definition) are composed
    with — not overwritten by — the ZeRO data-axis sharding, giving 2D
    (data × model) sharding like ZeRO+Megatron in the reference.
    """

    def __init__(self, mesh: Mesh, config: ZeroConfig,
                 persistence_threshold: Optional[int] = None):
        self.mesh = mesh
        self.config = config
        self.policy = ZeroPolicy.for_stage(config.stage)
        self.data_size = mesh.shape.get(DATA_AXIS, 1)
        self.persistence_threshold = int(
            persistence_threshold if persistence_threshold is not None
            else config.param_persistence_threshold)

    # -- spec computation ---------------------------------------------------
    def _data_shard_spec(self, shape: Tuple[int, ...],
                         base_spec: Optional[PartitionSpec],
                         min_size: int = 1) -> PartitionSpec:
        """Add a data-axis sharding to base_spec on the best free dimension."""
        base = tuple(base_spec) if base_spec is not None else ()
        base = base + (None,) * (len(shape) - len(base))
        # A base spec may already place the data axis (e.g. TiledLinear's
        # stage-3 kernel spec) — adding it again would duplicate the axis.
        for s in base:
            parts = s if isinstance(s, tuple) else (s,)
            if DATA_AXIS in parts:
                return PartitionSpec(*base)
        # Dimensions already taken by model/sequence axes are not available.
        free_dims = [i for i, s in enumerate(base) if s is None]
        candidates = []
        for i in free_dims:
            d = shape[i]
            # the dim must divide by data axis AFTER any existing sharding on
            # other dims (existing specs shard other dims, so d is intact)
            if d % self.data_size == 0:
                candidates.append((d, i))
        if not candidates or int(np.prod(shape)) < min_size:
            return PartitionSpec(*base) if any(s is not None for s in base) else PartitionSpec()
        _, dim = max(candidates)
        new = list(base)
        new[dim] = DATA_AXIS
        return PartitionSpec(*new)

    def param_spec(self, shape: Tuple[int, ...],
                   base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        if self.policy.shard_params:
            # Small params stay resident/replicated — the stage-3
            # param_persistence_threshold (stage3.py:1406).
            return self._data_shard_spec(shape, base_spec,
                                         min_size=self.persistence_threshold)
        return base_spec if base_spec is not None else PartitionSpec()

    def grad_spec(self, shape: Tuple[int, ...],
                  base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        if self.policy.shard_grads or self.policy.shard_params:
            return self._data_shard_spec(shape, base_spec)
        return base_spec if base_spec is not None else PartitionSpec()

    def opt_state_spec(self, shape: Tuple[int, ...],
                       base_spec: Optional[PartitionSpec] = None) -> PartitionSpec:
        if self.policy.shard_optimizer_state:
            return self._data_shard_spec(shape, base_spec)
        return base_spec if base_spec is not None else PartitionSpec()

    # -- tree-level helpers -------------------------------------------------
    def param_specs(self, params: Any, base_specs: Any = None) -> Any:
        return self._tree_specs(params, base_specs, self.param_spec)

    def grad_specs(self, params: Any, base_specs: Any = None) -> Any:
        return self._tree_specs(params, base_specs, self.grad_spec)

    def opt_state_specs(self, params: Any, base_specs: Any = None) -> Any:
        return self._tree_specs(params, base_specs, self.opt_state_spec)

    def _tree_specs(self, params: Any, base_specs: Any, fn) -> Any:
        def leaf_spec(p, base):
            shape = tuple(p.shape) if hasattr(p, "shape") else ()
            return fn(shape, base)

        if base_specs is None:
            return jax.tree_util.tree_map(lambda p: leaf_spec(p, None), params)
        return jax.tree_util.tree_map(leaf_spec, params, base_specs)

    def param_shardings(self, params: Any, base_specs: Any = None) -> Any:
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), self.param_specs(params, base_specs))

    def opt_state_shardings(self, params: Any, base_specs: Any = None) -> Any:
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), self.opt_state_specs(params, base_specs))


# ---------------------------------------------------------------------------
# Memory estimation (reference stage2.py:2005-2106, stage3 estimators)
# ---------------------------------------------------------------------------

def estimate_zero_model_states_mem_needs(total_params: int,
                                         num_devices: int,
                                         stage: int,
                                         cpu_offload: bool = False,
                                         param_dtype_bytes: int = 2,
                                         master_dtype_bytes: int = 4,
                                         optim_states_per_param: int = 2):
    """Per-device HBM and host bytes for model states under a ZeRO stage
    (reference ``estimate_zero2_model_states_mem_needs`` stage2.py:2005 and
    ``estimate_zero3_model_states_mem_needs`` stage3.py:3272, re-framed
    per-device for the placement-policy design).

    Model states = params (bf16) + grads (bf16/fp32) + master params (fp32)
    + optimizer moments (2×fp32 for Adam).

    ``cpu_offload`` models this engine's offload tiers: the fp32
    master+moments move to host, sharded over devices for stage >= 1 and
    FULL per host at stage 0 (no ZeRO sharding to exploit). At stage 3 the
    offload_optimizer tier requires offload_param (runtime/engine.py), so
    the compute-dtype param partition leaves HBM too — the reference's
    18-vs-16-bytes/param distinction between its zero-3 offload_params and
    zero-2 offload estimates.
    """
    gb = 1024**3
    p = total_params
    master_and_optim = (master_dtype_bytes + optim_states_per_param * 4) * p
    grads = param_dtype_bytes * p
    params = param_dtype_bytes * p
    if stage == 0:
        hbm = params + grads + master_and_optim
    elif stage == 1:
        hbm = params + grads + master_and_optim / num_devices
    elif stage == 2:
        hbm = params + (grads + master_and_optim) / num_devices
    else:
        hbm = (params + grads + master_and_optim) / num_devices
    host = 0
    if cpu_offload:
        opt_shard = num_devices if stage >= 1 else 1
        host = master_and_optim / opt_shard
        hbm -= master_and_optim / opt_shard
        if stage == 3:
            # offload_param: the bf16 param partition lives host-side and
            # streams on demand (runtime/zero/param_offload.py).
            host += params / num_devices
            hbm -= params / num_devices
    return {"hbm_gb": hbm / gb, "host_gb": host / gb}
