"""ZeRO-Offload / ZeRO-Infinity optimizer-state offloading.

TPU-native re-design of the reference's offload tier:

- **cpu tier** (reference ``ops/adam/cpu_adam.py:13`` + ``csrc/adam/
  cpu_adam.cpp`` AVX kernel, wired by stage2's ``cpu_offload``): fp32 master
  params and Adam moments live in HOST RAM as arrays committed to the CPU
  backend; the optimizer step is a jitted XLA:CPU program (the AVX analogue —
  XLA vectorizes the elementwise chain). Per step, the device sends only the
  (ZeRO-sharded, then gathered) fp32 grads down and receives compute-dtype
  params back — the same traffic pattern as the reference's
  grad-copy-down / param-copy-up.
- **nvme tier** (reference ``swap_tensor/optimizer_utils.py``,
  ``pipelined_optimizer_swapper.py:60``, ``csrc/aio/``): moments + master
  params live on disk, streamed leaf-by-leaf through
  ``PipelinedLeafSwapper`` double buffering — read of leaf i+1 and write of
  leaf i-1 overlap the update of leaf i. Host RAM holds only
  O(largest-leaf) at a time.

Device HBM per step holds only compute-dtype params + grads; the 12-16
bytes/param optimizer tier (m, v, fp32 master) moves off-chip, which is the
reference's "13B on one GPU" headline economics.
"""

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist


def host_device():
    """The host-RAM placement target (CPU backend device 0)."""
    return jax.local_devices(backend="cpu")[0]


def to_host(tree: Any) -> Any:
    """Commit a pytree to host RAM (gathers sharded leaves; in multi-process
    each process holds only its addressable shards' gather)."""
    cpu = host_device()
    return jax.device_put(tree, cpu)


def leaf_names(tree: Any) -> Tuple[str, ...]:
    paths = jax.tree_util.tree_flatten_with_path(tree)[0]
    return tuple(
        "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
        for path, _ in paths)


class OptimizerOffloader:
    """Holds the host/NVMe-resident optimizer tier and runs the step.

    ``update(grads_host, lr, clip_coef, skip)`` applies one optimizer step on
    host-resident master params + moments, returning the compute-dtype param
    tree to send back to the device. ``skip`` (overflow) leaves state
    untouched.
    """

    def __init__(self, optimizer, master_params: Any, *,
                 device: str = "cpu", nvme_path: Optional[str] = None,
                 buffer_count: int = 2, compute_dtype=jnp.bfloat16,
                 aio_threads: Optional[int] = None):
        self.optimizer = optimizer
        self.tier = device
        self.compute_dtype = compute_dtype
        cpu = host_device()
        # The jitted cast materializes NEW host buffers: a bare device_put
        # of already-host fp32 arrays would alias the caller's params, and
        # the donating host step would then delete them out from under the
        # user (same hazard as TPUEngine._init_state's shard_like).
        host = to_host(master_params)
        self.master = jax.jit(
            lambda t: jax.tree_util.tree_map(
                lambda p: p.astype(jnp.float32), t))(host)

        if self.tier == "cpu":
            # Init the moments ON the host device — jnp.zeros otherwise
            # materialises the full moment tree on the accelerator first,
            # which OOMs exactly the models this tier exists for.
            with jax.default_device(cpu):
                self.opt_state = jax.device_put(optimizer.init(self.master),
                                                cpu)
            self._host_step = None  # built lazily (needs lr dtype etc.)
            self.swapper = None
        elif self.tier == "nvme":
            if nvme_path is None:
                raise ValueError("offload_optimizer.device='nvme' requires "
                                 "nvme_path")
            probe = optimizer.init({"w": jnp.zeros((1,), jnp.float32)})
            if not (hasattr(probe, "_fields")
                    and {"step", "exp_avg", "exp_avg_sq"} <= set(probe._fields)):
                raise ValueError(
                    f"nvme offload packs (master, exp_avg, exp_avg_sq) per "
                    f"leaf and needs an Adam/LAMB-state optimizer; "
                    f"{type(optimizer).__name__} has state "
                    f"{type(probe).__name__} — use device='cpu' (generic) "
                    f"instead")
            from deepspeed_tpu.runtime.swap_tensor import (
                AsyncTensorSwapper, PipelinedLeafSwapper)
            # aio.thread_count (reference csrc/aio thread pool size) wins
            # over the offload buffer_count default when configured.
            self.swapper = AsyncTensorSwapper(
                nvme_path, num_threads=aio_threads or buffer_count)
            self.pipeline = PipelinedLeafSwapper(self.swapper)
            self._names = leaf_names(self.master)
            self._treedef = jax.tree_util.tree_structure(self.master)
            self._state_cls = type(probe)
            leaves = jax.tree_util.tree_leaves(self.master)
            self._abstract = [jax.ShapeDtypeStruct(tuple(l.shape), np.float32)
                              for l in leaves]
            # Swap out initial state: packed [3, ...] = (master, m, v) per
            # leaf so one file read yields the whole per-leaf working set.
            futs = []
            for name, leaf in zip(self._names, leaves):
                p = np.asarray(leaf, np.float32)
                packed = np.stack([p, np.zeros_like(p), np.zeros_like(p)])
                futs.append(self.swapper.swap_out(name, packed))
            for f in futs:
                f.result()
            del leaves
            self._step_count = 0
            self.master = None       # lives on disk now
            self.opt_state = None
            self._leaf_update = None
            log_dist(f"nvme offload: optimizer tier swapped to "
                     f"{nvme_path} ({len(self._names)} leaves)", ranks=[0])
        else:
            raise ValueError(f"unknown offload device '{device}'")

    # ------------------------------------------------------------------
    def _build_host_step(self, clip: float):
        optimizer = self.optimizer
        dtype = self.compute_dtype
        clip = float(clip)

        def host_step(master, opt_state, grads, lr, coef, norm, skip):
            # ``coef`` is the unscale(+predivide) factor; ``norm`` the
            # device-computed SCALED global grad norm. Folding the clip
            # arithmetic in here (instead of python float(norm)) keeps the
            # whole step free of blocking device fetches — the round-2
            # advisor/VERDICT "per-step host round-trip" finding.
            if clip > 0.0:
                unscaled = norm * coef
                coef = coef * jnp.minimum(1.0, clip / (unscaled + 1e-6))
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32) * coef, grads)
            new_p, new_opt = optimizer.update(grads, opt_state, master, lr=lr)
            keep = lambda new, old: jax.tree_util.tree_map(
                lambda a, b: jnp.where(skip, b, a), new, old)
            new_p = keep(new_p, master)
            new_opt = keep(new_opt, opt_state)
            compute = jax.tree_util.tree_map(lambda p: p.astype(dtype), new_p)
            return new_p, new_opt, compute

        return jax.jit(host_step, donate_argnums=(0, 1))

    def update(self, grads_host: Any, lr, clip_coef, skip,
               norm=None, clip: float = 0.0) -> Any:
        """One offloaded optimizer step; returns compute-dtype params (on
        host — caller places them onto the device mesh).

        Async contract (cpu tier): every argument may be a lazy/committed
        jax array — nothing here forces a device sync; gradient clipping
        uses ``norm`` (scaled global norm) + static ``clip`` inside the
        jitted host step. The nvme tier is host-driven leaf streaming and
        synchronises by construction."""
        if self.tier == "cpu":
            if self._host_step is None or getattr(
                    self, "_host_step_clip", None) != float(clip):
                self._host_step = self._build_host_step(clip)
                self._host_step_clip = float(clip)
            if norm is None:
                norm = jnp.float32(0.0)     # clip==0 path ignores it
            self.master, self.opt_state, compute = self._host_step(
                self.master, self.opt_state, grads_host,
                jnp.float32(lr), jnp.float32(clip_coef), norm, skip)
            return compute
        if norm is not None and clip > 0.0:
            unscaled = float(norm) * float(clip_coef)
            if unscaled > clip:
                clip_coef = float(clip_coef) * clip / (unscaled + 1e-6)

        # ---- nvme tier: stream leaves through the double buffer --------
        if self._leaf_update is None:
            opt = self.optimizer

            def leaf_update(packed, g, step, lr, clip_coef, skip):
                p, m, v = packed[0], packed[1], packed[2]
                tree_p = {"w": p}
                state = type(opt.init(tree_p))(
                    step=step, exp_avg={"w": m}, exp_avg_sq={"w": v})
                g = {"w": g.astype(jnp.float32) * clip_coef}
                new_p, new_state = opt.update(g, state, tree_p, lr=lr)
                out = jnp.stack([new_p["w"], new_state.exp_avg["w"],
                                 new_state.exp_avg_sq["w"]])
                return jnp.where(skip, packed, out)

            self._leaf_update = jax.jit(leaf_update)

        flat_grads = jax.tree_util.tree_leaves(grads_host)
        by_name = dict(zip(self._names, flat_grads))
        step = jnp.int32(self._step_count)
        compute_leaves = {}
        skip_bool = bool(skip)

        def compute_fn(name, packed):
            new_packed = np.asarray(self._leaf_update(
                packed, by_name[name], step, jnp.float32(lr),
                jnp.float32(clip_coef), skip))
            # fp32 here; the engine casts to compute dtype on device placement
            compute_leaves[name] = new_packed[0]
            return new_packed

        self.pipeline.stream(list(self._names), compute_fn)
        if not skip_bool:
            self._step_count += 1
        ordered = [compute_leaves[n] for n in self._names]
        return jax.tree_util.tree_unflatten(self._treedef, ordered)

    # ------------------------------------------------------------------
    def master_tree(self) -> Any:
        """Full fp32 master params (reads NVMe tier back into RAM)."""
        if self.tier == "cpu":
            return self.master
        outs = []
        for name in self._names:
            outs.append(self.swapper.swap_in(name).result()[0])
        return jax.tree_util.tree_unflatten(self._treedef, outs)

    # --- nvme-tier checkpoint bridge (reference stage3.py:3250
    # save_checkpoint_prologue reads the swapped tensors back) -----------
    def export_state(self):
        """Read the on-disk (master, moments) back into host RAM as the
        (params_tree, optimizer_state) pair the checkpointer saves. Host
        RAM transiently holds the full fp32 state — same as the
        reference's prologue."""
        assert self.tier == "nvme"
        futs = [(n, self.swapper.swap_in(n)) for n in self._names]
        ps, ms, vs = [], [], []
        for _, f in futs:
            packed = f.result()
            ps.append(packed[0])
            ms.append(packed[1])
            vs.append(packed[2])
        unflat = lambda ls: jax.tree_util.tree_unflatten(self._treedef, ls)
        opt = self._state_cls(step=jnp.int32(self._step_count),
                              exp_avg=unflat(ms), exp_avg_sq=unflat(vs))
        return unflat(ps), opt

    def import_state(self, master: Any, opt_state: Any) -> None:
        """Write restored (master, moments) back onto the NVMe tier."""
        assert self.tier == "nvme"
        p_leaves = jax.tree_util.tree_leaves(master)
        m_leaves = jax.tree_util.tree_leaves(opt_state.exp_avg)
        v_leaves = jax.tree_util.tree_leaves(opt_state.exp_avg_sq)
        futs = []
        for n, p, m, v in zip(self._names, p_leaves, m_leaves, v_leaves):
            packed = np.stack([np.asarray(p, np.float32),
                               np.asarray(m, np.float32),
                               np.asarray(v, np.float32)])
            futs.append(self.swapper.swap_out(n, packed))
        for f in futs:
            f.result()
        self._step_count = int(opt_state.step)

    def abstract_state(self):
        """(params, opt_state) ShapeDtypeStruct trees for checkpoint
        restore templates (the real trees live on disk)."""
        assert self.tier == "nvme"
        unflat = lambda ls: jax.tree_util.tree_unflatten(self._treedef, ls)
        params = unflat(list(self._abstract))
        opt = self._state_cls(
            step=jax.ShapeDtypeStruct((), np.int32),
            exp_avg=unflat(list(self._abstract)),
            exp_avg_sq=unflat(list(self._abstract)))
        return params, opt

    def close(self):
        if self.swapper is not None:
            self.swapper.close(remove_files=True)
