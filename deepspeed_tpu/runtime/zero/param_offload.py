"""ZeRO-Infinity parameter offload — the ``offload_param`` tier.

Reference: ``deepspeed/runtime/swap_tensor/partitioned_param_swapper.py:36``
(fp16 param partitions streamed off-device), wired through
``partition_parameters.py:663`` and stage-3 sub-groups
(``stage3.py:1084-1247``): CUDA-side hooks fetch each sub-module's params
right before its forward/backward and release them after, so device memory
holds only the working set — the "40B params on one device" headline
(``docs/_posts/2021-03-08-zero3-offload.md:77``).

TPU-native re-design: no hooks, no swapper state machine. The compute-dtype
parameters live in the TPU runtime's *host memory space* (arrays committed
to shardings with ``memory_kind='pinned_host'``, sharded over the ``data``
axis — each host stores the ZeRO-3 partition). The traced train step fetches
each transformer block on-device right before use (``jax.device_put`` to
``TransferToMemoryKind('device')`` inside a ``lax.scan`` over the stacked
blocks) and ``jax.checkpoint`` makes the backward *re-fetch* instead of
keeping fwd copies alive — the fetch/release economy of the reference's
``PartitionedParameterCoordinator``, scheduled by XLA's latency-hiding
scheduler (H2D DMA of block i+1 overlaps compute of block i) instead of a
Python prefetcher.

The model must expose per-block fetch points, exactly as the reference needs
``nn.Module`` boundaries for its hooks: we use the block-structured
``PipeModel`` contract (``parallel/pipe/module.py``) — embed / stacked
blocks / head. ``deepspeed_tpu.initialize`` converts in-tree model families
automatically; arbitrary opaque ``loss_fn`` callables cannot be streamed.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import DATA_AXIS

from deepspeed_tpu.utils.jax_compat import DEVICE_MEMORY_SPACE


def _pick_host_memory_kind() -> str:
    """pinned_host on TPU/GPU (and new XLA:CPU, which aliases it); old
    XLA:CPU only addresses unpinned_host — placement-identical for the
    virtual-mesh tests, so fall through rather than fail."""
    try:
        kinds = {m.kind for m in jax.devices()[0].addressable_memories()}
    except Exception:
        return "pinned_host"
    for kind in ("pinned_host", "unpinned_host"):
        if kind in kinds:
            return kind
    return "pinned_host"


# Resolved lazily on first use: probing jax.devices() at import time would
# initialise the backend and break the init_distributed() ordering invariant
# (parallel/mesh.py — a backend query before jax.distributed.initialize
# silently degrades a pod to disconnected single-process runs).
_HOST_MEMORY_KIND: str = ""
_TO_DEVICE = DEVICE_MEMORY_SPACE


def host_memory_kind() -> str:
    global _HOST_MEMORY_KIND
    if not _HOST_MEMORY_KIND:
        _HOST_MEMORY_KIND = _pick_host_memory_kind()
    return _HOST_MEMORY_KIND


def __getattr__(name: str):
    # Back-compat for the old module constant (probes the backend, so it
    # must stay lazy).
    if name == "HOST_MEMORY_KIND":
        return host_memory_kind()
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def fetch(tree: Any) -> Any:
    """Move a (host-resident) param subtree into device memory inside a
    traced computation. Keeps the array's sharding layout — a host-sharded
    partition arrives device-sharded and GSPMD inserts the ZeRO-3
    all-gather at first use."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, _TO_DEVICE), tree)


def host_storage_specs(tree: Any, data_size: int,
                       stacked_keys: tuple = ("blocks",)) -> Any:
    """Host-RAM storage PartitionSpecs: shard each leaf's largest
    data-divisible dimension over ``data`` (multi-host: each host stores
    1/dp — the ZeRO-3 param partition). For stacked block subtrees the
    leading L dim is excluded so a scan slice never crosses the shard axis.
    """
    def spec_for(x, skip_leading):
        shape = tuple(x.shape) if hasattr(x, "shape") else ()
        best, best_len = None, 0
        for i, d in enumerate(shape):
            if skip_leading and i == 0 and len(shape) > 1:
                continue
            if data_size > 1 and d % data_size == 0 and d > best_len:
                best, best_len = i, d
        if best is None:
            return PartitionSpec()
        parts = [None] * len(shape)
        parts[best] = DATA_AXIS
        return PartitionSpec(*parts)

    if not isinstance(tree, dict):
        return jax.tree_util.tree_map(lambda x: spec_for(x, False), tree)
    out = {}
    for key, sub in tree.items():
        stacked = key in stacked_keys
        out[key] = jax.tree_util.tree_map(
            lambda x, s=stacked: spec_for(x, s), sub)
    return out


def host_shardings(mesh, specs: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s, memory_kind=host_memory_kind()),
        specs)


def place_host(tree: Any, mesh, specs: Any) -> Any:
    """Commit a param tree to pinned host memory with ZeRO-3 storage specs."""
    return jax.device_put(tree, host_shardings(mesh, specs))


def cast_host(tree: Any, dtype) -> Any:
    """Cast on the host (numpy/ml_dtypes) — never materialises a device
    copy of the full tree, which is the whole point of this tier."""
    npdt = np.dtype(dtype)
    return jax.tree_util.tree_map(
        lambda a: np.asarray(a) if np.asarray(a).dtype == npdt
        else np.asarray(a).astype(npdt), tree)


def pack_blocks(blocks: Any):
    """Flat-pack the stacked [L, ...] block tree into one [L, P] buffer.

    The streamed copy lives in host memory as ONE contiguous row per block
    — the analogue of the reference's contiguous fp16 partition buffers
    (``stage3.py:1084 _create_fp16_partitions_with_defragmentation``), and
    on TPU it means one H2D DMA per block instead of a dozen small ones.
    (It also works around an axon-runtime crash when a scan walks a
    multi-leaf host-memory operand tree with per-iteration fetches.)
    Returns ``(flat [L, P], meta)`` for :func:`unpack_block`.
    """
    leaves, treedef = jax.tree_util.tree_flatten(blocks)
    num = leaves[0].shape[0]
    shapes = [tuple(l.shape[1:]) for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    dtypes = [l.dtype for l in leaves]
    flat = jnp.concatenate([jnp.reshape(l, (num, -1)) for l in leaves],
                           axis=1)
    # Rows are stored [P/128, 128]: the TPU runtime cannot DMA a 1-D
    # dynamic-slice row out of pinned host memory inside a scan (hard
    # runtime fault, found r3), and the sliced row's leading dim must be a
    # sublane multiple (8) or the compiler faults — so pad P to 8·128.
    total = flat.shape[1]
    pad = (-total) % (8 * 128)
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    flat = flat.reshape(num, -1, 128)
    return flat, (treedef, shapes, sizes, tuple(dtypes))


def pack_blocks_tp(blocks: Any, leaf_specs: Any, mesh, data_size: int):
    """Tensor-parallel-aware flat packing (ZeRO-Infinity × MP composition,
    reference ``stage3.py:590`` takes an mpu for the same reason).

    Leaves with a model-axis PartitionSpec (one-block specs, no leading L)
    are packed PER TP SHARD: ``tp_buf [L, tp, R, 128]`` whose dim 1 is
    sharded over the model axes and dim 2 over ``data`` — each device's
    host partition holds exactly its TP shard of every block, so the
    streamed fetch moves 1/(dp·tp) of the block and the rebuilt leaves are
    born TP-sharded (no gather past the shard level). Unsharded leaves
    (biases, norms) keep the replicated-row layout of :func:`pack_blocks`.

    Returns ``({"tp": buf|None, "rep": buf|None}, meta)``; falls back to
    the plain layout (``tp is None``) when no leaf is model-sharded.
    """
    leaves, treedef = jax.tree_util.tree_flatten(blocks)
    specs = treedef.flatten_up_to(leaf_specs)
    mesh_shape = dict(mesh.shape)
    num = leaves[0].shape[0]

    tp_axes = None
    recs = []   # (is_tp, shard_dim j, shape, dtype)
    for leaf, spec in zip(leaves, specs):
        dims = tuple(leaf.shape[1:])
        entries = tuple(spec) if spec is not None else ()
        entries = entries + (None,) * (len(dims) - len(entries))
        j = None
        axes = None
        for i, e in enumerate(entries):
            parts = e if isinstance(e, tuple) else ((e,) if e else ())
            parts = tuple(a for a in parts
                          if a != DATA_AXIS and mesh_shape.get(a, 1) > 1)
            if parts:
                if j is not None:
                    raise ValueError(
                        "pack_blocks_tp: at most one model-sharded dim per "
                        f"leaf (got spec {spec})")
                j, axes = i, parts
        if j is not None:
            if tp_axes is None:
                tp_axes = axes
            elif tp_axes != axes:
                raise ValueError(
                    f"pack_blocks_tp: all model-sharded leaves must use the "
                    f"same axes (got {axes} vs {tp_axes})")
        recs.append((j, dims, leaf.dtype))

    tp = 1
    if tp_axes is not None:
        for a in tp_axes:
            tp *= mesh_shape[a]
    if tp <= 1:
        flat, meta = pack_blocks(blocks)
        return {"tp": None, "rep": flat}, {
            "treedef": treedef, "recs": recs, "tp_axes": None, "tp": 1,
            "rep_meta": meta, "specs": specs}

    tp_parts, rep_leaves = [], []
    for leaf, (j, dims, _) in zip(leaves, recs):
        if j is None:
            rep_leaves.append(leaf)
            continue
        if dims[j] % tp:
            raise ValueError(f"dim {dims[j]} not divisible by tp={tp}")
        arr = jnp.moveaxis(leaf, j + 1, 1)           # [L, dj, rest...]
        tp_parts.append(arr.reshape(num, tp, -1))    # [L, tp, dj/tp*rest]
    tp_flat = jnp.concatenate(tp_parts, axis=2)
    align = 128 * 8 * max(data_size, 1)
    pad = (-tp_flat.shape[2]) % align
    if pad:
        tp_flat = jnp.pad(tp_flat, ((0, 0), (0, 0), (0, pad)))
    tp_flat = tp_flat.reshape(num, tp, -1, 128)

    rep_flat, rep_meta = (None, None)
    if rep_leaves:
        rep_flat, rep_meta = pack_blocks(
            jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(list(range(len(rep_leaves)))),
                rep_leaves))
    meta = {"treedef": treedef, "recs": recs, "tp_axes": tp_axes, "tp": tp,
            "rep_meta": rep_meta, "specs": specs}
    return {"tp": tp_flat, "rep": rep_flat}, meta


def unpack_block_tp(rows, meta, mesh) -> Any:
    """One block from the TP-aware packed layout. ``rows``: dict with
    ``tp`` [tp, R, 128] (dim 0 model-sharded) and ``rep`` [R2, 128].
    Rebuilt TP leaves are constrained to their one-block specs, so the
    merge reshape stays device-local (dim 0 and the target shard dim carry
    the same axes)."""
    treedef, recs = meta["treedef"], meta["recs"]
    tp, tp_axes = meta["tp"], meta["tp_axes"]
    specs = meta["specs"]
    if tp_axes is None:
        return unpack_block(rows["rep"], meta["rep_meta"])

    def shard_leaves(chunk):
        flat = chunk.reshape(-1)
        out, off = [], 0
        for j, dims, dt in recs:
            if j is None:
                continue
            n = int(np.prod(dims)) // tp
            moved = (dims[j] // tp,) + tuple(
                d for i, d in enumerate(dims) if i != j)
            out.append(flat[off:off + n].reshape(moved))
            off += n
        return out

    shards = jax.vmap(shard_leaves)(rows["tp"])  # leaves [tp, dj/tp, rest]
    rep_leaves = []
    if rows.get("rep") is not None:
        rep_tree = unpack_block(rows["rep"], meta["rep_meta"])
        rep_leaves = jax.tree_util.tree_leaves(rep_tree)
    rep_i = 0
    tp_i = 0
    leaves = []
    for (j, dims, dt), spec in zip(recs, specs):
        if j is None:
            leaves.append(rep_leaves[rep_i])
            rep_i += 1
            continue
        x = shards[tp_i]
        tp_i += 1
        # [tp, dj/tp, rest...] -> [dj, rest...] -> moveaxis back to j
        x = x.reshape((dims[j],) + x.shape[2:])
        x = jnp.moveaxis(x, 0, j)
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec if spec is not None
                             else PartitionSpec()))
        leaves.append(x)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def unpack_block(row: jax.Array, meta) -> Any:
    """One packed [P/128, 128] row -> the single-block param tree (static
    slices — fused by XLA, no copies).

    Homogeneous trees (the engine path: everything cast to the compute
    dtype before packing) keep the row's dtype, so an engine-level cast of
    the packed buffer is respected. Mixed-dtype trees get each leaf cast
    back to its pre-pack dtype (concatenate promoted them)."""
    treedef, shapes, sizes, dtypes = meta
    homogeneous = len(set(dtypes)) == 1
    row = row.reshape(-1)
    out, off = [], 0
    for s, n, dt in zip(shapes, sizes, dtypes):
        leaf = row[off:off + n].reshape(s)
        out.append(leaf if homogeneous else leaf.astype(dt))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def build_streamed_loss(pipe_model, remat: bool = True, params: Any = None,
                        tp_specs: Any = None, mesh=None):
    """(loss_fn, host_layout_params) over HOST-resident params.

    ``loss_fn(host_params, batch, rng) -> loss`` with per-block device
    fetches: embed + head params are fetched once per microbatch (they feed
    both ends — weight tying), each block's packed row is fetched inside
    the layer scan right before its compute (one DMA), and with ``remat``
    (default) the backward re-fetches blocks instead of holding every
    forward copy live. The returned params tree stores the blocks
    flat-packed (:func:`pack_blocks`).

    ``tp_specs`` + ``mesh``: one-block PartitionSpecs for tensor-parallel
    composition — the packing becomes shard-aligned
    (:func:`pack_blocks_tp`) so each device stores and fetches only its TP
    shard; ``loss_fn.host_storage_spec_overrides`` then carries the
    storage specs the engine must use for the blocks entry.

    ``params``: optional weights to serve instead of the PipeModel's —
    either pipe layout (blocks get packed) or an already-packed tree
    (e.g. restored from an offload checkpoint; used as-is after a shape
    check — re-packing a packed array would destroy the block structure).
    """
    pm = pipe_model
    data_size = mesh.shape.get(DATA_AXIS, 1) if mesh is not None else 1
    use_tp = tp_specs is not None and mesh is not None
    if use_tp:
        packed, meta = pack_blocks_tp(pm.params["blocks"], tp_specs, mesh,
                                      data_size)
        use_tp = meta["tp_axes"] is not None
    if not use_tp:
        flat, meta = pack_blocks(pm.params["blocks"])
        packed = flat

    def shapes_of(tree):
        return jax.tree_util.tree_map(lambda x: tuple(x.shape), tree)

    if params is None:
        blocks = packed
        params = {"embed": pm.params["embed"], "blocks": packed,
                  "head": pm.params["head"]}
    else:
        blocks = params["blocks"]
        looks_packed = (isinstance(blocks, jax.Array)
                        or isinstance(blocks, np.ndarray)
                        or (isinstance(blocks, dict)
                            and set(blocks) == {"tp", "rep"}))
        if not looks_packed:                   # pipe layout: pack it
            blocks = (pack_blocks_tp(blocks, tp_specs, mesh, data_size)[0]
                      if use_tp else pack_blocks(blocks)[0])
        if shapes_of(blocks) != shapes_of(packed):
            raise ValueError(
                f"provided blocks {shapes_of(blocks)} do not match the "
                f"model's packed layout {shapes_of(packed)}")
        params = {"embed": params["embed"], "blocks": blocks,
                  "head": params["head"]}

    def loss_fn(host_params, batch, rng):
        persistent = fetch({"embed": host_params["embed"],
                            "head": host_params["head"]})
        if rng is not None:
            rng, r_embed = jax.random.split(rng)
        else:
            r_embed = None
        x = pm.embed_fn(persistent, batch, r_embed)
        aux = pm.aux_fn(persistent, batch) if pm.aux_fn is not None else None

        def inner(row_host, x, sub, idx):
            fetched = jax.tree_util.tree_map(
                lambda a: jax.device_put(a, _TO_DEVICE), row_host)
            if use_tp:
                blk = unpack_block_tp(fetched, meta, mesh)
            else:
                blk = unpack_block(fetched, meta)
            if pm.block_takes_layer_idx:
                # per-layer schedules (PLD) need the block index — without
                # it the gate runs at layer 0's keep-prob 1.0, silently
                # inert (parallel/pipe/pipeline.py threads it the same way)
                y = pm.block_fn(blk, x, aux, sub, idx)
            else:
                y = pm.block_fn(blk, x, aux, sub)
            if not pm.block_returns_aux:
                y = (y, jnp.float32(0.0))
            return y

        if remat:
            inner = jax.checkpoint(inner)

        def body(carry, row_i):
            row_host, idx = row_i
            x, r, aux_acc = carry
            if r is not None:
                r, sub = jax.random.split(r)
            else:
                sub = None
            y, a_l = inner(row_host, x, sub, idx)
            return (y, r, aux_acc + a_l.astype(jnp.float32)), None

        n_blocks = jax.tree_util.tree_leaves(
            host_params["blocks"])[0].shape[0]
        (x, rng, aux_acc), _ = jax.lax.scan(
            body, (x, rng, jnp.float32(0.0)),
            (host_params["blocks"], jnp.arange(n_blocks)))
        loss = pm.head_fn(persistent, x, batch)
        # MoE blocks' (alpha-scaled) balance losses; zero otherwise.
        return loss + aux_acc

    if use_tp:
        tp_entry = (meta["tp_axes"][0] if len(meta["tp_axes"]) == 1
                    else tuple(meta["tp_axes"]))
        r_blocks = packed["tp"].shape[2]
        over = {"tp": PartitionSpec(
            None, tp_entry,
            DATA_AXIS if data_size > 1 and r_blocks % data_size == 0
            else None, None)}
        if packed["rep"] is not None:
            rr = packed["rep"].shape[1]
            over["rep"] = PartitionSpec(
                None,
                DATA_AXIS if data_size > 1 and rr % data_size == 0 else None,
                None)
        loss_fn.host_storage_spec_overrides = {"blocks": over}
    return loss_fn, params
