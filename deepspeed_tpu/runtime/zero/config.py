"""ZeRO configuration.

Key names match the reference (``deepspeed/runtime/zero/config.py`` and
``zero/constants.py``) so DeepSpeed JSON configs parse unchanged.

TPU semantics: stages 1-3 are realised as sharding rules over the ``data``
mesh axis (see ``runtime/zero/partition.py``) rather than torch flat-buffer
surgery, so several GPU-era knobs (bucket sizes, overlap_comm) are accepted,
validated, and recorded, but only influence behaviour where XLA exposes an
equivalent lever (e.g. ``overlap_comm`` toggles the latency-hiding scheduler
hint; bucket sizes inform the compressed-collective chunking).
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

ZERO_OPTIMIZATION = "zero_optimization"

ZERO_STAGE = "stage"
ZERO_STAGE_DEFAULT = 0

ALLGATHER_PARTITIONS = "allgather_partitions"
ALLGATHER_PARTITIONS_DEFAULT = True
ALLGATHER_BUCKET_SIZE = "allgather_bucket_size"
ALLGATHER_BUCKET_SIZE_DEFAULT = 5e8
OVERLAP_COMM = "overlap_comm"
OVERLAP_COMM_DEFAULT = False
REDUCE_SCATTER = "reduce_scatter"
REDUCE_SCATTER_DEFAULT = True
REDUCE_BUCKET_SIZE = "reduce_bucket_size"
REDUCE_BUCKET_SIZE_DEFAULT = 5e8
CONTIGUOUS_GRADIENTS = "contiguous_gradients"
CONTIGUOUS_GRADIENTS_DEFAULT = False
CPU_OFFLOAD = "cpu_offload"  # legacy stage-2 flag
ELASTIC_CHECKPOINT = "elastic_checkpoint"
ELASTIC_CHECKPOINT_DEFAULT = True
LEGACY_STAGE1 = "legacy_stage1"

OFFLOAD_PARAM = "offload_param"
OFFLOAD_OPTIMIZER = "offload_optimizer"
OFFLOAD_DEVICE = "device"
OFFLOAD_DEVICE_NONE = "none"
OFFLOAD_DEVICE_CPU = "cpu"
OFFLOAD_DEVICE_NVME = "nvme"
OFFLOAD_NVME_PATH = "nvme_path"
OFFLOAD_BUFFER_COUNT = "buffer_count"
OFFLOAD_BUFFER_SIZE = "buffer_size"
OFFLOAD_MAX_IN_CPU = "max_in_cpu"
OFFLOAD_PIN_MEMORY = "pin_memory"
OFFLOAD_PIPELINE = "pipeline"

SUB_GROUP_SIZE = "sub_group_size"
SUB_GROUP_SIZE_DEFAULT = 1e9

STAGE3_MAX_LIVE_PARAMETERS = "stage3_max_live_parameters"
STAGE3_MAX_LIVE_PARAMETERS_DEFAULT = 1e9
STAGE3_MAX_REUSE_DISTANCE = "stage3_max_reuse_distance"
STAGE3_MAX_REUSE_DISTANCE_DEFAULT = 1e9
STAGE3_PREFETCH_BUCKET_SIZE = "stage3_prefetch_bucket_size"
STAGE3_PREFETCH_BUCKET_SIZE_DEFAULT = 5e8
STAGE3_PARAM_PERSISTENCE_THRESHOLD = "stage3_param_persistence_threshold"
STAGE3_PARAM_PERSISTENCE_THRESHOLD_DEFAULT = 1e5
STAGE3_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE = "stage3_gather_fp16_weights_on_model_save"

# ZeRO++ (arXiv 2306.10209) weight-path block — see ZeroPPConfig.
ZEROPP = "zeropp"
ZEROPP_QUANTIZED_WEIGHTS = "quantized_weights"
ZEROPP_QUANTIZED_WEIGHTS_DEFAULT = "off"      # off | bf16 | int8
ZEROPP_QUANT_BLOCK_SIZE = "quant_block_size"
ZEROPP_QUANT_BLOCK_SIZE_DEFAULT = 256
ZEROPP_HPZ = "hpz"
ZEROPP_HPZ_DEFAULT = "off"                    # off | on

# Wire bits of each quantized_weights tier (the comm/quantize.py core's
# bits argument — 32 is the exact fp32 passthrough hpZ alone uses).
ZEROPP_WIRE_BITS = {"off": 32, "bf16": 16, "int8": 8}


@dataclass
class ZeroOffloadConfig:
    """Offload target for optimizer state or parameters (ZeRO-Offload/Infinity)."""

    device: str = OFFLOAD_DEVICE_NONE
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: float = 1e8
    max_in_cpu: float = 1e9
    pin_memory: bool = False
    pipeline: bool = False

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ZeroOffloadConfig":
        if d is None:
            return cls()
        if not isinstance(d, dict):
            raise ValueError(f"offload config must be a dict, got {type(d)}")
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown offload config keys: {sorted(unknown)}")
        return cls(**d)

    @property
    def enabled(self) -> bool:
        return self.device not in (None, OFFLOAD_DEVICE_NONE)


@dataclass
class ZeroPPConfig:
    """``zero_optimization.zeropp`` — the ZeRO++ weight path
    (arXiv 2306.10209 qwZ/hpZ + weight-update sharding arXiv 2004.13336;
    runtime/zero/partition.py for the placement half,
    comm/grad_sync.py ``ParamGatherPlan`` for the wire protocol).

    ``quantized_weights``: the wire dtype of the explicit fwd/bwd param
    all-gather — ``int8`` (blockwise RTNE codes + per-block fp32 scales,
    the one int8 core in comm/quantize.py), ``bf16``, or ``off`` (fp32
    passthrough when the block is otherwise active; with ``hpz`` off too
    the whole block is inert and the lowered step is bit-identical to a
    zeropp-less config).
    ``quant_block_size``: elements per quantization block.
    ``hpz``: ``on`` keeps the param partition *intra-slice* (the
    hierarchical secondary partition — fwd/bwd gathers ride ICI only and
    cross-slice param traffic is zero; the dcn-replica HBM cost is
    charged to the memory ledger); ``off`` (with the block active) spans
    the primary partition over the full (dcn x data) world — maximal
    HBM savings, param gathers cross DCN (quantized).
    """

    quantized_weights: str = ZEROPP_QUANTIZED_WEIGHTS_DEFAULT
    quant_block_size: int = ZEROPP_QUANT_BLOCK_SIZE_DEFAULT
    hpz: str = ZEROPP_HPZ_DEFAULT

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ZeroPPConfig":
        if d is None:
            return cls()
        if not isinstance(d, dict):
            raise ValueError(f"{ZEROPP} must be a dict, got {type(d)}")
        d = dict(d)
        cfg = cls(
            quantized_weights=str(d.pop(
                ZEROPP_QUANTIZED_WEIGHTS,
                ZEROPP_QUANTIZED_WEIGHTS_DEFAULT)).lower(),
            quant_block_size=int(d.pop(ZEROPP_QUANT_BLOCK_SIZE,
                                       ZEROPP_QUANT_BLOCK_SIZE_DEFAULT)),
            hpz=str(d.pop(ZEROPP_HPZ, ZEROPP_HPZ_DEFAULT)).lower(),
        )
        if d:
            raise ValueError(f"unknown {ZEROPP} keys: {sorted(d)}")
        if cfg.quantized_weights not in ZEROPP_WIRE_BITS:
            raise ValueError(
                f"{ZEROPP}.{ZEROPP_QUANTIZED_WEIGHTS} must be one of "
                f"{sorted(ZEROPP_WIRE_BITS)}, got "
                f"'{cfg.quantized_weights}'")
        if cfg.quant_block_size <= 0:
            raise ValueError(
                f"{ZEROPP}.{ZEROPP_QUANT_BLOCK_SIZE} must be positive, "
                f"got {cfg.quant_block_size}")
        if cfg.hpz not in ("off", "on"):
            raise ValueError(
                f"{ZEROPP}.{ZEROPP_HPZ} must be off|on, got '{cfg.hpz}'")
        return cfg

    @property
    def active(self) -> bool:
        """Whether the block changes the step at all: any lossy wire tier
        OR the hpZ partition. Inactive (the default) must leave the
        lowered step bit-identical — the PR 4 off-identity contract."""
        return self.quantized_weights != "off" or self.hpz == "on"

    @property
    def wire_bits(self) -> int:
        return ZEROPP_WIRE_BITS[self.quantized_weights]

    def to_dict(self) -> Dict[str, Any]:
        return {
            ZEROPP_QUANTIZED_WEIGHTS: self.quantized_weights,
            ZEROPP_QUANT_BLOCK_SIZE: self.quant_block_size,
            ZEROPP_HPZ: self.hpz,
        }


@dataclass
class ZeroConfig:
    stage: int = ZERO_STAGE_DEFAULT
    allgather_partitions: bool = ALLGATHER_PARTITIONS_DEFAULT
    allgather_bucket_size: float = ALLGATHER_BUCKET_SIZE_DEFAULT
    overlap_comm: bool = OVERLAP_COMM_DEFAULT
    reduce_scatter: bool = REDUCE_SCATTER_DEFAULT
    reduce_bucket_size: float = REDUCE_BUCKET_SIZE_DEFAULT
    contiguous_gradients: bool = CONTIGUOUS_GRADIENTS_DEFAULT
    elastic_checkpoint: bool = ELASTIC_CHECKPOINT_DEFAULT
    offload_param: ZeroOffloadConfig = field(default_factory=ZeroOffloadConfig)
    offload_optimizer: ZeroOffloadConfig = field(default_factory=ZeroOffloadConfig)
    sub_group_size: float = SUB_GROUP_SIZE_DEFAULT
    max_live_parameters: float = STAGE3_MAX_LIVE_PARAMETERS_DEFAULT
    max_reuse_distance: float = STAGE3_MAX_REUSE_DISTANCE_DEFAULT
    prefetch_bucket_size: float = STAGE3_PREFETCH_BUCKET_SIZE_DEFAULT
    param_persistence_threshold: float = STAGE3_PARAM_PERSISTENCE_THRESHOLD_DEFAULT
    gather_fp16_weights_on_model_save: bool = False
    legacy_stage1: bool = False
    zeropp: ZeroPPConfig = field(default_factory=ZeroPPConfig)

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "ZeroConfig":
        if d is None:
            return cls()
        if not isinstance(d, dict):
            raise ValueError(f"{ZERO_OPTIMIZATION} must be a dict, got {type(d)}")
        d = dict(d)
        cfg = cls()
        cfg.stage = int(d.pop(ZERO_STAGE, ZERO_STAGE_DEFAULT))
        if cfg.stage not in (0, 1, 2, 3):
            raise ValueError(f"ZeRO stage must be 0-3, got {cfg.stage}")
        cfg.allgather_partitions = bool(d.pop(ALLGATHER_PARTITIONS, cfg.allgather_partitions))
        cfg.allgather_bucket_size = float(d.pop(ALLGATHER_BUCKET_SIZE, cfg.allgather_bucket_size))
        cfg.overlap_comm = bool(d.pop(OVERLAP_COMM, cfg.overlap_comm))
        cfg.reduce_scatter = bool(d.pop(REDUCE_SCATTER, cfg.reduce_scatter))
        cfg.reduce_bucket_size = float(d.pop(REDUCE_BUCKET_SIZE, cfg.reduce_bucket_size))
        cfg.contiguous_gradients = bool(d.pop(CONTIGUOUS_GRADIENTS, cfg.contiguous_gradients))
        cfg.elastic_checkpoint = bool(d.pop(ELASTIC_CHECKPOINT, cfg.elastic_checkpoint))
        cfg.sub_group_size = float(d.pop(SUB_GROUP_SIZE, cfg.sub_group_size))
        cfg.max_live_parameters = float(d.pop(STAGE3_MAX_LIVE_PARAMETERS, cfg.max_live_parameters))
        cfg.max_reuse_distance = float(d.pop(STAGE3_MAX_REUSE_DISTANCE, cfg.max_reuse_distance))
        cfg.prefetch_bucket_size = float(d.pop(STAGE3_PREFETCH_BUCKET_SIZE, cfg.prefetch_bucket_size))
        cfg.param_persistence_threshold = float(
            d.pop(STAGE3_PARAM_PERSISTENCE_THRESHOLD, cfg.param_persistence_threshold))
        cfg.gather_fp16_weights_on_model_save = bool(
            d.pop(STAGE3_GATHER_FP16_WEIGHTS_ON_MODEL_SAVE, cfg.gather_fp16_weights_on_model_save))
        cfg.legacy_stage1 = bool(d.pop(LEGACY_STAGE1, cfg.legacy_stage1))
        cfg.offload_param = ZeroOffloadConfig.from_dict(d.pop(OFFLOAD_PARAM, None))
        cfg.offload_optimizer = ZeroOffloadConfig.from_dict(d.pop(OFFLOAD_OPTIMIZER, None))
        cfg.zeropp = ZeroPPConfig.from_dict(d.pop(ZEROPP, None))
        # Legacy stage-2 flag: cpu_offload=true ≡ offload_optimizer.device=cpu.
        if d.pop(CPU_OFFLOAD, False):
            cfg.offload_optimizer = ZeroOffloadConfig(device=OFFLOAD_DEVICE_CPU)
        unknown = set(d)
        if unknown:
            raise ValueError(f"unknown {ZERO_OPTIMIZATION} keys: {sorted(unknown)}")
        return cfg

    @property
    def enabled(self) -> bool:
        return self.stage > 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            ZERO_STAGE: self.stage,
            ALLGATHER_PARTITIONS: self.allgather_partitions,
            ALLGATHER_BUCKET_SIZE: self.allgather_bucket_size,
            OVERLAP_COMM: self.overlap_comm,
            REDUCE_SCATTER: self.reduce_scatter,
            REDUCE_BUCKET_SIZE: self.reduce_bucket_size,
            CONTIGUOUS_GRADIENTS: self.contiguous_gradients,
            ELASTIC_CHECKPOINT: self.elastic_checkpoint,
            SUB_GROUP_SIZE: self.sub_group_size,
            OFFLOAD_OPTIMIZER: {"device": self.offload_optimizer.device},
            OFFLOAD_PARAM: {"device": self.offload_param.device},
            ZEROPP: self.zeropp.to_dict(),
        }
