"""ZeRO subsystem: sharding policies, offload tiers, shard-at-construction."""

from deepspeed_tpu.runtime.zero.config import ZeroConfig, ZeroOffloadConfig
from deepspeed_tpu.runtime.zero.init import zero_init
from deepspeed_tpu.runtime.zero.partition import (
    ZeroPartitioner, ZeroPolicy, estimate_zero_model_states_mem_needs)

__all__ = ["ZeroConfig", "ZeroOffloadConfig", "ZeroPartitioner",
           "ZeroPolicy", "zero_init",
           "estimate_zero_model_states_mem_needs"]
