"""zero.Init analogue — shard-at-construction parameter initialization.

Reference: ``deepspeed/runtime/zero/partition_parameters.py:315`` (`Init`
context manager): modules built under it allocate each parameter directly as
its rank's partition so no process ever materializes the full model — the
prerequisite for training models larger than one host's memory.

TPU-native: the flax ``model.init`` is traced abstractly (``jax.eval_shape``
— zero bytes allocated), ZeRO-3 PartitionSpecs are computed from the
abstract shapes, and the real initialization runs as ONE jitted program with
``out_shardings`` — XLA materializes every leaf directly into its shard on
its device. On a multi-host pod each host only ever allocates its
addressable shards; there is no transient full-tree copy anywhere (contrast
``TPUEngine._init_state``, which re-shards a caller-materialized tree).
"""

from typing import Any, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding

from deepspeed_tpu.runtime.zero.config import ZeroConfig
from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner


def zero_init(model, example_batch: Any, *,
              mesh: Optional[Mesh] = None,
              zero_stage: int = 3,
              partition_specs: Any = None,
              rngs: Any = None,
              zero_config: Optional[ZeroConfig] = None) -> Tuple[Any, Any]:
    """Initialize ``model``'s params directly into their ZeRO sharding.

    Returns ``(params, specs)``; pass both to ``deepspeed_tpu.initialize``
    (params=..., param_partition_specs can stay the TP ``partition_specs``
    you provided here). ``example_batch`` is only traced, never computed on.
    """
    if mesh is None:
        from deepspeed_tpu.parallel.mesh import build_mesh
        mesh = build_mesh(data=-1)
    if rngs is None:
        rngs = {"params": jax.random.PRNGKey(0),
                "dropout": jax.random.PRNGKey(1)}
    if zero_config is not None:
        zcfg = zero_config        # caller's stage wins; never mutated
    else:
        zcfg = ZeroConfig()
        zcfg.stage = zero_stage

    def init_fn(r):
        return model.init(r, example_batch)["params"]

    abstract = jax.eval_shape(init_fn, rngs)
    partitioner = ZeroPartitioner(mesh, zcfg)
    specs = partitioner.param_specs(abstract, partition_specs)
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs)
    with mesh:
        params = jax.jit(init_fn, out_shardings=shardings)(rngs)
    return params, specs
