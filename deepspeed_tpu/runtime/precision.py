"""Mixed precision + dynamic loss scaling.

Parity with the reference's ``deepspeed/runtime/fp16/loss_scaler.py``
(``LossScaler`` :34, ``DynamicLossScaler`` :56) and the FP16 optimizer wrap
(``fp16/fused_optimizer.py:17``).

TPU-first: bf16 is the native mixed-precision mode and needs *no* loss
scaling (same exponent range as fp32); fp16 support keeps the dynamic scaler
for capability parity. The scaler state is a pytree carried inside the jitted
train step — scale growth/backoff and the skip-step decision are traced
``jnp.where`` branches, so overflow handling costs no recompilation and no
host sync (the reference needed an allreduce + host readback per step,
engine.py:1253-1302).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jax.Array          # fp32 scalar, current loss scale
    good_steps: jax.Array     # int32, consecutive non-overflow steps
    hysteresis: jax.Array     # int32, remaining tolerated overflows before backoff


class DynamicLossScaler:
    """Pure functional dynamic loss scaler.

    Growth: after ``scale_window`` consecutive good steps, scale *= scale_factor.
    Backoff: on overflow, hysteresis decrements; when exhausted scale /= factor
    (min ``min_scale``). Mirrors reference loss_scaler.py:56-131 semantics.
    """

    def __init__(self, init_scale: float = 2.0**32, scale_factor: float = 2.0,
                 scale_window: int = 1000, min_scale: float = 1.0,
                 hysteresis: int = 2):
        self.init_scale = float(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.hysteresis = int(hysteresis)

    def init(self) -> LossScaleState:
        return LossScaleState(scale=jnp.float32(self.init_scale),
                              good_steps=jnp.zeros((), jnp.int32),
                              hysteresis=jnp.full((), self.hysteresis, jnp.int32))

    def update(self, state: LossScaleState, overflow: jax.Array) -> LossScaleState:
        hys = jnp.where(overflow, jnp.maximum(state.hysteresis - 1, 0), state.hysteresis)
        backoff = overflow & (hys == 0)
        new_scale = jnp.where(
            backoff,
            jnp.maximum(state.scale / self.scale_factor, self.min_scale),
            state.scale)
        good = jnp.where(overflow, 0, state.good_steps + 1)
        grow = (~overflow) & (good >= self.scale_window)
        new_scale = jnp.where(grow, new_scale * self.scale_factor, new_scale)
        good = jnp.where(grow, 0, good)
        hys = jnp.where(backoff, self.hysteresis, hys)
        hys = jnp.where(grow | (~overflow), jnp.full((), self.hysteresis, jnp.int32), hys)
        return LossScaleState(scale=new_scale, good_steps=good, hysteresis=hys)


class StaticLossScaler:
    """Fixed loss scale (reference LossScaler :34)."""

    def __init__(self, scale: float = 1.0):
        self.scale = float(scale)

    def init(self) -> LossScaleState:
        return LossScaleState(scale=jnp.float32(self.scale),
                              good_steps=jnp.zeros((), jnp.int32),
                              hysteresis=jnp.zeros((), jnp.int32))

    def update(self, state: LossScaleState, overflow: jax.Array) -> LossScaleState:
        return state


def make_loss_scaler(fp16_enabled: bool, dynamic: bool, static_scale: float,
                     initial_scale_power: int, scale_window: int,
                     min_scale: float, hysteresis: int):
    if not fp16_enabled:
        return StaticLossScaler(1.0)
    if dynamic:
        return DynamicLossScaler(init_scale=2.0**initial_scale_power,
                                 scale_window=scale_window,
                                 min_scale=min_scale, hysteresis=hysteresis)
    return StaticLossScaler(static_scale)


# ---------------------------------------------------------------------------
# Precision policy
# ---------------------------------------------------------------------------

_DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}


class PrecisionPolicy:
    """Casting rules: compute dtype for fwd/bwd, fp32 master for the update.

    Equivalent to the reference's model.half() + fp32 master copies
    (engine.py:642, fused_optimizer.py). ``cast_params`` produces the compute
    copy fed to the loss fn; masters stay fp32.
    """

    def __init__(self, dtype_name: str):
        if dtype_name not in _DTYPES:
            raise ValueError(f"unknown precision {dtype_name}")
        self.name = dtype_name
        self.dtype = _DTYPES[dtype_name]
        self.mixed = dtype_name != "float32"

    def cast_params(self, params):
        if not self.mixed:
            return params
        return jax.tree_util.tree_map(
            lambda p: p.astype(self.dtype) if jnp.issubdtype(p.dtype, jnp.floating) else p,
            params)

    def cast_batch(self, batch):
        if not self.mixed:
            return batch
        return jax.tree_util.tree_map(
            lambda x: x.astype(self.dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) else x,
            batch)
