"""Data pipeline.

Parity with the reference ``deepspeed/runtime/dataloader.py``:
``DeepSpeedDataLoader`` (:33) wraps the user dataset with an automatic
distributed sampler sized by the data-parallel world, and ``RepeatingLoader``
(:10) provides the infinite iterator the pipeline engine consumes.

TPU-first: batches are numpy pytrees (host-side), sharded onto the mesh by
``engine.put_batch``. One *process* per host feeds all its addressable chips,
so the sampler granularity is (process_index, process_count) — each process
draws the micro-batches for every data-parallel position it hosts.
"""

import math
from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np


class DistributedSampler:
    """Deterministic rank-strided sampler (torch DistributedSampler semantics:
    pad to a multiple of world, stride by rank, reshuffle per epoch)."""

    def __init__(self, dataset_len: int, num_replicas: int, rank: int,
                 shuffle: bool = True, seed: int = 0, drop_last: bool = False):
        if rank >= num_replicas or rank < 0:
            raise ValueError(f"invalid rank {rank} for world {num_replicas}")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        if drop_last:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = math.ceil(dataset_len / num_replicas)
        self.total_size = self.num_samples * num_replicas
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            g = np.random.default_rng(self.seed + self.epoch)
            indices = g.permutation(self.dataset_len).tolist()
        else:
            indices = list(range(self.dataset_len))
        if not self.drop_last:
            pad = self.total_size - len(indices)
            indices += indices[:pad]
        else:
            indices = indices[:self.total_size]
        return iter(indices[self.rank:self.total_size:self.num_replicas])

    def __len__(self) -> int:
        return self.num_samples


def default_collate(samples: Sequence[Any]):
    """Stack a list of sample pytrees into one batch pytree of numpy arrays."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples])
                           for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:
    """Batch iterator over an indexable dataset with DP-aware sampling."""

    def __init__(self,
                 dataset,
                 batch_size: int,
                 data_parallel_world_size: int = 1,
                 data_parallel_rank: int = 0,
                 collate_fn: Optional[Callable] = None,
                 shuffle: bool = True,
                 seed: int = 0,
                 drop_last: bool = True,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.collate_fn = collate_fn or default_collate
        if data_sampler is None:
            data_sampler = DistributedSampler(
                len(dataset), num_replicas=data_parallel_world_size,
                rank=data_parallel_rank, shuffle=shuffle, seed=seed,
                drop_last=drop_last)
        self.sampler = data_sampler
        self.drop_last = drop_last
        self.len = len(self.sampler) // self.batch_size if drop_last else \
            math.ceil(len(self.sampler) / self.batch_size)

    def __len__(self) -> int:
        return self.len

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(self.dataset[idx])
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration (reference :10).

    Positional and replayable: tracks (epoch, batch_in_epoch) so an
    auto-resumed job can fast-forward the data stream to exactly where the
    checkpoint was taken (``state_dict``/``load_state_dict`` — register
    ``loader.state_dict`` as the engine's client-state fn and the position
    rides every resilience checkpoint). Replay re-draws the same sampler
    permutations, so the post-resume batch sequence is bit-identical to the
    uninterrupted run's.
    """

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)
        self.epoch = 0
        self.batch_in_epoch = 0

    def __iter__(self):
        return self

    def __next__(self):
        try:
            out = next(self.data_iter)
        except StopIteration:
            self.epoch += 1
            self.batch_in_epoch = 0
            if hasattr(self.loader, "sampler") and hasattr(self.loader.sampler, "set_epoch"):
                self.loader.sampler.set_epoch(self.epoch)
            self.data_iter = iter(self.loader)
            out = next(self.data_iter)
        self.batch_in_epoch += 1
        return out

    def skip_batches(self, n: int) -> int:
        """Advance the stream past ``n`` batches without yielding them —
        the guardrails rollback hook (register as
        ``engine.register_data_skip_fn(loader.skip_batches)``; the policy
        calls it to move past a poisoned window). Goes *through*
        ``__next__`` so epoch rollovers behave identically to consumption,
        keeping ``state_dict`` replay exact across a skip. Returns n."""
        if n < 0:
            raise ValueError("skip_batches: n must be >= 0")
        for _ in range(int(n)):
            next(self)
        return int(n)

    def state_dict(self) -> dict:
        return {"epoch": self.epoch, "batch_in_epoch": self.batch_in_epoch}

    def load_state_dict(self, sd: dict) -> None:
        """Rewind to the start of the saved epoch, then replay forward —
        going *through* ``__next__`` so epoch rollovers during the replay
        behave identically to the original pass."""
        self.epoch = int(sd["epoch"])
        self.batch_in_epoch = 0
        if hasattr(self.loader, "sampler") and hasattr(self.loader.sampler, "set_epoch"):
            self.loader.sampler.set_epoch(self.epoch)
        self.data_iter = iter(self.loader)
        for _ in range(int(sd["batch_in_epoch"])):
            next(self)


class PrefetchLoader:
    """Device-prefetching wrapper: while step N computes, batch N+1 is
    already being placed onto the mesh (the TPU analogue of the reference's
    pin_memory + async H2D; jax dispatch is async so ``put`` returns
    immediately and the transfer overlaps compute).

    ``put`` is required — pass ``engine.put_batch`` (the typical choice) or
    any host->device placement callable.
    """

    def __init__(self, loader, put: Callable[[Any], Any],
                 prefetch: int = 2):
        if prefetch < 1:
            raise ValueError("prefetch must be >= 1")
        self.loader = loader
        self.put = put
        self.prefetch = prefetch

    def __iter__(self):
        import collections

        queue = collections.deque()
        it = iter(self.loader)

        def refill():
            # next() inside the guard, put() outside: a StopIteration
            # escaping the user's put must surface, not truncate the epoch.
            try:
                batch = next(it)
            except StopIteration:
                return
            queue.append(self.put(batch))

        for _ in range(self.prefetch):
            refill()
        while queue:
            out = queue.popleft()
            refill()
            yield out

    def __len__(self):
        return len(self.loader)
