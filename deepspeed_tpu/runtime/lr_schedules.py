"""LR schedules.

Parity with the reference ``deepspeed/runtime/lr_schedules.py``:
``LRRangeTest`` (:301), ``OneCycle`` (:408), ``WarmupLR`` (:677),
``WarmupDecayLR`` (:761). Each schedule is a pure ``step -> lr`` function
(jit-safe jnp math) wrapped in a small stateless object exposing the
reference's ``get_lr()/step()`` surface for API compatibility; the engine
passes the scalar into the jitted train step, so LR changes never trigger
recompilation.
"""

import math
from typing import Callable, Dict, Optional

import jax.numpy as jnp

VALID_SCHEDULES = ["LRRangeTest", "OneCycle", "WarmupLR", "WarmupDecayLR"]

# Config keys (reference lr_schedules.py:24-53)
LR_RANGE_TEST_MIN_LR = "lr_range_test_min_lr"
LR_RANGE_TEST_STEP_SIZE = "lr_range_test_step_size"
LR_RANGE_TEST_STEP_RATE = "lr_range_test_step_rate"
LR_RANGE_TEST_STAIRCASE = "lr_range_test_staircase"
WARMUP_MIN_LR = "warmup_min_lr"
WARMUP_MAX_LR = "warmup_max_lr"
WARMUP_NUM_STEPS = "warmup_num_steps"
TOTAL_NUM_STEPS = "total_num_steps"


class _Schedule:
    """Minimal stateful wrapper: holds last_step, mirrors torch scheduler API."""

    def __init__(self, fn: Callable[[jnp.ndarray], jnp.ndarray]):
        self._fn = fn
        self.last_step = 0

    def lr_at(self, step) -> jnp.ndarray:
        """Pure lookup — call from inside jit with a traced step."""
        return self._fn(jnp.asarray(step, jnp.float32))

    # torch-scheduler-compatible surface --------------------------------
    def step(self, increment: int = 1) -> None:
        self.last_step += increment

    def get_lr(self) -> float:
        return float(self._fn(jnp.float32(self.last_step)))

    def get_last_lr(self):
        return [self.get_lr()]

    def state_dict(self) -> Dict:
        return {"last_step": self.last_step}

    def load_state_dict(self, sd: Dict) -> None:
        self.last_step = int(sd["last_step"])


class WarmupLR(_Schedule):
    """Linear warmup from min_lr to max_lr, then constant (reference :677)."""

    def __init__(self, warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
                 warmup_num_steps: int = 1000, last_batch_iteration: int = -1):
        lo, hi, n = float(warmup_min_lr), float(warmup_max_lr), max(int(warmup_num_steps), 1)

        def fn(step):
            frac = jnp.clip(step / n, 0.0, 1.0)
            return lo + (hi - lo) * frac

        super().__init__(fn)
        self.last_step = last_batch_iteration + 1


class WarmupDecayLR(_Schedule):
    """Warmup then linear decay to zero over total_num_steps (reference :761)."""

    def __init__(self, total_num_steps: int, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 last_batch_iteration: int = -1):
        lo, hi = float(warmup_min_lr), float(warmup_max_lr)
        n = max(int(warmup_num_steps), 1)
        total = max(int(total_num_steps), n + 1)

        def fn(step):
            warm = lo + (hi - lo) * jnp.clip(step / n, 0.0, 1.0)
            decay = hi * jnp.clip((total - step) / (total - n), 0.0, 1.0)
            return jnp.where(step < n, warm, decay)

        super().__init__(fn)
        self.last_step = last_batch_iteration + 1


class OneCycle(_Schedule):
    """Two-phase cycle then decay (reference :408).

    Phase 1: first_step_size up from cycle_min_lr to cycle_max_lr; phase 2:
    back down; then decay_lr_rate per post-cycle step. Momentum cycling is
    exposed via ``momentum_at`` for optimizers that consume it.
    """

    def __init__(self, cycle_min_lr: float, cycle_max_lr: float,
                 cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 decay_step_size: int = 0, decay_lr_rate: float = 0.0,
                 cycle_min_mom: float = 0.85, cycle_max_mom: float = 0.99,
                 cycle_momentum: bool = True, decay_mom_rate: float = 0.0,
                 last_batch_iteration: int = -1):
        lo, hi = float(cycle_min_lr), float(cycle_max_lr)
        up = max(int(cycle_first_step_size), 1)
        down = int(cycle_second_step_size) if cycle_second_step_size else up
        cycle_len = up + down
        dr = float(decay_lr_rate)
        ds = max(int(decay_step_size), 1)

        def fn(step):
            in_cycle = step < cycle_len
            pos_up = jnp.clip(step / up, 0.0, 1.0)
            pos_down = jnp.clip((step - up) / down, 0.0, 1.0)
            cyc = jnp.where(step < up, lo + (hi - lo) * pos_up,
                            hi - (hi - lo) * pos_down)
            post = jnp.maximum(step - cycle_len, 0.0)
            decayed = lo * (1.0 / (1.0 + dr * post / ds)) if dr > 0 else jnp.full_like(cyc, lo)
            return jnp.where(in_cycle, cyc, decayed)

        super().__init__(fn)
        self.last_step = last_batch_iteration + 1
        m_lo, m_hi = float(cycle_min_mom), float(cycle_max_mom)
        dm = float(decay_mom_rate)

        def mom_fn(step):
            pos_up = jnp.clip(step / up, 0.0, 1.0)
            pos_down = jnp.clip((step - up) / down, 0.0, 1.0)
            cyc = jnp.where(step < up, m_hi - (m_hi - m_lo) * pos_up,
                            m_lo + (m_hi - m_lo) * pos_down)
            post = jnp.maximum(step - cycle_len, 0.0)
            decayed = m_hi * (1.0 + dm * post / ds) if dm > 0 else jnp.full_like(cyc, m_hi)
            return jnp.where(step < cycle_len, cyc, jnp.minimum(decayed, m_hi))

        self._mom_fn = mom_fn if cycle_momentum else None

    def momentum_at(self, step):
        if self._mom_fn is None:
            return None
        return self._mom_fn(jnp.asarray(step, jnp.float32))


class LRRangeTest(_Schedule):
    """LR range test: ramp lr by step_rate every step_size steps, linearly or
    staircase (reference :301)."""

    def __init__(self, lr_range_test_min_lr: float = 1e-3,
                 lr_range_test_step_size: int = 2000,
                 lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False,
                 last_batch_iteration: int = -1):
        lo = float(lr_range_test_min_lr)
        size = max(int(lr_range_test_step_size), 1)
        rate = float(lr_range_test_step_rate)

        def fn(step):
            interval = jnp.floor(step / size) if lr_range_test_staircase else step / size
            return lo * (1.0 + rate * interval)

        super().__init__(fn)
        self.last_step = last_batch_iteration + 1


SCHEDULE_REGISTRY = {
    "WarmupLR": WarmupLR,
    "WarmupDecayLR": WarmupDecayLR,
    "OneCycle": OneCycle,
    "LRRangeTest": LRRangeTest,
}


def build_lr_schedule(name: Optional[str], params: Dict) -> Optional[_Schedule]:
    if name is None:
        return None
    if name not in SCHEDULE_REGISTRY:
        raise ValueError(f"unknown scheduler '{name}'; valid: {VALID_SCHEDULES}")
    return SCHEDULE_REGISTRY[name](**params)


def add_tuning_arguments(parser):
    """argparse LR-tuning overrides (reference lr_schedules.py:54-240)."""
    group = parser.add_argument_group("Convergence Tuning", "Convergence tuning configurations")
    group.add_argument("--lr_schedule", type=str, default=None,
                       help=f"LR schedule, one of {VALID_SCHEDULES}")
    group.add_argument(f"--{LR_RANGE_TEST_MIN_LR}", type=float, default=0.001)
    group.add_argument(f"--{LR_RANGE_TEST_STEP_SIZE}", type=int, default=1000)
    group.add_argument(f"--{LR_RANGE_TEST_STEP_RATE}", type=float, default=1.0)
    group.add_argument(f"--{LR_RANGE_TEST_STAIRCASE}", action="store_true")
    group.add_argument(f"--{WARMUP_MIN_LR}", type=float, default=0.0)
    group.add_argument(f"--{WARMUP_MAX_LR}", type=float, default=0.001)
    group.add_argument(f"--{WARMUP_NUM_STEPS}", type=int, default=1000)
    group.add_argument(f"--{TOTAL_NUM_STEPS}", type=int, default=10000)
    return parser
